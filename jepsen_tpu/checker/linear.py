"""`linear` — memoized, dominance-pruned host linearizability checker.

The reference exposes three knossos algorithms — :linear, :wgl and
:competition (jepsen/src/jepsen/checker.clj:122-126).  `checker/seq.py`
is the WGL analog (plain DFS over configurations); this module is the
`linear` analog: a *memoized configuration search* in the spirit of
Lowe's algorithm and Horn & Kroening's P-compositionality (PAPERS.md,
arXiv:1504.00204), specialized to what actually makes histories
expensive:

* **Compact configuration encoding.**  The same (prefix, window-bitmask)
  encoding the device engine uses (checker/linearizable.py's
  EncodedSearch): the linearized determinate set is `p` leading ops plus
  a bitmask over the next `window` ops, so set operations are small-int
  operations instead of n-bit bigint masks (the WGL oracle's per-config
  cost grows linearly with history length; this one's does not).

* **Per-(p, window) candidate memoization.**  Which determinate ops may
  linearize next — and the minimum outstanding return that gates crashed
  ops — depends only on (p, win), not on model state or crash set.  The
  candidate scan runs once per distinct (p, win) and is shared by every
  state/crash variant (the analog of knossos `linear`'s memoized
  configuration cache).

* **Crash-set dominance pruning.**  Crashed (:info) ops never block
  other ops (their return is +inf) and are never *required* to linearize
  (the goal is "every :ok op linearized" — core.clj:387-397 semantics).
  Hence if configurations A and B share (p, win, state) and A's
  linearized-crash set is a subset of B's, every completion of B is a
  completion of A — B is redundant.  Each (p, win, state) keeps only an
  antichain of minimal crash masks.  The level-synchronous device BFS
  cannot see this (the two configs sit at different depths); here it
  collapses the crash-subset dimension of the search, often by orders of
  magnitude on crash-heavy histories.

* **Level-synchronous sweep, level-local memory.**  Depth = number of
  determinate ops linearized; crashed ops linearize *within* a level
  (they do not advance depth).  A configuration's depth is a function of
  its encoding, so dedup never needs to cross levels and memory is
  bounded by the widest level, not the whole visited set (the WGL
  oracle's visited set is why the reference sizes its JVM at -Xmx32g,
  jepsen/project.clj:25).

Like the WGL oracle it is exact: verdicts are True/False, with
"unknown" only on budget/deadline/cancellation.  Differential-tested
against checker/seq.py (tests/test_linear_algo.py).
"""

from __future__ import annotations

import time

from ..history import OpSeq
from ..models import ModelSpec
from .linearizable import INF32, encode_search

#: the ONE default parent-table bound for witness-tracking callers
#: (user-facing Linearizable, competition/portfolio legs, decomposed
#: sub-searches, segment sweeps): ~a few hundred MB of dict at worst,
#: after which the witness is dropped with an explicit reason and the
#: verdict continues unaffected
DEFAULT_WITNESS_CAP = 2_000_000


def _advance(p: int, win: int, bit: int, n_det: int):
    """Set ``bit`` (window-relative) in win, then slide the prefix over
    the run of low set bits.  Returns (p', win')."""
    win |= 1 << bit
    # count trailing ones
    t = ((~win) & (win + 1)).bit_length() - 1
    return p + t, win >> t


class _Frame:
    """Per-(p, win) memoized expansion data (state-independent)."""

    __slots__ = ("det", "crash", "goal")

    def __init__(self, det, crash, goal):
        self.det = det      # list of (window_bit, f, v1, v2)
        self.crash = crash  # list of (crash_idx, f, v1, v2)
        self.goal = goal    # bool: all determinate ops linearized


def check_opseq_linear(seq: OpSeq, model: ModelSpec, *,
                       max_configs: int = 50_000_000,
                       deadline: float | None = None,
                       cancel=None,
                       witness_cap: int = 0,
                       checkpoint_path: str | None = None,
                       checkpoint_every: int = 0,
                       resume_from: str | None = None,
                       decompose: bool = False,
                       decompose_cache=None,
                       lint: bool | None = None,
                       audit: bool | None = None,
                       hb: bool | None = None,
                       dpor: bool | None = None) -> dict:
    """Exact linearizability check.  Returns a knossos-style map
    {"valid": True|False|"unknown", "configs": n, "max_depth": d, ...};
    on invalid, ``final_ops`` holds the un-linearizable candidate rows at
    the deepest level reached (the :final-paths analog, truncated to 10
    as checker.clj:136-139 truncates) — the blocking frontier the search
    exhausted.  With ``witness_cap`` > 0, a valid verdict carries
    ``linearization`` — witness row indices in linearization order — as
    long as the parent table stayed under the cap (a big sweep drops
    witness tracking rather than memory-bloat).  The default is OFF:
    verdict-only callers (competition legs, the portfolio, fuzzers)
    keep the level-local memory profile; the user-facing Linearizable
    checker opts in.  Whenever a valid verdict has no witness it says
    so explicitly: ``witness_dropped`` names the reason (tracking
    disabled, cap exceeded, witnessless checkpoint), so a missing
    certificate is a statement, never an accident.

    Checkpointing (SURVEY §5.4's search-checkpoint story, host side):
    with ``checkpoint_path`` and ``checkpoint_every`` N, the level set
    is snapshotted every N levels (atomic rename); ``resume_from``
    continues a run from such a snapshot after verifying it binds to
    this exact (history, model) — the level set IS the whole search
    state, so nothing else needs saving.  When witness tracking is
    live at snapshot time the shared parent table (the pre-snapshot
    prefix orders, bounded by ``witness_cap``) is serialized too, so a
    resumed run with ``witness_cap`` > 0 still emits a full witness;
    resuming from a witnessless snapshot reports ``witness_dropped``
    instead.

    ``decompose`` routes through the P-compositional decomposition
    layer (jepsen_tpu/decompose/) with this sweep as the sub-engine —
    verdict-identical, default off; ``decompose_cache`` is its
    VerdictCache or jsonl path.

    ``lint`` runs the O(n) well-formedness linter (analyze/lint.py)
    over the OpSeq first — on by default (None follows JEPSEN_TPU_LINT);
    errors raise :class:`~jepsen_tpu.analyze.HistoryLintError`.
    ``audit`` replays the emitted certificate through the independent
    audit pass (analyze/audit.py; None follows JEPSEN_TPU_AUDIT).
    ``hb`` runs the happens-before pre-pass (analyze/hb.py; None
    follows JEPSEN_TPU_HB, default on): decided histories return
    immediately with an audited certificate and zero explored configs;
    undecided ones sweep under the must-order candidate mask —
    verdict-identical either way.  ``dpor`` (None follows
    JEPSEN_TPU_DPOR, default on) enables the dynamic layer
    (analyze/dpor.py): duplicate-op canonical edges join the
    must-order mask, and register states holding observation-dead
    values collapse onto the canonical token
    (decompose/canonical.py's quotient) so symmetric level rows merge
    in the dominance dedup — verdict-identical by construction."""
    from ..analyze.audit import maybe_audit
    from ..analyze.dpor import _M_DEDUP, _M_MASK, resolve_dpor
    from ..analyze.hb import attach, maybe_hb
    from ..analyze.lint import maybe_lint

    maybe_lint(seq, model, lint)

    dpor_stats: dict | None = None
    hbres = None
    if not decompose and resume_from is None:
        hbres = maybe_hb(seq, model, hb, dpor)

    def finish(out: dict) -> dict:
        if dpor_stats is not None:
            out.setdefault("dpor", dpor_stats)
        return maybe_audit(seq, model, attach(out, hbres), audit)

    if hbres is not None and hbres.decided is not None:
        return maybe_audit(seq, model, dict(hbres.decided), audit)
    if decompose:
        if checkpoint_path or resume_from:
            # the decomposed funnel has no serialized level-set to
            # snapshot; dropping the contract silently would cost a
            # crashed multi-hour run its resume point
            raise ValueError(
                "decompose=True does not support checkpoint_path/"
                "resume_from (sub-searches are independent; use the "
                "verdict cache for cross-run reuse instead)")
        from ..decompose.engine import check_opseq_decomposed

        def _direct(s):
            return check_opseq_linear(s, model, max_configs=max_configs,
                                      deadline=deadline, cancel=cancel,
                                      witness_cap=witness_cap,
                                      lint=False, hb=hb, dpor=dpor)

        def _sub(s, m, *, max_configs=max_configs, deadline=deadline):
            return check_opseq_linear(s, m, max_configs=max_configs,
                                      deadline=deadline, cancel=cancel,
                                      witness_cap=witness_cap,
                                      lint=False, hb=hb, dpor=dpor)

        return check_opseq_decomposed(seq, model, cache=decompose_cache,
                                      direct=_direct, sub_check=_sub,
                                      sub_max_configs=max_configs,
                                      deadline=deadline, lint=False,
                                      witness=witness_cap > 0,
                                      audit=audit, hb=hb, dpor=dpor)
    es = encode_search(seq)
    n_det, n_crash, W = es.n_det, es.n_crash, es.window
    if n_det == 0 and n_crash == 0:
        return finish({"valid": True, "configs": 0, "max_depth": 0,
                       "linearization": []})

    det_inv = [int(x) for x in es.det_inv]
    det_ret = [int(x) for x in es.det_ret]
    det_f = [int(x) for x in es.det_f]
    det_v1 = [int(x) for x in es.det_v1]
    det_v2 = [int(x) for x in es.det_v2]
    sfx = [int(x) for x in es.suffix_min_ret]  # len n_det+1
    crash_inv = [int(x) for x in es.crash_inv]
    crash_f = [int(x) for x in es.crash_f]
    crash_v1 = [int(x) for x in es.crash_v1]
    crash_v2 = [int(x) for x in es.crash_v2]
    # global row index per det/crash position (for final_ops reporting)
    import numpy as np

    ok = np.asarray(seq.ok, dtype=bool)
    det_rows = np.nonzero(ok)[0]
    crash_rows = np.nonzero(~ok)[0]

    pystep = model.pystep
    INF = int(INF32)

    # dead-value quotient (decompose/canonical.py): successor states
    # whose value no un-linearized row compares against rewrite onto
    # the canonical token, so symmetric rows merge in the level dict.
    # The coarse prefix-cutoff rule is used here (exactly the device
    # kernels' rule): a value is dead at prefix p once every det row
    # comparing it sits at a position < p and no crashed row compares
    # it at all.
    dead_cut: dict | None = None
    dead_tok = 0
    if resolve_dpor(dpor):
        from ..decompose.canonical import dead_value_cutoffs

        dv = dead_value_cutoffs(seq, model)
        if dv is not None:
            # per-VALUE cutoff in det-position space (the sweep's p)
            dead_cut = dv.cutoffs
            dead_tok = dv.token
        dpor_stats = {"enabled": True, "dedup_rewrites": 0,
                      "dedup_hits": 0, "mask_lanes_killed": 0,
                      "dedup": dead_cut is not None}

    from ..history import NIL as _NIL

    def canon_state(ns: tuple, p: int) -> tuple:
        """Rewrite an observation-dead successor state to the token.
        NIL states never fold (a crashed cas may compare NIL at any
        future point — decompose/canonical.py's rule)."""
        v = ns[0]
        if v == dead_tok or v == _NIL or p < dead_cut.get(v, 0):
            return ns
        dpor_stats["dedup_rewrites"] += 1
        _M_DEDUP.inc(site="host-linear", event="rewrite")
        return (dead_tok,)

    # must-order mask (HB pre-pass): per det position / crash index,
    # the det-position preds (checked against (p, win) in the frame)
    # and the crash-index preds (a bitmask checked against each cmask
    # at expansion time — frames are crash-set-independent)
    mp_det: dict[int, tuple] = {}
    mp_crash: dict[int, tuple] = {}
    if hbres is not None and hbres.must_pred:
        det_pos_of = {int(r): p for p, r in enumerate(det_rows)}
        crash_of = {int(r): c for c, r in enumerate(crash_rows)}
        for dst, srcs in hbres.must_pred.items():
            dp = tuple(det_pos_of[s] for s in srcs if s in det_pos_of)
            cp = 0
            for s in srcs:
                c = crash_of.get(s)
                if c is not None:
                    cp |= 1 << c
            if not dp and not cp:
                continue
            if dst in det_pos_of:
                mp_det[det_pos_of[dst]] = (dp, cp)
            else:
                mp_crash[crash_of[dst]] = (dp, cp)
    _NO_PRED = ((), 0)

    frames: dict[tuple, _Frame] = {}

    def frame(p: int, win: int) -> _Frame:
        fr = frames.get((p, win))
        if fr is not None:
            return fr
        if len(frames) > 2_000_000:
            frames.clear()  # cap the memo; entries are cheap to rebuild
        # window scan: returns of unlinearized dets in [p, p+W)
        hi = min(p + W, n_det)
        w_ret = []
        for j in range(p, hi):
            w_ret.append(INF if (win >> (j - p)) & 1 else det_ret[j])
        tail = sfx[hi] if hi < len(sfx) else INF
        # min / second-min over w_ret + tail
        m1 = tail
        m2 = INF + 1
        m1_at = -1
        for i, r in enumerate(w_ret):
            if r < m1:
                m2 = m1
                m1 = r
                m1_at = i
            elif r < m2:
                m2 = r
        def det_done(q: int) -> bool:
            return q < p or (q - p < W and (win >> (q - p)) & 1)

        det_cands = []
        for i in range(hi - p):
            if (win >> i) & 1:
                continue
            j = p + i
            excl = m2 if i == m1_at else m1
            if det_inv[j] < excl:
                dp, cp = mp_det.get(j, _NO_PRED)
                if dp and not all(det_done(q) for q in dp):
                    # a must-predecessor det is unlinearized
                    if dpor_stats is not None:
                        dpor_stats["mask_lanes_killed"] += 1
                        _M_MASK.inc(site="host-frame")
                    continue
                det_cands.append((i, det_f[j], det_v1[j], det_v2[j],
                                  cp))
        crash_cands = []
        for c in range(n_crash):
            if crash_inv[c] < m1:
                dp, cp = mp_crash.get(c, _NO_PRED)
                if dp and not all(det_done(q) for q in dp):
                    if dpor_stats is not None:
                        dpor_stats["mask_lanes_killed"] += 1
                        _M_MASK.inc(site="host-frame")
                    continue
                crash_cands.append((c, crash_f[c], crash_v1[c],
                                    crash_v2[c], cp))
        fr = _Frame(det_cands, crash_cands,
                    p + bin(win).count("1") >= n_det)
        frames[(p, win)] = fr
        return fr

    # level: {(p, win, state): [minimal cmask antichain]}
    root = ((0, 0, model.init), 0)
    level: dict[tuple, list[int]] = {root[0]: [0]}
    configs = 0
    depth = 0
    t_check = 0
    _digest = None
    if checkpoint_path or resume_from:
        from .linearizable import history_digest

        _digest = history_digest(seq, model)  # computed once per run
    #: why a valid verdict will carry no witness (None = witness live)
    witness_drop = None if witness_cap else \
        "witness tracking disabled (witness_cap=0)"
    # (key, cmask) -> (op row, parent (key, cmask)); None once capped
    parents: dict | None = {root: None} if witness_cap else None
    if resume_from is not None:
        level, depth, configs, saved_parents = _load_linear_checkpoint(
            resume_from, model, _digest)
        if witness_cap and saved_parents is not None:
            # the snapshot's parent table resumes the walk as if the
            # run had never stopped (a live table is whole, so every
            # level config's chain reaches the root through it)
            parents = saved_parents
            parents.setdefault(root, None)
        elif witness_cap:
            witness_cap = 0
            parents = None
            witness_drop = ("resumed from a witnessless checkpoint "
                            "(no parent table was serialized)")
        else:
            witness_cap = 0
            parents = None

    def remember(child_key, child_cm, op_row, par_key, par_cm):
        nonlocal parents, witness_drop
        if parents is None:
            return
        if len(parents) >= witness_cap:
            parents = None  # witness off; the verdict is unaffected
            witness_drop = (f"parent table exceeded "
                            f"witness_cap={witness_cap}")
            return
        parents.setdefault((child_key, child_cm),
                           (op_row, (par_key, par_cm)))

    def walk(key, cm):
        if parents is None:
            return None
        lin: list[int] = []
        node = (key, cm)
        while node != root:
            # every kept config was remembered while parents was live,
            # and the cap nulls the whole table — a live table is whole
            op_row, node = parents[node]
            lin.append(op_row)
        lin.reverse()
        return lin

    def over_budget() -> str | None:
        nonlocal t_check
        t_check += 1
        if configs > max_configs:
            return f"exceeded max_configs={max_configs}"
        if t_check % 1024 == 0:
            if deadline is not None and time.perf_counter() > deadline:
                return "exceeded deadline"
            if cancel is not None and cancel.is_set():
                return "cancelled"
        return None

    def insert(d: dict, key: tuple, cmask: int) -> bool:
        """Dominance-pruned insert; True if the config was kept."""
        ac = d.get(key)
        if ac is None:
            d[key] = [cmask]
            return True
        for cm in ac:
            if cm & cmask == cm:  # cm subset of cmask: dominated
                return False
        d[key] = [cm for cm in ac if cm & cmask != cmask] + [cmask]
        return True

    while True:
        if (checkpoint_path and checkpoint_every
                and depth and depth % checkpoint_every == 0):
            _save_linear_checkpoint(checkpoint_path, model, _digest,
                                    level, depth, configs,
                                    parents=parents)
        # --- crash closure within the level (depth unchanged) ----------
        work = [(k, cm) for k, ac in level.items() for cm in ac]
        while work:
            why = over_budget()
            if why:
                return finish({"valid": "unknown", "configs": configs,
                               "max_depth": depth, "info": why})
            (p, win, state), cmask = work.pop()
            fr = frame(p, win)
            for c, f, v1, v2, cp in fr.crash:
                if (cmask >> c) & 1:
                    continue
                if cp & ~cmask:
                    continue  # a must-predecessor crash op is missing
                ns = pystep(state, f, v1, v2)
                if ns is None:
                    continue
                configs += 1
                if dead_cut is not None:
                    ns = canon_state(ns, p)
                nk = (p, win, ns)
                ncm = cmask | (1 << c)
                if insert(level, nk, ncm):
                    remember(nk, ncm, int(crash_rows[c]),
                             (p, win, state), cmask)
                    work.append((nk, ncm))
                elif dead_cut is not None and ns[0] == dead_tok:
                    dpor_stats["dedup_hits"] += 1
                    _M_DEDUP.inc(site="host-linear", event="hit")

        # --- goal test -------------------------------------------------
        for (p, win, _s), ac in level.items():
            if frame(p, win).goal:
                out = {"valid": True, "configs": configs,
                       "max_depth": depth}
                lin = walk((p, win, _s), ac[0])
                if lin is not None:
                    out["linearization"] = lin
                else:
                    out["witness_dropped"] = witness_drop
                return finish(out)

        # --- expand determinate candidates to the next level -----------
        nxt: dict[tuple, list[int]] = {}
        for (p, win, state), ac in level.items():
            fr = frame(p, win)
            for i, f, v1, v2, cp in fr.det:
                ns = pystep(state, f, v1, v2)
                if ns is None:
                    continue
                p2, win2 = _advance(p, win, i, n_det)
                if dead_cut is not None:
                    # p2, not p: the advanced prefix has strictly more
                    # comparers behind it, so more values are provably
                    # dead — still exact (every det position < p2 is
                    # linearized by construction)
                    ns = canon_state(ns, p2)
                nk = (p2, win2, ns)
                for cmask in ac:
                    if cp & ~cmask:
                        continue  # must-predecessor crash op missing
                    configs += 1
                    if insert(nxt, nk, cmask):
                        remember(nk, cmask, int(det_rows[p + i]),
                                 (p, win, state), cmask)
                    elif dead_cut is not None and ns[0] == dead_tok:
                        dpor_stats["dedup_hits"] += 1
                        _M_DEDUP.inc(site="host-linear", event="hit")
            why = over_budget()
            if why:
                return finish({"valid": "unknown", "configs": configs,
                               "max_depth": depth, "info": why})
        if not nxt:
            # frontier died: collect the blocked candidates for reporting
            final_ops: list[int] = []
            seen = set()
            for (p, win, _s) in list(level)[:10]:
                fr = frame(p, win)
                for i, *_ in fr.det:
                    r = int(det_rows[p + i])
                    if r not in seen:
                        seen.add(r)
                        final_ops.append(r)
                for c, *_ in fr.crash:
                    r = int(crash_rows[c])
                    if r not in seen:
                        seen.add(r)
                        final_ops.append(r)
            return finish({"valid": False, "configs": configs,
                           "max_depth": depth,
                           "final_ops": sorted(final_ops)})
        level = nxt
        depth += 1


# ---------------------------------------------------------------------------
# Checkpointing (SURVEY §5.4 — the host-sweep counterpart of the device
# engine's carry checkpoint in checker/linearizable.py)
# ---------------------------------------------------------------------------


def _node_json(node) -> list:
    (p, win, state), cm = node
    return [p, win, list(state), cm]


def _node_from_json(row) -> tuple:
    p, win, state, cm = row
    return ((p, win, tuple(state)), cm)


def _save_linear_checkpoint(path: str, model: ModelSpec, digest: str,
                            level: dict, depth: int, configs: int, *,
                            parents: dict | None = None) -> None:
    import json
    import os

    # JSON, not pickle: a checkpoint may travel between machines, and
    # loading untrusted pickle executes code (the device checkpoint
    # uses npz with allow_pickle=False for the same reason); the
    # payload is pure ints/lists, so JSON loses nothing
    payload = {
        "digest": digest,
        "model": model.name,
        "depth": depth,
        "configs": configs,
        "level": [[k[0], k[1], list(k[2]), list(ac)]
                  for k, ac in level.items()],
    }
    if parents is not None:
        # the SHARED parent table (bounded by witness_cap), not one
        # root-to-config chain per level config — per-config chains
        # would be O(|level| x depth) ints where the table is O(kept
        # configs); a resumed run walks it exactly like a live one
        payload["parents"] = [
            _node_json(child) + [op_row, _node_json(par)]
            for child, entry in parents.items() if entry is not None
            for op_row, par in (entry,)]
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)  # atomic: a crash never leaves a torn file


def _load_linear_checkpoint(path: str, model: ModelSpec, digest: str):
    """Returns (level, depth, configs, parents) — ``parents`` is the
    snapshot's witness parent table ((key, cmask) -> (op row, parent
    node)), or None when the snapshot carried no witness data (witness
    tracking was off or capped when it was taken)."""
    import json

    with open(path) as f:
        payload = json.load(f)
    if payload["model"] != model.name:
        raise ValueError(
            f"checkpoint is for model {payload['model']!r}, "
            f"got {model.name!r}")
    if payload["digest"] != digest:
        raise ValueError(
            "checkpoint was taken on a different history or model "
            "parameterization (digest mismatch)")
    level = {(p, win, tuple(state)): list(ac)
             for p, win, state, ac in payload["level"]}
    parents = None
    raw = payload.get("parents")
    if raw is not None:
        parents = {}
        for p, win, state, cm, op_row, par in raw:
            parents[((p, win, tuple(state)), cm)] = \
                (op_row, _node_from_json(par))
    return level, payload["depth"], payload["configs"], parents
