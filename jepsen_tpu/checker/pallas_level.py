"""Pallas TPU level-loop kernel — the whole BFS slice as ONE device op.

Why this exists (VERDICT r4 weak #2 / item 2): the XLA step kernel's
level body compiles to ~70-140 fused computations, and on the axon TPU
each one pays a fixed few-microsecond overhead, flooring the per-level
cost at ~1.3 ms no matter how narrow the live frontier is
(docs/tpu/r4/tpubench_resweep.jsonl).  Depth-bound searches (mutex2k:
1,971 sequential levels; 10k: ~9.8k) are therefore op-COUNT-bound, not
compute-bound.  This module re-expresses the entire slice loop —
``lvl_cap`` levels of mask/closure/expand/prune/compact — as a single
``pl.pallas_call`` whose interior is ~dozens of large VPU/MXU
operations per level with no per-op dispatch overhead.

Design notes (the reference's analog of this engine is knossos's JVM
search loop, jepsen/src/jepsen/checker.clj:114-139 — redesigned here
for the TPU's compute model rather than translated):

* The frontier lives UNPACKED inside the kernel: window/crash masks as
  [F, W]/[F, NC] 0/1 planes instead of packed u32 words.  Packing
  exists for host/HBM compactness; in VMEM the unpacked planes turn
  every bit-twiddle (funnel shifts, trailing-ones, kth-set-bit) into
  plain elementwise/matmul algebra the VPU/MXU like.  Pack/unpack
  happens once per SLICE at the XLA boundary, amortized over
  ``lvl_cap`` levels.
* Every gather is a one-hot CONTRACTION (MXU), never a dynamic gather:
  table windows are read with one dynamic slice per level, then
  addressed by `(off + lane == j)` one-hot tensors.  Values that can
  exceed f32's 2^24 integer-exact range (model-state words, op v1/v2)
  go through a 12-bit limb split — two exact f32 matmuls, recombined
  in int32.  Comparison tables (inv/ret/suffix-min) are clamped to
  CLAMP_INF < 2^24 at the boundary (all real positions are < 2^17, so
  every comparison is preserved).
* Stream compaction is hierarchical: per-row counts -> triangular-
  matmul cumsum -> `[cap, F]` row one-hot -> `[cap, L]` lane one-hot
  (two small matmuls + compares).  No sorts anywhere.
* Dominance pruning is the exact all-pairs rule (mirrors
  `_allpairs_dominance` in linearizable.py): equality via popcount
  matmul identities, crash-subset via |cr_j| - |cr_i ∩ cr_j| == 0.
* Control flow is `fori_loop` + `@pl.when` predication only (Mosaic-
  safe): the level loop runs ``lvl_cap`` rounds gated on a `running`
  scalar, the crash closure runs ``n_crash+1`` rounds gated on a
  `progress` scalar — predicated-off rounds skip at runtime.

Semantics contract: bit-for-bit the SAME search as
`build_search_step_fn` with the all-pairs prune — identical survivor
order (f-major, lane-ascending), identical configs counts, identical
overflow/bail/revert behavior — so the slice driver, checkpoints, and
escalation ladder work unchanged.  Differential tests enforce this
(tests/test_pallas_level.py).

Eligibility: F <= 64, W <= 64, NC <= 64, state_width <= 4, and a model
whose ``jstep`` is elementwise (register / cas-register / mutex /
noop).  Wider rungs fall back to the XLA kernel — the pallas engine
exists for the narrow, depth-dominated regime that floors on op count.

Phase-2 reductions (the device must-order mask and the dead-value
dedup rewrite) also route to the XLA kernel: the mask's per-lane
linearized-predecessor test costs ~W predicated plane ops per
predecessor slot on unpacked planes (there is no cheap batched
win[q - p] gather without a 3-D reduce Mosaic dislikes), which would
triple exactly the op count this kernel exists to eliminate — while on
the XLA kernel the same test is a handful of fused gathers.  So
``eligible`` declines ``masked``/``dedup`` searches and `get_kernel`
builds the XLA step for them; the step signature still carries the
reduction planes (ignored) so every driver stays signature-uniform.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

try:  # pltpu imports fine off-TPU; only lowering needs the hardware
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover - ancient jax
    pltpu = None

#: internal "infinity" for clamped comparison tables — above every real
#: position (< 2^17) and exactly representable in f32
CLAMP_INF = np.int32(1 << 23)

#: models whose jstep is elementwise (vmaps to Mosaic-friendly ops)
SAFE_MODELS = frozenset({"register", "cas-register", "mutex", "noop"})

#: scalar-scratch slots (12-14 are telemetry-only: level cursor,
#: per-level crash-closure round count, post-closure occupancy)
(_CNT, _STA, _CFG, _MD, _OVF, _RUN, _FOUND, _CLGO,
 _CNT0, _CFG0, _MD0, _OVF0, _TLVL, _TROUNDS, _TOCC) = range(15)


def eligible(model, dims, *, masked: bool = False,
             dedup: bool = False) -> bool:
    # masked/dedup searches run the XLA kernel (see module doc): the
    # reduction checks are matmul-hostile on unpacked planes and would
    # triple the per-level op count this kernel exists to eliminate
    return (not masked and not dedup
            and model.name in SAFE_MODELS
            and dims.frontier <= 64
            and dims.window <= 64
            and dims.n_crash_pad <= 64
            and dims.state_width <= 4)


def _f32(x):
    return x.astype(jnp.float32)


def _mm(a, b):
    """f32 matmul, always through the MXU contraction path."""
    return lax.dot_general(_f32(a), _f32(b), (((1,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32)


def _gather_i32(oh, plane):
    """Exact int32 gather `oh @ plane` for arbitrary int32 values via a
    12-bit limb split (each oh row has at most one nonzero)."""
    lo = _f32(jnp.bitwise_and(plane, 0xFFF))
    hi = _f32(jnp.right_shift(plane, 12))
    return (_mm(oh, hi).astype(jnp.int32) * 4096
            + _mm(oh, lo).astype(jnp.int32))


def _iota(n, axis, shape):
    return lax.broadcasted_iota(jnp.int32, shape, axis)


def build_pallas_step_fn(model, dims, *, interpret: bool = False,
                         masked: bool = False,
                         telemetry: bool = False):
    """Build a slice-step function with `build_search_step_fn`'s exact
    signature, backed by one pallas_call running the whole level loop.

    ``masked`` is accepted for get_kernel symmetry but must be False —
    masked searches are not pallas-eligible (module doc); the step
    still ACCEPTS the reduction-plane arguments and ignores them, so
    drivers and differential tests stay signature-uniform.

    ``telemetry`` emits the per-level aux counter block (obs/
    telemetry.py schema) as an extra output, matching the XLA kernel's
    telemetry contract.  The block is built from pure elementwise
    one-hot adds on a tiny [TELE_ROWS, TELE_COLS] plane (no dynamic
    stores — Mosaic-safe), is write-only, and never feeds back into
    the search.  mask_killed / dedup_folds are structurally zero here:
    pallas-eligible searches carry no reductions by design."""
    if masked:
        raise ValueError("masked searches are not pallas-eligible; "
                         "build the XLA kernel instead (see "
                         "pallas_level.eligible)")
    from ..obs.telemetry import (C_EXP, C_GOAL, C_NEXT, C_OCC, C_OVF,
                                 C_ROUNDS, TELE_COLS, TELE_ROWS)
    F = dims.frontier
    W = dims.window
    NC = dims.n_crash_pad
    SW = dims.state_width
    WW = dims.win_words
    CW = dims.crash_words
    ND = dims.n_det_pad
    L = W + NC
    SCAP = 4 * F
    # +32: the table-window base is rounded DOWN to a 32-multiple so
    # every dynamic slice offset is aligned (Mosaic handles aligned
    # lane offsets far more reliably than arbitrary ones); the window
    # grows by one granule to keep covering [min_p, min_p + 2W + NC]
    W2P = min(-(-(2 * W + NC + 32) // 32) * 32, ND)
    jstep2 = jax.vmap(jax.vmap(model.jstep))

    # constant unpack/pack index tables (host-side numpy)
    w_word = np.arange(W) // 32
    w_bit = np.arange(W) % 32
    c_word = np.arange(NC) // 32
    c_bit = np.arange(NC) % 32

    def kernel(scal, tf, tv1, tv2, tinv, tret, sfx, crf, crv1, crv2,
               crinv, p_in, win_in, crash_in, state_in,
               p_out, win_out, crash_out, state_out, scal_out,
               *rest):
        if telemetry:
            tele_out = rest[0]
            rest = rest[1:]
        (pc, wc, cc, stc, ps, ws, cs, sts, v2r, g2r, nsr, st) = rest
        n_det = scal[5, 0]
        n_crash = scal[6, 0]
        budget = scal[7, 0]
        lvl_cap = scal[8, 0]
        bail = scal[9, 0]

        pc[:] = p_in[:]
        wc[:] = win_in[:]
        cc[:] = crash_in[:]
        stc[:] = state_in[:]
        for i, slot in ((0, _CNT), (1, _STA), (2, _CFG), (3, _MD),
                        (4, _OVF)):
            st[slot, 0] = scal[i, 0]
        st[_RUN, 0] = jnp.where(
            (scal[1, 0] == -1) & (scal[0, 0] > 0)
            & (scal[2, 0] < budget)
            & ~((bail == 1) & (scal[4, 0] == 1)), 1, 0)
        if telemetry:
            tele_out[:] = jnp.zeros((TELE_ROWS, TELE_COLS), jnp.int32)
            st[_TLVL, 0] = 0

        lane_i = _iota(L, 1, (1, L))          # [1, L] candidate lane ids
        is_det_lane = lane_i < W

        def mask_phase():
            """Expand the CURRENT planes: valid/goal per candidate lane
            + successor model states.  Mirrors expand_mask_one
            (linearizable.py:1054) on unpacked planes, all lanes (no
            K-cap: the cap was a no-loss bound; S-cap still applies at
            compaction)."""
            count = st[_CNT, 0]
            p = pc[:]                          # [F, 1]
            win = wc[:]                        # [F, W]
            crash = cc[:]                      # [F, NC]
            state = stc[:]                     # [F, SW]
            aliv = _iota(F, 0, (F, 1)) < count
            base = jnp.min(jnp.where(aliv, p, CLAMP_INF))
            # 32-aligned so pl.ds offsets lower cleanly (see W2P)
            base = (jnp.clip(base, 0, ND - W2P) // 32) * 32
            base = pl.multiple_of(base, 32)

            # 2D reads ([1, n] slices): Mosaic-friendly shapes
            t_ret = tret[:, pl.ds(base, W2P)].reshape(W2P, 1)
            t_inv = tinv[:, pl.ds(base, W2P)].reshape(W2P, 1)
            t_f = tf[:, pl.ds(base, W2P)].reshape(W2P, 1)
            t_v1 = tv1[:, pl.ds(base, W2P)].reshape(W2P, 1)
            t_v2 = tv2[:, pl.ds(base, W2P)].reshape(W2P, 1)
            # max suffix index = (min_p - base) + 2W + NC, and the
            # 32-aligned-down base leaves min_p - base <= 31, so with
            # W2P >= 2W + NC + 32 the index is <= W2P - 1; the slice
            # still takes W2P + 1 entries (base <= ND - W2P keeps it
            # in range: sfx has ND + 1 entries).  Do NOT tighten this
            # to 2W + NC + 1 or drop the +32 from W2P without removing
            # the base down-rounding.
            sfxw = sfx[:, pl.ds(base, W2P + 1)].reshape(W2P + 1, 1)

            off = p - base                     # [F, 1]
            lw = _iota(W, 1, (1, W))
            # one-hot [F, W, W2P]: (off + l == j)
            idx3 = ((off[:, :, None] + lw[:, :, None])
                    == _iota(W2P, 2, (1, 1, W2P)))
            oh2 = _f32(idx3).reshape(F * W, W2P)

            def gat(tab):
                return _mm(oh2, tab).reshape(F, W)

            wret = gat(_f32(t_ret))
            winv = gat(_f32(t_inv))
            pos_in = (p + lw) < n_det          # [F, W]
            no_win = win == 0
            INF = jnp.float32(CLAMP_INF)
            wret_eff = jnp.where(pos_in & no_win, wret, INF)
            m1 = jnp.min(wret_eff, axis=1, keepdims=True)
            am = jnp.min(jnp.where(wret_eff == m1, lw, W), axis=1,
                         keepdims=True)
            m2 = jnp.min(jnp.where(lw == am, INF, wret_eff), axis=1,
                         keepdims=True)
            # suffix-min beyond the window
            sidx = jnp.minimum(p + W, n_det) - base        # [F, 1]
            soh = _f32(sidx == _iota(W2P + 1, 1, (1, W2P + 1)))
            sfxv = _mm(soh, _f32(sfxw))                    # [F, 1]
            m1_tot = jnp.minimum(m1, sfxv)
            excl_w = jnp.where(lw == am, m2, m1)
            excl_tot = jnp.minimum(excl_w, sfxv)
            det_en = pos_in & no_win & (winv < excl_tot)

            cl = _iota(NC, 1, (1, NC))
            crinv_f = _f32(crinv[:])                     # [1, NC]
            crash_en = ((cl < n_crash) & (crash == 0)
                        & (crinv_f < m1_tot))

            # candidate op tables on all L lanes
            d_f = _gather_i32(oh2, t_f).reshape(F, W)
            d_v1 = _gather_i32(oh2, t_v1).reshape(F, W)
            d_v2 = _gather_i32(oh2, t_v2).reshape(F, W)
            c_f = jnp.broadcast_to(crf[:], (F, NC))
            c_v1 = jnp.broadcast_to(crv1[:], (F, NC))
            c_v2 = jnp.broadcast_to(crv2[:], (F, NC))
            aF = jnp.concatenate([d_f, c_f], axis=1)
            aV1 = jnp.concatenate([d_v1, c_v1], axis=1)
            aV2 = jnp.concatenate([d_v2, c_v2], axis=1)
            enab = jnp.concatenate([det_en, crash_en], axis=1)

            stateB = jnp.broadcast_to(state[:, None, :], (F, L, SW))
            ns, legal = jstep2(stateB, aF, aV1, aV2)
            valid = aliv & enab & legal

            wsum = jnp.sum(win, axis=1, keepdims=True)
            remaining = n_det - (p + wsum)               # [F, 1]
            goal = valid & jnp.where(is_det_lane, remaining <= 1,
                                     remaining <= 0)
            v2r[:] = valid.astype(jnp.int32)
            g2r[:] = goal.astype(jnp.int32)
            nsr[:] = ns.astype(jnp.int32)

        def succ_compact(vmask, cap):
            """Compact the [F, L] valid mask to ``cap`` survivors in
            (f-major, lane-ascending) order and build their successor
            planes.  Returns (p2, win2, crash2, state2, svalid, total).
            Mirrors _succ_block + succ_one."""
            vf = _f32(vmask)
            c_row = jnp.sum(vf, axis=1, keepdims=True)   # [F, 1]
            # trilF[i, j] = (j <= i): cum = trilF @ c_row is the
            # INCLUSIVE prefix sum cum[i] = sum_{j<=i} c_row[j]
            trilF = _f32(_iota(F, 1, (F, F)) <= _iota(F, 0, (F, F)))
            cum = _mm(trilF, c_row)                      # [F, 1]
            o = cum - c_row                              # exclusive
            total = jnp.sum(vf).astype(jnp.int32)
            s_i = _iota(cap, 0, (cap, 1))
            oT = o.reshape(1, F)
            cT = c_row.reshape(1, F)
            row_oh = _f32((oT <= _f32(s_i)) & (_f32(s_i) < oT + cT))
            q = _f32(s_i) - _mm(row_oh, o)               # [cap, 1]
            trilL = _f32(_iota(L, 0, (L, L)) <= _iota(L, 1, (L, L)))
            r = _mm(vf, trilL)                           # [F, L] ranks
            Rg = _mm(row_oh, r)                          # [cap, L]
            Vg = _mm(row_oh, vf)
            lane_oh = (Rg == q + 1) & (Vg > 0.5)         # [cap, L]
            svalid = s_i < total                         # [cap, 1]

            lane = jnp.sum(jnp.where(lane_oh, _iota(L, 1, (cap, L)), 0),
                           axis=1, keepdims=True)        # [cap, 1]
            p_src = _mm(row_oh, _f32(pc[:])).astype(jnp.int32)
            win_src = (_mm(row_oh, _f32(wc[:])) > 0.5)   # [cap, W] bool
            crash_src = (_mm(row_oh, _f32(cc[:])) > 0.5)
            state_src = _gather_i32(row_oh, stc[:])      # [cap, SW]

            lane_f = _f32(lane_oh)
            ns_cols = []
            for swi in range(SW):
                g = _gather_i32(row_oh * 1.0, nsr[:, :, swi])
                # row-gathered [cap, L] already int; select the lane
                ns_cols.append(jnp.sum(jnp.where(lane_oh, g, 0),
                                       axis=1, keepdims=True))
            ns_sel = jnp.concatenate(ns_cols, axis=1)    # [cap, SW]

            is_d = lane < W                              # [cap, 1]
            lwc = _iota(W, 1, (cap, W))
            win1 = win_src | (is_d & (lwc == lane))
            first_zero = jnp.min(jnp.where(~win1, lwc, W), axis=1,
                                 keepdims=True)          # = shift
            shift = first_zero
            # win2[s, l] = win1[s, l + shift_s]: per-row dynamic shift
            # as a STATIC correlation loop — W+1 predicated adds of 2D
            # planes (tiny compute, no batched 3D dot_general for
            # Mosaic to choke on; the shift values are 1..W)
            win1i = win1.astype(jnp.int32)
            # v = 0 (bit 0 unset, p does not advance) is the common
            # case and must map win2 = win1 unchanged
            win2acc = (shift == 0).astype(jnp.int32) * win1i
            for v in range(1, W + 1):
                sel = (shift == v).astype(jnp.int32)     # [cap, 1]
                shifted = jnp.concatenate(
                    [win1i[:, v:], jnp.zeros((cap, v), jnp.int32)],
                    axis=1)
                win2acc = win2acc + sel * shifted
            win2 = win2acc > 0
            p2 = jnp.where(is_d, p_src + shift, p_src)
            w_out = jnp.where(is_d, win2, win_src)
            cloh = (lane - W) == _iota(NC, 1, (cap, NC))
            c_out = jnp.where(is_d, crash_src, crash_src | cloh)
            return (p2, w_out.astype(jnp.int32), c_out.astype(jnp.int32),
                    ns_sel, svalid, total)

        def prune(pm, winm, crashm, statem, validm, M):
            """Exact all-pairs dominance over M rows; mirrors
            _allpairs_dominance (linearizable.py:479) on planes."""
            eq = pm.reshape(M, 1) == pm.reshape(1, M)
            wf = _f32(winm)
            wsum = jnp.sum(wf, axis=1, keepdims=True)
            wcom = _mm(wf, wf.T)
            eq &= (wsum + wsum.T - 2.0 * wcom) == 0
            for swi in range(SW):
                col = statem[:, swi]
                eq &= col.reshape(M, 1) == col.reshape(1, M)
            cf_ = _f32(crashm)
            csum = jnp.sum(cf_, axis=1, keepdims=True)
            ccom = _mm(cf_, cf_.T)
            eq_cr = (csum + csum.T - 2.0 * ccom) == 0
            # sub[i, j]: cr_j subset of cr_i  <=>  |cr_j| - |inter| == 0
            sub = (csum.T - ccom) == 0
            ident = eq & eq_cr
            strict = eq & sub & ~eq_cr
            im = _iota(M, 0, (M, M))
            jm = _iota(M, 1, (M, M))
            dom = validm.reshape(1, M) & (strict | (ident & (jm < im)))
            return validm.reshape(M) & ~jnp.any(dom, axis=1)

        def compact_rows(kept, pm, winm, crashm, statem, M):
            """First-F kept rows, in order; returns planes + kept
            count."""
            kf = _f32(kept)[:, None]                     # [M, 1]
            trilM = _f32(_iota(M, 1, (M, M)) <= _iota(M, 0, (M, M)))
            rank = _mm(trilM, kf)                        # [M, 1] incl
            n_kept = jnp.sum(kf).astype(jnp.int32)
            out_oh = _f32(kept.reshape(1, M)
                          & (rank.reshape(1, M)
                             == _f32(_iota(F, 0, (F, 1)) + 1)))
            p_n = _mm(out_oh, _f32(pm.reshape(M, 1))).astype(jnp.int32)
            w_n = (_mm(out_oh, _f32(winm)) > 0.5).astype(jnp.int32)
            c_n = (_mm(out_oh, _f32(crashm)) > 0.5).astype(jnp.int32)
            s_n = _gather_i32(out_oh, statem)
            return p_n, w_n, c_n, s_n, n_kept

        def closure_round(_j, carry):
            @pl.when(st[_CLGO, 0] == 1)
            def _():
                if telemetry:
                    st[_TROUNDS, 0] = st[_TROUNDS, 0] + 1
                cvalid = (v2r[:] == 1) & ~is_det_lane
                p2, w2, c2, s2, svld, ntot = succ_compact(cvalid, F)
                st[_OVF, 0] = st[_OVF, 0] | jnp.where(ntot > F, 1, 0)
                count = st[_CNT, 0]
                aliv = _iota(F, 0, (F, 1)) < count
                pm = jnp.concatenate([pc[:], p2], axis=0)
                wm = jnp.concatenate([wc[:], w2], axis=0)
                cm = jnp.concatenate([cc[:], c2], axis=0)
                sm = jnp.concatenate([stc[:], s2], axis=0)
                vm = jnp.concatenate([aliv, svld], axis=0).reshape(2 * F)
                kept = prune(pm, wm, cm, sm, vm, 2 * F)
                p_n, w_n, c_n, s_n, nk = compact_rows(
                    kept, pm, wm, cm, sm, 2 * F)
                st[_OVF, 0] = st[_OVF, 0] | jnp.where(nk > F, 1, 0)
                progress = jnp.any(
                    kept & (_iota(2 * F, 0, (2 * F, 1)).reshape(2 * F)
                            >= F))
                pc[:] = p_n
                wc[:] = w_n
                cc[:] = c_n
                stc[:] = s_n
                st[_CNT, 0] = jnp.minimum(nk, F)
                mask_phase()
                st[_FOUND, 0] = st[_FOUND, 0] | jnp.where(jnp.any(g2r[:] == 1), 1, 0)
                st[_CLGO, 0] = jnp.where(progress, 1, 0)
            return carry

        def level(_i, carry):
            @pl.when(st[_RUN, 0] == 1)
            def _():
                # entry snapshot for the uncommitted-overflow revert
                ps[:] = pc[:]
                ws[:] = wc[:]
                cs[:] = cc[:]
                sts[:] = stc[:]
                st[_CNT0, 0] = st[_CNT, 0]
                st[_CFG0, 0] = st[_CFG, 0]
                st[_MD0, 0] = st[_MD, 0]
                st[_OVF0, 0] = st[_OVF, 0]
                if telemetry:
                    st[_TROUNDS, 0] = 0

                mask_phase()
                found0 = jnp.any(g2r[:] == 1)
                st[_FOUND, 0] = jnp.where(found0, 1, 0)
                crash_any = jnp.any((v2r[:] == 1) & ~is_det_lane)
                st[_CLGO, 0] = jnp.where(crash_any, 1, 0)
                lax.fori_loop(0, n_crash + 1, closure_round, 0)
                # exit-by-cap while still adding rows: not proven
                # closed — degrade like an overflow
                st[_OVF, 0] = st[_OVF, 0] | st[_CLGO, 0]

                # determinate expansion
                dvalid = (v2r[:] == 1) & is_det_lane
                p2, w2, c2, s2, svld, ntot = succ_compact(dvalid, SCAP)
                st[_OVF, 0] = st[_OVF, 0] | jnp.where(ntot > SCAP, 1, 0)
                kept = prune(p2, w2, c2, s2, svld.reshape(SCAP), SCAP)
                p_n, w_n, c_n, s_n, nk = compact_rows(
                    kept, p2, w2, c2, s2, SCAP)
                st[_OVF, 0] = st[_OVF, 0] | jnp.where(nk > F, 1, 0)

                count = st[_CNT, 0]
                if telemetry:
                    st[_TOCC, 0] = count  # post-closure occupancy
                aliv = _iota(F, 0, (F, 1)) < count
                st[_CFG, 0] = st[_CFG, 0] + count
                st[_MD, 0] = jnp.maximum(
                    st[_MD, 0], jnp.max(jnp.where(aliv, pc[:], 0)))
                found = st[_FOUND, 0] == 1
                st[_STA, 0] = jnp.where(found, 2, st[_STA, 0])
                new_ovf = (st[_OVF, 0] == 1) & (st[_OVF0, 0] == 0)
                revert = (bail == 1) & new_ovf & ~found
                pc[:] = jnp.where(revert, ps[:], p_n)
                wc[:] = jnp.where(revert, ws[:], w_n)
                cc[:] = jnp.where(revert, cs[:], c_n)
                stc[:] = jnp.where(revert, sts[:], s_n)
                st[_CNT, 0] = jnp.where(revert, st[_CNT0, 0],
                                        jnp.minimum(nk, F))
                st[_CFG, 0] = jnp.where(revert, st[_CFG0, 0],
                                        st[_CFG, 0])
                st[_MD, 0] = jnp.where(revert, st[_MD0, 0], st[_MD, 0])
                st[_RUN, 0] = jnp.where(
                    (st[_STA, 0] == -1) & (st[_CNT, 0] > 0)
                    & (st[_CFG, 0] < budget)
                    & ~((bail == 1) & (st[_OVF, 0] == 1)), 1, 0)
                if telemetry:
                    # one aux row per level, written as a one-hot
                    # elementwise add on the [TELE_ROWS, TELE_COLS]
                    # plane (no dynamic stores).  mask_killed (col 2)
                    # and dedup_folds (col 3) are structurally 0 —
                    # pallas-eligible searches carry no reductions.
                    idx = jnp.minimum(st[_TLVL, 0], TELE_ROWS - 1)
                    roh = (_iota(TELE_ROWS, 0,
                                 (TELE_ROWS, TELE_COLS)) == idx)
                    colI = _iota(TELE_COLS, 1, (TELE_ROWS, TELE_COLS))
                    expd = jnp.sum(v2r[:]).astype(jnp.int32)
                    vals = (st[_TOCC, 0] * (colI == C_OCC)
                            + expd * (colI == C_EXP)
                            + st[_TROUNDS, 0] * (colI == C_ROUNDS)
                            + st[_CNT, 0] * (colI == C_NEXT)
                            + jnp.where((st[_OVF, 0] == 1)
                                        & (st[_OVF0, 0] == 0), 1, 0)
                            * (colI == C_OVF)
                            + st[_FOUND, 0] * (colI == C_GOAL))
                    tele_out[:] = tele_out[:] + jnp.where(
                        roh, vals.astype(jnp.int32), 0)
                    st[_TLVL, 0] = st[_TLVL, 0] + 1
            return carry

        lax.fori_loop(0, lvl_cap, level, 0)

        p_out[:] = pc[:]
        win_out[:] = wc[:]
        crash_out[:] = cc[:]
        state_out[:] = stc[:]
        for i, slot in ((0, _CNT), (1, _STA), (2, _CFG), (3, _MD),
                        (4, _OVF)):
            scal_out[i, 0] = st[slot, 0]

    vmem = {} if pltpu is None else {"memory_space": pltpu.VMEM}
    smem = {} if pltpu is None else {"memory_space": pltpu.SMEM}

    def _scratch(shape, dtype=jnp.int32):
        if pltpu is None:  # pragma: no cover
            raise RuntimeError("pallas tpu unavailable")
        return pltpu.VMEM(shape, dtype)

    out_specs = [pl.BlockSpec(**vmem)] * 4 + [pl.BlockSpec(**smem)]
    out_shape = [
        jax.ShapeDtypeStruct((F, 1), jnp.int32),
        jax.ShapeDtypeStruct((F, W), jnp.int32),
        jax.ShapeDtypeStruct((F, NC), jnp.int32),
        jax.ShapeDtypeStruct((F, SW), jnp.int32),
        jax.ShapeDtypeStruct((5, 1), jnp.int32),
    ]
    if telemetry:
        out_specs.append(pl.BlockSpec(**vmem))
        out_shape.append(
            jax.ShapeDtypeStruct((TELE_ROWS, TELE_COLS), jnp.int32))
    call = pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(**smem)] + [pl.BlockSpec(**vmem)] * 14,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            _scratch((F, 1)), _scratch((F, W)), _scratch((F, NC)),
            _scratch((F, SW)),
            _scratch((F, 1)), _scratch((F, W)), _scratch((F, NC)),
            _scratch((F, SW)),
            _scratch((F, L)), _scratch((F, L)), _scratch((F, L, SW)),
            pltpu.SMEM((16, 1), jnp.int32) if pltpu is not None
            else None,
        ],
        interpret=interpret,
    )

    def step(det_f, det_v1, det_v2, det_inv, det_ret, sfx_min,
             crash_f, crash_v1, crash_v2, crash_inv, det_mpred,
             det_cpredw, crash_mpred, crash_cpredw, dead_from,
             n_det, n_crash, dead_lo, dead_tok,
             budget, lvl_cap, bail,
             frontier, count, status, configs, max_depth, ovf):
        # det_mpred..dead_tok: phase-2 reduction planes, part of the
        # shared step signature; unmasked/undeduped by eligibility, so
        # they are deliberately unused here
        del det_mpred, det_cpredw, crash_mpred, crash_cpredw
        del dead_from, dead_lo, dead_tok
        # ---- XLA boundary: unpack packed words to planes ----------
        win = ((frontier[:, 1 + w_word] >> w_bit) & 1).astype(jnp.int32)
        crash = ((frontier[:, 1 + WW + c_word] >> c_bit)
                 & 1).astype(jnp.int32)
        p = frontier[:, 0:1]
        state = frontier[:, 1 + WW + CW:]
        scal = jnp.stack([
            count.astype(jnp.int32), status.astype(jnp.int32),
            configs.astype(jnp.int32), max_depth.astype(jnp.int32),
            ovf.astype(jnp.int32), n_det, n_crash, budget, lvl_cap,
            bail.astype(jnp.int32), jnp.int32(0), jnp.int32(0),
        ]).reshape(12, 1)
        clamp = functools.partial(jnp.minimum, CLAMP_INF)
        outs = call(
            scal,
            det_f[None, :], det_v1[None, :], det_v2[None, :],
            clamp(det_inv)[None, :], clamp(det_ret)[None, :],
            clamp(sfx_min)[None, :],
            crash_f[None, :], crash_v1[None, :], crash_v2[None, :],
            clamp(crash_inv)[None, :],
            p, win, crash, state)
        if telemetry:
            p_o, win_o, crash_o, state_o, scal_o, tele_o = outs
        else:
            p_o, win_o, crash_o, state_o, scal_o = outs
        # ---- pack planes back to words ----------------------------
        wshift = jnp.asarray(w_bit, jnp.int32)
        cshift = jnp.asarray(c_bit, jnp.int32)
        # disjoint bit values sum to their OR (int32 addition wraps, so
        # bit 31 round-trips through its negative two's-complement value)
        win_words = jnp.stack(
            [(win_o[:, wi * 32:min((wi + 1) * 32, W)]
              << wshift[wi * 32:min((wi + 1) * 32, W)]).sum(axis=1)
             for wi in range(WW)], axis=1)
        crash_words = jnp.stack(
            [(crash_o[:, wi * 32:min((wi + 1) * 32, NC)]
              << cshift[wi * 32:min((wi + 1) * 32, NC)]).sum(axis=1)
             for wi in range(CW)], axis=1)
        frontier_o = jnp.concatenate(
            [p_o, win_words, crash_words, state_o], axis=1)
        out = (frontier_o, scal_o[0, 0], scal_o[1, 0], scal_o[2, 0],
               scal_o[3, 0], scal_o[4, 0].astype(bool))
        if telemetry:
            out = out + (tele_o,)
        return out

    return step
