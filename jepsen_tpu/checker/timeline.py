"""HTML timeline renderer — per-process Gantt of operations.

Reference: jepsen/src/jepsen/checker/timeline.clj — pairs invocations
with completions (pairs, timeline.clj:33-53), lays each process out in
its own column with one div per op spanning its duration, color-coded by
completion type (stylesheet at 24-31, pair->div at 97-141), written into
the store as timeline.html (html checker, 159-179).
"""

from __future__ import annotations

import html as html_mod

from .. import store
from ..history import Op
from .core import Checker

TIMESCALE = 1e6  # nanoseconds per pixel (timeline.clj:19)
COL_WIDTH = 100
GUTTER_WIDTH = 106
HEIGHT = 16

STYLESHEET = """
.ops        { position: absolute; }
.op         { position: absolute; padding: 2px; border-radius: 2px;
              box-shadow: 0 1px 3px rgba(0,0,0,0.12); font-size: 10px;
              font-family: sans-serif; overflow: hidden; }
.op.invoke  { background: #eeeeee; }
.op.ok      { background: #6DB6FE; }
.op.info    { background: #FFAA26; }
.op.fail    { background: #FEB5DA; }
.op:target  { box-shadow: 0 14px 28px rgba(0,0,0,0.25); }
"""


def pairs(history: list[Op]):
    """[invoke, completion] / [lone-info] pairs (timeline.clj:33-53)."""
    invocations: dict = {}
    out = []
    for op in history:
        if op.type == "invoke":
            assert op.process not in invocations
            invocations[op.process] = op
        elif op.type == "info":
            if op.process in invocations:
                out.append((invocations.pop(op.process), op))
            else:
                out.append((op, None))
        elif op.type in ("ok", "fail"):
            if op.process in invocations:
                out.append((invocations.pop(op.process), op))
    # unterminated invokes render open-ended
    for op in invocations.values():
        out.append((op, None))
    return out


def _title(start: Op, stop: Op | None) -> str:
    bits = []
    if stop is not None and start.time is not None and stop.time is not None:
        bits.append(f"Dur: {int((stop.time - start.time) / 1e6)} ms")
    op = stop or start
    if op.error is not None:
        bits.append(f"Err: {op.error}")
    bits.append(f"Op: {op.to_dict()}")
    return "\n".join(bits)


def _body(start: Op, stop: Op | None) -> str:
    op = stop or start
    s = f"{op.process} {op.f}"
    if op.process != "nemesis":
        s += f" {start.value}"
    if stop is not None and stop.value != start.value:
        s += f"<br />{html_mod.escape(str(stop.value))}"
    return s


def html(test: dict, history: list[Op], opts: dict | None = None) -> str:
    """Render timeline.html into the store (timeline.clj:143-179)."""
    procs = []
    for op in history:
        if op.process not in procs:
            procs.append(op.process)
    process_index = {p: i for i, p in enumerate(procs)}

    t0 = min((op.time or 0) for op in history) if history else 0
    divs = []
    for start, stop in pairs(history):
        op = stop or start
        top = ((start.time or 0) - t0) / TIMESCALE
        bottom = (((stop.time or 0) - t0) / TIMESCALE
                  if stop is not None and stop.time is not None
                  else top + HEIGHT)
        height = max(HEIGHT, bottom - top)
        left = GUTTER_WIDTH * process_index[start.process]
        divs.append(
            f'<a href="#i{op.index}"><div class="op {op.type}" '
            f'id="i{op.index}" title="{html_mod.escape(_title(start, stop))}"'
            f' style="width:{COL_WIDTH}px;left:{left:.0f}px;'
            f'top:{top:.0f}px;min-height:{height:.0f}px">'
            f"{_body(start, stop)}</div></a>")

    headers = "".join(
        f'<div style="position:absolute;left:{GUTTER_WIDTH * i}px;'
        f'top:-20px;font-weight:bold;font-family:sans-serif;'
        f'font-size:11px">{html_mod.escape(str(p))}</div>'
        for p, i in process_index.items())

    doc = (f"<html><head><style>{STYLESHEET}</style></head><body>"
           f'<h1 style="font-family:sans-serif">'
           f"{html_mod.escape(str(test.get('name', 'test')))}</h1>"
           f'<div class="ops" style="margin-top:40px">{headers}{divs and "".join(divs)}'
           f"</div></body></html>")
    p = store.path_mkdirs(test, *(opts or {}).get("subdirectory", []),
                          "timeline.html")
    with open(p, "w") as f:
        f.write(doc)
    return p


class Timeline(Checker):
    """timeline.clj:159-179."""

    def check(self, test, history, opts=None):
        html(test, history, opts)
        return {"valid": True}


def timeline() -> Checker:
    return Timeline()
