"""Dirty-read detection checkers.

Two members of the family, both absent from the core reference library
but carried by its suites (a *capability* the rebuild must own,
VERDICT r1 item 5):

  * ``dirty_reads`` — the galera/percona flavor
    (galera/src/jepsen/galera/dirty_reads.clj:72-95): writers race to set
    every row of a table to their op's unique value inside a serializable
    txn; readers snapshot all rows.  A read containing a FAILED write's
    value is a dirty read (the txn's effects were visible before it
    aborted).  A read whose rows are not all equal is an inconsistent
    (non-atomic) read.

  * ``strong_dirty_read`` — the elasticsearch flavor
    (elasticsearch/src/jepsen/elasticsearch/dirty_read.clj:106-157):
    processes write unique ids and read back the most recent in-flight
    id; after quiescence every process takes a final "strong read" of
    the full set.  A successful read of an id absent from every strong
    read is dirty (saw uncommitted state); a successful write absent
    from every strong read is lost; strong reads disagreeing across
    nodes is divergence.

Both consume event-level histories (Op dataclasses) like the rest of
checker/.
"""

from __future__ import annotations

from ..history import is_fail, is_ok
from .core import Checker


class DirtyReadsChecker(Checker):
    """galera dirty_reads.clj:72-95."""

    def check(self, test, history, opts=None):
        failed_writes = {op.value for op in history
                         if is_fail(op) and op.f == "write"}
        reads = [op.value for op in history
                 if is_ok(op) and op.f == "read" and op.value is not None]
        inconsistent = [r for r in reads if len(set(r)) > 1]
        dirty = [r for r in reads
                 if any(x in failed_writes for x in r)]
        return {
            "valid": not dirty,
            "read_count": len(reads),
            "inconsistent_reads": inconsistent,
            "dirty_reads": dirty,
        }


def dirty_reads() -> Checker:
    return DirtyReadsChecker()


class StrongDirtyReadChecker(Checker):
    """elasticsearch dirty_read.clj:106-157.

    Expects ops: write(value=id) / read(value=id, :ok iff found) /
    strong-read(value=set-of-ids).
    """

    def check(self, test, history, opts=None):
        ok = [op for op in history if is_ok(op)]
        writes = {op.value for op in ok if op.f == "write"}
        reads = {op.value for op in ok if op.f == "read"}
        strong = [set(op.value) for op in ok if op.f == "strong-read"
                  and op.value is not None]
        if not strong:
            return {"valid": "unknown",
                    "error": "no strong reads completed"}
        on_all = set.intersection(*strong)
        on_some = set.union(*strong)
        not_on_all = on_some - on_all
        unchecked = on_some - reads
        dirty = reads - on_some
        lost = writes - on_some
        some_lost = writes - on_all
        nodes_agree = on_all == on_some
        return {
            "valid": bool(nodes_agree and not dirty and not lost),
            "nodes_agree": nodes_agree,
            "read_count": len(reads),
            "strong_read_count": len(strong),
            "on_all_count": len(on_all),
            "on_some_count": len(on_some),
            "unchecked_count": len(unchecked),
            "not_on_all_count": len(not_on_all),
            "not_on_all": sorted(not_on_all, key=str),
            "dirty_count": len(dirty),
            "dirty": sorted(dirty, key=str),
            "lost_count": len(lost),
            "lost": sorted(lost, key=str),
            "some_lost_count": len(some_lost),
            "some_lost": sorted(some_lost, key=str),
        }


def strong_dirty_read() -> Checker:
    return StrongDirtyReadChecker()
