"""Shape-bucketed device batching — tight pads, pipelined host prep.

`search_batch` pads EVERY key in a batch to the widest key's dims
(`batch_dims` takes maxes over the batch), so one contentious key
inflates the padded work of the other 255.  This module is the
scheduler in front of the device engine that fixes that — the
GPU-model-checking lesson (GPUexplore, arXiv:1801.05857) applied to
the batch axis: keep the accelerator saturated with uniformly-shaped
work instead of one ragged megabatch.

* **Bucketing** — keys group by their power-of-two-rounded SearchDims
  bucket (:func:`bucket_key`: the exact (n_det_pad, window,
  n_crash_pad) quantization `choose_dims`/`batch_dims` apply), so
  every key in a bucket shares the bucket's padded shape with zero
  extra padding.  Each bucket runs as its own
  `linearizable._search_batch_ladder` call at its own tight dims.
* **Kernel memoization** — buckets reuse compiled kernels per (model,
  dims, bucket-size-class) through the ordinary kernel cache
  (`get_batch_kernel`; hit/miss counters in `KERNEL_CACHE_STATS`), so
  a steady stream of same-shaped buckets never retraces.  Point
  ``jax_compilation_cache_dir`` at a persistent path (the
  JEPSEN_TPU_COMPILE_CACHE_DIR knob, the CLI's --compile-cache-dir,
  or bench.py's .jax_cache default) and compiles survive processes
  too.
* **Pipelining** — while bucket k executes on device (the ladder
  blocks inside XLA executions, which release the GIL), a prep thread
  greedy-witnesses and tight-pads bucket k+1, so that host
  preprocessing hides under device time.  (Encoding itself happens
  upfront: bucket PLANNING needs every key's window, which only
  `encode_search` computes.)

The mesh-sharded route gets the same treatment
(:func:`search_batch_sharded_bucketed`): bucket first, then cover the
mesh per bucket via ``shard_map`` at that bucket's tight dims, padding
with inert keys only up to mesh divisibility within the bucket instead
of one fused batch-wide shape — ScalaBFS's bucket-then-distribute
applied to the device axis (arXiv:2105.11754).

Bucketing is verdict-identical to the fused batch by construction
(the searches are exact at any padding, and every key rides the same
escalation ladder); per-key ``configs``/``engine`` labels come
straight from the engines that produced them.  It wins when key
shapes are heterogeneous (mixed op counts / windows / crash counts);
uniform batches degenerate to ONE bucket — the fused path plus a
negligible plan.  Env knob: ``JEPSEN_TPU_BATCH_BUCKETS=0`` disables,
an integer caps the bucket count (cheapest buckets merge into their
nearest larger neighbor first), unset/auto = on, at most 8 buckets.
"""

from __future__ import annotations

import os
import time
from concurrent.futures import ThreadPoolExecutor

from .. import obs
from ..history import OpSeq
from ..models import ModelSpec
from ..obs import metrics as obs_metrics

#: flight-recorder counters: padded-vs-useful rows shipped to device
#: (padding efficiency on /metrics) and per-stage wall histograms —
#: the same numbers the per-run ``bucket_batch`` stats dict reports,
#: aggregated process-wide
_M_BUCKET_OPS = obs_metrics.REGISTRY.counter(
    "jtpu_bucket_ops_total",
    "Bucketed device batch rows, useful vs padded", ("kind",))
_M_BUCKET_S = obs_metrics.REGISTRY.histogram(
    "jtpu_bucket_seconds",
    "Wall seconds per bucket stage (prep/device)", ("stage",))
#: the mesh-sharded twins: rows here include the inert
#: mesh-divisibility pad lanes in "padded" (billed honestly against
#: efficiency, though they never touch configs/occupancy counters)
_M_SHARD_OPS = obs_metrics.REGISTRY.counter(
    "jtpu_shard_ops_total",
    "Mesh-sharded bucketed batch rows, useful vs padded", ("kind",))
_M_SHARD_S = obs_metrics.REGISTRY.histogram(
    "jtpu_shard_seconds",
    "Wall seconds per sharded bucket stage (prep/device)", ("stage",))

#: default cap on distinct buckets per batch: each bucket is a device
#: dispatch (and possibly a compile on first contact), so unbounded
#: fragmentation would trade padding waste for dispatch/compile waste
_DEFAULT_MAX_BUCKETS = 8


def _bucket_mode() -> tuple[bool, int]:
    """(enabled, max_buckets) from JEPSEN_TPU_BATCH_BUCKETS: "0"/"off"
    turns the DEFAULT routing off (an explicit ``bucket=True`` call
    still buckets at the default cap — the env knob must not silently
    neuter a per-call override), an integer caps the bucket count
    ("1" pins a single fused-shape bucket and counts as
    default-disabled), unset/other = on at the default cap."""
    v = os.environ.get("JEPSEN_TPU_BATCH_BUCKETS", "").strip().lower()
    if v in ("0", "off", "false", "no"):
        return False, _DEFAULT_MAX_BUCKETS
    if v.isdigit():
        n = int(v)
        return n > 1, max(1, n)
    return True, _DEFAULT_MAX_BUCKETS


def bucketing_enabled() -> bool:
    """The env-knob default `search_batch` consults when ``bucket`` is
    not passed explicitly."""
    return _bucket_mode()[0]


def bucket_key(es) -> tuple[int, int, int]:
    """The power-of-two-rounded dims bucket an EncodedSearch lands in.

    Exactly the (n_det_pad, window, n_crash_pad) quantization
    `choose_dims`/`batch_dims` apply to a single key, so a bucket of
    equal-keyed histories pads each member to the dims it would have
    chosen for itself — zero padding attributable to batching."""
    from .linearizable import _next_pow2, _round_up

    nd = max(64, _next_pow2(es.n_det))
    w = _round_up(es.window, 32)
    nc = _round_up(es.n_crash, 32) if es.n_crash else 32
    return nd, w, nc


def _bucket_cost(key: tuple[int, int, int], n_keys: int) -> int:
    """Padded rows a bucket ships to the device (its schedule weight)."""
    nd, _w, nc = key
    return (nd + nc) * n_keys


def plan_buckets(keys: list[tuple[int, int, int]],
                 max_buckets: int) -> list[list[int]]:
    """Group key indices by bucket, then merge down to ``max_buckets``.

    Merging always folds the cheapest bucket into its nearest
    neighbor in dims order (members re-pad to the elementwise-max dims
    of the pair, so adjacent dim tuples waste the least padding).
    Returns index groups ordered largest-padded-cost-first: the big
    bucket's device time hides the most pipelined host prep, and —
    like the ladder's largest-first key order — the straggler starts
    first."""
    groups: dict[tuple, list[int]] = {}
    for i, k in enumerate(keys):
        groups.setdefault(k, []).append(i)
    while len(groups) > max(1, max_buckets):
        order = sorted(groups)
        costs = [_bucket_cost(k, len(groups[k])) for k in order]
        j = min(range(len(order)), key=costs.__getitem__)
        t = j + 1 if j + 1 < len(order) else j - 1
        a, b = order[j], order[t]
        merged = tuple(max(x, y) for x, y in zip(a, b))
        rows = groups.pop(a) + groups.pop(b)
        groups.setdefault(merged, []).extend(rows)
    return [idxs for _k, idxs in
            sorted(groups.items(),
                   key=lambda kv: -_bucket_cost(kv[0], len(kv[1])))]


def search_batch_bucketed(seqs: list[OpSeq], model: ModelSpec, *,
                          budget: int = 2_000_000,
                          hb: bool | None = None,
                          dpor: bool | None = None) -> list[dict]:
    """Bucketed drop-in for `search_batch`'s ladder path.

    Per-key results are exactly what the underlying engines report
    (greedy-witness / device-batch ladder / host-linear fallback for
    keys past the device encoding limits); the FIRST result
    additionally carries the ``bucket_batch`` stats dict — per-bucket
    padding efficiency (useful_ops / padded_ops), the fused-batch
    counterfactual, and kernel-cache hit counts — the bench's evidence
    that bucketing actually cut wasted padded work.
    """
    from . import linearizable as lin
    from ..analyze.dpor import resolve_dpor
    from ..analyze.hb import maybe_hb, resolve_hb

    hb = resolve_hb(hb)
    dpor_on = resolve_dpor(dpor)
    n = len(seqs)
    t_start = time.perf_counter()
    kc0 = lin.kernel_cache_stats()
    ess = [lin.encode_search(s) for s in seqs]
    results: list = [None] * n
    hard, fit = [], []
    for i, e in enumerate(ess):
        (hard if e.window > lin.MAX_WINDOW
         or e.n_crash > lin.MAX_CRASH else fit).append(i)
    _enabled, max_buckets = _bucket_mode()
    plans = plan_buckets([bucket_key(ess[i]) for i in fit], max_buckets)
    plans = [[fit[p] for p in grp] for grp in plans]

    stats: dict = {"n_keys": n, "n_buckets": len(plans), "buckets": [],
                   "greedy": 0, "hard": len(hard), "hb_decided": 0,
                   "constraint_decided": 0}

    # pin span attribution to the run that started THIS drive: the
    # prep closure runs on the pipeline thread, where the process-wide
    # current run may have moved on under a multiplexing service by
    # the time the span closes (T001/T004 — the PR 17 race class)
    run_pin = obs.current_run()

    def prep(idxs: list[int]):
        """Host stage for one bucket: greedy-witness disposal, then
        tight dims + padding for the keys that must ride the device.
        Pure numpy/Python — safe to run in the pipeline thread while
        the previous bucket executes (its span lands on the prep
        thread's track, so the trace timeline SHOWS the overlap)."""
        t_prep = time.perf_counter()
        with obs.span("bucket.prep", cat="host", run=run_pin,
                      keys=len(idxs)):
            ready: dict[int, dict] = {}
            run: list[int] = []
            run_mask: dict[int, dict | None] = {}
            for i in idxs:
                s = seqs[i]
                if lin.greedy_witness(s, model):
                    # the certificate indexes the key's OWN OpSeq, so
                    # it survives bucket assignment and reordering
                    # untouched
                    ready[i] = {"valid": True, "configs": s.n_must,
                                "max_depth": s.n_must,
                                "engine": "greedy-witness",
                                "linearization":
                                    lin.greedy_linearization(s)}
                else:
                    r = mp = None
                    if hb:
                        hbres = maybe_hb(s, model, True, dpor)
                        if hbres is not None and \
                                hbres.decided is not None:
                            r = dict(hbres.decided)
                        elif hbres is not None and hbres.must_pred:
                            mp = hbres.must_pred
                    if r is not None:
                        # HB-decided next to the greedy disposal: the
                        # key never pads into the bucket's dims, never
                        # costs a device config (explain_batch mirrors
                        # this split exactly)
                        ready[i] = r
                    else:
                        run.append(i)
                        run_mask[i] = mp
            if not run:
                _M_BUCKET_S.observe(time.perf_counter() - t_prep,
                                    stage="prep")
                return ready, run, None, None
            dims = lin.batch_dims([ess[i] for i in run], model,
                                  frontier=32)
            if dpor_on:
                # thread the undecided keys' must-order maps into the
                # encodings as device planes + the dead-value table —
                # the bucket's ladder reads the flags off the padded
                # encodings and builds the masked kernel.  Buckets in
                # the pallas regime drop the optional prune and keep
                # the fused kernel instead (engine priority).
                for i in run:
                    lin.attach_reductions(ess[i], seqs[i], model,
                                          run_mask.get(i), dedup=True)
                    lin._strip_reductions_for_pallas(ess[i], model,
                                                     dims)
            dead_pad = lin.batch_dead_pad([ess[i] for i in run])
            esps = [lin.pad_search(ess[i], dims.n_det_pad,
                                   dims.n_crash_pad,
                                   dead_pad=dead_pad) for i in run]
        _M_BUCKET_S.observe(time.perf_counter() - t_prep, stage="prep")
        return ready, run, dims, esps

    useful_total = padded_total = 0
    run_all: list[int] = []
    if plans:
        ex = ThreadPoolExecutor(max_workers=1,
                                thread_name_prefix="bucket-prep")
        try:
            fut = ex.submit(prep, plans[0])
            for b, idxs in enumerate(plans):
                ready, run, dims, esps = fut.result()
                if b + 1 < len(plans):
                    # bucket b+1's host prep overlaps bucket b's device
                    # execution below
                    fut = ex.submit(prep, plans[b + 1])
                for i, r in ready.items():
                    results[i] = r
                n_hb = sum(1 for r in ready.values()
                           if r.get("engine") == "hb-decide")
                n_cs = sum(1 for r in ready.values()
                           if r.get("engine") == "constraint-decide")
                stats["hb_decided"] += n_hb
                stats["constraint_decided"] += n_cs
                stats["greedy"] += len(ready) - n_hb - n_cs
                t0 = time.perf_counter()
                if run:
                    with obs.span("bucket.device", cat="device",
                                  bucket=b, keys=len(run),
                                  dims=[dims.n_det_pad, dims.window,
                                        dims.n_crash_pad]):
                        sub = lin._search_batch_ladder(
                            [seqs[i] for i in run], esps, model, dims,
                            budget)
                    for i, r in zip(run, sub):
                        results[i] = r
                dt = time.perf_counter() - t0
                if run:
                    _M_BUCKET_S.observe(dt, stage="device")
                useful = sum(ess[i].n_det + ess[i].n_crash for i in run)
                padded = (len(run) * (dims.n_det_pad + dims.n_crash_pad)
                          if run else 0)
                useful_total += useful
                padded_total += padded
                run_all += run
                stats["buckets"].append({
                    "dims": ([dims.n_det_pad, dims.window,
                              dims.n_crash_pad] if run else None),
                    "n_keys": len(idxs), "searched": len(run),
                    "useful_ops": useful, "padded_ops": padded,
                    "padding_efficiency": (round(useful / padded, 4)
                                           if padded else None),
                    "seconds": round(dt, 3)})
        finally:
            ex.shutdown(wait=True)
    if hard:
        # past the device encoding limits: greedy witness FIRST (the
        # fused path disposes of well-behaved keys in O(n) before its
        # hard check — skipping it here could degrade a True verdict
        # to "unknown" via an exhausted host sweep), then the same
        # host-linear fallback per key
        from .linear import check_opseq_linear

        for i in hard:
            s = seqs[i]
            if lin.greedy_witness(s, model):
                results[i] = {"valid": True, "configs": s.n_must,
                              "max_depth": s.n_must,
                              "engine": "greedy-witness",
                              "linearization": lin.greedy_linearization(s)}
                stats["greedy"] += 1
                continue
            r = check_opseq_linear(seqs[i], model, lint=False, hb=hb,
                                   dpor=dpor)
            r["engine"] = "host-linear(fallback)"
            results[i] = r
    # the single-fused-batch counterfactual over the SAME device-ridden
    # keys: what `batch_dims` over the whole set would have padded to
    fused_padded = 0
    if run_all:
        fdims = lin.batch_dims([ess[i] for i in run_all], model)
        fused_padded = len(run_all) * (fdims.n_det_pad
                                       + fdims.n_crash_pad)
    kc1 = lin.kernel_cache_stats()
    if useful_total or padded_total:
        _M_BUCKET_OPS.inc(useful_total, kind="useful")
        _M_BUCKET_OPS.inc(padded_total, kind="padded")
    stats.update({
        "useful_ops": useful_total,
        "padded_ops": padded_total,
        "padding_efficiency": (round(useful_total / padded_total, 4)
                               if padded_total else None),
        "fused_padded_ops": fused_padded or None,
        "fused_padding_efficiency": (round(useful_total / fused_padded,
                                           4) if fused_padded else None),
        "kernel_cache": {k: kc1[k] - kc0[k] for k in kc1},
        "seconds": round(time.perf_counter() - t_start, 3),
    })
    # stats ride on the FIRST result only: attaching the shared dict
    # (with its per-bucket list) to every key would serialize it N
    # times through per-key report stores, and one shared mutable
    # object on N results invites spooky cross-key mutation
    if results:
        results[0].setdefault("bucket_batch", stats)
    return results


def search_batch_sharded_bucketed(seqs: list[OpSeq], model: ModelSpec,
                                  sharding, *,
                                  budget: int = 2_000_000,
                                  hb: bool | None = None,
                                  dpor: bool | None = None
                                  ) -> list[dict]:
    """Bucket-then-shard: the mesh analog of `search_batch_bucketed`.

    The fused sharded path pins EVERY key to one batch-wide
    `SearchDims` "to keep the mesh covered", so one contentious key
    inflates the padded rows of all shards.  Here keys bucket exactly
    like the single-device scheduler (same `bucket_key` quantization,
    same `plan_buckets` merge), and each bucket covers the mesh on its
    own via `linearizable._search_batch_sharded_fixed` — a `shard_map`
    dispatch at the bucket's tight dims, padded with inert keys only
    up to mesh divisibility WITHIN the bucket.  Host prep for bucket
    k+1 (greedy witness, HB/constraint disposal, DPOR attach, tight
    pad) pipelines under bucket k's device time on the same
    one-worker prep thread.

    Verdict- and certificate-identical to the fused sharded path by
    construction: every key runs the same exact search at its bucket's
    padding, results carry the same "device-batch" engine label and
    drop-reason certificates, and overflowed keys take the same solo
    redo.  The FIRST result carries the ``shard_batch`` stats dict —
    per-bucket padding efficiency (mesh pad lanes billed in
    padded_ops), the fused-shape counterfactual, kernel-cache hits,
    shard count — mirrored exactly by
    `analyze.plan.explain_batch(..., n_devices=...)`.
    """
    from . import linearizable as lin
    from ..analyze.dpor import resolve_dpor
    from ..analyze.hb import maybe_hb, resolve_hb
    from ..obs import telemetry as _tele

    hb = resolve_hb(hb)
    dpor_on = resolve_dpor(dpor)
    n = len(seqs)
    t_start = time.perf_counter()
    kc0 = lin.kernel_cache_stats()
    n_dev = getattr(sharding, "num_devices", 1) or 1
    tele_acc = _tele.SearchTelemetry("device-batch-sharded") \
        if _tele.enabled() else None
    ess = [lin.encode_search(s) for s in seqs]
    results: list = [None] * n
    hard, fit = [], []
    for i, e in enumerate(ess):
        (hard if e.window > lin.MAX_WINDOW
         or e.n_crash > lin.MAX_CRASH else fit).append(i)
    _enabled, max_buckets = _bucket_mode()
    plans = plan_buckets([bucket_key(ess[i]) for i in fit], max_buckets)
    plans = [[fit[p] for p in grp] for grp in plans]

    stats: dict = {"n_keys": n, "n_buckets": len(plans),
                   "n_devices": n_dev, "buckets": [],
                   "greedy": 0, "hard": len(hard), "hb_decided": 0,
                   "constraint_decided": 0}

    # same run pin as the single-device scheduler: prep spans close on
    # the pipeline thread, which must not read the racy process-wide
    # current run (T004)
    run_pin = obs.current_run()

    def prep(idxs: list[int]):
        """Host stage for one bucket — the single-device scheduler's
        prep with the sharded route's two differences: dims start at
        the wide frontier (no escalation ladder on a mesh), and DPOR
        planes are never stripped (the sharded kernel is always XLA,
        never pallas)."""
        t_prep = time.perf_counter()
        with obs.span("shard.prep", cat="host", run=run_pin,
                      keys=len(idxs)):
            ready: dict[int, dict] = {}
            run: list[int] = []
            run_mask: dict[int, dict | None] = {}
            for i in idxs:
                s = seqs[i]
                if lin.greedy_witness(s, model):
                    ready[i] = {"valid": True, "configs": s.n_must,
                                "max_depth": s.n_must,
                                "engine": "greedy-witness",
                                "linearization":
                                    lin.greedy_linearization(s)}
                else:
                    r = mp = None
                    if hb:
                        hbres = maybe_hb(s, model, True, dpor)
                        if hbres is not None and \
                                hbres.decided is not None:
                            r = dict(hbres.decided)
                        elif hbres is not None and hbres.must_pred:
                            mp = hbres.must_pred
                    if r is not None:
                        ready[i] = r
                    else:
                        run.append(i)
                        run_mask[i] = mp
            if not run:
                _M_SHARD_S.observe(time.perf_counter() - t_prep,
                                   stage="prep")
                return ready, run, None, None, None
            dims = lin.batch_dims([ess[i] for i in run], model,
                                  frontier=64)
            if dpor_on:
                for i in run:
                    lin.attach_reductions(ess[i], seqs[i], model,
                                          run_mask.get(i), dedup=True)
            dead_pad = lin.batch_dead_pad([ess[i] for i in run])
            esps = [lin.pad_search(ess[i], dims.n_det_pad,
                                   dims.n_crash_pad,
                                   dead_pad=dead_pad) for i in run]
        _M_SHARD_S.observe(time.perf_counter() - t_prep, stage="prep")
        return ready, run, dims, esps, dead_pad

    useful_total = padded_total = 0
    pad_lanes_total = redo_total = 0
    shard_map_all = True
    run_all: list[int] = []
    if plans:
        ex = ThreadPoolExecutor(max_workers=1,
                                thread_name_prefix="shard-prep")
        try:
            fut = ex.submit(prep, plans[0])
            for b, idxs in enumerate(plans):
                ready, run, dims, esps, dead_pad = fut.result()
                if b + 1 < len(plans):
                    # bucket b+1's host prep overlaps bucket b's mesh
                    # execution below
                    fut = ex.submit(prep, plans[b + 1])
                for i, r in ready.items():
                    results[i] = r
                n_hb = sum(1 for r in ready.values()
                           if r.get("engine") == "hb-decide")
                n_cs = sum(1 for r in ready.values()
                           if r.get("engine") == "constraint-decide")
                stats["hb_decided"] += n_hb
                stats["constraint_decided"] += n_cs
                stats["greedy"] += len(ready) - n_hb - n_cs
                t0 = time.perf_counter()
                info = None
                if run:
                    with obs.span("shard.device", cat="device",
                                  bucket=b, keys=len(run),
                                  shards=n_dev,
                                  dims=[dims.n_det_pad, dims.window,
                                        dims.n_crash_pad]):
                        sub, info = lin._search_batch_sharded_fixed(
                            [seqs[i] for i in run],
                            [ess[i] for i in run], model, dims,
                            sharding, budget, tele_acc=tele_acc,
                            esps=esps, dead_pad=dead_pad)
                    for i, r in zip(run, sub):
                        results[i] = r
                dt = time.perf_counter() - t0
                if run:
                    _M_SHARD_S.observe(dt, stage="device")
                useful = sum(ess[i].n_det + ess[i].n_crash for i in run)
                lanes = info["batch_lanes"] if info else 0
                # mesh-divisibility pad lanes bill into padded_ops
                # (they occupy device rows) even though they never
                # touch configs/occupancy counters
                padded = lanes * (dims.n_det_pad + dims.n_crash_pad) \
                    if run else 0
                useful_total += useful
                padded_total += padded
                if info:
                    pad_lanes_total += info["pad_lanes"]
                    redo_total += info["overflow_redo"]
                    shard_map_all &= info["shard_map"]
                run_all += run
                stats["buckets"].append({
                    "dims": ([dims.n_det_pad, dims.window,
                              dims.n_crash_pad] if run else None),
                    "n_keys": len(idxs), "searched": len(run),
                    "lanes": lanes,
                    "pad_lanes": info["pad_lanes"] if info else 0,
                    "useful_ops": useful, "padded_ops": padded,
                    "padding_efficiency": (round(useful / padded, 4)
                                           if padded else None),
                    "seconds": round(dt, 3)})
        finally:
            ex.shutdown(wait=True)
    if hard:
        from .linear import check_opseq_linear

        for i in hard:
            s = seqs[i]
            if lin.greedy_witness(s, model):
                results[i] = {"valid": True, "configs": s.n_must,
                              "max_depth": s.n_must,
                              "engine": "greedy-witness",
                              "linearization": lin.greedy_linearization(s)}
                stats["greedy"] += 1
                continue
            r = check_opseq_linear(seqs[i], model, lint=False, hb=hb,
                                   dpor=dpor)
            r["engine"] = "host-linear(fallback)"
            results[i] = r
    # the fused-shape counterfactual over the SAME device-ridden keys:
    # one batch at global max dims, rounded up to cover the mesh once
    fused_padded = 0
    if run_all:
        fdims = lin.batch_dims([ess[i] for i in run_all], model,
                               frontier=64)
        fused_padded = lin._round_up(len(run_all), n_dev) \
            * (fdims.n_det_pad + fdims.n_crash_pad)
    kc1 = lin.kernel_cache_stats()
    if useful_total or padded_total:
        _M_SHARD_OPS.inc(useful_total, kind="useful")
        _M_SHARD_OPS.inc(padded_total, kind="padded")
    stats.update({
        "useful_ops": useful_total,
        "padded_ops": padded_total,
        "pad_keys": pad_lanes_total,
        "overflow_redo": redo_total,
        "shard_map": shard_map_all if run_all else None,
        "padding_efficiency": (round(useful_total / padded_total, 4)
                               if padded_total else None),
        "fused_padded_ops": fused_padded or None,
        "fused_padding_efficiency": (round(useful_total / fused_padded,
                                           4) if fused_padded else None),
        "kernel_cache": {k: kc1[k] - kc0[k] for k in kc1},
        "seconds": round(time.perf_counter() - t_start, 3),
    })
    if tele_acc is not None and results and results[0] is not None:
        _tele.finalize_result(results[0], tele_acc)
    if results:
        results[0].setdefault("shard_batch", stats)
    return results


# ---------------------------------------------------------------------------
# kernel route registration — the bucket scheduler's half of the
# device-contract enumeration (see linearizable.KernelRoute)
# ---------------------------------------------------------------------------

from . import linearizable as _lin  # noqa: E402

_lin.register_route(_lin.KernelRoute(
    name="bucketed-batch", engine="xla", span_kind="batch",
    getter="get_batch_kernel", module=_lin.__name__,
    build=_lin._build_batch, request=_lin._request_batch,
    batched=True))
_lin.register_route(_lin.KernelRoute(
    name="mesh-sharded", engine="xla", span_kind="batch-sharded",
    getter="get_sharded_batch_kernel", module=_lin.__name__,
    build=_lin._build_sharded, request=_lin._request_sharded,
    batched=True, sharded=True))
