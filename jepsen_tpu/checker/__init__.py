"""Checker layer (reference L6): validity analysis over histories.

See :mod:`jepsen_tpu.checker.core` for the Checker protocol,
:mod:`jepsen_tpu.checker.basic` for the O(n) checkers,
:mod:`jepsen_tpu.checker.seq` for the sequential linearizability oracle and
:mod:`jepsen_tpu.checker.linearizable` for the TPU engine.
"""

from .core import (  # noqa: F401
    Checker,
    CheckerFn,
    check_safe,
    compose,
    merge_valid,
    unbridled_dionysus,
)
