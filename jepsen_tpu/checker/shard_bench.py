"""The shard bench tier — ``python bench.py --shard-tier``.

Measures the bucket-then-shard scheduler
(:func:`checker.bucket.search_batch_sharded_bucketed`) against the
fused single-shape sharded dispatch on a mixed-size key set over the
local device mesh (the virtual 8-device CPU mesh under
``XLA_FLAGS=--xla_force_host_platform_device_count=8``, or real chips).
Writes ``BENCH_shard.json`` (numbers) and ``BENCH_trace_shard.json``
(the flight recording: ``shard.prep``/``shard.device`` spans show the
pipelining, per-shard ``device.level`` spans the occupancy, and
``device.compile`` spans that the warm lap paid every compile).

Gates that ride on the numbers (tools/obs_guard.py ``check_shard`` via
the ``obs_thresholds.json`` "shard" block):

  * **parity** — bucketed-sharded verdicts match the fused sharded
    route key-for-key, and a sample re-checks against the host oracle.
  * **padding efficiency** — the bucketed route's useful/padded row
    ratio (mesh pad lanes billed) clears the floor; the fused
    counterfactual over the same keys is recorded next to it.
  * **zero steady-state compiles** — the measured laps re-run the warm
    lap's shapes and the kernel cache's miss counter must not move.
  * **warmup round-trip** — `fleet.warmup.shapes_from_trace` over this
    run's own trace reconstructs the sharded kernel set exactly:
    `warm_boot` on those shapes reports zero fresh compiles.
"""

from __future__ import annotations

import json
import os
import random
import time

#: oracle re-checks sweep the full config space per key — sample
_PARITY_SAMPLE = 6

#: per-bucket stats fields that must match `analyze.plan.explain_batch`
#: field-for-field (the closed-loop cost-model contract)
_EXPLAIN_BUCKET_FIELDS = ("searched", "dims", "lanes", "pad_lanes",
                          "useful_ops", "padded_ops")
_EXPLAIN_TOTAL_FIELDS = ("n_buckets", "greedy", "hb_decided",
                         "constraint_decided", "hard", "useful_ops",
                         "padded_ops", "fused_padded_ops")


def _mk_keys(*, n_small: int, n_big: int, small_ops: int, big_ops: int,
             seed0: int):
    """The mixed-size tier: many small keys + a few big ones, every
    device-bound key corrupted so none dispose via greedy witness (the
    whole point is to measure the device path's padding)."""
    from ..history import encode_ops
    from ..models import cas_register
    from ..synth import corrupt_read, register_history

    model = cas_register()
    seqs = []
    for k in range(n_small + n_big):
        rng = random.Random(seed0 + k)
        n_ops = small_ops if k < n_small else big_ops
        h = register_history(rng, n_ops=n_ops, n_procs=6, overlap=4)
        h = corrupt_read(rng, h, at=0.85)
        seqs.append(encode_ops(h, model.f_codes))
    return seqs, model


def _stats_match_plan(sb: dict, plan: dict) -> tuple[bool, list]:
    """Field-for-field comparison of the live ``shard_batch`` stats
    against ``explain_batch(..., n_devices=...)``'s prediction."""
    diffs = []
    for f in _EXPLAIN_TOTAL_FIELDS:
        if sb.get(f) != plan.get(f):
            diffs.append({"field": f, "live": sb.get(f),
                          "plan": plan.get(f)})
    live_b, plan_b = sb.get("buckets", []), plan.get("buckets", [])
    if len(live_b) != len(plan_b):
        diffs.append({"field": "len(buckets)", "live": len(live_b),
                      "plan": len(plan_b)})
    else:
        for i, (lb, pb) in enumerate(zip(live_b, plan_b)):
            for f in _EXPLAIN_BUCKET_FIELDS:
                if lb.get(f) != pb.get(f):
                    diffs.append({"field": f"buckets[{i}].{f}",
                                  "live": lb.get(f),
                                  "plan": pb.get(f)})
    return not diffs, diffs


def run_shard_tier(repo: str, *, quick: bool = False) -> dict:
    import numpy as np

    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from .. import obs as _obs
    from ..analyze.plan import explain_batch
    from ..fleet.warmup import shapes_from_trace, warm_boot
    from ..obs import metrics as obs_metrics
    from . import linearizable as lin
    from . import seq as oracle

    _obs.enable(True)
    devs = jax.devices()
    mesh = Mesh(np.array(devs), ("shard",))
    sharding = NamedSharding(mesh, PartitionSpec("shard"))
    n_dev = len(devs)

    if quick:
        n_small, n_big, small_ops, big_ops = 16, 4, 74, 120
    else:
        # sized so each pow-of-two bucket packs tight: 74-op keys land
        # ~56 useful rows under (64+32) padded, 240-op keys ~177 under
        # (256+32) — weighted ~0.59 useful/padded vs the fused ~0.29
        n_small, n_big, small_ops, big_ops = 40, 8, 74, 240
    budget = 1_500_000
    seqs, model = _mk_keys(n_small=n_small, n_big=n_big,
                           small_ops=small_ops, big_ops=big_ops,
                           seed0=31000)
    out: dict = {
        "metric": "shard tier: bucket-then-shard vs fused mesh batch",
        "quick": quick, "n_devices": n_dev,
        "n_keys": len(seqs),
        "mix": {"small": [n_small, small_ops], "big": [n_big, big_ops]},
    }

    # --- warm lap: pay every compile once ----------------------------
    t0 = time.perf_counter()
    warm_b = lin.search_batch(seqs, model, budget=budget,
                              sharding=sharding, audit=False)
    wall_warm_b = time.perf_counter() - t0
    t0 = time.perf_counter()
    warm_f = lin.search_batch(seqs, model, budget=budget,
                              sharding=sharding, bucket=False,
                              audit=False)
    wall_warm_f = time.perf_counter() - t0
    out["warm_lap"] = {"bucketed_wall_s": round(wall_warm_b, 3),
                       "fused_wall_s": round(wall_warm_f, 3)}

    # --- warmup round-trip: the trace's compile spans reconstruct the
    # exact sharded kernel set (zero fresh compiles on warm_boot) -----
    import tempfile

    with tempfile.TemporaryDirectory(prefix="shard-bench-") as td:
        mid_trace = os.path.join(td, "trace_mid.json")
        _obs.write_trace(mid_trace)
        with open(mid_trace) as f:
            shapes = shapes_from_trace(json.load(f))
    shard_shapes = [s for s in shapes if s.shards]
    wrep = warm_boot(shapes)
    out["warmup"] = wrep
    out["warmup_shapes"] = {"total": len(shapes),
                            "sharded": len(shard_shapes)}

    # --- measured laps: same workload, warm cache --------------------
    misses0 = lin.KERNEL_CACHE_STATS["misses"]
    t0 = time.perf_counter()
    got_b = lin.search_batch(seqs, model, budget=budget,
                             sharding=sharding, audit=True)
    wall_b = time.perf_counter() - t0
    t0 = time.perf_counter()
    got_f = lin.search_batch(seqs, model, budget=budget,
                             sharding=sharding, bucket=False,
                             audit=True)
    wall_f = time.perf_counter() - t0
    out["steady_state_compile_misses"] = (
        lin.KERNEL_CACHE_STATS["misses"] - misses0)

    sb = got_b[0].get("shard_batch") or {}
    out["bucketed"] = {
        "wall_s": round(wall_b, 3),
        "padding_efficiency": sb.get("padding_efficiency"),
        "n_buckets": sb.get("n_buckets"),
        "pad_keys": sb.get("pad_keys"),
        "shard_map": sb.get("shard_map"),
        "overflow_redo": sb.get("overflow_redo"),
        "kernel_cache": sb.get("kernel_cache"),
        "buckets": sb.get("buckets"),
    }
    out["fused_counterfactual"] = {
        "wall_s": round(wall_f, 3),
        "padded_ops": sb.get("fused_padded_ops"),
        "padding_efficiency": sb.get("fused_padding_efficiency"),
    }
    out["speedup_vs_fused"] = (round(wall_f / wall_b, 3)
                               if wall_b else None)

    # --- parity: bucketed vs fused key-for-key, oracle sampled -------
    parity = all(rb["valid"] == rf["valid"]
                 for rb, rf in zip(got_b, got_f))
    rng = random.Random(11)
    sample = rng.sample(range(len(seqs)),
                        min(_PARITY_SAMPLE, len(seqs)))
    for i in sample:
        want = oracle.check_opseq(seqs[i], model, dpor=False)["valid"]
        if got_b[i]["valid"] != want:
            parity = False
            out.setdefault("parity_diffs", []).append(
                {"key": i, "bucketed": got_b[i]["valid"],
                 "oracle": want})
    out["parity"] = parity
    out["parity_oracle_sampled"] = len(sample)

    # --- the closed loop: prediction == observation ------------------
    plan = explain_batch(seqs, model, n_devices=n_dev)
    match, diffs = _stats_match_plan(sb, plan)
    out["explain_match"] = match
    if diffs:
        out["explain_diffs"] = diffs[:16]

    out["derived_stats"] = {
        k: v for k, v in
        obs_metrics.derived_stats(obs_metrics.REGISTRY).items()
        if k in ("shard_padding_efficiency", "bucket_padding_efficiency",
                 "kernel_cache_hit_ratio", "device_idle_fraction")}

    path = os.path.join(repo, "BENCH_shard.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    _obs.write_trace(os.path.join(repo, "BENCH_trace_shard.json"))
    out["trace"] = "BENCH_trace_shard.json (shard.prep/shard.device " \
                   "pipelining, per-shard device.level spans)"
    print(json.dumps({
        "metric": "shard: bucketed padding efficiency on the "
                  f"mixed-size tier ({n_dev} devices; fused "
                  "counterfactual "
                  f"{sb.get('fused_padding_efficiency')})",
        "value": sb.get("padding_efficiency"),
        "unit": "useful/padded rows",
        "detail": out,
    }))
    return out
