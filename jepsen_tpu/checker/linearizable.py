"""TPU linearizability engine — batched JAX frontier search.

This is the rebuild's replacement for the external ``knossos`` JVM library
the reference delegates linearizability checking to (used from
jepsen/src/jepsen/checker.clj:114-139; algorithms selected at
checker.clj:122-126).  knossos explores configurations — (set of
linearized ops, model state) — by depth-first search with a visited memo,
sized at -Xmx32g (jepsen/project.clj:25).  Here the same configuration
space is explored breadth-first on device: a frontier of configurations is
expanded in lockstep under ``vmap`` (one lane per configuration ×
candidate), deduplicated exactly per level, and compacted into the next
frontier.  The BFS runs as a sequence of bounded device calls — a
``lax.while_loop`` capped at ``lvl_cap`` levels per call, with the search
state as an explicit carry — because the axon TPU worker kills any single
execution outliving its ~60s watchdog; the carry doubles as a checkpoint
and as the resume point for in-place frontier escalation.

Configuration encoding (the "hashing model states on TPU" problem,
SURVEY.md §7): a naive linearized-set needs n bits per config.  Instead we
exploit the real-time order:

  * Determinate ops (ok completions; they MUST linearize) are kept sorted
    by invocation.  In any reachable configuration, if ``p`` is the first
    unlinearized determinate op, every linearized op j > p was linearized
    while p was pending, so ``inv[j] < ret[p]``.  The number of such j is
    bounded and host-computable (``window_width``); hence the set of
    linearized determinate ops is exactly (prefix ``p``, bitmask over the
    next W ops).
  * Indeterminate ops (:info — crashed; ``ret = +inf``; they MAY linearize
    at any point after invocation, forever — core.clj:387-397) break that
    bound, so they live in their own bitmask of width ≤ 64; a history has
    at most #processes of them.

A config is then ``[p | window words | crash words | model state]`` — a
handful of int32 lanes instead of n bits, so millions of configs fit in
HBM and hash in a few vector ops.

Soundness: a "valid" verdict always carries a real witness path (every
transition was model-checked on device, and the goal test runs on every
candidate lane).  Dedup is *exact*: candidates are hash-sorted (one
packed uint32 key at moderate widths, a variadic (hash, iota) sort
above) and equal-key neighbors are compared on their full config words
before dropping either — hash collisions cost duplicate work, never a
merge — so an "invalid" verdict is not subject to fingerprinting.
Capacity is handled by the adaptive width driver (`_run_kernel`): the
frontier width moves both ways on a power-of-two grid — an overflowing
level is uncommitted by the kernel and the search resumes 4x wider from
the very level that overflowed (zero levels re-run); a shrunken live
frontier truncates back down.  Only at MAX_FRONTIER does
an overflow degrade the verdict, and then always to "unknown", never to
a wrong answer; exhausted budgets and deadlines also report "unknown".
Histories whose window or crash count exceed the device encoding fall
back to the exact `linear` host sweep (checker/linear.py);
Linearizable.check additionally re-runs short failing prefixes
(≤ witness_threshold ops) on the WGL host oracle (checker/seq.py) to
reconstruct a human-readable witness, and `check_competition` races
both host engines against the device search outright (the knossos
`competition` analog).

Batching: `search_batch` vmaps the whole search over a leading key axis —
the TPU analog of the reference's independent-key sharding
(jepsen/src/jepsen/independent.clj:247-298, bounded-pmap per key).  The
key axis shards across a device mesh with `jax.sharding`; searches are
embarrassingly parallel so the only collective is the final verdict
gather.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from dataclasses import dataclass, replace as _dc_replace

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import obs
from ..history import INF_RET, NIL, OpSeq, encode_ops
from ..models import ModelSpec
from ..obs import metrics as _obs_metrics
from ..obs import telemetry as _tele
from ..obs.telemetry import (C_DEDUP, C_EXP, C_GOAL, C_KILL, C_NEXT,
                             C_OCC, C_OVF, C_ROUNDS, TELE_COLS,
                             TELE_ROWS)

#: flight-recorder twin of KERNEL_CACHE_STATS (module handle: a
#: registry get-or-create per lookup would tax the dispatch path)
_M_KCACHE = _obs_metrics.REGISTRY.counter(
    "jtpu_kernel_cache_total",
    "Compiled-kernel cache lookups (hit/miss)", ("event",))

# int32 value standing in for "+infinity" event rank on device.
INF32 = np.int32(2**31 - 1)

# ---------------------------------------------------------------------------
# Host-side preprocessing
# ---------------------------------------------------------------------------


#: must-order predecessor slots per det/crash row shipped to device —
#: rows with more keep their LATEST (largest-position) preds; masking
#: with a subset of the predecessor set is sound, just a weaker prune
MASK_PREDS = 4

#: widest dead-value lookup table the device dedup will carry —
#: CANDIDATE state values only (compared-but-never-written values sit
#: outside it by design); wider value ranges simply skip the device
#: rewrite (host engines use the dict form and have no span limit).
#: 64k entries = 256 KB per solo key; batches stack to their max.
DEAD_TABLE_MAX = 1 << 16


@dataclass
class EncodedSearch:
    """Device-ready arrays for one history (padded to static shapes).

    The ``*_mpred``/``*_cpred`` planes and the ``dead_*`` table are the
    state-space-reduction phase-2 payload (attach_reductions): per-row
    must-order predecessors from the HB/constraint/dup-edge prepass,
    and the dead-value quotient table from decompose/canonical.py.
    ``masked``/``dedup`` say whether the kernels should emit the
    corresponding checks (the arrays are always materialized by
    pad_search so batch stacking stays uniform)."""

    det_f: np.ndarray  # int32 [n_det_pad]
    det_v1: np.ndarray
    det_v2: np.ndarray
    det_inv: np.ndarray  # int32; INF32 padding
    det_ret: np.ndarray  # int32; INF32 padding
    suffix_min_ret: np.ndarray  # int32 [n_det_pad + 1]
    crash_f: np.ndarray  # int32 [n_crash_pad]
    crash_v1: np.ndarray
    crash_v2: np.ndarray
    crash_inv: np.ndarray
    n_det: int
    n_crash: int
    window: int  # exact upper bound on linearized-beyond-prefix span
    concurrency: int  # max simultaneously-enabled candidates
    #: must-order mask (None until attach_reductions / pad_search):
    #: det positions of up to MASK_PREDS predecessors per row (-1 pad)
    det_mpred: np.ndarray | None = None   # int32 [n_det(_pad), P]
    det_cpred: np.ndarray | None = None   # uint64 [n_det] crash bitmask
    crash_mpred: np.ndarray | None = None  # int32 [n_crash(_pad), P]
    crash_cpred: np.ndarray | None = None  # uint64 [n_crash]
    #: packed crash-pred words (pad_search output only)
    det_cpredw: np.ndarray | None = None   # int32 [n_det_pad, CW]
    crash_cpredw: np.ndarray | None = None  # int32 [n_crash_pad, CW]
    #: dead-value quotient table (attach_reductions / pad_search)
    dead_from: np.ndarray | None = None    # int32 [VT]
    dead_lo: int = 0
    dead_tok: int = 0
    masked: bool = False
    mask_has_crash: bool = False
    dedup: bool = False


def split_rows(seq: OpSeq):
    """Partition OpSeq rows into determinate (ok) and crashed (info)."""
    ok = np.asarray(seq.ok, dtype=bool)
    det = np.nonzero(ok)[0]
    crash = np.nonzero(~ok)[0]
    return det, crash


def window_width(det_inv: np.ndarray, det_ret: np.ndarray) -> int:
    """Exact window bound: max over b of #{j >= b : inv[j] < ret[b]}.

    det rows are sorted by invocation, so the count is a searchsorted.
    Any linearized determinate op beyond the first unlinearized one b
    satisfies inv[j] < ret[b]; the window must cover all such j plus b
    itself.
    """
    n = len(det_inv)
    if n == 0:
        return 1
    # positions with inv < ret[b], among indices >= b
    upper = np.searchsorted(det_inv, det_ret, side="left")
    spans = upper - np.arange(n)
    return max(1, int(spans.max()))


def max_enabled(seq: OpSeq) -> int:
    """Upper bound on simultaneously-enabled candidates per config.

    Enabled candidates pairwise overlap in real time (each invoked before
    every other's return), and pairwise-intersecting intervals on a line
    share a common point (Helly, d=1), so the count is bounded by the
    history's max concurrency — crashed ops stay open forever and are
    counted by the sweep in history.max_concurrency.
    """
    events = []
    for i in range(len(seq)):
        events.append((int(seq.inv[i]), 1))
        if int(seq.ret[i]) != INF_RET:
            events.append((int(seq.ret[i]), -1))
    events.sort()
    cur = peak = 0
    for _, d in events:
        cur += d
        peak = max(peak, cur)
    return max(1, peak)


def encode_search(seq: OpSeq) -> EncodedSearch:
    det_idx, crash_idx = split_rows(seq)
    det_inv = np.asarray(seq.inv, dtype=np.int64)[det_idx]
    det_ret64 = np.asarray(seq.ret, dtype=np.int64)[det_idx]

    W = window_width(det_inv, det_ret64)
    C = max_enabled(seq)

    n_det = len(det_idx)
    n_crash = len(crash_idx)

    def i32(a):
        return np.asarray(a, dtype=np.int32)

    det = EncodedSearch(
        det_f=i32(seq.f[det_idx]),
        det_v1=i32(seq.v1[det_idx]),
        det_v2=i32(seq.v2[det_idx]),
        det_inv=i32(np.minimum(det_inv, INF32)),
        det_ret=i32(np.minimum(det_ret64, INF32)),
        suffix_min_ret=np.zeros(0, dtype=np.int32),  # filled below
        crash_f=i32(seq.f[crash_idx]),
        crash_v1=i32(seq.v1[crash_idx]),
        crash_v2=i32(seq.v2[crash_idx]),
        crash_inv=i32(np.minimum(np.asarray(seq.inv, np.int64)[crash_idx],
                                 INF32)),
        n_det=n_det,
        n_crash=n_crash,
        window=W,
        concurrency=C,
    )
    # suffix minima of det returns; suffix_min_ret[i] = min(ret[i:]), with
    # suffix_min_ret[n] = +inf
    sfx = np.full(n_det + 1, INF32, dtype=np.int32)
    for i in range(n_det - 1, -1, -1):
        sfx[i] = min(int(det.det_ret[i]), int(sfx[i + 1]))
    det.suffix_min_ret = sfx
    return det


def attach_reductions(es: EncodedSearch, seq: OpSeq, model: ModelSpec,
                      must_pred: dict | None, *,
                      dedup: bool = True) -> EncodedSearch:
    """Attach the phase-2 reduction payload to an EncodedSearch.

    ``must_pred`` is the prepass's row-index predecessor map
    (HB/constraint forced + canonical edges, plus dpor's duplicate-op
    edges) — split here into det-position / crash-index tables the
    kernels' ``expand_mask`` consumes.  ``dedup`` additionally builds
    the dead-value quotient table (decompose/canonical.py) when the
    model family and value range allow.  Mutates and returns ``es``.
    """
    det_rows, crash_rows = split_rows(seq)
    if must_pred:
        det_pos_of = {int(r): p for p, r in enumerate(det_rows)}
        crash_of = {int(r): c for c, r in enumerate(crash_rows)}
        dmp = np.full((es.n_det, MASK_PREDS), -1, np.int32)
        # unsigned: crash index 63 (MAX_CRASH - 1) sets bit 63, which
        # does not fit a signed int64
        dcp = np.zeros(es.n_det, np.uint64)
        cmp_ = np.full((es.n_crash, MASK_PREDS), -1, np.int32)
        ccp = np.zeros(es.n_crash, np.uint64)
        any_mask = False
        has_crash_pred = False
        for dst, srcs in must_pred.items():
            dp = sorted(det_pos_of[s] for s in srcs if s in det_pos_of)
            cp = 0
            for s in srcs:
                c = crash_of.get(s)
                if c is not None:
                    cp |= 1 << c
            if not dp and not cp:
                continue
            dp = dp[-MASK_PREDS:]  # keep the latest (binding longest)
            if dst in det_pos_of:
                p = det_pos_of[dst]
                dmp[p, :len(dp)] = dp
                dcp[p] = cp
            else:
                c = crash_of[dst]
                cmp_[c, :len(dp)] = dp
                ccp[c] = cp
            any_mask = True
            has_crash_pred = has_crash_pred or bool(cp)
        if any_mask:
            es.det_mpred, es.det_cpred = dmp, dcp
            es.crash_mpred, es.crash_cpred = cmp_, ccp
            es.masked = True
            es.mask_has_crash = has_crash_pred
    if dedup and model.state_width == 1:
        from ..decompose.canonical import NEVER_DEAD, dead_value_cutoffs

        dv = dead_value_cutoffs(seq, model)
        if dv is not None:
            lo, hi = dv.value_range()
            span = hi - lo + 1
            if span <= DEAD_TABLE_MAX:
                t = np.full(span, NEVER_DEAD, np.int32)
                for v, c in dv.cutoffs.items():
                    # compared-but-never-written values sit outside
                    # the candidate span by design: states never hold
                    # them, so they need no entry
                    if lo <= v < lo + span:
                        t[v - lo] = min(c, NEVER_DEAD)
                es.dead_from = t
                es.dead_lo = lo
                es.dead_tok = dv.token
                es.dedup = True
    return es


def _pack_cpred(bits: np.ndarray | None, n_rows: int,
                cw: int) -> np.ndarray:
    """uint64 per-row crash-pred bitmasks -> int32 words [n_rows, cw]."""
    out = np.zeros((n_rows, cw), np.int32)
    if bits is not None:
        b = bits.astype(np.uint64)
        for w in range(min(cw, 2)):
            out[:len(b), w] = ((b >> np.uint64(32 * w))
                               & np.uint64(0xFFFFFFFF)).astype(
                np.uint32).view(np.int32)
    return out


def pad_search(es: EncodedSearch, n_det_pad: int, n_crash_pad: int,
               dead_pad: int | None = None) -> EncodedSearch:
    """Pad arrays to static shapes (for jit caching / batching).

    The reduction planes are ALWAYS materialized here (empty = all -1
    preds / all-NEVER_DEAD table) so batch stacking and the kernel
    signature stay uniform whether or not a key carries reductions.
    ``dead_pad`` pins the dead-table width (batch callers pass the
    max over their keys so stacked shapes agree); default: this key's
    own power-of-two width."""
    from ..decompose.canonical import NEVER_DEAD

    def pad(a, n, fill):
        out = np.full(n, fill, dtype=np.int32)
        out[: len(a)] = a
        return out

    cw = max(1, n_crash_pad // 32)
    dmp = np.full((n_det_pad, MASK_PREDS), -1, np.int32)
    if es.det_mpred is not None:
        dmp[:len(es.det_mpred)] = es.det_mpred
    cmp_ = np.full((n_crash_pad, MASK_PREDS), -1, np.int32)
    if es.crash_mpred is not None:
        cmp_[:len(es.crash_mpred)] = es.crash_mpred
    if dead_pad is None:
        dead_pad = _next_pow2(len(es.dead_from)) \
            if es.dead_from is not None else 8
    dead_pad = max(8, dead_pad)
    dead = np.full(dead_pad, NEVER_DEAD, np.int32)
    if es.dead_from is not None:
        dead[:len(es.dead_from)] = es.dead_from
    return EncodedSearch(
        det_f=pad(es.det_f, n_det_pad, 0),
        det_v1=pad(es.det_v1, n_det_pad, NIL),
        det_v2=pad(es.det_v2, n_det_pad, NIL),
        det_inv=pad(es.det_inv, n_det_pad, INF32),
        det_ret=pad(es.det_ret, n_det_pad, INF32),
        suffix_min_ret=pad(es.suffix_min_ret, n_det_pad + 1, INF32),
        crash_f=pad(es.crash_f, n_crash_pad, 0),
        crash_v1=pad(es.crash_v1, n_crash_pad, NIL),
        crash_v2=pad(es.crash_v2, n_crash_pad, NIL),
        crash_inv=pad(es.crash_inv, n_crash_pad, INF32),
        n_det=es.n_det,
        n_crash=es.n_crash,
        window=es.window,
        concurrency=es.concurrency,
        det_mpred=dmp,
        det_cpredw=_pack_cpred(es.det_cpred, n_det_pad, cw),
        crash_mpred=cmp_,
        crash_cpredw=_pack_cpred(es.crash_cpred, n_crash_pad, cw),
        dead_from=dead,
        dead_lo=es.dead_lo,
        dead_tok=es.dead_tok,
        masked=es.masked,
        mask_has_crash=es.mask_has_crash,
        dedup=es.dedup,
    )


# ---------------------------------------------------------------------------
# Device kernel
# ---------------------------------------------------------------------------


def _hash_words(words, seed):
    """Vector fnv/murmur-style mix of int32 config words -> uint32.

    words: uint32 [..., w]; returns uint32 [...].
    """
    h = jnp.full(words.shape[:-1], np.uint32(seed), dtype=jnp.uint32)
    w = words.shape[-1]
    for i in range(w):
        h = (h ^ words[..., i]) * np.uint32(0x85EBCA6B)
        h = (h ^ (h >> 13)) * np.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


@dataclass(frozen=True)
class SearchDims:
    """Static kernel dimensions (jit cache key)."""

    n_det_pad: int
    n_crash_pad: int  # multiple of 32, <= 64
    window: int  # W, multiple of 32
    k: int  # successor lanes per config (>= max concurrency)
    state_width: int
    frontier: int  # F: max configs per BFS level

    @property
    def win_words(self) -> int:
        return self.window // 32

    @property
    def crash_words(self) -> int:
        return max(1, self.n_crash_pad // 32)

    @property
    def words(self) -> int:
        # p | win | crash | state
        return 1 + self.win_words + self.crash_words + self.state_width


def _pack_bits(bits, n_words):
    """bool [..., 32*n_words] -> int32 words [..., n_words]."""
    shape = bits.shape[:-1]
    b = bits.reshape(shape + (n_words, 32)).astype(jnp.uint32)
    shifts = jnp.arange(32, dtype=jnp.uint32)
    words = (b << shifts).sum(axis=-1, dtype=jnp.uint32)
    return words.astype(jnp.int32)


def _unpack_bits(words, n_words):
    """int32 words [..., n_words] -> bool [..., 32*n_words]."""
    shape = words.shape[:-1]
    w = words.astype(jnp.uint32)[..., :, None]
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (w >> shifts) & np.uint32(1)
    return bits.reshape(shape + (n_words * 32,)).astype(bool)


def _kth_bit_in_word(w, r):
    """Index of the (r+1)-th set bit of uint32 ``w`` (branchless binary
    search over chunk popcounts); garbage when w has <= r set bits —
    callers mask on the count."""
    idx = jnp.zeros_like(r)
    cur = w
    for half in (16, 8, 4, 2, 1):
        m = np.uint32((1 << half) - 1)
        lowc = lax.population_count(cur & m).astype(jnp.int32)
        go_hi = r >= lowc
        r = jnp.where(go_hi, r - lowc, r)
        idx = idx + jnp.where(go_hi, half, 0)
        cur = jnp.where(go_hi, cur >> half, cur & m)
    return idx


def _select_enabled(mask, k_out: int):
    """Indices of the first k_out set lanes of a SMALL bool mask, plus
    the count — the per-config candidate selection.  Packs the mask into
    uint32 words and extracts k-th set bits with pure ALU ops (popcount
    + branchless in-word binary search): no per-lane gathers, which cost
    ~3x more than this under vmap on both backends (the selection was
    ~70% of expand_mask with the cumsum+searchsorted form)."""
    n_lanes = mask.shape[0]
    nw = (n_lanes + 31) // 32
    pad = nw * 32 - n_lanes
    if pad:
        mask = jnp.concatenate([mask, jnp.zeros(pad, bool)])
    words = _pack_bits(mask, nw).astype(jnp.uint32)          # [nw]
    pc = lax.population_count(words).astype(jnp.int32)
    cum = jnp.cumsum(pc)
    n = cum[-1]
    cum_before = jnp.concatenate([jnp.zeros(1, jnp.int32), cum[:-1]])
    ks = jnp.arange(k_out, dtype=jnp.int32)
    wi = (cum[None, :] <= ks[:, None]).sum(axis=1).astype(jnp.int32)
    wi = jnp.minimum(wi, nw - 1)
    w = jnp.take(words, wi)
    r = jnp.maximum(ks - jnp.take(cum_before, wi), 0)
    return _kth_bit_in_word(w, r) + wi * 32, n


#: compaction implementation: "search" (cumsum + searchsorted),
#: "matrix" (one-hot reduce), or "auto" — matrix on TPU when the
#: [k_out, n] one-hot fits the element budget.  searchsorted compiles
#: to a while loop + ~15 fusions; on TPU that fixed op count floors
#: narrow levels (the compaction runs 2-3x per level), while the
#: matrix form is ~5 large VPU ops.
_COMPACT_MODE = os.environ.get("JEPSEN_TPU_COMPACT", "auto")
_COMPACT_ELEMS = int(os.environ.get("JEPSEN_TPU_COMPACT_ELEMS",
                                    str(1 << 24)))


def _backend() -> str:
    """The active JAX backend, defaulting to "cpu" when none exists
    yet (build-time selectors must never fail on an uninitialized
    backend)."""
    try:
        return jax.default_backend()
    except Exception:  # noqa: BLE001 — no backend: assume host
        return "cpu"


def _use_matrix_compact(k_out: int, n: int, batch: int = 1) -> bool:
    """``batch`` multiplies the [k_out, n] one-hot: a vmapped kernel
    (batch keys) or a vmap-over-destinations route materializes one
    instance per lane, exactly like `_use_allpairs`'s budget.

    Forced "matrix" still honors the element budget — an escalated
    frontier (width 256k was reached by the r4 wide-history fuzz)
    would otherwise ask for a >100 GB one-hot and OOM the process."""
    if _COMPACT_MODE == "matrix":
        return batch * k_out * n <= _COMPACT_ELEMS
    if _COMPACT_MODE == "search":
        return False
    backend = _backend()
    return backend == "tpu" and batch * k_out * n <= _COMPACT_ELEMS


def _compact_indices(mask, k_out: int, batch: int = 1):
    """Indices of the first k_out set lanes of a bool mask (stable), plus
    the total count.  Sort-free stream compaction; two forms with
    identical semantics (out-of-range output rows hold an arbitrary
    in-bounds index — callers mask on the count):

    * cumsum + binary-search gather — O(n + k log n);
    * one-hot matrix reduce — O(k*n) FLOPs but a handful of large ops
      (picked on TPU at narrow widths, where op COUNT is the floor).

    ``batch`` is the memory-budget hint for callers whose instance gets
    vmapped (the form choice is static per call site)."""
    csum = jnp.cumsum(mask.astype(jnp.int32))
    n = mask.shape[0]
    targets = jnp.arange(1, k_out + 1, dtype=jnp.int32)
    if _use_matrix_compact(k_out, n, batch):
        # rank[i] = 1-based rank of lane i among set lanes (0 if unset);
        # each target rank matches exactly one lane, so the masked
        # iota-reduce recovers its index (unmatched targets sum to 0 —
        # in-bounds, masked by the count downstream)
        rank = jnp.where(mask, csum, 0)
        onehot = rank[None, :] == targets[:, None]
        idx = (onehot * jnp.arange(n, dtype=jnp.int32)[None, :]).sum(
            axis=1)
        return idx.astype(jnp.int32), csum[-1]
    idx = jnp.searchsorted(csum, targets, side="left")
    return jnp.minimum(idx, n - 1).astype(jnp.int32), csum[-1]


#: dominance-pass window: each sorted row is tested for domination
#: against this many predecessors.  Misses past the window keep
#: redundant configs (wasted work), never drop reachable ones.
_DOM_WINDOW = 8


def _pw_parts(cfgs, dims: SearchDims):
    """(hash over the non-crash words, crash popcount) per row.

    The dominance sort groups rows by (p, window, state) — the crash
    words are excluded from the hash so every crash variant of one
    det-configuration lands in the same bucket, ordered small-mask-first
    by the popcount key."""
    u = cfgs.astype(jnp.uint32)
    a = 1 + dims.win_words
    b = a + dims.crash_words
    pw = jnp.concatenate([u[:, :a], u[:, b:]], axis=1)
    pwh = _hash_words(pw, 0x9E3779B1)
    popc = lax.population_count(u[:, a:b]).sum(
        axis=1, dtype=jnp.uint32)
    return pwh, popc


def _sort_dominance(pwh, popc, valid, cfgs, M: int, dims: SearchDims,
                    R: int = _DOM_WINDOW):
    """Sort rows so equal-(p, win, state) configs group together with
    smaller crash masks first, then drop every row *dominated* by an
    earlier row: same (p, win, state) and the earlier row's crash mask
    a subset of this row's.

    Soundness: crashed ops never block other ops (ret = +inf) and are
    never required to linearize, so any completion of the dominated row
    is a completion of the dominator — dropping the dominated row can
    never lose a reachable goal, and a frontier that dies without one
    still proves invalidity.  Domination is decided on FULL word
    equality + a real subset test (hashes only order), so a collision
    can only *miss* a drop, never cause a wrong one.  A dominator that
    was itself dropped is fine: ⊆ is transitive, so a kept row
    dominates transitively.

    Sort keys are (pw-hash, [crash-popcount | full-hash bits], iota):
    identical rows tie on 57 hash bits and so sort ADJACENT (the o=1
    window is exact dedup, modulo a ~2^-57 collision that merely keeps
    a duplicate), and any dominator of a row sorts earlier (equal
    pw-hash, smaller-or-equal popcount in the second key's top bits).
    Two reaches of the prune:

      * a backward window of R rows (nearby dominators, exact dups);
      * the row's RUN FIRST (run = maximal span of equal (p, win,
        state) words): the run's minimum-popcount row, tested at any
        distance — this is what keeps huge crash-variant buckets from
        retaining duplicates of their minimal masks.

    Returns (kept, sorted_cfgs, perm) — perm maps sorted rows to input
    rows (callers use it to detect which survivors came from which
    input block)."""
    big = np.uint32(0xFFFFFFFF)
    h2 = _hash_words(cfgs.astype(jnp.uint32), 0x7FEB352D)
    k1 = jnp.where(valid, pwh, big)
    # one packed secondary key: popcount (<= 64, 7 bits) above 25 bits
    # of the full-config hash — popcount-ascending within a pw bucket
    # (dominators first), identical rows adjacent on 32+25 hash bits.
    # A valid row's key2 top bits are < 127 << 25 so the all-ones
    # invalid marker still sorts strictly last.
    k2 = jnp.where(valid, (popc << np.uint32(25)) | (h2 >> np.uint32(7)),
                   big)
    _s1, _s2, perm = lax.sort(
        (k1, k2, jnp.arange(M, dtype=jnp.int32)), num_keys=2)
    svalid = jnp.take(valid, perm)
    scfgs = jnp.take(cfgs, perm, axis=0)
    a = 1 + dims.win_words
    b = a + dims.crash_words
    spw = jnp.concatenate([scfgs[:, :a], scfgs[:, b:]], axis=1)
    scr = scfgs[:, a:b].astype(jnp.uint32)
    drop = jnp.zeros(M, bool)
    for o in range(1, R + 1):
        eq = jnp.all(spw[o:] == spw[:-o], axis=1)
        sub = jnp.all((scr[:-o] & ~scr[o:]) == 0, axis=1)
        d = svalid[:-o] & eq & sub
        drop = drop | jnp.concatenate([jnp.zeros(o, bool), d])
    # run-first domination at any distance
    iota = jnp.arange(M, dtype=jnp.int32)
    boundary = jnp.concatenate(
        [jnp.ones(1, bool), jnp.any(spw[1:] != spw[:-1], axis=1)])
    starts = lax.cummax(jnp.where(boundary, iota, 0))
    fcr = jnp.take(scr, starts, axis=0)
    fdom = (jnp.all((fcr & ~scr) == 0, axis=1) & (iota != starts)
            & jnp.take(svalid, starts))
    drop = drop | fdom
    return svalid & ~drop, scfgs, perm


def _allpairs_dominance(cfgs, valid, dims: SearchDims):
    """EXACT dominance/dedup prune as one [M, M] comparison — the
    TPU-shaped alternative to `_sort_dominance`.

    The sort pipeline compiles to hundreds of tiny ops (bitonic stages,
    windowed compares, run-first gathers) whose fixed per-op overhead
    floors the on-chip level cost (~1.3 ms/level at F=16..256 measured,
    docs/tpu/r4/tpubench_resweep.jsonl) no matter how narrow the live
    frontier is.  This form is a handful of LARGE elementwise ops: for
    every pair (i, j), row i is dropped when a valid row j has the same
    (p, window, state) words and j's crash mask is a subset of i's —
    strictly, or with identical rows tie-broken to the lowest index.

    Unlike the sorted prune (window R=8 + run-first: may KEEP dominated
    rows), this is exact, so it can only shrink levels further — the
    soundness argument of `_sort_dominance` applies unchanged, and
    domination is decided on full words (hashes are never trusted).

    Returns kept over the INPUT row order (no permutation): callers
    compact against the original cfgs, and block-origin tests are plain
    index-range tests.  O(M^2 * WORDS) work and [M, M] intermediates:
    meant for the narrow rungs (S <= ~8k) where the op-count floor —
    not FLOPs — dominates; the driver picks per backend/width."""
    M = cfgs.shape[0]
    u = cfgs.astype(jnp.uint32)
    a = 1 + dims.win_words
    b = a + dims.crash_words
    pw = jnp.concatenate([u[:, :a], u[:, b:]], axis=1)
    cr = u[:, a:b]
    # pairwise equal (p, window, state): fold word compares into [M, M]
    eq_pw = jnp.ones((M, M), bool)
    for w in range(pw.shape[1]):
        col = pw[:, w]
        eq_pw &= col[:, None] == col[None, :]
    # pairwise crash-mask subset (j's ⊆ i's) and equality
    sub = jnp.ones((M, M), bool)   # sub[i, j]: cr_j subset of cr_i
    eq_cr = jnp.ones((M, M), bool)
    for w in range(cr.shape[1]):
        col = cr[:, w]
        sub &= (col[None, :] & ~col[:, None]) == 0
        eq_cr &= col[:, None] == col[None, :]
    iota = jnp.arange(M, dtype=jnp.int32)
    identical = eq_pw & eq_cr
    strict = eq_pw & sub & ~eq_cr
    dom = valid[None, :] & (strict
                            | (identical & (iota[None, :] < iota[:, None])))
    return valid & ~jnp.any(dom, axis=1)


#: dominance-prune implementation: "sort" (windowed sorted prune),
#: "allpairs" (exact [M,M] prune), or "auto" — allpairs on TPU at
#: S <= _ALLPAIRS_MAX rows (where per-op overhead, not FLOPs, floors
#: the level cost), sort everywhere else
_DOMINANCE_MODE = os.environ.get("JEPSEN_TPU_DOMINANCE", "auto")
_ALLPAIRS_MAX = int(os.environ.get("JEPSEN_TPU_ALLPAIRS_MAX", "8192"))
#: cap on batch * M * M elements for a vmapped all-pairs prune — the
#: pairwise masks are [batch, M, M]; past ~256M bools the intermediates
#: stop fitting comfortably between fusions
_ALLPAIRS_ELEMS = int(os.environ.get("JEPSEN_TPU_ALLPAIRS_ELEMS",
                                     str(1 << 28)))


def _use_allpairs(M: int, batch: int = 1) -> bool:
    """Decide the prune implementation for an M-row site.  Called at
    kernel BUILD time only (the builders hoist the result), so the
    decision is always consistent with the cache key computed from the
    same module state."""
    if _DOMINANCE_MODE == "allpairs":
        return batch * M * M <= _ALLPAIRS_ELEMS
    if _DOMINANCE_MODE == "sort":
        return False
    backend = _backend()
    return (backend == "tpu" and M <= _ALLPAIRS_MAX
            and batch * M * M <= _ALLPAIRS_ELEMS)


def _prune_rows(cfgs, valid, M: int, dims: SearchDims,
                use_allpairs: bool):
    """Dominance prune over M rows — the ONE dispatch point shared by
    the single-device, batch, and sharded kernels.  Returns (kept,
    cfgs_out, origin): origin[i] is the input row behind output row i
    (identity for the order-preserving all-pairs path, the sort
    permutation otherwise), so block-origin tests work uniformly."""
    if use_allpairs:
        return (_allpairs_dominance(cfgs, valid, dims), cfgs,
                jnp.arange(M, dtype=jnp.int32))
    pwh, popc = _pw_parts(cfgs, dims)
    return _sort_dominance(pwh, popc, valid, cfgs, M, dims)


def _level_mask(pieces, op_args, frontier, alive):
    """Run the mask phase (enabled candidates + model steps + goal test)
    over a frontier, with the per-level shared table slice."""
    base, sargs = _slice_tables(op_args, frontier, alive,
                                w2p=pieces["w2p"])
    return pieces["expand_mask"](frontier, alive, base, *sargs)


def _succ_block(pieces, frontier, validf, cand2, ns2, cap: int, K: int,
                batch: int = 1):
    """Compact the [F*K] valid lane mask to ``cap`` survivors and build
    their packed successor words.  ``batch`` is the vmap memory-budget
    hint for the compaction."""
    F = frontier.shape[0]
    vsrc, n_valid = _compact_indices(validf, cap, batch)
    row = vsrc // K
    src_cfg = jnp.take(frontier, row, axis=0)
    src_lane = jnp.take(cand2.reshape(F * K), vsrc)
    sw = ns2.shape[-1]
    src_state = jnp.take(ns2.reshape(F * K, sw), vsrc, axis=0)
    cvalid = jnp.arange(cap) < n_valid
    ccfgs, _p2s = pieces["succ"](src_cfg, src_lane, src_state)
    return ccfgs, cvalid, n_valid


def build_search_step_fn(model: ModelSpec, dims: SearchDims,
                         batch: int = 1, *, masked: bool = False,
                         masked_crash: bool = False,
                         dedup: bool = False,
                         telemetry: bool = False):
    """Compile one *slice* of the frontier search for a (model, dims) pair.

    ``batch`` is a hint for the dominance-prune selector only: a vmapped
    instance multiplies every [M, M] all-pairs intermediate by the batch
    size, so the selector needs it to stay inside the memory budget.
    ``masked``/``dedup`` emit the phase-2 reduction checks
    (see _make_kernel_pieces); the signature is identical either way —
    unreduced callers pass inert tables.

    Level-synchronous search where a level's depth counts DETERMINATE
    (:ok) linearizations only; crashed (:info) ops linearize *within* a
    level via an inner closure loop.  Per level:

      1. expand the frontier (mask phase: enabled candidates + model
         steps + goal test on every lane);
      2. crash closure: while any crash successor survives, merge crash
         successors into the level (sort + dominance prune) and
         re-expand — at most n_crash+1 rounds closes the level under
         crash linearization (each genuinely new config adds a crash
         bit), and levels with no enabled crash candidate (the common
         case) skip the loop entirely;
      3. expand determinate successors into the next level (sort +
         dominance prune).

    Co-locating every crash variant of a configuration in one level is
    what makes the dominance prune (`_sort_dominance`) possible — under
    the old depth-counts-everything scheme the variants sat at different
    depths and the crash-subset dimension exploded the frontier (8.5x
    more configs and ~40x wider levels on the 10k-op bench history).
    Depth remains a function of the configuration (d = p + popcount(win),
    crash bits excluded), so dedup still never needs to cross levels and
    there is no global visited table.

    The search state (frontier, count, status, configs, max_depth, ovf) is
    an explicit *carry* passed in and returned, and each call runs at most
    ``lvl_cap`` BFS levels: long searches are driven as a sequence of
    bounded device calls from the host.  This is load-bearing on the axon
    TPU backend, whose worker kills any single execution running past its
    watchdog (~60 s); it also makes the carry a natural checkpoint
    (SURVEY.md §5.4's device-side frontier checkpoint) and turns
    ``budget``/``bail`` into runtime scalars so every budget shares one
    compiled program.

    status: -1 running, 2 valid, 1 frontier died out (invalid; sound iff
    not overflowed), 0 unknown.  The final -1 -> verdict mapping happens
    host-side in the slice driver.
    """
    K = dims.k
    F = dims.frontier
    W = dims.window
    S = 4 * F
    pieces = _make_kernel_pieces(model, dims, masked=masked,
                                 masked_crash=masked_crash,
                                 dedup=dedup, telemetry=telemetry)
    # prune implementation per site, decided at BUILD time (consistent
    # with the cache keys, which carry _dominance_key())
    ap_cl = _use_allpairs(2 * F, batch)
    ap_det = _use_allpairs(S, batch)

    def step(det_f, det_v1, det_v2, det_inv, det_ret, sfx_min,
             crash_f, crash_v1, crash_v2, crash_inv, det_mpred,
             det_cpredw, crash_mpred, crash_cpredw, dead_from,
             n_det, n_crash, dead_lo, dead_tok,
             budget, lvl_cap, bail,
             frontier, count, status, configs, max_depth, ovf):
        # telemetry builds thread the per-level aux counter block
        # (obs/telemetry.py schema) through the loop carry and return
        # it as a 7th output; the block is write-only — nothing reads
        # it back, so verdicts stay byte-identical on/off
        carry0 = (frontier, count, status, configs, max_depth, ovf,
                  jnp.int32(0))
        if telemetry:
            carry0 = carry0 + (jnp.zeros((TELE_ROWS, TELE_COLS),
                                         jnp.int32),)
        op_args = (det_f, det_v1, det_v2, det_inv, det_ret, sfx_min,
                   crash_f, crash_v1, crash_v2, crash_inv, det_mpred,
                   det_cpredw, crash_mpred, crash_cpredw, dead_from,
                   n_det, n_crash, dead_lo, dead_tok)

        def mask_phase(frontier, alive):
            return _level_mask(pieces, op_args, frontier, alive)

        def succ_block(frontier, validf, cand2, ns2, cap: int):
            return _succ_block(pieces, frontier, validf, cand2, ns2,
                               cap, K, batch)

        def cond(c):
            _, count, status, configs, _, ovf, lvl = c[:7]
            go = ((status == -1) & (count > 0) & (configs < budget)
                  & (lvl < lvl_cap))
            # when a wider re-run is coming (bail), don't waste time on a
            # truncated (unsound-for-invalid) frontier
            return go & ~(bail & ovf)

        def body(c):
            frontier, count, status, configs, max_depth, ovf, lvl = c[:7]
            tele = c[7] if telemetry else None
            # entry snapshot: if THIS level overflows under bail, the
            # level is not committed and the carry exits at the last
            # clean state — the wider re-run resumes with zero lost
            # levels (the old behavior re-ran every level since the
            # slice began)
            f_in, c_in, cfg_in, md_in, ovf_in = (frontier, count,
                                                 configs, max_depth, ovf)
            alive = jnp.arange(F) < count

            mp = mask_phase(frontier, alive)
            valid2, cand2, ns2, goal2 = mp[:4]
            kil = mp[4].sum() if telemetry else None
            ded = mp[5].sum() if telemetry else None
            found = jnp.any(goal2)
            crash_any = jnp.any(valid2 & (cand2 >= W))

            # --- crash closure (within-level) --------------------------
            def cl_cond(cc):
                it, progress = cc[8], cc[9]
                first = it == 0
                return ((first & crash_any)
                        | (~first & progress & (it < n_crash + 1)))

            def cl_body(cc):
                (frontier, count, valid2, cand2, ns2, _goal2, configs,
                 ovf, it, _pr, found) = cc[:11]
                alive = jnp.arange(F) < count
                cvalidf = (valid2 & (cand2 >= W)).reshape(F * K)
                # crash successors are capped at F rows (not S): they
                # merge back into a <= F-row level, so more than F of
                # them overflows the level anyway — and the merge sort
                # stays at 2F rows instead of 5F
                ccfgs, cvalid, n_valid = succ_block(
                    frontier, cvalidf, cand2, ns2, F)
                ovf = ovf | (n_valid > F)
                merged = jnp.concatenate([frontier, ccfgs], axis=0)
                mvalid = jnp.concatenate([alive, cvalid])
                kept, scfgs, origin = _prune_rows(merged, mvalid, 2 * F,
                                                  dims, ap_cl)
                src, new_count = _compact_indices(kept, F, batch)
                new_frontier = jnp.take(scfgs, src, axis=0)
                ovf = ovf | (new_count > F)
                new_count = jnp.minimum(new_count, F)
                # progress iff any successor-block row survived the
                # merge (input rows >= F).  A merge that only DROPPED
                # existing rows does not require another round:
                # surviving rows' crash successors were all generated
                # and merged this round, and dropped rows are covered by
                # their dominators — the level is closed.
                progress = jnp.any(kept & (origin >= F))
                # configs is NOT bumped here: closure-added rows are
                # part of this level and the det phase counts the closed
                # level's rows once — counting per closure round would
                # inflate the figure (and eat the budget) k+1 times on
                # k-round levels, losing comparability with the host
                # checkers' per-config counts
                # re-expand so the carried expansion always aligns with
                # the (sorted, compacted) frontier rows the det phase
                # will gather from
                alive2 = jnp.arange(F) < new_count
                mp2 = mask_phase(new_frontier, alive2)
                v2, c2, n2, g2 = mp2[:4]
                found = found | jnp.any(g2)
                out = (new_frontier, new_count, v2, c2, n2, g2,
                       configs, ovf, it + 1, progress, found)
                if telemetry:
                    # accumulate closure-round mask kills / dedup folds
                    out = out + (cc[11] + mp2[4].sum(),
                                 cc[12] + mp2[5].sum())
                return out

            # progress starts False: the first iteration is gated on
            # crash_any, and an unentered loop must exit "closed"
            cc0 = (frontier, count, valid2, cand2, ns2, goal2, configs,
                   ovf, jnp.int32(0), jnp.bool_(False), found)
            if telemetry:
                cc0 = cc0 + (kil, ded)
            ccout = lax.while_loop(cl_cond, cl_body, cc0)
            (frontier, count, valid2, cand2, ns2, goal2, configs, ovf,
             _it, pr_exit, found) = ccout[:11]
            if telemetry:
                kil, ded = ccout[11], ccout[12]
            # exiting via the iteration cap while still adding rows
            # means the level was NOT proven closed under crash
            # linearization; that must degrade like an overflow
            # (escalate / unknown), never decide invalid.  Real chains
            # add a crash bit per round (length <= n_crash < cap), so
            # this only fires on pathological duplicate survival.
            ovf = ovf | pr_exit
            alive = jnp.arange(F) < count

            # --- determinate expansion to the next level ---------------
            dvalidf = (valid2 & (cand2 < W)).reshape(F * K)
            dcfgs, dvalid, n_valid = succ_block(
                frontier, dvalidf, cand2, ns2, S)
            ovf = ovf | (n_valid > S)
            kept, scfgs, _origin = _prune_rows(dcfgs, dvalid, S, dims,
                                               ap_det)
            src, new_count = _compact_indices(kept, F, batch)
            new_frontier = jnp.take(scfgs, src, axis=0)
            ovf = ovf | (new_count > F)
            new_count = jnp.minimum(new_count, F)

            configs = configs + count
            max_depth = jnp.maximum(max_depth, jnp.max(
                jnp.where(alive, frontier[:, 0], 0)))
            status = jnp.where(found, 2, status)
            # uncommit an overflowing level when a wider re-run is
            # coming (bail) and no goal was found (a found goal is
            # sound regardless: it was reached through real rows)
            revert = bail & (ovf & ~ovf_in) & ~found
            new_frontier = jnp.where(revert, f_in, new_frontier)
            new_count = jnp.where(revert, c_in, new_count)
            configs = jnp.where(revert, cfg_in, configs)
            max_depth = jnp.where(revert, md_in, max_depth)
            out = (new_frontier, new_count, status, configs, max_depth,
                   ovf, lvl + 1)
            if telemetry:
                # one aux row per level (additive: levels past the
                # buffer fold into the last row; an uncommitted/bailed
                # level still records, flagged by overflow=1), built
                # by column index so kernel row order stays locked to
                # telemetry.COLUMNS
                cols = [None] * TELE_COLS
                cols[C_OCC] = count
                cols[C_EXP] = jnp.sum(valid2, dtype=jnp.int32)
                cols[C_KILL] = kil
                cols[C_DEDUP] = ded
                cols[C_ROUNDS] = _it
                cols[C_NEXT] = new_count
                cols[C_OVF] = (ovf & ~ovf_in).astype(jnp.int32)
                cols[C_GOAL] = found.astype(jnp.int32)
                idx = jnp.minimum(lvl, TELE_ROWS - 1)
                tele = tele.at[idx].add(jnp.stack(cols))
                out = out + (tele,)
            return out

        out = lax.while_loop(cond, body, carry0)
        if telemetry:
            return out[:6] + (out[7],)
        return out[:6]

    return step


# ---------------------------------------------------------------------------
# Mesh-sharded search — one big history's frontier across many devices
# ---------------------------------------------------------------------------


def build_sharded_search_step_fn(model: ModelSpec, dims: SearchDims,
                                 mesh, axis: str = "shard", *,
                                 masked: bool = False,
                                 masked_crash: bool = False,
                                 dedup: bool = False,
                                 telemetry: bool = False):
    """One *slice* of a search whose frontier is sharded over a mesh.

    Each device owns the hash partition ``pw_hash % D`` of the
    configuration space — the hash EXCLUDES the crash words, so every
    crash variant of one (p, window, state) configuration lands on the
    same shard and the local dominance prune (`_sort_dominance`) is
    globally complete, exactly as on a single device.  Per det level:
    devices expand their local slice, close it under crashed-op
    linearization (the closure loop routes crash successors to their
    home shard each round), then route determinate successors home and
    dominance-prune into the next level.  Termination, the goal test,
    closure progress, and overflow are `psum` reductions.  This is the
    scale-out path for histories whose levels outgrow one chip's
    frontier — the reference's analog is simply "buy a bigger JVM heap"
    (-Xmx32g, jepsen/project.clj:25).

    Like `build_search_step_fn`, the search state is an explicit carry
    and each call runs at most ``lvl_cap`` levels, so device executions
    stay bounded.  The per-device frontier slice travels as a global
    ``[D*F, WORDS]`` array sharded on its leading axis; loop-control
    scalars (status, configs, total, any_ovf, closure progress) are
    replicated (psum'd in the body, never in a cond — collectives
    inside a while cond can diverge between devices and deadlock or
    corrupt the all_to_alls; every shard must run the same number of
    closure rounds).

    dims.frontier is the PER-DEVICE frontier width.
    """
    try:
        from jax import shard_map
    except ImportError:  # pre-0.4.35 jax: the experimental home
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    K = dims.k
    F = dims.frontier
    S = 4 * F
    W = dims.window
    WORDS = dims.words
    D = mesh.shape[axis]
    # per-destination routing capacities (det successors / crash
    # successors per closure round)
    C_DET = max(64, _round_up(S // D, 32))
    C_CR = max(64, _round_up(2 * F // D, 32))

    pieces = _make_kernel_pieces(model, dims, masked=masked,
                                 masked_crash=masked_crash,
                                 dedup=dedup, telemetry=telemetry)
    # prune implementation per merge site, decided at BUILD time.  M
    # already counts every row a device can hold after routing (local F
    # + D routing buckets of C rows), and under shard_map each device
    # materializes exactly ONE [M, M] instance — so batch=1, not D
    # (ADVICE r4: batch=D made the budget test D^3*C^2 and all-pairs
    # was never selected on sharded runs even at widths where it fits,
    # which is the TPU-narrow-rung win the mode exists for).  On a
    # virtual CPU mesh all D instances share one host's RAM, but the
    # budget guards TPU HBM — hosts never select all-pairs anyway.
    ap_cl = _use_allpairs(F + D * C_CR)
    ap_det = _use_allpairs(D * C_DET)

    def route(cfgs, valid, cap: int):
        """all_to_all home-routing by pw-hash.  Returns the received
        rows + validity + a did-any-bucket-overflow flag."""
        pwh, _popc = _pw_parts(cfgs, dims)
        owner = (pwh % np.uint32(D)).astype(jnp.int32)

        def bucket(d):
            mask = valid & (owner == d)
            idx, cnt = _compact_indices(mask, cap, D)
            return jnp.take(cfgs, idx, axis=0), cnt

        send_cfgs, send_cnt = jax.vmap(bucket)(
            jnp.arange(D, dtype=jnp.int32))  # [D, cap, WORDS], [D]
        r_ovf = jnp.any(send_cnt > cap)
        send_cnt = jnp.minimum(send_cnt, cap)
        recv_cfgs = lax.all_to_all(send_cfgs, axis, 0, 0, tiled=False)
        recv_cnt = lax.all_to_all(send_cnt, axis, 0, 0, tiled=False)
        rcfgs = recv_cfgs.reshape(D * cap, WORDS)
        lane = jnp.arange(D * cap) % cap
        rvalid = lane < jnp.repeat(recv_cnt, cap)
        return rcfgs, rvalid, r_ovf

    def merge_dominance(local_cfgs, local_valid, in_cfgs, in_valid,
                        use_ap):
        """Dominance-prune the union of resident + received rows into a
        fresh F-row frontier.  Locality = globality: both inputs are
        pw-home on this shard.  (Exception: the root config starts on
        device 0 whatever its hash — at level 0 it has no siblings, so
        a missed prune there only wastes a row, never drops one.)

        Per-shard merges are narrow by construction (the global
        frontier splits D ways); ``use_ap`` is the build-time selector
        result for this site."""
        merged = jnp.concatenate([local_cfgs, in_cfgs], axis=0)
        mvalid = jnp.concatenate([local_valid, in_valid])
        kept, scfgs, origin = _prune_rows(merged, mvalid,
                                          merged.shape[0], dims, use_ap)
        src, new_count = _compact_indices(kept, F)
        new_frontier = jnp.take(scfgs, src, axis=0)
        m_ovf = new_count > F
        progress = jnp.any(kept & (origin >= local_cfgs.shape[0]))
        return new_frontier, jnp.minimum(new_count, F), m_ovf, progress

    def step_device(det_f, det_v1, det_v2, det_inv, det_ret, sfx_min,
                    crash_f, crash_v1, crash_v2, crash_inv, det_mpred,
                    det_cpredw, crash_mpred, crash_cpredw, dead_from,
                    n_det, n_crash, dead_lo, dead_tok,
                    budget, lvl_cap, bail,
                    frontier, count, status, configs, max_depth,
                    any_ovf, total):
        count = count[0]  # [1] local slice of the [D] count array

        carry0 = (frontier, count, status, configs, max_depth, any_ovf,
                  total, jnp.int32(0))
        if telemetry:
            # per-SHARD aux block: each device records its local
            # counters; the host sums shard blocks per level (levels
            # are lockstep — replicated loop control)
            carry0 = carry0 + (jnp.zeros((TELE_ROWS, TELE_COLS),
                                         jnp.int32),)
        op_args = (det_f, det_v1, det_v2, det_inv, det_ret, sfx_min,
                   crash_f, crash_v1, crash_v2, crash_inv, det_mpred,
                   det_cpredw, crash_mpred, crash_cpredw, dead_from,
                   n_det, n_crash, dead_lo, dead_tok)

        def cond(c):
            _, _, status, configs, _, any_ovf, total, lvl = c[:8]
            go = ((status == -1) & (total > 0) & (configs < budget)
                  & (lvl < lvl_cap))
            return go & ~(bail & any_ovf)

        def body(c):
            frontier, count, status, configs, max_depth, ovf, _total, \
                lvl = c[:8]
            tele = c[8] if telemetry else None
            ovf_in = ovf
            alive = jnp.arange(F) < count
            mp = _level_mask(pieces, op_args, frontier, alive)
            valid2, cand2, ns2, goal2 = mp[:4]
            kil = mp[4].sum() if telemetry else None
            ded = mp[5].sum() if telemetry else None
            found_loc = jnp.any(goal2)
            crash_any = lax.psum(
                jnp.any(valid2 & (cand2 >= W)).astype(jnp.int32),
                axis) > 0

            # --- crash closure (within-level; replicated control) ------
            def cl_cond(cc):
                it, progress = cc[8], cc[9]
                first = it == 0
                return ((first & crash_any)
                        | (~first & progress & (it < n_crash + 1)))

            def cl_body(cc):
                (frontier, count, valid2, cand2, ns2, _goal2, ovf,
                 found_loc, it, _pr) = cc[:10]
                alive = jnp.arange(F) < count
                cvalidf = (valid2 & (cand2 >= W)).reshape(F * K)
                ccfgs, cvalid, n_valid = _succ_block(
                    pieces, frontier, cvalidf, cand2, ns2, F, K)
                ovf = ovf | (n_valid > F)
                rcfgs, rvalid, r_ovf = route(ccfgs, cvalid, C_CR)
                ovf = ovf | r_ovf
                new_frontier, new_count, m_ovf, progress_loc = \
                    merge_dominance(frontier, alive, rcfgs, rvalid,
                                    ap_cl)
                ovf = ovf | m_ovf
                progress = lax.psum(progress_loc.astype(jnp.int32),
                                    axis) > 0
                alive2 = jnp.arange(F) < new_count
                mp2 = _level_mask(pieces, op_args,
                                  new_frontier, alive2)
                v2, c2, n2, g2 = mp2[:4]
                found_loc = found_loc | jnp.any(g2)
                out = (new_frontier, new_count, v2, c2, n2, g2, ovf,
                       found_loc, it + 1, progress)
                if telemetry:
                    out = out + (cc[10] + mp2[4].sum(),
                                 cc[11] + mp2[5].sum())
                return out

            cc0 = (frontier, count, valid2, cand2, ns2, goal2, ovf,
                   found_loc, jnp.int32(0), jnp.bool_(False))
            if telemetry:
                cc0 = cc0 + (kil, ded)
            ccout = lax.while_loop(cl_cond, cl_body, cc0)
            (frontier, count, valid2, cand2, ns2, goal2, ovf, found_loc,
             _it, pr_exit) = ccout[:10]
            if telemetry:
                kil, ded = ccout[10], ccout[11]
            # cap-exit while still adding rows: level not proven closed
            # — degrade like an overflow, never decide invalid
            ovf = ovf | pr_exit
            alive = jnp.arange(F) < count

            # --- determinate successors to the next level --------------
            dvalidf = (valid2 & (cand2 < W)).reshape(F * K)
            dcfgs, dvalid, n_valid = _succ_block(
                pieces, frontier, dvalidf, cand2, ns2, S, K)
            ovf = ovf | (n_valid > S)
            rcfgs, rvalid, r_ovf = route(dcfgs, dvalid, C_DET)
            ovf = ovf | r_ovf
            empty = jnp.zeros((0, WORDS), jnp.int32)
            new_frontier, new_count, m_ovf, _pr = merge_dominance(
                empty, jnp.zeros((0,), bool), rcfgs, rvalid, ap_det)
            ovf = ovf | m_ovf

            found = lax.psum(found_loc.astype(jnp.int32), axis) > 0
            configs = configs + lax.psum(count, axis)
            max_depth = jnp.maximum(max_depth, lax.pmax(jnp.max(
                jnp.where(alive, frontier[:, 0], 0)), axis))
            status = jnp.where(found, 2, status)
            total = lax.psum(new_count, axis)
            any_ovf = lax.psum(ovf.astype(jnp.int32), axis) > 0
            out = (new_frontier, new_count, status, configs, max_depth,
                   any_ovf, total, lvl + 1)
            if telemetry:
                cols = [None] * TELE_COLS
                cols[C_OCC] = count
                cols[C_EXP] = jnp.sum(valid2, dtype=jnp.int32)
                cols[C_KILL] = kil
                cols[C_DEDUP] = ded
                cols[C_ROUNDS] = _it
                cols[C_NEXT] = new_count
                cols[C_OVF] = (ovf & ~ovf_in).astype(jnp.int32)
                cols[C_GOAL] = found_loc.astype(jnp.int32)
                idx = jnp.minimum(lvl, TELE_ROWS - 1)
                out = out + (tele.at[idx].add(jnp.stack(cols)),)
            return out

        cout = lax.while_loop(cond, body, carry0)
        (frontier, count, status, configs, max_depth, any_ovf,
         total) = cout[:7]

        ret = (frontier, count[None], status, configs, max_depth,
               any_ovf, total)
        if telemetry:
            ret = ret + (cout[8],)
        return ret

    specs = (P(),) * 22
    carry_in = (P(axis), P(axis), P(), P(), P(), P(), P())
    carry_out = carry_in + ((P(axis),) if telemetry else ())
    try:
        return shard_map(step_device, mesh=mesh,
                         in_specs=specs + carry_in,
                         out_specs=carry_out, check_vma=False)
    except TypeError:  # pre-0.4.35 jax spells the knob check_rep
        return shard_map(step_device, mesh=mesh,
                         in_specs=specs + carry_in,
                         out_specs=carry_out, check_rep=False)


def _trailing_ones(w):
    """uint32 [..., n] -> per-word count of consecutive 1-bits from bit
    0 (32 when the word is all-ones): popcount((~w & -~w) - 1)."""
    inv = ~w
    lsb = inv & (~inv + np.uint32(1))
    t = lax.population_count(lsb - np.uint32(1))
    return jnp.where(inv == 0, np.uint32(32), t.astype(jnp.uint32))


def _make_kernel_pieces(model: ModelSpec, dims: SearchDims, *,
                        masked: bool = False,
                        masked_crash: bool = False,
                        dedup: bool = False,
                        telemetry: bool = False):
    """Kernel building blocks shared by the single-device, sharded, and
    batch step functions.

    ``masked`` emits the must-order linearized-predecessor check in
    ``expand_mask`` (state-space reduction phase 2): a candidate lane is
    enabled only once every must-predecessor — det positions via the
    prefix/window test ``q < p or win[q - p]``, crash indices via a
    packed-word subset test against the config's crash mask — is
    already linearized, mirroring exactly the host DFS's ``preds`` and
    the `linear` sweep's frame mask.  ``dedup`` emits the dead-value
    canonical-state rewrite (decompose/canonical.py's quotient) on
    successor states, so symmetric interleavings collapse in the
    dominance dedup BEFORE they are expanded apart.  Both default off:
    unreduced searches compile the exact pre-phase-2 kernels.

    The per-level pipeline is split so the expensive successor-word
    construction happens ONLY for compacted survivors:

      * ``expand_mask`` (vmapped over the frontier): per config, find the
        enabled candidates, step the model, and return validity + the
        chosen candidate lane + the successor model state — K lanes per
        config, but NO successor words are built;
      * the step fn compacts the [F*K] valid mask down to S rows;
      * ``succ`` (vmapped over the S survivors): build the packed
        successor words (set-bit, trailing-ones popcount, funnel shift)
        from (source config words, candidate lane, new state).

    At K=16 and S=4F this does the word construction for a quarter of
    the lanes the fused form paid for — and most candidate lanes are
    dead (narrow levels, disabled candidates, illegal steps).
    """
    out = {}
    W, K, NC = dims.window, dims.k, dims.n_crash_pad
    WW, CW, S = dims.win_words, dims.crash_words, dims.state_width
    WORDS = dims.words
    #: width of the per-level shared det-table slice (_slice_tables);
    #: capped at the table so small histories use it whole at base 0
    W2P = min(_round_up(2 * W + NC, 32), dims.n_det_pad)
    out["w2p"] = W2P
    jstep = model.jstep

    def unpack(cfg):
        p = cfg[0]
        win = _unpack_bits(cfg[1:1 + WW], WW)
        crash = _unpack_bits(cfg[1 + WW:1 + WW + CW], CW)[:NC]
        state = cfg[1 + WW + CW:]
        return p, win, crash, state

    def pack(p, win, crash, state):
        crash_pad = jnp.zeros(CW * 32, dtype=bool).at[:NC].set(crash)
        return jnp.concatenate([
            p[None].astype(jnp.int32),
            _pack_bits(win, WW),
            _pack_bits(crash_pad, CW),
            state.astype(jnp.int32),
        ])

    dedup = dedup and dims.state_width == 1

    def expand_mask_one(cfg, alive, base, det_f, det_v1, det_v2,
                        det_inv, det_ret, sfx_min, crash_f, crash_v1,
                        crash_v2, crash_inv, det_mpred, det_cpredw,
                        crash_mpred, crash_cpredw, dead_from, n_det,
                        n_crash, dead_lo, dead_tok):
        # det_* / sfx_min / det_mpred / det_cpredw are the per-level
        # W2P-entry shared slices starting at `base` (_slice_tables);
        # positions stay absolute for comparisons and are rebased only
        # for table lookups.
        p, win, crash, state = unpack(cfg)
        pos = p + jnp.arange(W, dtype=jnp.int32)
        rel = pos - base
        in_range = pos < n_det
        w_ret = jnp.where(in_range & ~win,
                          jnp.take(det_ret, rel, mode="clip"), INF32)
        w_inv = jnp.where(in_range,
                          jnp.take(det_inv, rel, mode="clip"), INF32)
        m1 = jnp.min(w_ret)
        am = jnp.argmin(w_ret)
        lanes = jnp.arange(W, dtype=jnp.int32)
        # second-min via select, not scatter (.at[am].set vmaps into a
        # serialized scatter on TPU)
        m2 = jnp.min(jnp.where(lanes == am, INF32, w_ret))
        sfx = jnp.take(sfx_min,
                       jnp.minimum(p + W, n_det) - base, mode="clip")
        m1_tot = jnp.minimum(m1, sfx)

        excl_w = jnp.where(lanes == am, m2, m1)
        excl_tot = jnp.minimum(excl_w, sfx)
        det_enabled = in_range & ~win & (w_inv < excl_tot)

        c_lanes = jnp.arange(NC, dtype=jnp.int32)
        c_enabled = (c_lanes < n_crash) & ~crash & (crash_inv < m1_tot)

        if telemetry and masked:
            # telemetry taps the PRE-mask enabled sets so the mask's
            # kill count is observable; pure reads — the search math
            # below is untouched (byte-identity fuzzed)
            pre_enabled = (det_enabled.sum(dtype=jnp.int32)
                           + c_enabled.sum(dtype=jnp.int32))

        if masked:
            # must-order mask: a lane stays enabled only once every
            # must-predecessor is linearized.  det preds q are done iff
            # q < p (inside the prefix) or q - p < W with the window
            # bit set; q >= p + W can never be linearized yet, so the
            # lane is blocked.  Crash preds are a packed-word subset
            # test against the config's crash mask.  -1 pads are < p.
            relc = jnp.clip(rel, 0, W2P - 1)
            mp = jnp.take(det_mpred, relc, axis=0)          # [W, P]
            qr = mp - p
            win_at = jnp.take(win, jnp.clip(qr, 0, W - 1))  # [W, P]
            done = (mp < p) | ((qr >= 0) & (qr < W) & win_at)
            det_enabled = det_enabled & done.all(axis=1)
            qc = crash_mpred - p                            # [NC, P]
            win_c = jnp.take(win, jnp.clip(qc, 0, W - 1))
            done_c = ((crash_mpred < p)
                      | ((qc >= 0) & (qc < W) & win_c))
            c_enabled = c_enabled & done_c.all(axis=1)
            if masked_crash:
                # crash-PRED word tests only when some edge actually
                # has a crashed source (identical crashed rows, rf off
                # anchored crashed writes) — det-only masks, the
                # common case, skip the gathers entirely
                crash_w_u = cfg[1 + WW:1 + WW + CW].astype(jnp.uint32)
                cw_u = jnp.take(det_cpredw, relc,
                                axis=0).astype(jnp.uint32)  # [W, CW]
                det_enabled = (det_enabled
                               & ((cw_u & ~crash_w_u[None, :]) == 0)
                               .all(axis=1))
                ccw_u = crash_cpredw.astype(jnp.uint32)     # [NC, CW]
                c_enabled = (c_enabled
                             & ((ccw_u & ~crash_w_u[None, :]) == 0)
                             .all(axis=1))

        enabled = jnp.concatenate([det_enabled, c_enabled])
        cand, n_enabled = _select_enabled(enabled, K)
        cand_on = jnp.arange(K) < n_enabled

        is_det = cand < W
        det_pos = jnp.clip(p + cand - base, 0, W2P - 1)
        c_id = jnp.clip(cand - W, 0, NC - 1)
        cf = jnp.where(is_det, jnp.take(det_f, det_pos),
                       jnp.take(crash_f, c_id))
        cv1 = jnp.where(is_det, jnp.take(det_v1, det_pos),
                        jnp.take(crash_v1, c_id))
        cv2 = jnp.where(is_det, jnp.take(det_v2, det_pos),
                        jnp.take(crash_v2, c_id))

        st = jnp.broadcast_to(state, (K, S))
        new_state, legal = jax.vmap(jstep)(st, cf, cv1, cv2)
        valid = alive & cand_on & legal

        if dedup:
            # dead-value canonical-state rewrite: a successor state
            # whose value every det comparer at positions < p already
            # consumed (and no crashed row ever compares) is
            # observation-equivalent to the token state — rewrite so
            # the dominance dedup collapses symmetric interleavings.
            # p (not p2) keeps the rule conservative: deadness is
            # monotone in the prefix.
            vt = dead_from.shape[0]
            v = new_state[:, 0]
            df = jnp.take(dead_from, jnp.clip(v - dead_lo, 0, vt - 1))
            is_dead = ((v >= dead_lo) & (v < dead_lo + vt)
                       & (p >= df))
            new_state = jnp.where(is_dead[:, None], dead_tok,
                                  new_state)

        # exact goal test WITHOUT successor words: a det candidate is a
        # goal iff it is the last unlinearized det (p2 >= n_det is
        # equivalent to p + popcount(win) + 1 >= n_det); a crash
        # candidate never advances p, so it is a goal only if every det
        # was already linearized.  Computed on ALL K lanes so a goal can
        # never be lost to the survivor cap, even at MAX_FRONTIER where
        # no wider re-run would come.
        remaining = n_det - (p + win.sum(dtype=jnp.int32))
        goal = valid & jnp.where(is_det, remaining <= 1, remaining <= 0)
        if not telemetry:
            return valid, cand, new_state, goal
        # per-config telemetry scalars (aux counter block, obs/
        # telemetry.py): mask-killed lanes and dead-value folds.
        # Computed only in telemetry builds — the off-mode kernel is
        # the exact pre-telemetry graph (separate cache key).
        zero = jnp.int32(0)
        if masked:
            post = (det_enabled.sum(dtype=jnp.int32)
                    + c_enabled.sum(dtype=jnp.int32))
            killed = jnp.where(alive, pre_enabled - post, zero)
        else:
            killed = zero
        if dedup:
            dedupct = jnp.where(
                alive, (valid & is_dead).sum(dtype=jnp.int32), zero)
        else:
            dedupct = zero
        return valid, cand, new_state, goal, killed, dedupct

    def succ_one(cfg, lane, ns):
        """Build one survivor's packed successor words."""
        p = cfg[0]
        win_words = cfg[1:1 + WW].astype(jnp.uint32)
        crash_words = cfg[1 + WW:1 + WW + CW].astype(jnp.uint32)

        is_d = lane < W
        d_lane = jnp.clip(lane, 0, W - 1)
        wi = d_lane >> 5
        bit = (d_lane & 31).astype(jnp.uint32)
        setmask = jnp.where(jnp.arange(WW) == wi,
                            np.uint32(1) << bit, np.uint32(0))
        nw = win_words | setmask  # window with the new bit set

        # shift = run of 1-bits from position 0, chained across words
        t = _trailing_ones(nw)  # [WW]
        shift = jnp.uint32(0)
        open_run = jnp.bool_(True)
        for i in range(WW):
            shift = shift + jnp.where(open_run, t[i], np.uint32(0))
            open_run = open_run & (t[i] == 32)

        # funnel shift right by `shift` across the word array
        s_words = (shift >> 5).astype(jnp.int32)
        s_bits = shift & np.uint32(31)
        idx = jnp.arange(WW) + s_words
        lo = jnp.take(nw, idx, mode="fill", fill_value=np.uint32(0))
        hi = jnp.take(nw, idx + 1, mode="fill",
                      fill_value=np.uint32(0))
        shifted = jnp.where(
            s_bits == 0, lo,
            (lo >> s_bits) | (hi << (np.uint32(32) - s_bits)))

        p2 = jnp.where(is_d, p + shift.astype(jnp.int32), p)
        win2 = jnp.where(is_d, shifted, win_words)

        cl = jnp.clip(lane - W, 0, NC - 1)
        csetmask = jnp.where(
            jnp.arange(CW) == (cl >> 5),
            np.uint32(1) << (cl & 31).astype(jnp.uint32),
            np.uint32(0))
        crash2 = jnp.where(is_d, crash_words,
                           crash_words | csetmask)
        cfg2 = jnp.concatenate([
            p2[None].astype(jnp.int32),
            win2.astype(jnp.int32),
            crash2.astype(jnp.int32),
            ns.astype(jnp.int32)])
        return cfg2, p2

    out["pack"] = pack
    out["expand_mask"] = jax.vmap(expand_mask_one,
                                  in_axes=(0, 0) + (None,) * 20)
    out["succ"] = jax.vmap(succ_one)
    return out


def _slice_tables(op_args, frontier, alive, *, w2p: int):
    """Per-level shared slice of the determinate-op tables.

    Every config in a BFS level shares the level's depth d = p +
    popcount(window) + popcount(crash), so prefix positions span at most
    window + n_crash and every table lookup the level performs lands in
    [min_p, min_p + 2*window + n_crash).  Slicing that strip ONCE per
    level turns every per-lane gather from an n_det_pad-entry table into
    a w2p-entry one — small enough to live in VMEM on TPU, where big-
    table gathers are the expensive lowering.  ``w2p`` is capped at
    n_det_pad by the caller, so small histories degrade to a full-table
    "slice" at base 0 and nothing changes.

    Returns (base, sliced op_args) — positions INSIDE the kernel remain
    absolute for comparisons; only table indexing is rebased.
    """
    (det_f, det_v1, det_v2, det_inv, det_ret, sfx_min, crash_f,
     crash_v1, crash_v2, crash_inv, det_mpred, det_cpredw,
     crash_mpred, crash_cpredw, dead_from, n_det, n_crash,
     dead_lo, dead_tok) = op_args
    n_det_pad = det_f.shape[0]
    p = frontier[:, 0]
    base = jnp.min(jnp.where(alive, p, INF32))
    base = jnp.clip(base, 0, n_det_pad - w2p)

    def sl(a):
        return lax.dynamic_slice(a, (base,), (w2p,))

    def sl2(a):
        return lax.dynamic_slice(a, (base, 0), (w2p, a.shape[1]))

    sfx = lax.dynamic_slice(sfx_min, (base,), (w2p + 1,))
    return base, (sl(det_f), sl(det_v1), sl(det_v2), sl(det_inv),
                  sl(det_ret), sfx, crash_f, crash_v1, crash_v2,
                  crash_inv, sl2(det_mpred), sl2(det_cpredw),
                  crash_mpred, crash_cpredw, dead_from, n_det,
                  n_crash, dead_lo, dead_tok)


_SHARDED_CACHE: dict = {}


def search_opseq_sharded(seq: OpSeq, model: ModelSpec, mesh, *,
                         axis: str = "shard",
                         budget: int = 20_000_000,
                         frontier_per_device: int = 1024,
                         deadline: float | None = None,
                         stop=None, on_slice=None,
                         lint: bool | None = None,
                         audit: bool | None = None,
                         hb: bool | None = None,
                         dpor: bool | None = None) -> dict:
    """Check one history with its frontier sharded over `mesh`.

    ``deadline``/``stop``/``on_slice(carry, dims)`` mirror
    `search_opseq`: the drive ends between slices past the deadline
    (verdict "unknown"), and every slice's carry reaches the hook.
    The sharded carry ([D*F, WORDS] frontier, [D] counts, replicated
    counters + total) is NOT `save_checkpoint`-compatible — that format
    is the single-device 6-tuple; the escalation loop here resumes
    from in-memory carries only.

    Certificates mirror `search_opseq`: greedy/trivial verdicts carry
    their ``linearization``; sharded device verdicts carry the explicit
    ``witness_dropped``/``frontier_dropped`` reasons (no shard keeps
    parent chains), so a mesh verdict is never silently witness-less;
    ``audit`` replays whatever certificate is emitted (None follows
    JEPSEN_TPU_AUDIT).  ``hb``/``dpor`` run the static prepass and
    thread the must-order/dedup planes exactly as on one device; the
    dead-token rewrite happens BEFORE shard routing, so every copy of
    a collapsed state still hashes to the same home shard and the
    local dominance prune stays globally complete."""
    from ..analyze.audit import maybe_audit
    from ..analyze.dpor import resolve_dpor
    from ..analyze.hb import attach, maybe_hb
    from ..analyze.lint import maybe_lint

    maybe_lint(seq, model, lint)
    hbres = maybe_hb(seq, model, hb, dpor)

    def finish(out: dict) -> dict:
        return maybe_audit(seq, model, attach(out, hbres), audit)

    if hbres is not None and hbres.decided is not None:
        return _tele.emit_decided(
            maybe_audit(seq, model, dict(hbres.decided), audit),
            hbres=hbres)
    es = encode_search(seq)
    if es.n_det == 0 and es.n_crash == 0:
        return finish({"valid": True, "configs": 0, "max_depth": 0,
                       "engine": "trivial", "linearization": []})
    if greedy_witness(seq, model):
        return finish({"valid": True, "configs": es.n_det,
                       "max_depth": es.n_det,
                       "engine": "greedy-witness",
                       "linearization": greedy_linearization(seq)})
    if es.window > MAX_WINDOW or es.n_crash > MAX_CRASH:
        from .linear import check_opseq_linear

        out = check_opseq_linear(seq, model, deadline=deadline,
                                 cancel=stop, lint=False, hb=hb,
                                 dpor=dpor)
        out["engine"] = "host-linear(fallback)"
        return finish(out)

    dims = choose_dims(es, model, frontier=frontier_per_device)
    if resolve_dpor(dpor):
        attach_reductions(es, seq, model,
                          hbres.must_pred if hbres is not None
                          else None, dedup=True)
    esp = pad_search(es, dims.n_det_pad, dims.n_crash_pad)
    _masked, _mcrash, _dedup, _vt = _reduction_key(esp)
    D = mesh.shape[axis]
    tele_on = _tele.enabled()
    acc = _tele.SearchTelemetry("device-sharded") if tele_on else None
    resume = None
    while True:
        bail = dims.frontier < MAX_FRONTIER
        mesh_key = (tuple(mesh.shape.items()),
                    tuple(d.id for d in mesh.devices.flat))
        key = (model.name, dims, axis, mesh_key, _dominance_key(),
               _masked, _mcrash, _dedup, _vt, tele_on)
        fn = _SHARDED_CACHE.get(key)
        _kc_record(fn is not None)
        if fn is None:
            # full cache-key coords, like every other route's span —
            # K007 (analyze/devlint.py) flags a device-sharded compile
            # span that only names the frontier as coord drift
            with _tele.compile_span(engine="device-sharded",
                                    shards=D, frontier=dims.frontier,
                                    n_det_pad=dims.n_det_pad,
                                    n_crash_pad=dims.n_crash_pad,
                                    window=dims.window, k=dims.k,
                                    masked=_masked,
                                    masked_crash=_mcrash,
                                    dedup=_dedup, vt=_vt,
                                    model=model.name,
                                    model_init=int(model.init[0]),
                                    model_width=model.state_width):
                fn = jax.jit(build_sharded_search_step_fn(
                    model, dims, mesh, axis, masked=_masked,
                    masked_crash=_mcrash, dedup=_dedup,
                    telemetry=tele_on))
            _SHARDED_CACHE[key] = fn
        args = search_args(esp, es)
        if resume is not None:
            carry0 = tuple(jnp.asarray(c) for c in resume)
        else:
            # global carry: device 0's frontier row 0 holds the root
            frontier0 = np.zeros((D * dims.frontier, dims.words),
                                 np.int32)
            frontier0[0] = _init_config(dims, model)
            count0 = np.zeros(D, np.int32)
            count0[0] = 1
            carry0 = (jnp.asarray(frontier0), jnp.asarray(count0),
                      jnp.int32(-1), jnp.int32(0), jnp.int32(0),
                      jnp.bool_(False), jnp.int32(1))

        def sc(carry, i):
            return int(np.asarray(carry[i]).reshape(-1)[0])

        def call(carry, lvl_cap):
            t0 = time.perf_counter()
            res = fn(*args, jnp.int32(budget), jnp.int32(lvl_cap),
                     jnp.bool_(bail), *carry)
            if acc is not None:
                # per-shard blocks [D*R, C] -> per-level shard sum
                # (levels run lockstep under replicated loop control)
                jax.block_until_ready(res)
                try:
                    t = np.asarray(res[7]).reshape(
                        D, TELE_ROWS, TELE_COLS).sum(axis=0)
                    acc.add_slice(t, t0, time.perf_counter(),
                                  frontier=dims.frontier)
                except Exception:  # noqa: BLE001 — non-addressable
                    pass           # multihost shards: totals only
                res = res[:7]
            return res

        def is_active(carry):
            return (sc(carry, 2) == -1 and sc(carry, 6) > 0
                    and sc(carry, 3) < budget
                    and not (bail and sc(carry, 5)))

        prev = [carry0]

        def track(carry):
            if not sc(carry, 5):  # clean (pre-overflow) carry
                prev[0] = carry
            if on_slice is not None:
                on_slice(carry, dims)

        carry = _drive_slices(call, carry0, is_active, on_slice=track,
                              deadline=deadline, stop=stop)
        status = sc(carry, 2)
        configs = sc(carry, 3)
        ovf = bool(sc(carry, 5))
        total = sc(carry, 6)
        timed_out = ((deadline is not None
                      and time.perf_counter() > deadline)
                     or (stop is not None and stop.is_set()))
        if status == -1:
            status = (UNKNOWN if ovf else INVALID) if total <= 0 \
                else UNKNOWN
        if (status == UNKNOWN and ovf and not timed_out
                and dims.frontier < MAX_FRONTIER):
            # escalate, resuming from the last clean carry: each
            # device's frontier block zero-pads from F to F' rows
            new_f = _grid_width(dims.frontier * 4)
            resume = _widen_sharded_carry(prev[0], D, dims.frontier,
                                          new_f)
            dims = SearchDims(**{**dims.__dict__, "frontier": new_f})
            continue
        break
    out = {"valid": _STATUS[status],
           "configs": configs,
           "max_depth": int(np.asarray(carry[4]).reshape(-1)[0]),
           "engine": f"device-sharded-x{mesh.shape[axis]}",
           "frontier_per_device": dims.frontier}
    # certificate contract (satellite of the phase-2 PR): the mesh
    # route states WHY a verdict ships without a witness/frontier,
    # exactly like the single-device engine — and the audit pass can
    # therefore replay it (W002 would flag a certificate-less verdict)
    if out["valid"] is True:
        out["witness_dropped"] = WITNESS_DROPPED_DEVICE
    elif out["valid"] is False:
        out["frontier_dropped"] = FRONTIER_DROPPED_DEVICE
    _tele.finalize_result(out, acc, hbres=hbres)
    return finish(out)


# ---------------------------------------------------------------------------
# Host driver
# ---------------------------------------------------------------------------

_KERNEL_CACHE: dict = {}

#: compiled-kernel cache accounting across get_kernel/get_batch_kernel/
#: the sharded cache — the bucketed batch scheduler's bench evidence
#: that steady-state runs never retrace (a memoized kernel costs a dict
#: lookup; a miss costs a trace + XLA compile)
KERNEL_CACHE_STATS = {"hits": 0, "misses": 0}


def kernel_cache_stats() -> dict:
    """Snapshot of the process-lifetime kernel-cache counters."""
    return dict(KERNEL_CACHE_STATS)


def _kc_record(hit: bool) -> None:
    """One kernel-cache lookup, counted in BOTH sinks: the legacy
    process dict (bucket_batch deltas, bench rows) and the flight-
    recorder registry (/metrics jtpu_kernel_cache_total)."""
    KERNEL_CACHE_STATS["hits" if hit else "misses"] += 1
    _M_KCACHE.inc(event="hit" if hit else "miss")

#: initial BFS levels per device call; the driver adapts from here so
#: each call lands near _SLICE_TARGET_S seconds of device time (axon
#: kills executions past its ~60 s watchdog; slices also amortize to
#: near-zero overhead on fast backends)
_SLICE_LEVELS0 = int(os.environ.get("JEPSEN_TPU_SLICE_LEVELS", "32"))
_SLICE_TARGET_S = float(os.environ.get("JEPSEN_TPU_SLICE_TARGET_S", "2.0"))
_SLICE_MAX = 16384

#: per-slice trace lines on stderr (width, cap, wall, live rows, configs,
#: depth) — the r4 10k wedge gave ZERO visibility into which slice hung;
#: with this on, the last trace line IS the diagnosis
_TRACE_SLICES = os.environ.get("JEPSEN_TPU_TRACE_SLICES", "") not in ("",
                                                                      "0")


def _trace(msg: str) -> None:
    if _TRACE_SLICES:
        print(f"slice: {msg}", file=sys.stderr, flush=True)


_SLICE_HARD_S: float | None = None


def _slice_hard_s() -> float:
    """Hard bound on a single device execution's predicted wall time.

    The axon worker kills executions past its ~60 s watchdog and the
    kill wedges the tunnel for every later client (docs/perf-notes.md
    round 4).  On TPU the level cap is clamped so a slice predicted
    from the measured per-level rate stays well under that; hosts get
    no bound (a long CPU slice is merely slow)."""
    global _SLICE_HARD_S
    if _SLICE_HARD_S is None:
        env = os.environ.get("JEPSEN_TPU_SLICE_HARD_S")
        if env:
            _SLICE_HARD_S = float(env)
        else:
            backend = _backend()
            _SLICE_HARD_S = 20.0 if backend == "tpu" else float("inf")
    return _SLICE_HARD_S


def _adapt_lvl_cap(lvl_cap: int, dt: float,
                   target_s: float | None = None) -> int:
    """Grow/shrink the per-call level cap toward the target slice time.

    The x16 rung matters on the axon TPU with the pallas engine:
    per-level cost drops to the µs scale, and a x4-only ramp from 32
    levels pays ~6 dispatches (x ~14 ms tunnel floor each) before the
    cap covers a deep search — enough overhead to lose a ~0.1 s-scale
    verdict race on dispatch alone."""
    t = _SLICE_TARGET_S if target_s is None else target_s
    if dt < t / 16:
        return min(lvl_cap * 16, _SLICE_MAX)
    if dt < t / 4:
        return min(lvl_cap * 4, _SLICE_MAX)
    if dt < t / 2:
        return min(lvl_cap * 2, _SLICE_MAX)
    if dt > t * 2:
        return max(lvl_cap // 2, 8)
    return lvl_cap


def _drive_slices(call, carry, is_active, *, on_slice=None,
                  deadline: float | None = None, stop=None):
    """Shared host loop for the batch and sharded kernels.  (The
    single-device path has its own driver inside ``_run_kernel``: it
    re-keys the kernel between slices as the frontier width adapts,
    which this fixed-kernel loop cannot express.)

    ``call(carry, lvl_cap)`` runs one bounded device slice;
    ``is_active(carry)`` says whether another slice is needed;
    ``on_slice(carry)`` is the checkpoint hook.  ``deadline``
    (perf_counter clock) / ``stop`` (threading.Event) end the drive
    between slices with the carry as-is — still-active carries map to
    an "unknown" verdict in the callers.  The first slice's wall time
    includes trace+compile, so it never feeds cap adaptation."""
    from .. import obs

    lvl_cap = _SLICE_LEVELS0
    first = True
    while True:
        t0 = time.perf_counter()
        with obs.span("device.slice", cat="device", levels=lvl_cap,
                      first=first):
            carry = call(carry, lvl_cap)
            jax.block_until_ready(carry)
        dt = time.perf_counter() - t0
        _tele.record_device_seconds(dt)
        if on_slice is not None:
            on_slice(carry)
        if not is_active(carry):
            return carry
        if deadline is not None and time.perf_counter() > deadline:
            return carry
        if stop is not None and stop.is_set():
            return carry
        if not first:
            lvl_cap = _adapt_lvl_cap(lvl_cap, dt)
        first = False


def _round_up(x: int, m: int) -> int:
    return ((max(1, x) + m - 1) // m) * m


def _init_config(dims: SearchDims, model: ModelSpec) -> np.ndarray:
    """Root configuration words: p=0, empty window/crash masks, init
    state."""
    cfg = np.zeros(dims.words, np.int32)
    cfg[1 + dims.win_words + dims.crash_words:] = np.asarray(
        model.init, np.int32)
    return cfg


def _init_carry(dims: SearchDims, model: ModelSpec):
    """Fresh single-device search carry (also the checkpoint format)."""
    frontier = np.zeros((dims.frontier, dims.words), np.int32)
    frontier[0] = _init_config(dims, model)
    return (frontier, np.int32(1), np.int32(-1), np.int32(0),
            np.int32(0), np.bool_(False))


def _widen_carry(carry, old_f: int, new_f: int):
    """Zero-pad a carry's frontier from old_f to new_f rows (frontier
    escalation without restarting the search)."""
    frontier = np.zeros((new_f, np.asarray(carry[0]).shape[1]), np.int32)
    frontier[:old_f] = np.asarray(carry[0])
    return (frontier,) + tuple(np.asarray(c) for c in carry[1:])


def _widen_sharded_carry(carry, d: int, old_f: int, new_f: int):
    """Widen a sharded carry's global [D*F, WORDS] frontier to
    [D*F', WORDS], keeping each device's rows in its own block."""
    fr = np.asarray(carry[0]).reshape(d, old_f, -1)
    fr2 = np.zeros((d, new_f, fr.shape[2]), np.int32)
    fr2[:, :old_f] = fr
    return (fr2.reshape(d * new_f, -1),) + tuple(
        np.asarray(c) for c in carry[1:])


def _dominance_key():
    """Everything the prune/compaction selectors depend on — part of
    the kernel cache key so a mode flip (tests; env overrides) can't
    reuse a kernel built for the other implementation."""
    backend = _backend()
    return (_DOMINANCE_MODE, _ALLPAIRS_MAX, _ALLPAIRS_ELEMS,
            _COMPACT_MODE, _COMPACT_ELEMS, backend)


#: level-kernel implementation: "xla" (build_search_step_fn),
#: "pallas" (pallas_level's fused level-loop kernel), or "auto" —
#: pallas on TPU whenever the dims/model are eligible (the narrow,
#: depth-dominated regime where the XLA body's op-count floor costs
#: ~1.3 ms/level), xla everywhere else
_ENGINE_MODE = os.environ.get("JEPSEN_TPU_ENGINE", "auto")
#: sticky fallback: the first Mosaic lowering failure on real hardware
#: must cost one rebuilt slice, not the bench tier (the pallas path's
#: first chip contact happens inside a live tunnel window)
_PALLAS_BROKEN = False

#: the ACTIVE single-device slice driver's cumulative "any slice
#: executed on pallas" flag (thread-local: the competition checker
#: races the device leg in a thread).  save_checkpoint reads it so a
#: checkpoint written mid-run records the search's real engine
#: history; None outside a driver.
_RUN_PALLAS = threading.local()


def _use_pallas(model: ModelSpec, dims: SearchDims, *,
                masked: bool = False, dedup: bool = False) -> bool:
    if _ENGINE_MODE == "xla" or _PALLAS_BROKEN:
        return False
    from . import pallas_level

    if not pallas_level.eligible(model, dims, masked=masked,
                                 dedup=dedup):
        return False
    if _ENGINE_MODE == "pallas":
        return True
    backend = _backend()
    return backend == "tpu"


def _reduction_key(esp: EncodedSearch | None) -> tuple:
    """(masked, dedup, dead-table width) — the phase-2 part of every
    kernel cache key.  The dead table's width is a traced SHAPE, so two
    histories with different widths cannot share a compiled kernel
    even when both have dedup off (the inert table still traces)."""
    if esp is None:
        return (False, False, False, 8)
    vt = esp.dead_from.shape[0] if esp.dead_from is not None else 8
    return (bool(esp.masked), bool(esp.mask_has_crash),
            bool(esp.dedup), int(vt))


def get_kernel(model: ModelSpec, dims: SearchDims, *,
               masked: bool = False, masked_crash: bool = False,
               dedup: bool = False, vt: int = 8,
               telemetry: bool = False):
    use_p = _use_pallas(model, dims, masked=masked, dedup=dedup)
    key = (model.name, dims, _dominance_key(), masked, masked_crash,
           dedup, vt, telemetry, "pallas" if use_p else "xla")
    fn = _KERNEL_CACHE.get(key)
    _kc_record(fn is not None)
    if fn is None:
        # a miss is a trace + XLA compile: the device.compile span is
        # the cold-start tax's trace evidence (the hit path is a dict
        # get and never enters here)
        # FULL cache-key coordinates (model descriptor + phase-2 flags
        # included): fleet/warmup.py reconstructs this exact kernel
        # from the recorded span, and analyze/devlint.py's K007 check
        # verifies the coord set against its static cache-key model
        with _tele.compile_span(engine="pallas" if use_p else "xla",
                                frontier=dims.frontier,
                                n_det_pad=dims.n_det_pad,
                                n_crash_pad=dims.n_crash_pad,
                                window=dims.window, k=dims.k,
                                masked=masked, masked_crash=masked_crash,
                                dedup=dedup, vt=vt,
                                model=model.name,
                                model_init=int(model.init[0]),
                                model_width=model.state_width):
            if use_p:
                from . import pallas_level

                # off-TPU the pallas kernel runs in interpret mode
                # (tests; forced-engine differential fuzz) — Mosaic
                # lowering needs the hardware
                backend = _backend()
                fn = jax.jit(pallas_level.build_pallas_step_fn(
                    model, dims, interpret=backend != "tpu",
                    masked=masked, telemetry=telemetry))
            else:
                fn = jax.jit(build_search_step_fn(
                    model, dims, masked=masked,
                    masked_crash=masked_crash, dedup=dedup,
                    telemetry=telemetry))
        _KERNEL_CACHE[key] = fn
    return fn


def _strip_reductions_for_pallas(es: EncodedSearch, model: ModelSpec,
                                 dims: SearchDims) -> EncodedSearch:
    """Reduction-vs-engine priority call: where the pallas fused-loop
    kernel would be selected (narrow, depth-dominated searches on TPU
    or a forced-pallas mode), the must-order mask and dedup rewrite
    are DROPPED so the search keeps its zero-per-op-overhead engine —
    both reductions are optional prunes, and in that regime the fused
    loop's op-count win dominates anything the prune saves (see
    pallas_level's module doc).  Everywhere else the reductions stay
    and the XLA kernel emits the checks."""
    if (es.masked or es.dedup) and _use_pallas(model, dims):
        es.det_mpred = es.det_cpred = None
        es.crash_mpred = es.crash_cpred = None
        es.det_cpredw = es.crash_cpredw = None
        es.dead_from = None
        es.dead_lo = es.dead_tok = 0
        es.masked = es.mask_has_crash = es.dedup = False
    return es


def search_args(esp: EncodedSearch, es: EncodedSearch | None = None):
    """The positional device-arg tuple for the step kernels — ONE home
    for the signature (the single-device and sharded drivers consume
    it; the batch paths stack the same attributes via stack_batch).
    ``es`` supplies the true n_det/n_crash when ``esp`` is padded."""
    src = es if es is not None else esp
    # byte-counted host->device staging (obs/telemetry.py): these are
    # the argument tables the next device dispatch uploads
    _tele.record_transfer(_tele.transfer_bytes(
        (esp.det_f, esp.det_v1, esp.det_v2, esp.det_inv, esp.det_ret,
         esp.suffix_min_ret, esp.crash_f, esp.crash_v1, esp.crash_v2,
         esp.crash_inv, esp.det_mpred, esp.det_cpredw, esp.crash_mpred,
         esp.crash_cpredw, esp.dead_from)))
    return (
        jnp.asarray(esp.det_f), jnp.asarray(esp.det_v1),
        jnp.asarray(esp.det_v2), jnp.asarray(esp.det_inv),
        jnp.asarray(esp.det_ret), jnp.asarray(esp.suffix_min_ret),
        jnp.asarray(esp.crash_f), jnp.asarray(esp.crash_v1),
        jnp.asarray(esp.crash_v2), jnp.asarray(esp.crash_inv),
        jnp.asarray(esp.det_mpred), jnp.asarray(esp.det_cpredw),
        jnp.asarray(esp.crash_mpred), jnp.asarray(esp.crash_cpredw),
        jnp.asarray(esp.dead_from),
        jnp.int32(src.n_det), jnp.int32(src.n_crash),
        jnp.int32(esp.dead_lo), jnp.int32(esp.dead_tok))


def _next_pow2(x: int) -> int:
    return 1 << max(0, (x - 1)).bit_length()


def choose_dims(es: EncodedSearch, model: ModelSpec, *,
                frontier: int | None = None) -> SearchDims:
    """Pick kernel dimensions, quantized (powers of two / multiples of 32)
    so that differently-sized histories share compiled kernels."""
    W = _round_up(es.window, 32)
    NC = _round_up(es.n_crash, 32) if es.n_crash else 32
    K = _next_pow2(min(es.concurrency, W + es.n_crash))
    if frontier is None:
        # start narrow: most BFS levels are far smaller than the history;
        # the adaptive driver widens on overflow and narrows again when
        # the live frontier shrinks (on the power-of-two width grid)
        frontier = _grid_width(min(4096, (es.n_det + es.n_crash) // 8))
    return SearchDims(
        n_det_pad=max(64, _next_pow2(es.n_det)),
        n_crash_pad=NC,
        window=W,
        k=max(1, K),
        state_width=model.state_width,
        frontier=frontier,
    )


#: statuses
VALID, INVALID, UNKNOWN = 2, 1, 0
_STATUS = {2: True, 1: False, 0: "unknown"}

#: refuse device search past these (fall back to host oracle)
MAX_WINDOW = 512
MAX_CRASH = 64
#: widest shared-batch frontier rung; keys needing more go solo (the
#: solo ladder resumes from clean carries and widens to MAX_FRONTIER)
BATCH_FRONTIER_CAP = 512


#: frontier-width grid: powers of two from 64 to 256k.  Per-level cost
#: is proportional to width, so the finer grid (vs the old power-of-4
#: one) halves the cost of levels whose live width sits just past a
#: boundary — dominance pruning makes that the common case (e.g. the
#: 10k bench history peaks at ~1.2k rows: F=2048, not 4096).  The
#: adaptive driver still compiles only the widths a search visits, and
#: the persistent compile cache amortizes them across runs.
MAX_FRONTIER = 1 << 18


_WIDTH_FLOOR: int | None = None


def _width_floor() -> int:
    """Narrowest frontier rung, decided per backend (lazily — the
    backend may be pinned after import).

    CPU floor 16: near-deterministic histories (a mutex under low
    contention holds ONE live config for thousands of levels) ride the
    narrow rungs, where per-level cost tracks the frontier actually
    alive — at a floor of 64 such searches paid 64 lanes for 1 live
    row every level.  TPU floor 64: measured on-chip per-level cost is
    flat below F~64 (0.55 ms @ F=16 vs 0.67 ms @ F=64,
    docs/tpu/r4/tpubench.jsonl) — the VPU pads tiny shapes to its lane
    count anyway — while every extra rung visited costs an escalation
    bail and a 10-40 s kernel compile in a tunnel window."""
    global _WIDTH_FLOOR
    if _WIDTH_FLOOR is not None:
        return _WIDTH_FLOOR
    want = 0
    env = os.environ.get("JEPSEN_TPU_WIDTH_FLOOR")
    if env:
        try:
            v = int(env)
        except ValueError:
            v = 0  # unparsable override: fall back to the backend
        # values below the 8-row minimum (incl. 0) also fall back —
        # "0" must mean "no override", not "narrowest possible"
        want = min(v, MAX_FRONTIER) if v >= 8 else 0
    if not want:
        backend = _backend()
        want = 64 if backend == "tpu" else 16
    # snap onto the power-of-two grid (and under MAX_FRONTIER) so
    # differently-sized histories keep sharing compiled kernels
    w = 8
    while w < want:
        w *= 2
    _WIDTH_FLOOR = min(w, MAX_FRONTIER)
    return _WIDTH_FLOOR


def _grid_width(f: int) -> int:
    """Snap up to the power-of-two width grid, clamped to MAX_FRONTIER
    and floored per backend (see :func:`_width_floor`)."""
    w = _width_floor()
    while w < f and w < MAX_FRONTIER:
        w *= 2
    return w


def _run_kernel(esp: EncodedSearch, es: EncodedSearch, model: ModelSpec,
                dims: SearchDims, budget: int, *,
                escalate: bool = True, on_slice=None, resume=None,
                deadline: float | None = None, stop=None,
                used_pallas0: bool = False):
    """Drive the sliced kernel to completion with an adaptive width.

    The frontier width moves both ways on the power-of-two grid
    (escalation climbs two steps at a time, the downshift settles one):

    * a level that overflows the current width is UNCOMMITTED by the
      kernel (the ``bail`` flag): the slice exits holding the last clean
      frontier, and the search resumes two grid steps (4x) wider from
      exactly there — zero levels re-run;
    * when the live frontier shrinks well below the current width, the
      carry (live rows are prefix-compacted by the kernel) is truncated
      a grid step down, so per-level cost tracks the frontier actually
      alive rather than its high-water mark.  Deep histories alternate
      narrow valleys with rare wide bursts; without the downshift one
      burst taxes every later level at the burst's width.

    Returns (status, configs, max_depth, dims, used_pallas):
    ``used_pallas`` is True iff any slice executed on the pallas
    level-loop engine, OR ``used_pallas0`` was passed (the resumed
    checkpoint's accumulated flag — label evidence); the live value is
    mirrored into the `_RUN_PALLAS` thread-local around each on_slice
    call so checkpoint saves record it; status is finalized
    (-1 never escapes), dims reflects the final width.  ``on_slice(carry,
    dims)`` fires after every device call (the checkpoint hook);
    ``resume`` accepts a previously captured carry at ``dims.frontier``
    width.  ``deadline`` (``time.perf_counter()`` clock) stops cleanly
    with status UNKNOWN when exceeded — for time-bounded throughput runs.
    """
    args = search_args(esp, es)
    _masked, _mcrash, _dedup, _vt = _reduction_key(esp)
    carry = tuple(jnp.asarray(c) for c in
                  (resume if resume is not None
                   else _init_carry(dims, model)))
    F = dims.frontier
    lvl_cap = _SLICE_LEVELS0
    first = True
    timed_out = False
    low_streak = 0  # consecutive slices whose live width fit a lower rung
    per_lvl: float | None = None  # measured seconds/level at width F
    prev_depth = int(np.asarray(carry[4]))
    hard_s = _slice_hard_s()
    tele_on = _tele.enabled()
    acc = _tele.SearchTelemetry() if tele_on else None

    def _clamp_cap(cap: int) -> int:
        # keep a slice's PREDICTED wall under the worker watchdog; the
        # estimate tracks the current width (scaled on width changes)
        if per_lvl and per_lvl > 0 and hard_s != float("inf"):
            return max(8, min(cap, int(hard_s / per_lvl)))
        return cap

    used_pallas = used_pallas0  # any slice (incl. resumed-from runs)
    #                             ran on the pallas engine
    while True:
        bail = escalate and F < MAX_FRONTIER
        want_pallas = _use_pallas(model, dims, masked=_masked,
                                  dedup=_dedup)
        fn = get_kernel(model, dims, masked=_masked,
                        masked_crash=_mcrash, dedup=_dedup, vt=_vt,
                        telemetry=tele_on)
        _trace(f"run F={F} cap={lvl_cap} first={int(first)} "
               f"depth={prev_depth}")
        t0 = time.perf_counter()
        tele_buf = None
        # manual span (not `with`): the slice's wall is t0..dt below,
        # and the except arm re-runs the slice inside the same window
        _slice_span = obs.span("device.slice", cat="device", frontier=F,
                               levels=lvl_cap, first=first)
        _slice_span.__enter__()
        try:
            res = fn(*args, jnp.int32(budget), jnp.int32(lvl_cap),
                     jnp.bool_(bail), *carry)
            if tele_on:
                carry, tele_buf = res[:6], res[6]
            else:
                carry = res
            jax.block_until_ready(carry)
        except Exception as e:  # noqa: BLE001 — engine fallback
            global _PALLAS_BROKEN
            if _use_pallas(model, dims, masked=_masked,
                           dedup=_dedup) and not _PALLAS_BROKEN:
                # the pallas kernel failed to lower/run on this
                # backend: disable it for the process and redo the
                # slice on the XLA kernel — the carry is untouched
                # (the failed call never committed).  Its first real-
                # hardware contact happens inside a live tunnel
                # window, and a lowering bug there must cost one
                # rebuilt slice, not the bench tier.
                _PALLAS_BROKEN = True
                _trace(f"pallas kernel failed ({e!r}); falling back "
                       "to xla engine")
                fn = get_kernel(model, dims, masked=_masked,
                                masked_crash=_mcrash,
                                dedup=_dedup, vt=_vt,
                                telemetry=tele_on)
                res = fn(*args, jnp.int32(budget),
                         jnp.int32(lvl_cap), jnp.bool_(bail),
                         *carry)
                if tele_on:
                    carry, tele_buf = res[:6], res[6]
                else:
                    carry = res
                jax.block_until_ready(carry)
            else:
                raise
        finally:
            _slice_span.__exit__(None, None, None)
        # only a slice that actually EXECUTED on pallas counts (a
        # fallback flips _PALLAS_BROKEN before the redo)
        used_pallas = used_pallas or (want_pallas
                                      and not _PALLAS_BROKEN)
        dt = time.perf_counter() - t0
        _tele.record_device_seconds(dt)
        if acc is not None and tele_buf is not None:
            acc.add_slice(np.asarray(tele_buf), t0, t0 + dt,
                          frontier=F)
        if on_slice is not None:
            _RUN_PALLAS.flag = used_pallas
            try:
                on_slice(carry, dims)
            finally:
                _RUN_PALLAS.flag = None
        status = int(carry[2])
        count = int(carry[1])
        configs = int(carry[3])
        ovf = bool(carry[5])
        depth = int(carry[4])
        _trace(f"done F={F} cap={lvl_cap} dt={dt:.3f}s count={count} "
               f"configs={configs} depth={depth} ovf={int(ovf)} "
               f"status={status}")
        levels_run = depth - prev_depth
        prev_depth = depth
        if not first and levels_run > 0:
            per_lvl = dt / levels_run
        if status != -1 or count <= 0 or configs >= budget:
            break
        if deadline is not None and time.perf_counter() > deadline:
            timed_out = True
            break
        if stop is not None and stop.is_set():
            timed_out = True
            break
        if bail and ovf:
            # the kernel uncommits an overflowing level before bailing,
            # so the carry it returned IS the last clean state: resume
            # wider from right here, zero levels re-run.  climb fast
            # (x4): a growth phase that doubles per level would
            # otherwise pay a bailed slice per grid step; the downshift
            # below settles onto the tight width afterwards
            new_f = _grid_width(F * 4)
            base = tuple(carry[:5]) + (jnp.bool_(False),)
            carry = tuple(jnp.asarray(c) for c in
                          _widen_carry(base, F, new_f))
            low_streak = 0  # a burst just proved the width necessary
            # per-level cost scales with width: shrink the level cap by
            # the same ratio or the first wide slice runs lvl_cap
            # narrow-sized levels at 4x the cost (enough to blow a
            # wall-clock deadline — or the axon worker's ~60s watchdog)
            lvl_cap = max(8, lvl_cap * F // new_f)
            if per_lvl:
                per_lvl *= new_f / F  # per-level cost tracks width
            lvl_cap = _clamp_cap(lvl_cap)
            F = new_f
            dims = SearchDims(**{**dims.__dict__, "frontier": F})
            first = True  # next slice includes a compile
            continue
        if not first:
            # shorter slices while WIDE: the downshift check runs only
            # between slices, so a full-length slice at F=2048 would run
            # hundreds of post-burst narrow levels at 8x their cost
            # before the width could settle back down
            lvl_cap = _clamp_cap(_adapt_lvl_cap(
                lvl_cap, dt,
                target_s=(_SLICE_TARGET_S if F <= 512
                          else _SLICE_TARGET_S / 4)))
        first = False
        if not ovf and count > 0:
            # 4x headroom over the live width, with hysteresis: only
            # downshift after TWO consecutive slices fit the lower rung
            # (A/B'd against one-slice hysteresis: the register tier
            # thrashed 2x; see docs/perf-notes.md round 4).
            # A transient valley between wide bursts would otherwise
            # bounce the width (each bounce = a bailed slice + re-run
            # levels), which costs more than it saves — the register
            # tier thrashed 2x when the floor dropped to 16 without
            # this guard, while sustained-narrow searches (mutex) still
            # settle onto the tight width one slice later.
            # ONE grid step down, not straight to grid(4*count): the
            # overflow that sets the needed width is the EXPANSION burst
            # (successors before prune), which runs far above the pruned
            # live count — dropping to the count-derived width was
            # observed (r4 10k trace) to re-overflow within a level or
            # two, costing a bail + reclimb every few slices
            new_f = max(_grid_width(4 * count), F // 2)
            if new_f < F:
                low_streak += 1
            else:
                low_streak = 0
            if new_f < F and low_streak >= 2:
                low_streak = 0
                # live rows sit at the frontier's prefix: truncate
                carry = (carry[0][:new_f],) + tuple(carry[1:])
                # cheaper levels: grow the cap by the width ratio so
                # slice wall time stays near the target
                lvl_cap = min(_SLICE_MAX, lvl_cap * (F // new_f))
                if per_lvl:
                    per_lvl *= new_f / F
                lvl_cap = _clamp_cap(lvl_cap)
                F = new_f
                dims = SearchDims(**{**dims.__dict__, "frontier": F})
                first = True  # next slice may include a compile
    if status == -1:
        # frontier died out with no goal: invalid if we never overflowed,
        # otherwise unknown.  budget/deadline exceeded: unknown.
        if timed_out or count > 0:
            status = UNKNOWN
        else:
            status = UNKNOWN if ovf else INVALID
    return status, configs, int(carry[4]), dims, used_pallas, acc


def greedy_witness(seq: OpSeq, model: ModelSpec) -> bool:
    """Try ONE deterministic linearization host-side: ok ops in completion
    order, skipping crashed ops entirely.  Ops that returned earlier
    linearized earlier is always real-time consistent, so if every model
    step is legal this is a valid witness and the search is over — the
    O(n) analog of a DFS diving straight to the goal on a well-behaved
    history."""
    rows = sorted(range(len(seq)), key=lambda i: int(seq.ret[i]))
    state = model.init
    for i in rows:
        if not bool(seq.ok[i]):
            continue  # crashed ops may never linearize
        state = model.pystep(state, int(seq.f[i]), int(seq.v1[i]),
                             int(seq.v2[i]))
        if state is None:
            return False
    return True


def greedy_linearization(seq: OpSeq) -> list[int]:
    """The certificate behind a True `greedy_witness`: the ok rows in
    completion order — exactly the sequence the greedy replay already
    model-checked, emitted so the verdict is auditable
    (analyze/audit.py) instead of trust-me."""
    return [i for i in sorted(range(len(seq)),
                              key=lambda i: int(seq.ret[i]))
            if bool(seq.ok[i])]


#: certificate drop reasons for the device engines (the BFS keeps no
#: parent chains in HBM — by design: a frontier of millions of configs
#: times the search depth would not fit, and the user-facing checker
#: reconstructs witnesses host-side instead)
WITNESS_DROPPED_DEVICE = (
    "device-bfs keeps no parent chains; re-check with the host "
    "`linear` engine (witness_cap > 0) for a witness")
FRONTIER_DROPPED_DEVICE = (
    "device-bfs localizes the obstruction by depth/window only; "
    "Linearizable re-verifies invalid device verdicts host-side to "
    "extract the frontier")


#: sentinel distinguishing "prepass not run by the caller" from a
#: caller-supplied result (which may legitimately be None)
_HB_UNSET = object()


def search_opseq(seq: OpSeq, model: ModelSpec, *,
                 budget: int = 20_000_000,
                 dims: SearchDims | None = None,
                 on_slice=None, deadline: float | None = None,
                 stop=None, lint: bool | None = None,
                 audit: bool | None = None,
                 hb: bool | None = None,
                 dpor: bool | None = None,
                 _hbres=_HB_UNSET) -> dict:
    """Check one columnar history on device.  Returns a knossos-style map
    {"valid": True|False|"unknown", "configs": n, "max_depth": d}.

    ``on_slice(carry, dims)`` fires after every bounded device call — the
    checkpoint hook (see ``save_checkpoint``/``resume_opseq``); ``dims``
    reflects any frontier escalation, so checkpoints stay loadable.
    ``deadline`` (perf_counter clock) bounds wall time; an unexhausted
    search past it returns "unknown" with throughput still reported.
    ``stop`` (a ``threading.Event``) aborts between slices — the
    competition hook.  ``lint`` runs the O(n) well-formedness linter
    first (None follows JEPSEN_TPU_LINT; errors raise
    HistoryLintError).  Certificates: greedy/trivial verdicts carry
    their ``linearization``; device verdicts carry explicit
    ``witness_dropped``/``frontier_dropped`` reasons (the BFS keeps no
    parent chains); ``audit`` replays whatever certificate is emitted
    (None follows JEPSEN_TPU_AUDIT).

    ``hb`` (None follows JEPSEN_TPU_HB) runs the unified static
    prepass: decided histories return immediately with an audited
    certificate and zero device configs.  ``dpor`` (None follows
    JEPSEN_TPU_DPOR) threads the prepass's must-order predecessor
    tables into the ENCODING as extra packed planes and turns on the
    kernels' linearized-predecessor lane mask plus the dead-value
    canonical-state rewrite — device lanes masked exactly like the
    host DFS/frame candidate sets, symmetric states collapsed in the
    on-device dedup.  Verdict-identical by construction; off = the
    exact pre-phase-2 kernels."""
    from ..analyze.audit import maybe_audit
    from ..analyze.dpor import _M_MASK, resolve_dpor
    from ..analyze.hb import attach, maybe_hb
    from ..analyze.lint import maybe_lint

    maybe_lint(seq, model, lint)

    # _hbres: search_batch's fallback path hands over the prepass it
    # already ran per key, so the solve (and its metrics) fire once
    hbres = (maybe_hb(seq, model, hb, dpor)
             if _hbres is _HB_UNSET else _hbres)

    def finish(out: dict) -> dict:
        return maybe_audit(seq, model, attach(out, hbres), audit)

    if hbres is not None and hbres.decided is not None:
        # statically decided: no device work, but the telemetry span
        # still records observed=0 vs predicted=0 so traces (and
        # obs_guard's prune-delta check) cover decided tiers too
        return _tele.emit_decided(
            maybe_audit(seq, model, dict(hbres.decided), audit),
            hbres=hbres)

    es = encode_search(seq)
    if es.n_det == 0 and es.n_crash == 0:
        return finish({"valid": True, "configs": 0, "max_depth": 0,
                       "engine": "trivial", "linearization": []})
    if greedy_witness(seq, model):
        return finish({"valid": True, "configs": es.n_det,
                       "max_depth": es.n_det,
                       "engine": "greedy-witness",
                       "linearization": greedy_linearization(seq)})
    if es.window > MAX_WINDOW or es.n_crash > MAX_CRASH:
        # past the device encoding limits: the linear host sweep has no
        # window/crash caps and dominates the WGL DFS on exactly the
        # crash-heavy histories that land here
        from .linear import check_opseq_linear

        out = check_opseq_linear(seq, model, deadline=deadline,
                                 cancel=stop, lint=False, hb=hb,
                                 dpor=dpor)
        out["engine"] = "host-linear(fallback)"
        return finish(out)

    dims = dims or choose_dims(es, model)
    dpor_stats = None
    if resolve_dpor(dpor):
        attach_reductions(es, seq, model,
                          hbres.must_pred if hbres is not None
                          else None, dedup=True)
        _strip_reductions_for_pallas(es, model, dims)
        n_mask_rows = 0
        if es.det_mpred is not None:
            n_mask_rows = int(
                ((es.det_mpred[:, 0] >= 0)
                 | (es.det_cpred != 0)).sum()
                + ((es.crash_mpred[:, 0] >= 0)
                   | (es.crash_cpred != 0)).sum())
        dpor_stats = {"enabled": True, "device_masked": es.masked,
                      "device_mask_rows": n_mask_rows,
                      "dedup": es.dedup}
        if es.masked:
            _M_MASK.inc(dpor_stats["device_mask_rows"],
                        site="device-rows")
    esp = pad_search(es, dims.n_det_pad, dims.n_crash_pad)
    status, configs, max_depth, dims, used_pallas, tele_acc = \
        _run_kernel(esp, es, model, dims, budget, on_slice=on_slice,
                    deadline=deadline, stop=stop)
    out = {"valid": _STATUS[status], "configs": configs,
           "max_depth": max_depth,
           "engine": _engine_label(used_pallas),
           "frontier": dims.frontier,
           "window": es.window, "concurrency": es.concurrency}
    if dpor_stats is not None:
        out["dpor"] = dpor_stats
    if out["valid"] is True:
        out["witness_dropped"] = WITNESS_DROPPED_DEVICE
    elif out["valid"] is False:
        out["frontier_dropped"] = FRONTIER_DROPPED_DEVICE
    _tele.finalize_result(out, tele_acc, hbres=hbres)
    return finish(out)


def check_competition(seq: OpSeq, model: ModelSpec, *,
                      budget: int = 20_000_000,
                      max_configs: int = 50_000_000,
                      lint: bool | None = None,
                      audit: bool | None = None,
                      hb: bool | None = None,
                      dpor: bool | None = None) -> dict:
    """Race the exact host checkers against the device BFS search; the
    first conclusive verdict wins and retires the losers.

    The knossos `competition` analog (jepsen/src/jepsen/checker.clj:122-126
    selects between :linear, :wgl and :competition — the latter races
    algorithms and takes whichever finishes first).  The portfolio here is
    complementary three ways: the WGL host DFS can lucky-dive to a witness
    on well-behaved histories; the `linear` host sweep (checker/linear.py —
    memoized, dominance-pruned) kills invalid histories whose crash-subset
    space strands both DFS and BFS; the device BFS brute-forces wide state
    spaces at device throughput.  Host legs run in daemon threads (they
    release the GIL only at cancellation checks, but the device thread
    spends its time blocked in XLA executions, which do release it).

    The winner's CERTIFICATE propagates with its verdict: host legs
    carry real witnesses/frontiers (the wgl DFS for free, the linear
    sweep under a bounded witness_cap), the device leg explicit drop
    reasons; ``audit`` replays whichever certificate won (None follows
    JEPSEN_TPU_AUDIT).
    """
    import threading

    from . import seq as seqmod
    from .linear import DEFAULT_WITNESS_CAP, check_opseq_linear

    # one lint at the race's boundary; the legs run lint-free (they
    # share the seq, and a loser leg raising HistoryLintError inside a
    # daemon thread would be swallowed as a leg error)
    from ..analyze.audit import maybe_audit
    from ..analyze.lint import maybe_lint

    maybe_lint(seq, model, lint)

    def finish(out: dict) -> dict:
        return maybe_audit(seq, model, out, audit)

    # the host DFS memoizes each config TWICE (visited + parent_of) as a
    # (bigint linearized-set, state tuple) pair: ~n/8 bytes of mask plus
    # a couple hundred bytes of object overhead per copy.  Cap its
    # configs to a ~4 GB footprint so the loser thread cannot eat the
    # machine while the device grinds a long history (the reference
    # answers this with -Xmx32g; we'd rather lose the race than the
    # host).
    per_cfg = 2 * (len(seq) // 8 + 200)
    max_configs = min(max_configs, 4_000_000_000 // per_cfg)

    done = threading.Event()
    lock = threading.Lock()
    result: dict = {}

    def submit(r: dict, engine: str) -> bool:
        """Atomically claim the race for a CONCLUSIVE verdict."""
        if r.get("valid") == "unknown":
            return False
        with lock:
            if result:
                return False
            result.update(r)
            result["engine"] = engine
            done.set()
            return True

    def wgl_leg():
        try:
            r = seqmod.check_opseq(seq, model, max_configs=max_configs,
                                   cancel=done, lint=False, hb=hb,
                                   dpor=dpor)
        except Exception:  # noqa: BLE001 — loser errors must not win
            return
        submit(r, "competition(host-wgl)")

    def linear_leg():
        try:
            # a bounded witness_cap: the leg's verdict stays the same,
            # but a win carries a real certificate instead of a drop
            r = check_opseq_linear(seq, model, max_configs=max_configs,
                                   cancel=done,
                                   witness_cap=DEFAULT_WITNESS_CAP,
                                   lint=False, hb=hb, dpor=dpor)
        except Exception:  # noqa: BLE001
            return
        submit(r, "competition(host-linear)")

    threads = [threading.Thread(target=wgl_leg, daemon=True,
                                name="competition-host-wgl"),
               threading.Thread(target=linear_leg, daemon=True,
                                name="competition-host-linear")]
    for t in threads:
        t.start()

    es = encode_search(seq)
    if es.window > MAX_WINDOW or es.n_crash > MAX_CRASH:
        # the device search would itself fall back to a host DFS; let the
        # two host legs decide it (linear has no encoding limits)
        for t in threads:
            t.join()
        with lock:
            if result:
                out = dict(result)
                out["engine"] += "+device-skipped(encoding limits)"
                return finish(out)
        return {"valid": "unknown", "configs": 0,
                "engine": "competition(exhausted; device encoding limits)"}

    dev = search_opseq(seq, model, budget=budget, stop=done,
                       lint=False, hb=hb, dpor=dpor)
    submit(dev, "competition(device)")
    if not result:
        # device inconclusive: the race is only over when the hosts' own
        # bounded searches finish too (knossos competition waits for a
        # winner, not for the first to give up)
        for t in threads:
            t.join()
    else:
        done.set()  # retire still-running losers
        for t in threads:
            t.join(timeout=5.0)
    with lock:
        if result:
            return finish(dict(result))
    # all inconclusive (budgets exhausted)
    return {**dev, "engine": "competition(exhausted)"}


# ---------------------------------------------------------------------------
# Search checkpointing (SURVEY §5.4 — device-side frontier checkpoint)
# ---------------------------------------------------------------------------


def history_digest(seq: OpSeq, model: ModelSpec) -> str:
    """Identity of (history, model) — resuming against the wrong history
    would silently produce a garbage verdict.  The model's PARAMETERS
    bind too, not just its name: register(0) and register(7) share a
    name but give different verdicts."""
    import hashlib

    h = hashlib.sha256()
    for a in (seq.f, seq.v1, seq.v2, seq.inv, seq.ret, seq.ok):
        h.update(np.ascontiguousarray(np.asarray(a)).tobytes())
    h.update(model.name.encode())
    h.update(repr((model.init, model.state_width)).encode())
    return h.hexdigest()


def _engine_label(used_pallas: bool, resumed: bool = False,
                  base: str = "device-bfs") -> str:
    """One place assembles the engine strings (three emit sites)."""
    tags = [t for t, on in (("pallas", used_pallas),
                            ("resumed", resumed)) if on]
    return base + (f"({','.join(tags)})" if tags else "")


def save_checkpoint(path: str, carry, dims: SearchDims, model: ModelSpec,
                    budget: int, seq: OpSeq | None = None) -> None:
    """Persist a live search carry (as delivered to ``on_slice``).

    The BFS carry is the *entire* search state — frontier configs plus
    progress counters — so a checkpoint is one npz.  The reference's
    knossos search has no analog: a killed -Xmx32g JVM search restarts
    from scratch (jepsen/project.clj:25).  Pass ``seq`` to bind the
    checkpoint to its history so `resume_opseq` can refuse a mismatch.

    The checkpoint also carries ``used_pallas`` — whether any slice of
    the SEARCH SO FAR executed on the pallas engine (the engine label
    of a cross-window accumulated verdict must not forget a window
    that ran on-chip pallas just because a later CPU window saved
    last).  The truth comes from the ACTIVE slice driver via a
    thread-local (`_run_kernel` maintains it, seeded with the resumed
    checkpoint's flag) — never from re-reading the target file, which
    callers like bench.py write through a tmp-path + rename and which
    would therefore never show the prior state."""
    c = [np.asarray(x) for x in carry]
    digest = history_digest(seq, model) if seq is not None else ""
    used_p = getattr(_RUN_PALLAS, "flag", None)
    if used_p is None:
        # called outside a live slice driver (tests, tools): nothing
        # has executed, so nothing ran on pallas — recording mere
        # *eligibility* here would make a verdict resumed from this
        # checkpoint claim pallas execution that never happened
        used_p = False
    np.savez_compressed(
        path, frontier=c[0], count=c[1], status=c[2], configs=c[3],
        max_depth=c[4], ovf=c[5], budget=np.int64(budget),
        model=np.bytes_(model.name.encode()),
        digest=np.bytes_(digest.encode()),
        used_pallas=np.bool_(used_p),
        dims=np.asarray([dims.n_det_pad, dims.n_crash_pad, dims.window,
                         dims.k, dims.state_width, dims.frontier],
                        np.int64))


def load_checkpoint(path: str):
    """Returns (carry, dims, model_name, budget, digest, used_pallas)."""
    z = np.load(path)
    d = z["dims"]
    dims = SearchDims(n_det_pad=int(d[0]), n_crash_pad=int(d[1]),
                      window=int(d[2]), k=int(d[3]), state_width=int(d[4]),
                      frontier=int(d[5]))
    carry = (z["frontier"], z["count"][()], z["status"][()],
             z["configs"][()], z["max_depth"][()], z["ovf"][()])
    digest = bytes(z["digest"][()]).decode() if "digest" in z else ""
    used_p = bool(z["used_pallas"][()]) if "used_pallas" in z else False
    return (carry, dims, bytes(z["model"][()]).decode(), int(z["budget"]),
            digest, used_p)


def resume_opseq(seq: OpSeq, model: ModelSpec, path: str, *,
                 on_slice=None, deadline: float | None = None,
                 stop=None) -> dict:
    """Continue a checkpointed `search_opseq` from `save_checkpoint`.

    ``deadline``/``stop`` bound the continued run exactly as in
    `search_opseq` — a resumed search interrupted AGAIN is still a
    checkpoint (the bench's cross-tunnel-window accumulation relies on
    this)."""
    carry, dims, model_name, budget, digest, prior_pallas = \
        load_checkpoint(path)
    if model_name != model.name:
        raise ValueError(
            f"checkpoint is for model {model_name!r}, got {model.name!r}")
    if digest and digest != history_digest(seq, model):
        raise ValueError(
            "checkpoint was taken on a different history (digest mismatch)")
    es = encode_search(seq)
    esp = pad_search(es, dims.n_det_pad, dims.n_crash_pad)
    status, configs, max_depth, dims, used_pallas, tele_acc = \
        _run_kernel(esp, es, model, dims, budget, on_slice=on_slice,
                    resume=carry, deadline=deadline, stop=stop,
                    used_pallas0=prior_pallas)
    out = {"valid": _STATUS[status], "configs": configs,
           "max_depth": max_depth,
           "engine": _engine_label(used_pallas, resumed=True),
           "frontier": dims.frontier,
           "window": es.window, "concurrency": es.concurrency}
    return _tele.finalize_result(out, tele_acc)


# ---------------------------------------------------------------------------
# Checker wrapper (drop-in for checker/linearizable, checker.clj:114-139)
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# Batched search — vmap over independent keys, sharded over a device mesh
# ---------------------------------------------------------------------------


def batch_dims(ess: list[EncodedSearch], model: ModelSpec, *,
               frontier: int = 32) -> SearchDims:
    """Common static dims covering every history in the batch.  The
    shared frontier starts narrow — every key pays every lane of it
    each level, so the batch is sized for the typical key, not the
    worst: keys that outgrow a rung escalate TOGETHER through 4x-wider
    batch rungs (search_batch's ladder) up to BATCH_FRONTIER_CAP, and
    only past that fall back to solo adaptive-ladder runs."""
    W = _round_up(max(e.window for e in ess), 32)
    ncr = max(e.n_crash for e in ess)
    NC = _round_up(ncr, 32) if ncr else 32
    K = _next_pow2(max(1, min(max(e.concurrency for e in ess),
                              W + ncr)))
    nd = max(64, _next_pow2(max(e.n_det for e in ess)))
    return SearchDims(
        n_det_pad=nd, n_crash_pad=NC, window=W, k=K,
        state_width=model.state_width, frontier=frontier)


def batch_dead_pad(ess: list[EncodedSearch]) -> int:
    """The common dead-table width a batch pads to (stacked shapes
    must agree; keys without a table stack the inert 8-entry one)."""
    w = 8
    for e in ess:
        if e.dead_from is not None:
            w = max(w, _next_pow2(len(e.dead_from)))
    return w


def get_batch_kernel(model: ModelSpec, dims: SearchDims,
                     batch: int = 256, allow_pallas: bool = True,
                     masked: bool = False, masked_crash: bool = False,
                     dedup: bool = False, vt: int = 8,
                     telemetry: bool = False):
    # the batch size reaches the built HLO only through the prune and
    # compaction SELECTIONS — the two dominance sites (closure merge at
    # 2F, det expansion at 4F) and the four matrix-compaction sites
    # (crash/det succ-blocks over F*K lanes; closure-merge and
    # det-expansion compacts) — so key on those booleans, not the raw
    # count: a ladder whose live set shrinks between rungs keeps
    # sharing compiled kernels, while a kernel built under a small
    # batch can never be reused by a larger batch whose one-hot
    # [batch, k_out, n] exceeds the element budget (ADVICE r4: that
    # reuse could OOM the TPU — or pessimize the small batch)
    F, K = dims.frontier, dims.k
    S = 4 * F
    use_p = allow_pallas and _use_pallas(model, dims, masked=masked,
                                         dedup=dedup)
    sel = (_use_allpairs(2 * F, batch),
           _use_allpairs(S, batch),
           _use_matrix_compact(F, F * K, batch),
           _use_matrix_compact(S, F * K, batch),
           _use_matrix_compact(F, 2 * F, batch),
           _use_matrix_compact(F, S, batch))
    key = ("batch", model.name, dims, sel, _dominance_key(),
           masked, masked_crash, dedup, vt, telemetry,
           "pallas" if use_p else "xla")
    fn = _KERNEL_CACHE.get(key)
    _kc_record(fn is not None)
    if fn is None:
        with _tele.compile_span(engine="pallas" if use_p else "xla",
                                batch=batch, frontier=dims.frontier,
                                n_det_pad=dims.n_det_pad,
                                n_crash_pad=dims.n_crash_pad,
                                window=dims.window, k=dims.k,
                                masked=masked,
                                masked_crash=masked_crash,
                                dedup=dedup, vt=vt,
                                model=model.name,
                                model_init=int(model.init[0]),
                                model_width=model.state_width):
            if use_p:
                # vmap of the fused level-loop kernel: the pallas
                # batching rule runs one grid program per key, each a
                # whole level loop with zero per-op overhead (verified
                # row-equal to the vmapped XLA kernel,
                # tests/test_pallas_level.py)
                from . import pallas_level

                backend = _backend()
                base = pallas_level.build_pallas_step_fn(
                    model, dims, interpret=backend != "tpu",
                    masked=masked, telemetry=telemetry)
            else:
                base = build_search_step_fn(model, dims, batch=batch,
                                            masked=masked,
                                            masked_crash=masked_crash,
                                            dedup=dedup,
                                            telemetry=telemetry)
            fn = jax.jit(jax.vmap(
                base,
                in_axes=(0,) * 19 + (None, None, None) + (0,) * 6))
        _KERNEL_CACHE[key] = fn
    return fn


def _shard_map_target(sharding):
    """(mesh, axis) when ``sharding`` is a single-axis NamedSharding a
    batch kernel can be shard_map'd over, else (None, None).

    The bucketed scheduler's per-bucket dispatch wraps the vmapped
    batch kernel in shard_map so each device loops over ONLY its own
    lane block (a vmapped while_loop under plain GSPMD runs until the
    globally slowest lane; under shard_map the cond is local, so a
    shard whose keys resolve early goes quiet instead of spinning
    masked).  Meshes with extra axes (the DCN "keys"x"shard" layout)
    and non-addressable shards keep the device_put/GSPMD path — same
    math, compiler-chosen partitioning."""
    mesh = getattr(sharding, "mesh", None)
    spec = getattr(sharding, "spec", None)
    if mesh is None or spec is None or getattr(mesh, "empty", False):
        return None, None
    if not getattr(sharding, "is_fully_addressable", False):
        return None, None
    names = [n for n in spec if n is not None]
    if len(spec) != 1 or len(names) != 1 \
            or not isinstance(names[0], str):
        return None, None
    axis = names[0]
    try:
        if len(mesh.shape) != 1 or mesh.shape[axis] < 1:
            return None, None
    except (KeyError, TypeError):
        return None, None
    return mesh, axis


def get_sharded_batch_kernel(model: ModelSpec, dims: SearchDims, *,
                             batch: int, mesh, axis: str,
                             masked: bool = False,
                             masked_crash: bool = False,
                             dedup: bool = False, vt: int = 8,
                             telemetry: bool = False):
    """The mesh twin of :func:`get_batch_kernel`: the vmapped XLA batch
    kernel wrapped in ``shard_map`` over the key axis, so every device
    runs ``batch / D`` lanes at the bucket's tight dims and loops only
    until ITS lanes resolve.  ``batch`` must be mesh-divisible (the
    caller pads with inert keys).  Cached under the mesh's device set
    next to the other kernels, so steady-state bucket shapes are dict
    hits and warm-bootable (fleet/warmup.py)."""
    try:
        from jax import shard_map
    except ImportError:  # pre-0.4.35 jax: the experimental home
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    D = mesh.shape[axis]
    per = batch // D
    F, K = dims.frontier, dims.k
    S = 4 * F
    # the prune/compaction selections see the PER-SHARD lane count —
    # that is the batch the inner kernel is built at
    sel = (_use_allpairs(2 * F, per),
           _use_allpairs(S, per),
           _use_matrix_compact(F, F * K, per),
           _use_matrix_compact(S, F * K, per),
           _use_matrix_compact(F, 2 * F, per),
           _use_matrix_compact(F, S, per))
    key = ("batch-sharded", model.name, dims, sel, _dominance_key(),
           masked, masked_crash, dedup, vt, telemetry, axis, D,
           tuple(d.id for d in mesh.devices.flat))
    fn = _KERNEL_CACHE.get(key)
    _kc_record(fn is not None)
    if fn is None:
        # the span carries the FULL cache-key coordinates (per-shard
        # lanes, shard count, phase-2 flags) so fleet/warmup.py can
        # reconstruct and pre-compile exactly this kernel from a
        # recorded trace
        with _tele.compile_span(engine="xla", sharded=True, shards=D,
                                batch=per, frontier=dims.frontier,
                                n_det_pad=dims.n_det_pad,
                                n_crash_pad=dims.n_crash_pad,
                                window=dims.window, k=dims.k,
                                masked=masked,
                                masked_crash=masked_crash,
                                dedup=dedup, vt=vt,
                                model=model.name,
                                model_init=int(model.init[0]),
                                model_width=model.state_width):
            base = build_search_step_fn(model, dims, batch=per,
                                        masked=masked,
                                        masked_crash=masked_crash,
                                        dedup=dedup,
                                        telemetry=telemetry)
            vm = jax.vmap(base,
                          in_axes=(0,) * 19 + (None, None, None)
                          + (0,) * 6)
            fn = jax.jit(shard_map(
                vm, mesh=mesh,
                in_specs=(P(axis),) * 19 + (P(), P(), P())
                + (P(axis),) * 6,
                out_specs=P(axis), check_rep=False))
        _KERNEL_CACHE[key] = fn
    return fn


#: per-key array attributes, in the exact positional order of
#: build_search_step_fn's signature — the single source of truth for
#: both batch stackers
_BATCH_ARG_ATTRS = ("det_f", "det_v1", "det_v2", "det_inv", "det_ret",
                    "suffix_min_ret", "crash_f", "crash_v1", "crash_v2",
                    "crash_inv", "det_mpred", "det_cpredw",
                    "crash_mpred", "crash_cpredw", "dead_from")


def stack_batch(esps: list[EncodedSearch], *, pad_to: int | None = None):
    """Stack padded EncodedSearches along a leading key axis.  Rows past
    ``len(esps)`` (up to ``pad_to``) replicate row 0's arrays with
    n_det = n_crash = 0 — inert pad keys."""
    b = pad_to or len(esps)
    pad = b - len(esps)
    nbytes = [0]

    def st(attr):
        rows = [getattr(e, attr) for e in esps]
        rows += [rows[0]] * pad
        stacked = np.stack(rows)
        nbytes[0] += stacked.nbytes
        return jnp.asarray(stacked)

    def sc(vals):
        return jnp.asarray(np.array(list(vals) + [0] * pad, np.int32))

    out = tuple(st(a) for a in _BATCH_ARG_ATTRS) + (
        sc(e.n_det for e in esps),
        sc(e.n_crash for e in esps),
        sc(e.dead_lo for e in esps),
        sc(e.dead_tok for e in esps))
    _tele.record_transfer(nbytes[0])
    return out


def _init_batch_carry(n: int, dims: SearchDims, model: ModelSpec):
    """Stacked fresh carries for an n-key batch."""
    one = _init_config(dims, model)
    frontier = np.zeros((n, dims.frontier, dims.words), np.int32)
    frontier[:, 0] = one
    return (frontier, np.ones(n, np.int32),
            np.full(n, -1, np.int32), np.zeros(n, np.int32),
            np.zeros(n, np.int32), np.zeros(n, bool))


# ---------------------------------------------------------------------------
# kernel route registry — the static device contract's enumeration
# ---------------------------------------------------------------------------
#
# Every way a compiled search kernel can be requested is one ROUTE:
# single-device XLA, bucketed batch (vmapped), mesh-sharded batch
# (shard_map of the vmapped kernel), and the pallas fused level loop.
# ``analyze/devlint.py`` abstractly stages each route over
# representative SearchDims and walks the jaxpr for the K-codes; the
# declared fields ARE the contract the lint checks the live code
# against (donation policy, int-only dtypes, compile-span coords).


@dataclass(frozen=True)
class KernelRoute:
    """One kernel dispatch route and its device contract.

    ``build(model, dims)`` returns ``(fn, args)`` — the UNJITTED step
    callable and the exact positional example arguments the driver
    passes, so ``jax.make_jaxpr(fn)(*args)`` stages the route the way
    the driver traces it (weak types and python-scalar leaks included).
    ``request(model, dims)`` goes through the real cached getter
    (``get_kernel`` & co.), so a fresh process emits the route's
    ``device.compile`` span for the K007 coord check.

    ``donate_carry`` is the K004 policy: the slice drivers keep each
    pre-overflow carry (``prev[0]``) and re-feed it widened after a
    frontier escalation, so donating the carry buffers would hand XLA
    a buffer the host still needs — every shipped route declares
    False, and the lint flags a ``donate_argnums`` in the getter's
    ``jax.jit`` call as a contract break (and the reverse: a route
    declaring True whose jit never donates)."""

    name: str
    engine: str        # "xla" | "pallas"
    span_kind: str     # compile-span coord generation (devlint model)
    getter: str        # cache-getter function name (K004 AST anchor)
    module: str        # dotted module defining the getter
    build: object      # (model, dims) -> (fn, args) for staging
    request: object    # (model, dims) -> compiled fn via the cache
    int_only: bool = True
    donate_carry: bool = False
    carry_args: int = 6
    batched: bool = False
    sharded: bool = False


KERNEL_ROUTES: dict[str, KernelRoute] = {}


def register_route(route: KernelRoute) -> KernelRoute:
    KERNEL_ROUTES[route.name] = route
    return route


def route_sample_inputs(model: ModelSpec, dims: SearchDims, *,
                        batch: int = 0):
    """The positional example arguments a route's driver would pass at
    ``dims`` for a minimal one-op history — shared by devlint staging
    and the route builders below.  ``batch > 0`` stacks the batch-route
    form.  Returns the FULL operand tuple
    ``(*tables, budget, lvl_cap, bail, *carry)``."""
    from ..history import encode_ops, invoke_op, ok_op

    fc = model.f_codes
    try:
        names = list(fc)
    except TypeError:  # _AnyFCodes (noop model): accepts anything
        names = ["write"]
    f = next((c for c in ("write", "enqueue", "acquire")
              if c in names), names[0])
    v = 1 if f in ("write", "enqueue") else None
    seq = encode_ops([invoke_op(0, f, v), ok_op(0, f, v)], fc)
    es = encode_search(seq)
    esp = pad_search(es, dims.n_det_pad, dims.n_crash_pad)
    tail = (jnp.int32(64), jnp.int32(4), jnp.bool_(False))
    if batch:
        args = stack_batch([esp] * batch)
        carry = tuple(jnp.asarray(c)
                      for c in _init_batch_carry(batch, dims, model))
        return args + tail + carry
    args = search_args(esp, es)
    carry = tuple(jnp.asarray(c) for c in _init_carry(dims, model))
    return args + tail + carry


def _route_mesh():
    """A minimal single-axis mesh over the local devices (the sharded
    route's staging target; 1 device is a valid mesh)."""
    from jax.sharding import Mesh

    devs = jax.devices()
    return Mesh(np.array(devs[:1]), ("shard",)), "shard", 1


def _build_single(model: ModelSpec, dims: SearchDims):
    fn = build_search_step_fn(model, dims)
    return fn, route_sample_inputs(model, dims)


def _request_single(model: ModelSpec, dims: SearchDims):
    return get_kernel(model, dims)


def _build_pallas(model: ModelSpec, dims: SearchDims):
    from . import pallas_level

    fn = pallas_level.build_pallas_step_fn(
        model, dims, interpret=_backend() != "tpu")
    return fn, route_sample_inputs(model, dims)


def _request_pallas(model: ModelSpec, dims: SearchDims):
    global _ENGINE_MODE
    prev = _ENGINE_MODE
    _ENGINE_MODE = "pallas"
    try:
        return get_kernel(model, dims)
    finally:
        _ENGINE_MODE = prev


_ROUTE_BATCH = 4  # representative lane count for the batch routes


def _build_batch(model: ModelSpec, dims: SearchDims):
    base = build_search_step_fn(model, dims, batch=_ROUTE_BATCH)
    fn = jax.vmap(base, in_axes=(0,) * 19 + (None, None, None)
                  + (0,) * 6)
    return fn, route_sample_inputs(model, dims, batch=_ROUTE_BATCH)


def _request_batch(model: ModelSpec, dims: SearchDims):
    return get_batch_kernel(model, dims, batch=_ROUTE_BATCH,
                            allow_pallas=False)


def _build_sharded(model: ModelSpec, dims: SearchDims):
    try:
        from jax import shard_map
    except ImportError:  # pre-0.4.35 jax: the experimental home
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    mesh, axis, d = _route_mesh()
    per = _ROUTE_BATCH // d or 1
    base = build_search_step_fn(model, dims, batch=per)
    vm = jax.vmap(base, in_axes=(0,) * 19 + (None, None, None)
                  + (0,) * 6)
    fn = shard_map(vm, mesh=mesh,
                   in_specs=(P(axis),) * 19 + (P(), P(), P())
                   + (P(axis),) * 6,
                   out_specs=P(axis), check_rep=False)
    return fn, route_sample_inputs(model, dims, batch=per * d)


def _request_sharded(model: ModelSpec, dims: SearchDims):
    mesh, axis, d = _route_mesh()
    per = _ROUTE_BATCH // d or 1
    return get_sharded_batch_kernel(model, dims, batch=per * d,
                                    mesh=mesh, axis=axis)


register_route(KernelRoute(
    name="single-xla", engine="xla", span_kind="solo",
    getter="get_kernel", module=__name__,
    build=_build_single, request=_request_single))
register_route(KernelRoute(
    name="pallas-fused", engine="pallas", span_kind="solo",
    getter="get_kernel", module=__name__,
    build=_build_pallas, request=_request_pallas,
    # the fused kernel deliberately lowers the level fold through
    # float32 matmuls (MXU-shaped reductions in pallas_level.py), so
    # its dtype contract is "no 64-bit widening", not "int lanes only"
    int_only=False))
# the two batch routes are dispatched by the bucket scheduler, which
# registers them on import (checker/bucket.py; kernel_routes() below
# forces that import so the enumeration is always complete)


def kernel_routes() -> dict[str, KernelRoute]:
    """All registered routes (importing the bucket scheduler so its
    batch/mesh registrations are in)."""
    from . import bucket  # noqa: F401 — registers its routes on import

    return dict(KERNEL_ROUTES)


def _drive_batch_compacting(fn, esps, model: ModelSpec, dims: SearchDims,
                            budget: int, *, bail: bool = False,
                            tele_acc=None):
    """Slice driver for the vmapped batch kernel with active-key
    compaction.

    A vmapped `while_loop` runs until its SLOWEST lane finishes — already
    -resolved keys keep executing the (masked) body, so a long-tail key
    makes every finished key burn device time with it.  Between slices,
    finished keys are recorded host-side and, once the live set fits
    HALF the current lanes, the stacked args/carry are rebuilt at the
    smaller grid size (pad lanes carry status=VALID, count=0: they mask
    out immediately).  The grid steps in multiples of 32 above 32 lanes
    (pow2 below); the shrink rule (live set fits HALF the lanes on
    hosts, a QUARTER on TPU where each re-stack is a costly fresh
    compile) bounds re-traces to ~log2(n) / ~log4(n) batch sizes per
    drive, all served by the persistent compile cache.

    Returns final (status, count, configs, depth, ovf) arrays over ALL
    keys, in input order.
    """
    n = len(esps)

    fin = {}  # key -> (status, count, configs, depth, ovf)

    def grid(k: int) -> int:
        # pow2 up to 32 lanes, then multiples of 32: a 84-key batch runs
        # at 96 lanes instead of 128 (25% less padded work) while the
        # shape set stays small enough for the persistent compile cache
        if k <= 32:
            return max(4, _next_pow2(k))
        return _round_up(k, 32)

    def stack(keys, carry_rows):
        b = grid(len(keys))
        pad = b - len(keys)
        args = stack_batch([esps[k] for k in keys], pad_to=b)
        cs = []
        for j, proto in enumerate(carry_rows[0]):
            rows = [np.asarray(carry_rows[i][j]) for i in
                    range(len(keys))]
            pad_row = np.zeros_like(rows[0])
            if j == 2:
                pad_row = pad_row + VALID  # pad lanes: masked out
            cs.append(jnp.asarray(np.stack(rows + [pad_row] * pad)))
        return args, tuple(cs)

    # every re-stack is a fresh vmapped-kernel shape; an uncached
    # compile through the tunnel costs 10-90 s — far more than the
    # padded lanes it saves — so the accelerator waits for a QUARTER
    # fit (~log4(n) sizes) where hosts re-stack at HALF (~log2(n))
    shrink = 4 if _backend() == "tpu" else 2

    row0 = tuple(np.asarray(c)[0]
                 for c in _init_batch_carry(1, dims, model))
    lanes = list(range(n))  # lane position -> key id (fixed between
    # re-stacks, so carry rows and keys never misalign; retired keys
    # keep their dead lane until the next grid shrink)
    args, carry = stack(lanes, [row0] * n)

    lvl_cap = _SLICE_LEVELS0
    first = True
    while True:
        t0 = time.perf_counter()
        res = fn(*args, jnp.int32(budget), jnp.int32(lvl_cap),
                 jnp.bool_(bail), *carry)
        if tele_acc is not None:
            # per-lane aux blocks [B, R, C]: keys pace differently, so
            # only the lane-sum aggregate is meaningful — totals-only
            carry = res[:6]
            jax.block_until_ready(carry)
            tele_acc.add_totals(np.asarray(res[6]))
        else:
            carry = res
            jax.block_until_ready(carry)
        dt = time.perf_counter() - t0
        _tele.record_device_seconds(dt)
        status = np.asarray(carry[2])
        count = np.asarray(carry[1])
        configs = np.asarray(carry[3])
        depth = np.asarray(carry[4])
        ovf = np.asarray(carry[5])
        live = []  # lane indices still running
        for i, k in enumerate(lanes):
            if k in fin:
                continue
            # with bail, an overflowed lane halts inside the kernel (a
            # wider re-run is coming): it must retire here or the driver
            # would spin on it forever
            if (status[i] != -1 or count[i] <= 0
                    or configs[i] >= budget or (bail and ovf[i])):
                fin[k] = (status[i], count[i], configs[i], depth[i],
                          ovf[i])
            else:
                live.append(i)
        if not live:
            break
        if not first:
            lvl_cap = _adapt_lvl_cap(lvl_cap, dt)
        first = False
        if grid(len(live)) * shrink <= grid(len(lanes)):
            rows = [tuple(np.asarray(c)[i] for c in carry) for i in live]
            lanes = [lanes[i] for i in live]
            args, carry = stack(lanes, rows)
            first = True  # new shape: next slice may include a compile

    out = np.zeros((5, n), np.int64)
    for k, vals in fin.items():
        out[:, k] = [int(v) for v in vals]
    return (out[0].astype(np.int32), out[1].astype(np.int32),
            out[2].astype(np.int32), out[3].astype(np.int32),
            out[4].astype(bool))


def _audit_batch(seqs: list[OpSeq], model: ModelSpec,
                 results: list[dict], audit: bool) -> list[dict]:
    """Per-key certificate audit for the batch routes (one shared exit
    so every return path of `search_batch` applies the same policy;
    `search_batch` resolves the three-state flag to a bool at entry)."""
    if audit:
        from ..analyze.audit import maybe_audit

        for s, r in zip(seqs, results):
            maybe_audit(s, model, r, True)
    return results


def search_batch(seqs: list[OpSeq], model: ModelSpec, *,
                 budget: int = 2_000_000,
                 dims: SearchDims | None = None,
                 sharding=None,
                 decompose: bool = False,
                 decompose_cache=None,
                 bucket: bool | None = None,
                 lint: bool | None = None,
                 audit: bool | None = None,
                 hb: bool | None = None,
                 dpor: bool | None = None,
                 _prepass: list | None = None) -> list[dict]:
    """Check a batch of independent per-key histories in one device call.

    This is the TPU analog of jepsen.independent's bounded-pmap over
    per-key subhistories (independent.clj:247-298): the key axis becomes a
    batch dimension, vmap'd in one compiled search; pass a
    ``jax.sharding.NamedSharding`` (key axis) to spread the batch over a
    mesh — searches are embarrassingly parallel, so XLA partitions them
    with no communication beyond the verdict gather.

    ``decompose=True`` puts the canonical-hash verdict cache
    (jepsen_tpu/decompose/) in front of the batch: keys are
    canonicalized (process renaming, event-rank erasure, value
    renaming) and hashed; cached shapes return instantly, duplicate
    shapes within the batch run once, and only the remaining distinct
    shapes ride to the device.  ``decompose_cache`` is a VerdictCache,
    a jsonl path, or None for an in-memory cache (dedup only).

    ``bucket`` selects the shape-bucketed scheduler (checker/bucket.py):
    keys group by their power-of-two-rounded SearchDims bucket and each
    bucket runs at its own tight dims with pipelined host prep, instead
    of every key padding to the batch-wide max.  With a mesh
    ``sharding`` each bucket covers the mesh via ``shard_map`` at that
    bucket's dims (inert pad keys only up to mesh divisibility within
    the bucket); ``bucket=False`` pins the fused single-shape sharded
    dispatch.  ``None`` follows the JEPSEN_TPU_BATCH_BUCKETS env knob
    (default on); bucketing is verdict-identical either way; an
    explicit ``dims`` pins the fused shape.

    Per-key certificates: greedy-disposed keys carry their
    ``linearization``, host-fallback keys whatever the host engine
    emits, device-ridden keys explicit drop reasons — witnesses
    survive bucket padding/reordering because row indices always index
    the key's OWN OpSeq.  ``audit`` replays every key's certificate
    (None follows JEPSEN_TPU_AUDIT).

    ``hb`` (None follows JEPSEN_TPU_HB, default on) runs the
    happens-before pre-pass (analyze/hb.py) per key: statically decided
    keys are disposed host-side with certificates — right next to the
    greedy-witness disposal, and before any device padding is sized —
    so they never cost a device config at all.

    ``dpor`` (None follows JEPSEN_TPU_DPOR, default on) threads the
    undecided keys' must-order predecessor maps into their encodings
    as device mask planes and enables the dead-value dedup rewrite —
    the same phase-2 reductions `search_opseq` applies, batched.
    ``_prepass`` is internal: per-key must_pred maps a caller already
    computed (the post-disposal recursion), so the pre-pass never runs
    twice per key.
    """
    if not seqs:
        return []
    from ..analyze.dpor import resolve_dpor
    from ..analyze.hb import resolve_hb

    hb = resolve_hb(hb)
    dpor_on = resolve_dpor(dpor)
    if audit is None:
        from ..analyze.audit import audit_enabled

        audit = audit_enabled()
    from ..analyze.lint import (Diagnostic, HistoryLintError,
                                lint_enabled, lint_opseq)

    if lint if lint is not None else lint_enabled():
        # lint every key up front (O(total rows) numpy): errors raise
        # naming the offending key instead of shipping a malformed
        # encoding to the device
        bad: list = []
        for k, s in enumerate(seqs):
            for d in lint_opseq(s, model):
                bad.append(Diagnostic(d.code, d.severity,
                                      f"batch key {k}: {d.message}",
                                      index=d.index, process=d.process,
                                      f=d.f))
        if any(d.severity == "error" for d in bad):
            raise HistoryLintError(bad)
    if decompose:
        return _audit_batch(seqs, model, _search_batch_decomposed(
            seqs, model, budget=budget, dims=dims, sharding=sharding,
            cache=decompose_cache, bucket=bucket, hb=hb, dpor=dpor),
            audit)
    if bucket is None and dims is None and len(seqs) > 1:
        from .bucket import bucketing_enabled

        bucket = bucketing_enabled()
    if bucket and dims is None:
        if sharding is not None:
            # bucket-then-shard: each bucket covers the mesh at its
            # own tight dims (checker/bucket.py), instead of one fused
            # shape over the whole batch
            from .bucket import search_batch_sharded_bucketed

            return _audit_batch(seqs, model,
                                search_batch_sharded_bucketed(
                                    seqs, model, sharding,
                                    budget=budget, hb=hb, dpor=dpor),
                                audit)
        from .bucket import search_batch_bucketed

        return _audit_batch(seqs, model,
                            search_batch_bucketed(seqs, model,
                                                  budget=budget,
                                                  hb=hb, dpor=dpor),
                            audit)
    # greedy completion-order witnesses dispose of well-behaved keys
    # host-side in O(n), and the HB pre-pass disposes statically
    # decided keys next to them; only contentious keys ride the device
    # (undecided keys KEEP their must-order maps — the device mask)
    from ..analyze.hb import maybe_hb

    results_by_idx: dict = {}
    rest = []
    masks: list = []  # must_pred per rest key, aligned with `rest`
    hbs: list = []  # full prepass result per rest key (for the
    #               fallback path; _HB_UNSET when it didn't run here)
    for i, s in enumerate(seqs):
        r = None
        mp = _prepass[i] if _prepass is not None else None
        hbres = _HB_UNSET
        if greedy_witness(s, model):
            r = {"valid": True, "configs": s.n_must,
                 "max_depth": s.n_must,
                 "engine": "greedy-witness",
                 "linearization": greedy_linearization(s)}
        elif hb and _prepass is None:
            hbres = maybe_hb(s, model, True, dpor)
            if hbres is not None and hbres.decided is not None:
                r = dict(hbres.decided)
            elif hbres is not None and hbres.must_pred:
                mp = hbres.must_pred
        if r is not None:
            results_by_idx[i] = r
        else:
            rest.append(i)
            masks.append(mp)
            hbs.append(hbres)
    if not rest:
        return _audit_batch(seqs, model,
                            [results_by_idx[i]
                             for i in range(len(seqs))], audit)
    if results_by_idx:
        sub = search_batch([seqs[i] for i in rest], model, budget=budget,
                           dims=dims, sharding=sharding, bucket=False,
                           lint=False, audit=False, hb=False,
                           dpor=dpor, _prepass=masks)
        for i, r in zip(rest, sub):
            results_by_idx[i] = r
        return _audit_batch(seqs, model,
                            [results_by_idx[i]
                             for i in range(len(seqs))], audit)

    ess = [encode_search(s) for s in seqs]
    if dpor_on:
        for i, (s, e) in enumerate(zip(seqs, ess)):
            attach_reductions(e, s, model, masks[i], dedup=True)
    hard = [i for i, e in enumerate(ess)
            if e.window > MAX_WINDOW or e.n_crash > MAX_CRASH]
    if hard:
        # outliers fall back to individual host checks
        from .linear import check_opseq_linear

        out = []
        for i, s in enumerate(seqs):
            if i in hard:
                r = check_opseq_linear(s, model, lint=False, hb=hb,
                                       dpor=dpor)
                r["engine"] = "host-linear(fallback)"
                out.append(r)
            else:
                out.append(search_opseq(s, model, budget=budget,
                                        lint=False, audit=False,
                                        hb=hb, dpor=dpor,
                                        _hbres=hbs[i]))
        return _audit_batch(seqs, model, out, audit)

    # the sharded path has no escalation ladder (the key axis must keep
    # covering the mesh at a fixed shape), so it starts at the wider
    # frontier; the ladder path starts narrow and escalates in batches
    dims = dims or batch_dims(
        ess, model, frontier=64 if sharding is not None else 32)
    if dpor_on and sharding is None:
        # engine priority: rungs in the pallas regime keep the fused
        # kernel and drop the optional prune (see
        # _strip_reductions_for_pallas)
        for e in ess:
            _strip_reductions_for_pallas(e, model, dims)
    dead_pad = batch_dead_pad(ess)

    if sharding is not None:
        tele_acc = _tele.SearchTelemetry("device-batch-sharded") \
            if _tele.enabled() else None
        out, _info = _search_batch_sharded_fixed(
            seqs, ess, model, dims, sharding, budget,
            tele_acc=tele_acc)
        if tele_acc is not None and out:
            _tele.finalize_result(out[0], tele_acc)
        return _audit_batch(seqs, model, out, audit)
    esps = [pad_search(e, dims.n_det_pad, dims.n_crash_pad,
                       dead_pad=dead_pad) for e in ess]
    return _audit_batch(seqs, model,
                        _search_batch_ladder(seqs, esps, model, dims,
                                             budget), audit)


def _search_batch_sharded_fixed(seqs: list[OpSeq],
                                ess: list, model: ModelSpec,
                                dims: SearchDims, sharding,
                                budget: int, *, tele_acc=None,
                                esps=None, dead_pad=None):
    """One fixed-shape mesh-sharded batch dispatch at ``dims``.

    The shared device stage of BOTH mesh-sharded batch routes: the
    fused path (`search_batch(sharding=...)`, one call over global
    dims) and the bucketed scheduler (`checker/bucket.py`'s
    `search_batch_sharded_bucketed`, one call per bucket at that
    bucket's tight dims).  Mesh-sharded batches stay on the XLA
    kernel: partitioning a pallas_call's vmapped grid axis over a mesh
    is not a path the batching rule guarantees.

    The key axis must stay divisible by the mesh: disposal (greedy/hb)
    or a small bucket can shrink a batch below it, so the batch pads
    with inert keys (n_det = n_crash = 0, status pre-resolved VALID so
    the liveness reduction ignores them and no lane spins forever).
    Pad lanes are an artifact of mesh divisibility, NOT state-space
    work: they are stripped from the aux telemetry block BEFORE the
    lane-sum (no pad occupancy in ``search_telemetry``) and never read
    back into per-key ``configs``.

    On a single-axis, fully-addressable mesh the kernel is shard_map'd
    (`get_sharded_batch_kernel`) so each device loops only until its
    own lane block resolves; other layouts (the DCN "keys"x"shard"
    mesh, multi-process shards) take device_put + GSPMD — in a
    MULTI-PROCESS job each process owns only its addressable shards,
    and device_put from replicated host data is the supported
    construction path.

    Returns ``(results, info)``: per-key result dicts aligned with
    ``seqs`` and the dispatch info (shards, pad lanes, overflow
    redos) the bucketed scheduler folds into its stats.
    """
    tele_on = tele_acc is not None
    if dead_pad is None:
        dead_pad = batch_dead_pad(ess)
    n_dev = getattr(sharding, "num_devices", 1) or 1
    b = _round_up(len(seqs), n_dev)
    mesh, axis = _shard_map_target(sharding)
    n_shards = n_dev
    if mesh is not None and b % mesh.shape[axis] == 0:
        n_shards = mesh.shape[axis]
        fn = get_sharded_batch_kernel(
            model, dims, batch=b, mesh=mesh, axis=axis,
            masked=any(e.masked for e in ess),
            masked_crash=any(e.mask_has_crash for e in ess),
            dedup=any(e.dedup for e in ess),
            vt=dead_pad, telemetry=tele_on)
        used_shard_map = True
    else:
        fn = get_batch_kernel(model, dims, batch=len(seqs),
                              allow_pallas=False,
                              masked=any(e.masked for e in ess),
                              masked_crash=any(e.mask_has_crash
                                               for e in ess),
                              dedup=any(e.dedup for e in ess),
                              vt=dead_pad, telemetry=tele_on)
        used_shard_map = False
    if esps is None:
        # the bucketed scheduler pre-pads on its prep thread and hands
        # esps in; the fused route pads here
        esps = [pad_search(e, dims.n_det_pad, dims.n_crash_pad,
                           dead_pad=dead_pad) for e in ess]
    args = stack_batch(esps, pad_to=b)
    args = tuple(jax.device_put(np.asarray(a), sharding)
                 for a in args)
    carry0 = [np.asarray(c)
              for c in _init_batch_carry(b, dims, model)]
    carry0[1][len(seqs):] = 0
    carry0[2][len(seqs):] = VALID
    carry = tuple(jax.device_put(c, sharding) for c in carry0)

    def call(c, lvl_cap):
        t0 = time.perf_counter()
        res = fn(*args, jnp.int32(budget), jnp.int32(lvl_cap),
                 jnp.bool_(False), *c)
        if tele_acc is not None:
            jax.block_until_ready(res[:6])
            t1 = time.perf_counter()
            try:
                blk = np.asarray(res[6])
            except Exception:  # noqa: BLE001 — non-addressable
                pass           # multi-process shards: skip
            else:
                # inert mesh-divisibility pad lanes excluded BEFORE
                # the lane-sum: their rows must not bill occupancy
                tele_acc.add_totals(blk[:len(seqs)])
                _tele.emit_shard_levels(blk, len(seqs), n_shards,
                                        t0, t1)
            res = res[:6]
        return res

    # the liveness reduction runs jitted: its output is replicated,
    # so it stays readable when the carry itself is sharded over
    # processes (np.asarray on a non-fully-addressable array throws)
    active_fn = jax.jit(
        lambda s, c, g: jnp.any((s == -1) & (c > 0) & (g < budget)))

    def is_active(c):
        return bool(active_fn(c[2], c[1], c[3]))

    def gather(x):
        if getattr(x, "is_fully_addressable", True):
            return np.asarray(x)
        from jax.experimental import multihost_utils

        return np.asarray(
            multihost_utils.process_allgather(x, tiled=True))

    carry = _drive_slices(call, carry, is_active)
    status = gather(carry[2])
    count = gather(carry[1])
    configs = gather(carry[3])
    depth = gather(carry[4])
    ovf = gather(carry[5])
    status = _finalize_batch_status(status, count, ovf)
    out = []
    redo = 0
    for i in range(len(seqs)):
        if int(status[i]) == UNKNOWN and bool(ovf[i]):
            # overflowed the fixed mesh shape: redo solo with the
            # adaptive ladder
            redo += 1
            out.append(search_opseq(seqs[i], model,
                                    budget=budget, lint=False,
                                    audit=False))
        else:
            r = {"valid": _STATUS[int(status[i])],
                 "configs": int(configs[i]),
                 "max_depth": int(depth[i]),
                 "engine": "device-batch"}
            _device_batch_certificate(r)
            out.append(r)
    info = {"n_shards": int(n_shards), "batch_lanes": int(b),
            "pad_lanes": int(b - len(seqs)),
            "shard_map": used_shard_map, "overflow_redo": redo}
    return out, info


def _finalize_batch_status(status, count, ovf):
    """Host-side finalization of still -1 statuses (dead frontier or
    exhausted budget), mirroring _run_kernel — the ONE rule both the
    sharded and ladder batch paths apply."""
    return np.where(
        status == -1,
        np.where(count <= 0, np.where(ovf, UNKNOWN, INVALID), UNKNOWN),
        status)


def _device_batch_certificate(r: dict) -> dict:
    """Attach the device batch engines' explicit certificate-drop
    reasons — the ONE place the batch paths state why a device verdict
    ships without a witness/frontier."""
    if r.get("valid") is True:
        r.setdefault("witness_dropped", WITNESS_DROPPED_DEVICE)
    elif r.get("valid") is False:
        r.setdefault("frontier_dropped", FRONTIER_DROPPED_DEVICE)
    return r


def _search_batch_ladder(seqs: list[OpSeq], esps: list[EncodedSearch],
                         model: ModelSpec, dims: SearchDims,
                         budget: int) -> list[dict]:
    """The batched escalation ladder — `search_batch`'s device path for
    un-meshed batches, taking PRE-PADDED EncodedSearches at ``dims``.

    This is also the entry point the bucketed scheduler
    (checker/bucket.py) feeds directly: per-bucket host prep (greedy
    witnesses, encoding, padding) happens in its pipeline thread, and
    this function only pays the device work.

    Every pending key runs at the current frontier rung; keys that
    overflow it re-run TOGETHER at 4x width (one kernel call per rung,
    not one solo search per overflowing key — solo re-runs each pay
    dispatch/compile, which is exactly what hurts on a real
    accelerator).  Keys still overflowing past the rung cap fall back
    to the solo adaptive ladder.
    """
    global _PALLAS_BROKEN
    n = len(seqs)
    status = np.full(n, UNKNOWN, np.int32)
    count = np.zeros(n, np.int32)
    configs = np.zeros(n, np.int64)
    depth = np.zeros(n, np.int32)
    ovf = np.zeros(n, bool)
    pending = list(range(n))
    spent = np.zeros(n, np.int64)  # configs across ALL rungs
    rung = dims.frontier
    # phase-2 flags, derived from the pre-padded encodings (uniform
    # across the batch by construction: pad_search always materializes
    # the planes, and the kernel emits the checks when ANY key needs
    # them — inert tables no-op for the rest)
    b_masked = any(e.masked for e in esps)
    b_mcrash = any(e.mask_has_crash for e in esps)
    b_dedup = any(e.dedup for e in esps)
    b_vt = len(esps[0].dead_from) if esps else 8
    used_pallas = False  # any rung executed on the pallas engine
    tele_on = _tele.enabled()
    acc = _tele.SearchTelemetry("device-batch") if tele_on else None
    while pending:
        d = _dc_replace(dims, frontier=rung)
        want_pallas = _use_pallas(model, d, masked=b_masked,
                                  dedup=b_dedup)
        fnr = get_batch_kernel(model, d, batch=len(pending),
                               masked=b_masked,
                               masked_crash=b_mcrash, dedup=b_dedup,
                               vt=b_vt, telemetry=tele_on)
        try:
            st, ct, cf, dp, ov = _drive_batch_compacting(
                fnr, [esps[i] for i in pending], model, d, budget,
                bail=True, tele_acc=acc)
        except Exception as e:  # noqa: BLE001 — engine fallback
            if _use_pallas(model, d, masked=b_masked,
                           dedup=b_dedup) and not _PALLAS_BROKEN:
                # first hardware contact for the pallas batch path
                # happens inside a tunnel window; a lowering bug
                # must cost one rung rebuild, not the batch tier
                _PALLAS_BROKEN = True
                _trace(f"pallas batch kernel failed ({e!r}); "
                       "falling back to xla engine")
                fnr = get_batch_kernel(model, d,
                                       batch=len(pending),
                                       masked=b_masked,
                                       masked_crash=b_mcrash,
                                       dedup=b_dedup, vt=b_vt,
                                       telemetry=tele_on)
                st, ct, cf, dp, ov = _drive_batch_compacting(
                    fnr, [esps[i] for i in pending], model, d,
                    budget, bail=True, tele_acc=acc)
            else:
                raise
        used_pallas = used_pallas or (want_pallas
                                      and not _PALLAS_BROKEN)
        nxt = []
        for j, i in enumerate(pending):
            spent[i] += int(cf[j])
            if st[j] == -1 and bool(ov[j]) and spent[i] < budget:
                nxt.append(i)  # overflowed this rung: escalate
            else:
                # configs reports cumulative exploration across
                # rungs, and the per-key budget bounds the total —
                # a key never escalates once its cumulative spend
                # crosses it (worst case: budget + one rung)
                status[i], count[i] = st[j], ct[j]
                configs[i] = spent[i]
                depth[i], ovf[i] = dp[j], ov[j]
        pending = nxt
        if pending and rung >= BATCH_FRONTIER_CAP:
            break  # stragglers go solo below
        rung = min(rung * 4, BATCH_FRONTIER_CAP)
    status = _finalize_batch_status(status, count, ovf)
    out = []
    batch_engine = _engine_label(used_pallas, base="device-batch")
    solo = set(pending)
    for i in range(n):
        needs_solo = i in solo or (int(status[i]) == UNKNOWN
                                   and bool(ovf[i]))
        if needs_solo and spent[i] >= budget:
            # cumulative ladder spend already exhausted this key's
            # budget: a solo re-run would amplify work past the cap.
            # UNKNOWN stands, with the true cumulative count.
            out.append({"valid": "unknown", "configs": int(spent[i]),
                        "max_depth": int(depth[i]),
                        "engine": batch_engine})
        elif needs_solo:
            # overflowed every shared rung: redo solo with the adaptive
            # ladder, on the REMAINING budget, reporting cumulative
            # configs (ladder spend + solo spend)
            rem = budget - int(spent[i])
            r = search_opseq(seqs[i], model, budget=max(1000, rem),
                             lint=False, audit=False)
            r["configs"] = int(r.get("configs", 0)) + int(spent[i])
            out.append(r)
        else:
            out.append(_device_batch_certificate(
                {"valid": _STATUS[int(status[i])],
                 "configs": int(configs[i]),
                 "max_depth": int(depth[i]),
                 "engine": batch_engine}))
    if acc is not None and out:
        # batch-aggregate telemetry rides the FIRST result only (the
        # bucket_batch / decompose_batch convention: one shared stats
        # dict, not N serialized copies)
        _tele.finalize_result(out[0], acc)
    return out


def _search_batch_decomposed(seqs: list[OpSeq], model: ModelSpec, *,
                             budget: int, dims, sharding,
                             cache, bucket=None,
                             hb: bool | None = None,
                             dpor: bool | None = None) -> list[dict]:
    """Cache + dedup front-end for `search_batch` (decompose=True).

    Exact by construction: a canonical-hash collision means the two
    histories are the *same search problem* (same rows, same precedence
    ranks, value-bijective), so one verdict serves both.  Undecided
    results are never cached and never deduplicated onto other keys."""
    from ..decompose.cache import VerdictCache
    from ..decompose.canonical import canonical_key

    if isinstance(cache, str):
        cache = VerdictCache(cache)
    elif cache is None:
        cache = VerdictCache()  # in-memory: within-batch dedup only
    cache.reset_stats()
    keys = [canonical_key(s, model) for s in seqs]
    results: dict[int, dict] = {}
    rep: dict[str, int] = {}  # canonical key -> representative index
    todo: list[int] = []
    drop = "canonical verdict-cache hit (the cache stores verdicts, " \
           "not witnesses)"
    for i, k in enumerate(keys):
        e = cache.get(k)
        if e is not None and "v" in e:
            results[i] = {"valid": e["v"], "configs": 0,
                          "engine": "decompose-cache"}
            results[i]["witness_dropped" if e["v"] is True
                       else "frontier_dropped"] = drop
        elif k in rep:
            pass  # filled from the representative's verdict below
        else:
            rep[k] = i
            todo.append(i)
    if todo:
        sub = search_batch([seqs[i] for i in todo], model, budget=budget,
                           dims=dims, sharding=sharding, bucket=bucket,
                           lint=False, hb=hb, dpor=dpor)
        for i, r in zip(todo, sub):
            results[i] = r
            if r.get("valid") in (True, False):
                cache.put_verdict(keys[i], r["valid"])
    def _copy_cert(dst: dict, src: dict) -> dict:
        """Certificates transfer between canonically-equal keys: the
        histories are row-aligned and value-bijective (canonical.py),
        so one's witness row order / frontier rows are the other's.
        The audit pass replays the copy against ITS history, keeping
        this transfer falsifiable."""
        for field in ("linearization", "final_ops", "witness_dropped",
                      "frontier_dropped", "hb_cycle"):
            if field in src:
                v = src[field]
                dst[field] = list(v) if isinstance(v, list) else v
        return dst

    n_dup = 0
    solo: dict[str, dict] = {}
    for i, k in enumerate(keys):
        if i in results:
            continue
        r = results[rep[k]]
        if r.get("valid") in (True, False):
            n_dup += 1
            results[i] = _copy_cert({"valid": r["valid"], "configs": 0,
                                     "engine": "decompose-dedup"}, r)
            continue
        # the representative was undecided in the batch: retry solo —
        # ONCE per canonical shape (copies are isomorphic problems, so
        # a decided retry serves all of them, and sharing an undecided
        # one asserts nothing)
        r2 = solo.get(k)
        if r2 is None:
            r2 = solo[k] = search_opseq(seqs[i], model, budget=budget,
                                        lint=False)
            if r2.get("valid") in (True, False):
                cache.put_verdict(k, r2["valid"])
                # the decided retry serves the representative too: one
                # canonical shape must not report two verdicts in one
                # result list (its batch-spent configs stay billed)
                ri = results[rep[k]]
                ri["valid"] = r2["valid"]
                ri["engine"] = (ri.get("engine") or
                                "device-batch") + "+decompose-retry"
                _copy_cert(ri, r2)
            results[i] = r2
        else:
            n_dup += 1
            results[i] = _copy_cert(
                {"valid": r2.get("valid"), "configs": 0,
                 "engine": "decompose-dedup"}, r2)
    out = [results[i] for i in range(len(seqs))]
    stats = {"n_keys": len(seqs), "cache_hits": cache.hits,
             "cache_misses": cache.misses, "deduped": n_dup,
             "searched": len(todo),
             "hit_rate": round(cache.hits / max(1, len(seqs)), 4)}
    # first result only — attaching one shared mutable dict to every
    # key invites spooky cross-key mutation and serializes the stats
    # N times through per-key stores (same convention as bucket_batch)
    if out:
        out[0].setdefault("decompose_batch", stats)
    return out


def truncate_to_failure(seq: OpSeq, depth: int, window: int
                        ) -> OpSeq | None:
    """Cut the history just past the failure region, at a point where
    every kept determinate op returned before any removed op invoked.

    The device search localizes an invalid history's obstruction near
    determinate position `depth` (+ window).  The cut must be *closed*:
    if no removed op can linearize among the kept ones (kept det rets all
    precede removed invs; crashed rows before the cut are kept), then any
    valid linearization of the full history restricts to one of the
    prefix — so prefix-invalid ⟹ full-invalid, and the host oracle can
    confirm + extract a witness on the (much shorter) prefix
    (SURVEY.md §7 "witness reconstruction").

    Returns None when no quiescent cut exists before the end.
    """
    ok = np.asarray(seq.ok, dtype=bool)
    det_rows = np.nonzero(ok)[0]
    n_det = len(det_rows)
    want = min(depth + window + 1, n_det)
    if want >= n_det:
        return None
    det_inv = np.asarray(seq.inv)[det_rows]
    det_ret = np.asarray(seq.ret)[det_rows]
    run_max = np.maximum.accumulate(det_ret)
    # boundary after det i iff max ret of dets 0..i < inv of det i+1
    cut = None
    for i in range(want, n_det - 1):
        if run_max[i] < det_inv[i + 1]:
            cut = i
            break
    if cut is None:
        return None
    t = det_inv[cut + 1]  # first removed det's invocation rank
    keep = np.asarray(seq.inv) < t
    idx = np.nonzero(keep)[0]
    if len(idx) >= len(seq):
        return None
    return OpSeq(
        process=seq.process[idx], f=seq.f[idx], v1=seq.v1[idx],
        v2=seq.v2[idx], inv=seq.inv[idx], ret=seq.ret[idx],
        ok=seq.ok[idx], ops=[seq.ops[i] for i in idx],
        encoder=seq.encoder)


class Linearizable:
    """Linearizability checker backed by the device engine.

    The reference's `linearizable` checker hands the model + indexed
    history to knossos and truncates the failure analysis for reporting
    (checker.clj:114-139).  Here:

      * histories below `host_threshold` logical ops run on the exact host
        oracle (device dispatch has fixed overhead);
      * larger histories run the device search;
      * an invalid device verdict is re-verified (and a witness frontier
        extracted) by the host oracle when the history is small enough to
        afford it, closing the fingerprint-collision soundness hole.

    ``model`` may be given at construction or ride in test["model"].
    """

    name = "linearizable"

    #: algorithm aliases, mirroring checker.clj:122-126's
    #: :linear / :wgl / :competition selector.  `linear` is the memoized
    #: dominance-pruned host sweep (checker/linear.py), `wgl`/`host` the
    #: plain DFS oracle (checker/seq.py), `device`/`tpu` the device BFS,
    #: `competition` races all three.
    ALGORITHMS = {"auto": "auto", "device": "device", "tpu": "device",
                  "linear": "linear", "host": "host", "wgl": "host",
                  "competition": "competition"}

    def __init__(self, model: ModelSpec | None = None, *,
                 budget: int = 20_000_000,
                 host_threshold: int = 48,
                 witness_threshold: int = 3000,
                 algorithm: str = "auto",
                 decompose: bool = False,
                 verdict_cache=None,
                 lint: bool | None = None,
                 explain: bool | None = None,
                 audit: bool | None = None,
                 shrink: bool | None = None,
                 hb: bool | None = None,
                 dpor: bool | None = None):
        self.model = model
        # ``hb`` runs the happens-before pre-pass (analyze/hb.py) in
        # front of every host route: statically decided histories skip
        # the search entirely, undecided ones search under the
        # must-order mask.  None follows JEPSEN_TPU_HB (default on;
        # the CLI's --no-hb sets it to 0).  ``dpor`` enables the
        # dynamic layer (analyze/dpor.py: duplicate-op edges, sleep
        # sets, dead-value dedup, device mask planes).  None follows
        # JEPSEN_TPU_DPOR (default on; the CLI's --no-dpor sets it
        # to 0).
        self.hb = hb
        self.dpor = dpor
        self.budget = budget
        self.host_threshold = host_threshold
        self.witness_threshold = witness_threshold
        # ``audit`` replays every verdict's certificate through the
        # independent audit pass (analyze/audit.py; None follows
        # JEPSEN_TPU_AUDIT, set by the CLI's --audit).  ``shrink``
        # delta-debugs invalid verdicts into a minimal failing
        # subhistory for the report (analyze/shrink.py; None follows
        # JEPSEN_TPU_SHRINK, default on — reporting only, never
        # verdicts).
        self.audit = audit
        self.shrink = shrink
        # ``lint`` runs the well-formedness linter (analyze/lint.py)
        # over the history before any search: errors are fatal
        # (HistoryLintError), warnings ride the result dict as
        # ``lint_warnings``.  None follows the JEPSEN_TPU_LINT knob
        # (default on).  ``explain`` (or JEPSEN_TPU_EXPLAIN, set by the
        # CLI's --explain) reports the static search PLAN
        # (analyze/plan.py) without running any search.
        self.lint = lint
        if explain is None:
            explain = os.environ.get(
                "JEPSEN_TPU_EXPLAIN", "").lower() in ("1", "true", "on",
                                                      "yes")
        self.explain = explain
        # ``decompose=True`` runs the P-compositional decomposition
        # layer (jepsen_tpu/decompose/) in front of whichever engine
        # ``algorithm`` selects; verdict-identical, default off.
        # ``verdict_cache``: a decompose.VerdictCache, a jsonl path, or
        # True for the store-persisted default location.  The env knob
        # (set by the CLI's --lin-decompose) reaches suite-constructed
        # checkers the same way JEPSEN_TPU_LIN_ALGORITHM does.
        if not decompose:
            decompose = os.environ.get(
                "JEPSEN_TPU_LIN_DECOMPOSE", "").lower() in ("1", "true",
                                                            "on", "yes")
        self.decompose = decompose
        self.verdict_cache = verdict_cache
        src = "algorithm"
        if algorithm == "auto":
            # fleet-wide experiment knob: suites construct their own
            # checkers, so a per-suite flag can't reach them all
            env = os.environ.get("JEPSEN_TPU_LIN_ALGORITHM")
            if env:
                algorithm, src = env, "JEPSEN_TPU_LIN_ALGORITHM"
        try:
            self.algorithm = self.ALGORITHMS[algorithm]
        except KeyError:
            raise ValueError(
                f"unknown algorithm {algorithm!r} (from {src}); one of "
                f"{sorted(self.ALGORITHMS)}") from None

    def check(self, test, history, opts=None):
        model = self.model or test.get("model")
        if model is None:
            raise ValueError("linearizable checker needs a model")
        from ..analyze.lint import (check_history, check_opseq_lint,
                                    lint_enabled)

        lint_warnings: list = []
        do_lint = self.lint if self.lint is not None else lint_enabled()
        if do_lint:
            # event-level lint sees defects encoding erases (double
            # invokes, orphan completions, type drift); an OpSeq input
            # gets the columnar checks.  Errors raise HERE — before
            # encode_ops can silently mis-pair the malformed events —
            # and check_safe turns that into an "unknown" verdict
            # carrying the diagnostic, never a wrong True/False.
            if isinstance(history, OpSeq):
                lint_warnings = check_opseq_lint(history, model)
            else:
                lint_warnings = check_history(history, model)
        seq = history if isinstance(history, OpSeq) else \
            encode_ops(history, model.f_codes)
        if self.explain:
            # plan-only mode (--explain): report what the search WOULD
            # do — dims, bucket, route, decompositions — and stop
            from ..analyze.plan import explain as explain_plan
            from ..analyze.plan import render_plan

            plan = explain_plan(seq, model,
                                host_threshold=self.host_threshold)
            print(render_plan(plan))
            out = {"valid": "unknown", "engine": "explain(plan-only)",
                   "explain": plan, "configs": 0}
            if lint_warnings:
                out["lint_warnings"] = [d.to_dict()
                                        for d in lint_warnings]
            return out
        out = self._checked(test, seq, model, opts)
        if lint_warnings and isinstance(out, dict):
            out.setdefault("lint_warnings",
                           [d.to_dict() for d in lint_warnings])
        if isinstance(out, dict):
            from ..analyze.audit import maybe_audit

            maybe_audit(seq, model, out, self.audit)
        return out

    def _checked(self, test, seq, model, opts):
        if self.decompose:
            from ..decompose.cache import VerdictCache, default_cache_path
            from ..decompose.engine import check_opseq_decomposed

            cache = self.verdict_cache
            if cache is True:
                cache = default_cache_path()
            if isinstance(cache, str):
                # construct the cache ONCE per checker, not per check():
                # each construction re-parses the whole append-only
                # jsonl, which grows with every decided verdict
                if getattr(self, "_cache_obj", None) is None or \
                        self._cache_obj.path != cache:
                    self._cache_obj = VerdictCache(cache)
                cache = self._cache_obj
            sub_check = None
            if self.algorithm == "host":
                # honor the selected host engine for sub-searches too;
                # the other selections (device/competition/linear/auto)
                # keep the default host `linear` sub-engine — cells and
                # segments are small, where device dispatch only loses
                from . import seq as seqmod

                def sub_check(s, m, *, max_configs, deadline):
                    return seqmod.check_opseq(s, m,
                                              max_configs=max_configs,
                                              deadline=deadline,
                                              lint=False, hb=self.hb,
                                              dpor=self.dpor)
            # lint=False: this checker already linted (or deliberately
            # skipped) at its own boundary in check()
            out = check_opseq_decomposed(
                seq, model, cache=cache,
                sub_max_configs=self.budget,  # the user's sizing knob
                sub_check=sub_check, lint=False, witness=True,
                hb=self.hb, dpor=self.dpor,
                direct=lambda s: self._check_direct(test, s, model, opts))
            if out["valid"] is False and "report_file" not in out:
                # the direct fallback renders its own report; a verdict
                # decided by decomposition alone still gets one
                self._render_failure(test, seq, out, opts, model)
            return out
        return self._check_direct(test, seq, model, opts)

    def _check_direct(self, test, seq, model, opts):
        from . import seq as seqmod

        if (self.algorithm == "host"
                or (self.algorithm == "auto"
                    and len(seq) <= self.host_threshold)):
            # lint=False throughout _check_direct: check() linted (or
            # deliberately skipped) at the checker boundary already
            out = seqmod.check_opseq(seq, model, lint=False,
                                     hb=self.hb, dpor=self.dpor)
            out["engine"] = "host-oracle"
            if out["valid"] is False:
                self._render_failure(test, seq, out, opts, model)
            return out

        if self.algorithm == "linear":
            from .linear import DEFAULT_WITNESS_CAP, check_opseq_linear

            # user-facing path: track the valid-verdict witness (the
            # verdict-only callers — competition legs, portfolio,
            # fuzzers — leave it off and keep level-local memory)
            out = check_opseq_linear(seq, model,
                                     witness_cap=DEFAULT_WITNESS_CAP,
                                     lint=False, hb=self.hb,
                                     dpor=self.dpor)
            out["engine"] = "host-linear"
            if out["valid"] is False:
                self._render_failure(test, seq, out, opts, model)
            return out

        if self.algorithm in ("auto", "competition"):
            # the reference's default is :competition
            # (checker.clj:122-126): race the exact host DFS against the
            # device search; whichever concludes first wins.  The host
            # thread costs one core and wins exactly the histories a DFS
            # lucky-dives (deep valid ones); the device wins sweeps.
            out = check_competition(seq, model, budget=self.budget,
                                    lint=False, hb=self.hb,
                                    dpor=self.dpor)
        else:
            out = search_opseq(seq, model, budget=self.budget,
                               lint=False, hb=self.hb,
                               dpor=self.dpor)
        if out["valid"] is False:
            eng = out.get("engine", "")
            if "host-oracle" in eng or "host-linear" in eng:
                # an exact host engine already produced this verdict
                # (and its final_ops/final_paths report data);
                # re-confirming would repeat the same search
                self._render_failure(test, seq, out, opts, model)
                return out
            # exact confirmation + witness for the report, on the
            # shortest sound prefix covering the failure region
            target = seq
            trunc = truncate_to_failure(seq, out.get("max_depth", 0),
                                        out.get("window", 1))
            if trunc is not None:
                target = trunc
            if len(target) <= self.witness_threshold:
                confirm = seqmod.check_opseq(target, model, lint=False)
                if confirm["valid"] is False:
                    confirm["engine"] = out["engine"] + "+host-witness"
                    confirm["device_configs"] = out["configs"]
                    confirm["witness_prefix_ops"] = len(target)
                    self._render_failure(test, target, confirm, opts,
                                         model)
                    return confirm
                # prefix came back valid: fall through to the full
                # device verdict (obstruction lies past the cut)
        return out

    #: don't delta-debug failure reports past this many rows — each
    #: shrink probe is a bounded re-search, and a huge history's report
    #: should not cost more than its verdict did
    SHRINK_MAX_OPS = 400

    def _render_failure(self, test, seq, result, opts, model):
        """linear.html — the knossos linear.svg analog
        (checker.clj:128-135); reporting never affects the verdict.
        Invalid verdicts are first delta-debugged into a minimal
        failing subhistory (analyze/shrink.py) so the report tells a
        6-op story instead of dumping the whole history."""
        from . import linear_report

        if result.get("shrink") is None and len(seq) > 0 \
                and len(seq) <= self.SHRINK_MAX_OPS:
            from ..analyze.shrink import (shrink_enabled, shrink_invalid,
                                          shrink_summary)

            if self.shrink if self.shrink is not None \
                    else shrink_enabled():
                try:
                    s = shrink_invalid(seq, model)
                    result["shrink"] = shrink_summary(seq, s)
                except Exception:  # noqa: BLE001 — reporting only
                    pass
        path = linear_report.write_linear_html(test or {}, seq, result,
                                               opts)
        if path is not None:
            result["report_file"] = path

    def __call__(self, test, history, opts=None):
        return self.check(test, history, opts)


def linearizable(model: ModelSpec | None = None, **kw) -> Linearizable:
    return Linearizable(model, **kw)
