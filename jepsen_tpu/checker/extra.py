"""Additional consistency checkers from the suite layer.

Ports of the cockroachdb suite's reusable analyses:

  * sequential — client order must match DB visibility order
    (cockroachdb/src/jepsen/cockroach/sequential.clj:136-163): process A
    inserts x then y in separate transactions; process B reads y then x.
    Reading y (the later insert) but not x (the earlier) — a nil after a
    non-nil in the read vector — violates sequential consistency.
  * monotonic — timestamps and values must proceed in order
    (cockroachdb/src/jepsen/cockroach/monotonic.clj:144-230): a final
    read returns rows {val, sts, proc, node, tb}; checks global timestamp
    order, global/per-process/node/table value order, plus lost /
    duplicate / recovered accounting.

Both consume event-level histories like the rest of checker/.
"""

from __future__ import annotations

from collections import Counter

from ..history import is_fail, is_info, is_invoke, is_ok
from .core import Checker


def trailing_nil(coll) -> bool:
    """A nil anywhere after a non-nil element (sequential.clj:136-139)."""
    seen_value = False
    for x in coll:
        if x is None:
            if seen_value:
                return True
        else:
            seen_value = True
    return False


class SequentialChecker(Checker):
    """sequential.clj:141-163.  Reads carry values of [k, [reads...]]
    where the read vector is in reverse insert order."""

    def __init__(self, subkeys=None):
        # subkeys(key_count, k) -> the full expected subkey list
        self.subkeys = subkeys or (
            lambda key_count, k: [f"{k}_{i}" for i in range(key_count)])

    def check(self, test, history, opts=None):
        key_count = test.get("key_count")
        reads = [op.value for op in history
                 if is_ok(op) and op.f == "read" and op.value is not None]
        none = [r for r in reads if all(v is None for v in r[1])]
        some = [r for r in reads if any(v is None for v in r[1])]
        bad = [r for r in reads if trailing_nil(r[1])]
        all_ = [r for r in reads
                if key_count is not None
                and list(self.subkeys(key_count, r[0])) ==
                list(reversed(list(r[1])))]
        return {
            "valid": not bad,
            "all_count": len(all_),
            "some_count": len(some),
            "none_count": len(none),
            "bad_count": len(bad),
            "bad": bad,
        }


def sequential(subkeys=None) -> Checker:
    return SequentialChecker(subkeys)


def non_monotonic(cmp, key, xs) -> list:
    """Successive pairs where cmp(key(x), key(x')) fails
    (monotonic.clj:144-151)."""
    out = []
    for a, b in zip(xs, xs[1:]):
        if not cmp(key(a), key(b)):
            out.append((a, b))
    return out


def non_monotonic_by(group, cmp, key, xs) -> dict:
    """non_monotonic within groups (monotonic.clj:153-161)."""
    groups: dict = {}
    for x in xs:
        groups.setdefault(group(x), []).append(x)
    return {g: non_monotonic(cmp, key, sub) for g, sub in
            sorted(groups.items(), key=lambda kv: str(kv[0]))}


def _field(name):
    return lambda row: row[name] if isinstance(row, dict) else \
        getattr(row, name)


class MonotonicChecker(Checker):
    """monotonic.clj:163-230.  add ops carry {val, ...}; the final read
    carries an ordered list of {val, sts, proc, node, tb} rows."""

    def __init__(self, global_order: bool = True):
        self.global_order = global_order

    def check(self, test, history, opts=None):
        add_ok = [op.value for op in history
                  if is_ok(op) and op.f == "add"]
        add_fail = [op.value for op in history
                    if is_fail(op) and op.f == "add"]
        add_info = [op.value for op in history
                    if is_info(op) and op.f == "add"]
        final = None
        for op in history:
            if is_ok(op) and op.f == "read":
                final = op.value
        if final is None:
            return {"valid": "unknown", "error": "Set was never read"}

        val = _field("val")
        off_order_stss = non_monotonic(
            lambda a, b: a <= b, _field("sts"), final)
        off_order_vals = non_monotonic(lambda a, b: a < b, val, final)
        by_proc = non_monotonic_by(_field("proc"),
                                   lambda a, b: a < b, val, final)
        by_node = non_monotonic_by(_field("node"),
                                   lambda a, b: a < b, val, final)
        by_table = non_monotonic_by(_field("tb"),
                                    lambda a, b: a < b, val, final)

        def vals(rows):
            return {val(r) if isinstance(r, dict) else r for r in rows}

        adds = {v["val"] if isinstance(v, dict) else v for v in add_ok}
        infos = {v["val"] if isinstance(v, dict) else v for v in add_info}
        final_vals = [val(r) for r in final]
        dups = {v for v, n in Counter(final_vals).items() if n > 1}
        final_set = set(final_vals)
        lost = adds - final_set
        recovered = final_set & infos

        per_key_violations = (
            off_order_vals if self.global_order
            else [p for sub in by_proc.values() for p in sub])
        valid = not (lost or dups or off_order_stss or per_key_violations)
        return {
            "valid": valid,
            "lost": sorted(lost),
            "duplicates": sorted(dups),
            "recovered": sorted(recovered),
            "off_order_stss": off_order_stss,
            "off_order_vals": off_order_vals,
            "off_order_vals_per_process": by_proc,
            "off_order_vals_per_node": by_node,
            "off_order_vals_per_table": by_table,
        }


def monotonic(global_order: bool = True) -> Checker:
    return MonotonicChecker(global_order)
