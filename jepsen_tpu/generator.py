"""Workload generation — the composable generator DSL (reference L3).

Reference: jepsen/src/jepsen/generator.clj.  A generator is a stateful
object with one method ``op(test, process) -> op-dict | None`` (protocol at
generator.clj:23-24); None means exhausted.  Generators are demand-driven:
each worker thread repeatedly asks the (shared) generator tree for its next
operation, so generators may sleep to pace the test and may block on
barriers to synchronize phases.  Ops are plain dicts here ({"type":
"invoke", "f": ..., "value": ...}); workers fill in :process and :time
(the reference does the same — generator.clj:6-8).

Anything can act as a generator (generator.clj:40-52): a dict constantly
yields itself; a callable is invoked with (test, process) or no args; None
is exhausted.  Use :func:`gen_op` to pull from any such object.

Thread context: the dynamic var ``*threads*`` (generator.clj:52-58) — the
sorted collection of worker threads a generator subtree serves — becomes a
thread-local binding stack managed by :func:`with_threads`; `on`/`reserve`
rebind it so barriers inside subtrees count only their own threads.
"""

from __future__ import annotations

import random as _random
import threading
import time
from typing import Any, Callable, Iterable, Optional

from .util import sleep_seconds

OpDict = dict


class Generator:
    """Base class; subclasses override op(test, process)."""

    def op(self, test: dict, process) -> Optional[OpDict]:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# dynamic *threads* binding (generator.clj:52-67)
# ---------------------------------------------------------------------------

_ctx = threading.local()


def sort_processes(ps: Iterable) -> list:
    """Numeric processes ascending, then named ones (knossos
    history/sort-processes ordering: workers first, :nemesis last)."""
    nums = sorted(p for p in ps if isinstance(p, int))
    names = sorted((p for p in ps if not isinstance(p, int)), key=str)
    return nums + names


def current_threads() -> list:
    t = getattr(_ctx, "threads", None)
    if t is None:
        raise RuntimeError("no *threads* binding; use with_threads(...)")
    return t


class with_threads:
    """Bind the ordered thread collection for the duration of a block
    (generator.clj:60-67).  Asserts the collection is sorted."""

    def __init__(self, threads: list):
        threads = list(threads)
        assert threads == sort_processes(threads), \
            f"threads not sorted: {threads}"
        self.threads = threads

    def __enter__(self):
        self._old = getattr(_ctx, "threads", None)
        _ctx.threads = self.threads
        return self

    def __exit__(self, *exc):
        _ctx.threads = self._old
        return False


def process_to_thread(test: dict, process):
    """process mod concurrency for ints; names pass through
    (generator.clj:69-74)."""
    if isinstance(process, int):
        return process % test["concurrency"]
    return process


def process_to_node(test: dict, process):
    """The node this process is likely talking to (generator.clj:76-83)."""
    thread = process_to_thread(test, process)
    if isinstance(thread, int):
        nodes = test["nodes"]
        return nodes[thread % len(nodes)]
    return None


# ---------------------------------------------------------------------------
# lifting plain objects into generators (generator.clj:40-52)
# ---------------------------------------------------------------------------


def gen_op(gen, test: dict, process) -> Optional[OpDict]:
    """Pull one operation from anything generator-like."""
    if gen is None:
        return None
    if hasattr(gen, "op") and callable(gen.op):
        return gen.op(test, process)
    if isinstance(gen, dict):
        return dict(gen)  # constantly yields (a copy of) itself
    if callable(gen):
        return gen(test, process) if _arity_two(gen) else gen()
    return gen


import weakref

# Keyed by weakref so entries die with the callable; an id()-keyed cache
# can hand a new function a dead function's arity after id reuse.
_ARITY_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _compute_arity_two(f) -> bool:
    import inspect

    try:
        sig = inspect.signature(f)
        pos = [p for p in sig.parameters.values()
               if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
        var = any(p.kind == p.VAR_POSITIONAL
                  for p in sig.parameters.values())
        required = [p for p in pos if p.default is p.empty]
        return var or (len(required) <= 2 and len(pos) >= 2)
    except (ValueError, TypeError):
        return True


def _arity_two(f) -> bool:
    """Can f be called with (test, process)?  (The reference dispatches on
    ArityException, generator.clj:46-52; we inspect the signature.)"""
    try:
        hit = _ARITY_CACHE.get(f)
        if hit is None:
            hit = _compute_arity_two(f)
            _ARITY_CACHE[f] = hit
        return hit
    except TypeError:  # not weak-referenceable; compute uncached
        return _compute_arity_two(f)


class InvalidOp(Exception):
    pass


class StreamLinter:
    """Emit-time well-formedness guard over a live generator stream.

    The post-run history linter (analyze/lint.py) finds a double-invoke
    hours after the generator emitted it; this catches the same defects
    AT THE MOMENT OF EMISSION, naming the offending generator, so a
    broken custom generator fails its first op instead of poisoning a
    whole run's history.  Tracks per-process open ops from the emitted
    stream (completions are closed by the worker via
    :meth:`on_complete`); raises the SAME stable diagnostics as the
    post-run linter:

      * H001 — a generator emitted an invoke for a process whose
        previous op is still open (single-threaded-process invariant,
        core.clj:387-404);
      * H002 — a generator emitted a completion-typed op for a process
        with no open invoke.

    Installed by ``core.prepare_test`` under ``test["__stream_lint__"]``
    behind the same ``JEPSEN_TPU_LINT`` opt-out as the post-run linter;
    nemesis emissions (:info journal entries, core.clj:315-327) are
    exempt exactly as there.  Thread-safe: workers share one instance.
    """

    def __init__(self):
        self._open: dict = {}  # process -> f of the open invoke
        self._lock = threading.Lock()

    def on_emit(self, op: OpDict, process, gen) -> None:
        if not isinstance(process, int):
            return  # nemesis journals :info events freely
        t = op.get("type", "invoke")  # workers apply the same default
        from .analyze.lint import Diagnostic, HistoryLintError

        with self._lock:
            if t == "invoke":
                prev = self._open.get(process)
                if prev is not None:
                    raise HistoryLintError([Diagnostic(
                        "H001", "error",
                        f"generator {gen!r} emitted invoke "
                        f"{op.get('f')!r} for process {process} while "
                        f"its {prev!r} op is still open (live stream "
                        f"lint; single-threaded-process invariant, "
                        f"core.clj:387-404)",
                        process=process, f=op.get("f"))])
                self._open[process] = op.get("f")
            elif t in ("ok", "fail", "info"):
                if process not in self._open:
                    raise HistoryLintError([Diagnostic(
                        "H002", "error",
                        f"generator {gen!r} emitted {t!r} completion "
                        f"for process {process} with no open invoke "
                        f"(live stream lint)",
                        process=process, f=op.get("f"))])
                del self._open[process]
            # unknown types fall through to the post-run linter's H003

    def on_complete(self, process) -> None:
        """The worker closed this process's op (any completion type —
        an :info retires the process id entirely)."""
        with self._lock:
            self._open.pop(process, None)


def op_and_validate(gen, test, process) -> Optional[OpDict]:
    """Ops must be None or dicts (generator.clj:26-35); with the live
    stream linter installed (``test["__stream_lint__"]``), emissions
    are additionally checked for H001/H002 at emit time."""
    op = gen_op(gen, test, process)
    if op is not None and not isinstance(op, dict):
        raise InvalidOp(f"generator {gen!r} produced non-map op {op!r}")
    if op is not None and isinstance(test, dict):
        linter = test.get("__stream_lint__")
        if linter is not None:
            linter.on_emit(op, process, gen)
    return op


class _Fn(Generator):
    def __init__(self, f):
        self.f = f

    def op(self, test, process):
        return self.f(test, process)


# ---------------------------------------------------------------------------
# combinators
# ---------------------------------------------------------------------------


class _Void(Generator):
    def op(self, test, process):
        return None


void = _Void()


class FMap(Generator):
    """Rename :f values via a mapping (generator.clj:90-98); used to wire a
    workload's op names onto a composed nemesis."""

    def __init__(self, f_map: dict | Callable, gen):
        self.f_map = f_map if callable(f_map) else \
            (lambda f, m=dict(f_map): m.get(f, f))
        self.gen = gen

    def op(self, test, process):
        op = gen_op(self.gen, test, process)
        if op is None:
            return None
        op = dict(op)
        op["f"] = self.f_map(op.get("f"))
        return op


f_map = FMap


class DelayFn(Generator):
    """Each op takes (f)() extra seconds (generator.clj:111-117)."""

    def __init__(self, f: Callable[[], float], gen):
        self.f = f
        self.gen = gen

    def op(self, test, process):
        sleep_seconds(self.f())
        return gen_op(self.gen, test, process)


def delay(dt: float, gen) -> Generator:
    return DelayFn(lambda: dt, gen)


def stagger(dt: float, gen) -> Generator:
    """Uniform random delay, mean dt, range [0, 2dt)
    (generator.clj:159-163)."""
    return DelayFn(lambda: _random.uniform(0, 2 * dt), gen)


def next_tick_nanos(anchor: int, dt: int, now: int | None = None) -> int:
    """Next instant after `now` separated from anchor by a multiple of dt
    (generator.clj:119-127)."""
    if now is None:
        now = time.monotonic_ns()
    return now + (dt - (now - anchor) % dt)


class DelayTil(Generator):
    """Emit ops as close as possible to multiples of dt from an epoch —
    aligns invocations across threads "for triggering race conditions"
    (generator.clj:134-157)."""

    def __init__(self, dt: float, gen, precache: bool = True):
        self.anchor = time.monotonic_ns()
        self.dt = int(dt * 1e9)
        self.gen = gen
        self.precache = precache

    def _sleep_til(self, t):
        while time.monotonic_ns() + 10_000 < t:
            sleep_seconds((t - time.monotonic_ns()) / 1e9)

    def op(self, test, process):
        if self.precache:
            op = gen_op(self.gen, test, process)
            self._sleep_til(next_tick_nanos(self.anchor, self.dt))
            return op
        self._sleep_til(next_tick_nanos(self.anchor, self.dt))
        return gen_op(self.gen, test, process)


delay_til = DelayTil


def sleep(dt: float) -> Generator:
    """dt seconds of nothing (generator.clj:165-168)."""
    return delay(dt, void)


class Once(Generator):
    """Invoke the underlying generator at most once
    (generator.clj:170-177)."""

    def __init__(self, gen):
        self.gen = gen
        self._lock = threading.Lock()
        self._emitted = False

    def op(self, test, process):
        with self._lock:
            if self._emitted:
                return None
            self._emitted = True
        return gen_op(self.gen, test, process)


once = Once


class Derefer(Generator):
    """Resolve a generator lazily at op time (generator.clj:179-189)."""

    def __init__(self, fgen: Callable[[], Any]):
        self.fgen = fgen

    def op(self, test, process):
        return gen_op(self.fgen(), test, process)


derefer = Derefer


class LogEvery(Generator):
    def __init__(self, msg):
        self.msg = msg

    def op(self, test, process):
        import logging

        logging.getLogger("jepsen").info(self.msg)
        return None


def log_every(msg) -> Generator:
    return LogEvery(msg)


def log(msg) -> Generator:
    """Log once, yield nil (generator.clj:198-201)."""
    return once(LogEvery(msg))


class Each(Generator):
    """A fresh copy of the underlying generator per process
    (generator.clj:203-228)."""

    def __init__(self, gen_fn: Callable[[], Any]):
        self.gen_fn = gen_fn
        self._gens: dict = {}
        self._lock = threading.Lock()

    def op(self, test, process):
        g = self._gens.get(process)
        if g is None:
            with self._lock:
                g = self._gens.setdefault(process, self.gen_fn())
        return gen_op(g, test, process)


each = Each


class Seq(Generator):
    """One op from the first generator, then the second, ... skipping
    exhausted ones immediately (generator.clj:231-243).  NB: unlike
    `concat`, this advances to the next generator after every op."""

    def __init__(self, coll: Iterable):
        self._iter = iter(coll)
        self._lock = threading.Lock()

    def op(self, test, process):
        while True:
            with self._lock:
                gen = next(self._iter, None)
            if gen is None:
                return None
            op = gen_op(gen, test, process)
            if op is not None:
                return op


seq = Seq


def _cycle(xs):
    import itertools

    return itertools.cycle(xs)


def start_stop(t1: float, t2: float) -> Generator:
    """sleep t1, :start, sleep t2, :stop, forever (generator.clj:245-251);
    the standard nemesis schedule."""
    return Seq(_cycle([sleep(t1), {"type": "info", "f": "start"},
                       sleep(t2), {"type": "info", "f": "stop"}]))


class Mix(Generator):
    """Uniform random choice among generators (generator.clj:253-262)."""

    def __init__(self, gens):
        self.gens = list(gens)

    def op(self, test, process):
        if not self.gens:
            return None
        return gen_op(_random.choice(self.gens), test, process)


mix = Mix


class _Cas(Generator):
    """Random read/write/cas mix over small ints (generator.clj:264-276)."""

    def op(self, test, process):
        r = _random.random()
        if r > 0.66:
            return {"type": "invoke", "f": "read", "value": None}
        if r > 0.33:
            return {"type": "invoke", "f": "write",
                    "value": _random.randrange(5)}
        return {"type": "invoke", "f": "cas",
                "value": (_random.randrange(5), _random.randrange(5))}


cas = _Cas()


class QueueGen(Generator):
    """Random enqueue (consecutive ints) / dequeue mix
    (generator.clj:279-290)."""

    def __init__(self):
        self._i = -1
        self._lock = threading.Lock()

    def op(self, test, process):
        if _random.random() < 0.5:
            with self._lock:
                self._i += 1
                return {"type": "invoke", "f": "enqueue", "value": self._i}
        return {"type": "invoke", "f": "dequeue", "value": None}


queue = QueueGen


class DrainQueue(Generator):
    """After the wrapped generator is exhausted, emit one dequeue per
    attempted enqueue (generator.clj:292-307)."""

    def __init__(self, gen):
        self.gen = gen
        self._outstanding = 0
        self._lock = threading.Lock()

    def op(self, test, process):
        op = gen_op(self.gen, test, process)
        if op is not None:
            if op.get("f") == "enqueue":
                with self._lock:
                    self._outstanding += 1
            return op
        with self._lock:
            self._outstanding -= 1
            if self._outstanding >= 0:
                return {"type": "invoke", "f": "dequeue", "value": None}
        return None


drain_queue = DrainQueue


class Limit(Generator):
    """At most n operations (generator.clj:309-316)."""

    def __init__(self, n: int, gen):
        self._life = n
        self.gen = gen
        self._lock = threading.Lock()

    def op(self, test, process):
        with self._lock:
            if self._life <= 0:
                return None
            self._life -= 1
        return gen_op(self.gen, test, process)


limit = Limit


class TimeLimit(Generator):
    """Ops until dt seconds elapse from the first request
    (generator.clj:318-329)."""

    def __init__(self, dt: float, gen):
        self.dt = dt
        self.gen = gen
        self._deadline = None
        self._lock = threading.Lock()

    def op(self, test, process):
        with self._lock:
            if self._deadline is None:
                self._deadline = time.monotonic() + self.dt
        if time.monotonic() <= self._deadline:
            return gen_op(self.gen, test, process)
        return None


time_limit = TimeLimit


class Filter(Generator):
    """Only ops satisfying f (generator.clj:331-341)."""

    def __init__(self, f: Callable[[OpDict], bool], gen):
        self.f = f
        self.gen = gen

    def op(self, test, process):
        while True:
            op = gen_op(self.gen, test, process)
            if op is None:
                return None
            if self.f(op):
                return op


filter = Filter  # noqa: A001 - mirrors the reference name


class On(Generator):
    """Forward to the source iff (f thread); rebinds *threads* to the
    matching subset (generator.clj:343-351)."""

    def __init__(self, f: Callable, source):
        self.f = f
        self.source = source

    def op(self, test, process):
        if not self.f(process_to_thread(test, process)):
            return None
        sub = [t for t in current_threads() if self.f(t)]
        with with_threads(sub):
            return gen_op(self.source, test, process)


on = On


class Reserve(Generator):
    """(reserve 5, writes, 10, cas, reads): thread-range partitioning
    with a default pool (generator.clj:353-396)."""

    def __init__(self, *args):
        assert args, "reserve needs a default generator"
        *pairs, self.default = args
        assert len(pairs) % 2 == 0, "reserve takes count/gen pairs + default"
        self.ranges = []  # [lower, upper, gen) in thread-index space
        n = 0
        for i in range(0, len(pairs), 2):
            count, gen = pairs[i], pairs[i + 1]
            self.ranges.append((n, n + count, gen))
            n += count
        self._n = n

    def op(self, test, process):
        threads = list(current_threads())
        thread = process_to_thread(test, process)
        idx = threads.index(thread)
        for lower, upper, gen in self.ranges:
            if idx < upper:
                with with_threads(threads[lower:upper]):
                    return gen_op(gen, test, process)
        with with_threads(threads[self._n:]):
            return gen_op(self.default, test, process)


reserve = Reserve


class Concat(Generator):
    """First non-nil op from the sources, in order
    (generator.clj:398-407)."""

    def __init__(self, *sources):
        self.sources = sources

    def op(self, test, process):
        for source in self.sources:
            op = gen_op(source, test, process)
            if op is not None:
                return op
        return None


concat = Concat


def nemesis(nemesis_gen, client_gen=None) -> Generator:
    """Route :nemesis to one generator, workers to another
    (generator.clj:410-418)."""
    if client_gen is None:
        return On(lambda t: t == "nemesis", nemesis_gen)
    return Concat(On(lambda t: t == "nemesis", nemesis_gen),
                  On(lambda t: t != "nemesis", client_gen))


def clients(client_gen) -> Generator:
    """Only clients (generator.clj:420-423)."""
    return On(lambda t: t != "nemesis", client_gen)


class Await(Generator):
    """Block every op until f returns (f runs once)
    (generator.clj:425-437)."""

    def __init__(self, f: Callable[[], Any], gen=None):
        self.f = f
        self.gen = gen
        self._lock = threading.Lock()
        self._ready = False

    def op(self, test, process):
        if not self._ready:
            with self._lock:
                if not self._ready:
                    self.f()
                    self._ready = True
        return gen_op(self.gen, test, process)


await_fn = Await


class Synchronize(Generator):
    """All of *threads* must arrive before any proceeds; synchronizes once
    (generator.clj:440-456).  Workers blocked here are released (with
    WorkerAbort) if the test aborts — the analog of the reference breaking
    barriers via thread interrupts (core.clj:204-245)."""

    def __init__(self, gen):
        self.gen = gen
        self._lock = threading.Lock()
        self._barrier = None
        self._clear = False

    def op(self, test, process):
        if not self._clear:
            from .util import AbortableBarrier

            with self._lock:
                if self._barrier is None and not self._clear:
                    self._barrier = AbortableBarrier(
                        len(current_threads()),
                        abort_event=test.get("__abort__"))
                barrier = self._barrier
            if not self._clear and barrier is not None:
                barrier.wait()
                self._clear = True
        return gen_op(self.gen, test, process)


synchronize = Synchronize


def phases(*generators) -> Generator:
    """Like concat, but all threads finish each phase before the next
    begins (generator.clj:458-462)."""
    return Concat(*[Synchronize(g) for g in generators])


def then(a, b) -> Generator:
    """b, synchronize, then a — backwards for pipeline composition
    (generator.clj:464-468)."""
    return Concat(b, Synchronize(a))


class SingleThreaded(Generator):
    """Exclusive lock around the underlying generator
    (generator.clj:470-477)."""

    def __init__(self, gen):
        self.gen = gen
        self._lock = threading.Lock()

    def op(self, test, process):
        with self._lock:
            return gen_op(self.gen, test, process)


singlethreaded = SingleThreaded


def barrier(gen) -> Generator:
    """When gen completes, synchronize, then nil (generator.clj:479-482)."""
    return then(void, gen)
