"""Harness self-test fixtures — in-process fake DB and client.

Reference: jepsen/src/jepsen/tests.clj — `noop-test` (12-25), the base
test map suites merge into, and `atom-db`/`atom-client` (27-56): a
CAS register backed by an in-process atom, letting the whole runner +
checker stack execute with zero cluster infrastructure (Tier 2 of the
test strategy, SURVEY.md §4).
"""

from __future__ import annotations

import threading
from dataclasses import replace

from . import checker as checker_mod
from . import client as client_mod
from . import db as db_mod
from . import generator as gen
from . import nemesis as nemesis_mod
from . import net as net_mod
from . import os as os_mod


def noop_test() -> dict:
    """Boring test stub (tests.clj:12-25)."""
    return {
        "nodes": ["n1", "n2", "n3", "n4", "n5"],
        "name": "noop",
        "os": os_mod.noop,
        "db": db_mod.noop,
        "net": net_mod.iptables,
        "client": client_mod.noop,
        "nemesis": nemesis_mod.noop,
        "generator": gen.void,
        "checker": checker_mod.unbridled_dionysus,
    }


class AtomRegister:
    """The shared atom: a lock-protected register."""

    def __init__(self, value=None):
        self.value = value
        self.lock = threading.Lock()

    def read(self):
        with self.lock:
            return self.value

    def write(self, v):
        with self.lock:
            self.value = v

    def cas(self, cur, new) -> bool:
        with self.lock:
            if self.value == cur:
                self.value = new
                return True
            return False


class AtomDB(db_mod.DB):
    """Resets the atom on setup (tests.clj:27-32)."""

    def __init__(self, state: AtomRegister):
        self.state = state

    def setup(self, test, node):
        self.state.write(0)

    def teardown(self, test, node):
        self.state.write("done")


class AtomClient(client_mod.Client):
    """CAS client over the atom (tests.clj:34-56)."""

    def __init__(self, state: AtomRegister):
        self.state = state

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        # keyed (independent) workloads travel values as [key value]
        # tuples; the completion must carry the SAME keyed shape, or
        # every other key's subhistory inherits this op's completion as
        # an orphan (un-keyed ops pass the key filter) — the silent
        # mis-pairing the history linter (analyze/lint.py, H002) flags.
        # Real clients do exactly this re-wrap (e.g. etcdemo's reads).
        from . import independent

        v = op.value
        key = None
        if independent.is_tuple(v):
            key, v = v.key, v.value
        if op.f == "write":
            self.state.write(v)
            return replace(op, type="ok")
        if op.f == "cas":
            cur, new = v
            return replace(op, type="ok" if self.state.cas(cur, new)
                           else "fail")
        if op.f == "read":
            val = self.state.read()
            if key is not None:
                return replace(op, type="ok",
                               value=independent.tuple_(key, val))
            return replace(op, type="ok", value=val)
        raise ValueError(f"unknown op {op.f!r}")


def atom_db(state: AtomRegister) -> AtomDB:
    return AtomDB(state)


def atom_client(state: AtomRegister) -> AtomClient:
    return AtomClient(state)
