"""Report helpers.

Reference: jepsen/src/jepsen/report.clj — `to` redirects stdout into a
store file while also printing (report.clj:7-16).
"""

from __future__ import annotations

import contextlib
import io
import sys


class to(contextlib.AbstractContextManager):
    """Tee stdout into a file for the duration of the block
    (report.clj:7-16)."""

    def __init__(self, filename: str):
        self.filename = filename

    def __enter__(self):
        self._f = open(self.filename, "w")
        self._old = sys.stdout
        outer = self

        class Tee(io.TextIOBase):
            def write(self, s):
                outer._old.write(s)
                outer._f.write(s)
                return len(s)

            def flush(self):
                outer._old.flush()
                outer._f.flush()

        sys.stdout = Tee()
        return self

    def __exit__(self, *exc):
        sys.stdout = self._old
        self._f.close()
        return False
