"""Fleet router: one front door, N ``stream.service`` workers.

Clients speak the unchanged stream line protocol to the router; the
router rendezvous-hashes each ``run_id`` onto a worker and forwards
the run's lines over a per-worker upstream connection, pumping worker
replies straight back.  What the fleet adds over one big service:

**Routing** (:func:`route_run`) is rendezvous (highest-random-weight)
hashing: every (run, worker) pair gets a deterministic score and the
run goes to its max.  Adding a worker moves only the runs that now
score higher on it (~1/N of the keyspace); removing one moves ONLY its
own runs — no re-shuffle of survivors, which matters because a moved
run means a re-checked prefix.

**Health** — a probe loop per worker on a ``reconnect.Backoff``
schedule: probe, on failure sleep the jittered backoff step and probe
again, and when the schedule is exhausted declare the worker dead and
take it out of the ring.  A success resets the schedule, so a worker
that recovers re-ramps from the base delay.

**Salvage** — a dead worker's open runs are not lost: workers run
with ``--persist-dir`` on shared storage, and the existing
abandon/persist path (stream/service.py) lands every open run's
prefix verdict in ``<persist>/<run>.json`` when the upstream
connection drops.  The router reads that snapshot back, answers the
client with a ``final`` (``finalized_by: "salvage"``), and re-routes
the run's future lines onto the survivors by replaying its header.

**One scrape** — the router's own ``/metrics`` and ``/api/stats``
answer with the MERGED view: every live worker is scraped and the
series are relabelled with ``worker="<id>"`` (text) / summed
(snapshot), so a fleet dashboard needs one target, not N.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import re
import socket
import socketserver
import threading
import time

from ..obs import metrics as obs_metrics
from ..reconnect import Backoff
from ..stream.service import _safe_run_id

log = logging.getLogger(__name__)

_M_ROUTED = obs_metrics.REGISTRY.counter(
    "jtpu_fleet_routed_total",
    "Run headers routed to a worker, by worker id", ("worker",))
_M_REROUTED = obs_metrics.REGISTRY.counter(
    "jtpu_fleet_rerouted_total",
    "Runs re-routed off their worker, by reason", ("reason",))
_M_SALVAGED = obs_metrics.REGISTRY.counter(
    "jtpu_fleet_salvaged_total",
    "Dead-worker open runs finalized from the persist-dir salvage "
    "path")
_M_PROBES = obs_metrics.REGISTRY.counter(
    "jtpu_fleet_probe_total",
    "Worker health probes, by result (ok/failed/dead)", ("result",))
_M_WORKERS = obs_metrics.REGISTRY.gauge(
    "jtpu_fleet_workers",
    "Live (admitted, probe-passing) workers behind the router")


@dataclasses.dataclass(frozen=True)
class WorkerSpec:
    """One checking-service worker the router can route at."""

    wid: str
    host: str
    port: int
    persist_dir: str | None = None


# ---------------------------------------------------------------------------
# rendezvous hashing
# ---------------------------------------------------------------------------


def rendezvous_score(wid: str, run_id: str) -> int:
    """Deterministic (worker, run) weight — blake2b over both ids, so
    the ring needs no virtual nodes and no shared state."""
    h = hashlib.blake2b(f"{wid}\x00{run_id}".encode(),
                        digest_size=8)
    return int.from_bytes(h.digest(), "big")


def route_run(run_id: str, workers) -> WorkerSpec | None:
    """Highest-random-weight choice over ``workers`` (iterable of
    WorkerSpec); ties break on wid so the choice is total."""
    best = None
    best_key = None
    for w in workers:
        key = (rendezvous_score(w.wid, str(run_id)), w.wid)
        if best_key is None or key > best_key:
            best, best_key = w, key
    return best


# ---------------------------------------------------------------------------
# scrape plumbing
# ---------------------------------------------------------------------------


def _http_get(host: str, port: int, target: str, *,
              timeout: float = 2.0) -> bytes:
    """Minimal HTTP/1.0 GET against a worker's protocol port (the
    stream service answers scrapes on the same socket)."""
    with socket.create_connection((host, port), timeout=timeout) as s:
        s.sendall(f"GET {target} HTTP/1.0\r\n\r\n".encode())
        buf = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    head, _, body = buf.partition(b"\r\n\r\n")
    if not head.startswith(b"HTTP/") or b" 200 " not in head.split(
            b"\r\n", 1)[0] + b" ":
        raise OSError(f"scrape {target} failed: "
                      f"{head.splitlines()[:1]!r}")
    return body


_SERIES_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$")


def merge_metrics_texts(texts: dict) -> str:
    """Merge per-worker Prometheus texts into one exposition: every
    series gains a ``worker="<id>"`` label; HELP/TYPE lines are
    deduplicated by metric name.  Worker ids come from the dict keys
    (ordered), so the output is deterministic for a given scrape."""
    helps: list[str] = []
    seen_meta = set()
    series: list[str] = []
    for wid, text in texts.items():
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("#"):
                parts = line.split(None, 3)
                if len(parts) >= 3 and parts[2] not in seen_meta \
                        and parts[1] in ("HELP", "TYPE"):
                    # keep both HELP and TYPE the first time the
                    # metric name appears
                    pass
                if len(parts) >= 3:
                    key = (parts[1], parts[2])
                    if key in seen_meta:
                        continue
                    seen_meta.add(key)
                helps.append(line)
                continue
            m = _SERIES_RE.match(line)
            if not m:
                continue
            name, labels, value = m.groups()
            if labels:
                inner = labels[1:-1]
                labels = '{worker="%s",%s}' % (wid, inner)
            else:
                labels = '{worker="%s"}' % wid
            series.append(f"{name}{labels} {value}")
    return "\n".join(helps + series) + "\n"


def merge_snapshots(snaps: dict) -> dict:
    """Merge per-worker ``/api/stats`` snapshots: numeric values are
    summed across workers (labelled dicts key-wise), the ``derived``
    block is dropped (ratios do not sum), and the raw per-worker
    snapshots ride along under ``workers`` for drill-down."""

    def _merge_val(a, b):
        if isinstance(a, dict) or isinstance(b, dict):
            a = a if isinstance(a, dict) else {}
            b = b if isinstance(b, dict) else {}
            return {k: _merge_val(a.get(k, 0), b.get(k, 0))
                    for k in set(a) | set(b)}
        try:
            return (a or 0) + (b or 0)
        except TypeError:
            return b if b is not None else a

    merged: dict = {}
    for snap in snaps.values():
        for name, entry in snap.items():
            if name == "derived" or not isinstance(entry, dict):
                continue
            cur = merged.get(name)
            if cur is None:
                merged[name] = {"type": entry.get("type"),
                                "help": entry.get("help"),
                                "values": entry.get("values", 0)}
            else:
                cur["values"] = _merge_val(cur["values"],
                                           entry.get("values", 0))
    return {"workers": dict(snaps),
            "n_workers": len(snaps),
            **{name: e for name, e in merged.items()}}


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------


def _default_backoff() -> Backoff:
    # probe ramp: 50ms .. 2s, 8 attempts ≈ a few seconds from first
    # failure to a dead verdict — fast enough that clients notice a
    # crash as one salvaged final, slow enough to ride out a GC pause
    return Backoff(base=0.05, cap=2.0, factor=2.0, max_attempts=8,
                   jitter=0.5)


class FleetRouter:
    """Worker ring + health + salvage — the policy object the TCP
    front end (:func:`make_router_server`) and the fleet supervisor
    (fleet/__main__.py) share."""

    def __init__(self, workers=(), *, admission=None,
                 probe_interval: float = 0.25,
                 backoff_factory=_default_backoff,
                 require_warmup: bool = False,
                 on_spawn=None):
        #: called (no args, any thread) when admission decides
        #: "spawn-worker" — the supervisor's scale-up hook
        self.on_spawn = on_spawn
        self._lock = threading.RLock()
        self._workers: dict[str, WorkerSpec] = {}
        self._dead: dict[str, WorkerSpec] = {}
        self._backoffs: dict[str, Backoff] = {}
        self._backoff_factory = backoff_factory
        self.admission = admission
        self.probe_interval = probe_interval
        self.require_warmup = require_warmup
        self._probe_stop = threading.Event()
        self._probe_thread = None
        for w in workers:
            self.admit_worker(w)

    # -- membership ----------------------------------------------------

    def admit_worker(self, spec: WorkerSpec,
                     warmup_report: dict | None = None) -> bool:
        """Add a worker to the ring.  With ``require_warmup`` the
        worker must present a verified warm-boot report
        (fleet/warmup.py) — a cold worker is NOT admitted: routing
        runs at it would spend their first seconds compiling."""
        if self.require_warmup and not (
                warmup_report and warmup_report.get("verified")):
            log.warning("fleet: worker %s refused admission "
                        "(warmup report %r not verified)",
                        spec.wid, warmup_report)
            return False
        with self._lock:
            self._workers[spec.wid] = spec
            self._dead.pop(spec.wid, None)
            self._backoffs[spec.wid] = self._backoff_factory()
            _M_WORKERS.set(len(self._workers))
        return True

    def remove_worker(self, wid: str, *, reason: str = "leave") -> None:
        log.info("fleet: worker %s leaves the ring (%s)", wid, reason)
        with self._lock:
            spec = self._workers.pop(wid, None)
            if spec is not None:
                self._dead[wid] = spec
            self._backoffs.pop(wid, None)
            _M_WORKERS.set(len(self._workers))

    def workers(self) -> list[WorkerSpec]:
        with self._lock:
            return list(self._workers.values())

    def worker(self, wid: str) -> WorkerSpec | None:
        with self._lock:
            return self._workers.get(wid) or self._dead.get(wid)

    def is_live(self, wid: str) -> bool:
        with self._lock:
            return wid in self._workers

    # -- routing -------------------------------------------------------

    def route(self, run_id: str) -> WorkerSpec | None:
        return route_run(run_id, self.workers())

    # -- health --------------------------------------------------------

    def probe_worker(self, spec: WorkerSpec, *,
                     timeout: float = 1.0) -> bool:
        """One liveness probe: scrape ``/api/stats`` (proves the
        protocol loop answers, not merely that the port accepts)."""
        try:
            body = _http_get(spec.host, spec.port, "/api/stats",
                             timeout=timeout)
            json.loads(body.decode() or "{}")
        except (OSError, ValueError):
            _M_PROBES.inc(result="failed")
            return False
        _M_PROBES.inc(result="ok")
        return True

    def worker_failed(self, wid: str) -> None:
        """A forwarder hit a hard send/connect error: treat as dead
        immediately (the probe loop would get there anyway; a client
        mid-run shouldn't wait for it)."""
        if self.is_live(wid):
            log.warning("fleet: worker %s failed mid-stream; "
                        "removing from ring", wid)
            _M_PROBES.inc(result="dead")
            self.remove_worker(wid, reason="worker-died")

    def probe_all_once(self, *, sleep=time.sleep) -> None:
        """One probe round: each live worker probed once; a failing
        worker is re-probed on its Backoff schedule within this round
        and declared dead when the schedule exhausts."""
        for spec in self.workers():
            bo = self._backoffs.get(spec.wid)
            if bo is None:
                continue
            if self.probe_worker(spec):
                bo.reset()
                continue
            while not bo.exhausted():
                sleep(bo.step())
                if self.probe_worker(spec):
                    bo.reset()
                    break
            else:
                _M_PROBES.inc(result="dead")
                self.remove_worker(spec.wid, reason="probe-exhausted")

    def start_probes(self) -> None:
        if self._probe_thread is not None:
            return

        def loop():
            while not self._probe_stop.wait(self.probe_interval):
                try:
                    self.probe_all_once(
                        sleep=lambda s: self._probe_stop.wait(s))
                except Exception:  # noqa: BLE001 — probe must survive
                    log.warning("fleet: probe round failed",
                                exc_info=True)

        self._probe_thread = threading.Thread(
            target=loop, name="fleet-probes", daemon=True)
        self._probe_thread.start()

    def stop_probes(self) -> None:
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5)
            self._probe_thread = None

    # -- salvage -------------------------------------------------------

    def salvage_final(self, wid: str, run_id: str, *,
                      wait_s: float = 2.0) -> dict | None:
        """A dead worker's persisted snapshot for ``run_id``: the
        worker's abandon path (stream/service.py) finalizes open runs
        when its connection drops and lands ``{"...", "final": ...}``
        in its persist dir; we poll briefly for the final to appear
        (the worker may still be flushing as we arrive)."""
        spec = self.worker(wid)
        if spec is None or not spec.persist_dir:
            return None
        path = os.path.join(spec.persist_dir,
                            f"{_safe_run_id(run_id)}.json")
        deadline = time.monotonic() + wait_s
        snap = None
        while time.monotonic() < deadline:
            try:
                with open(path) as f:
                    snap = json.load(f)
            except (OSError, ValueError):
                snap = None
            if snap and "final" in snap:
                break
            time.sleep(0.05)
        if snap is None:
            return None
        _M_SALVAGED.inc()
        return snap

    # -- aggregation ---------------------------------------------------

    def scrape_workers(self, target: str) -> dict:
        """target -> {wid: payload} over the live ring (failed scrapes
        skipped; the probe loop deals with the worker)."""
        out = {}
        for spec in self.workers():
            try:
                out[spec.wid] = _http_get(spec.host, spec.port,
                                          target)
            except OSError:
                log.debug("fleet: scrape of %s failed", spec.wid,
                          exc_info=True)
        return out

    def aggregate_metrics(self) -> str:
        texts = {wid: body.decode()
                 for wid, body in
                 self.scrape_workers("/metrics").items()}
        # the router's own registry (routing/probe/salvage counters)
        # joins the merge as a pseudo-worker
        texts["router"] = obs_metrics.render()
        return merge_metrics_texts(texts)

    def aggregate_stats(self) -> dict:
        snaps = {}
        for wid, body in self.scrape_workers("/api/stats").items():
            try:
                snaps[wid] = json.loads(body.decode())
            except ValueError:
                continue
        snaps["router"] = obs_metrics.snapshot()
        return merge_snapshots(snaps)


# ---------------------------------------------------------------------------
# the TCP front end
# ---------------------------------------------------------------------------


class _Upstream:
    """One router->worker connection inside a client session: a
    socket, a writer file, and a reader thread pumping worker replies
    back to the client."""

    def __init__(self, spec: WorkerSpec, emit):
        self.spec = spec
        self.sock = socket.create_connection((spec.host, spec.port),
                                             timeout=10.0)
        self.sock.settimeout(None)
        self.wfile = self.sock.makefile("w", encoding="utf-8")
        self.rfile = self.sock.makefile("r", encoding="utf-8")
        self.thread = threading.Thread(
            target=self._pump, args=(emit,),
            name=f"fleet-pump-{spec.wid}", daemon=True)
        self.thread.start()

    def _pump(self, emit):
        try:
            for line in self.rfile:
                line = line.strip()
                if line:
                    emit(line)
        except (OSError, ValueError):
            pass

    def send(self, line: str) -> None:
        self.wfile.write(line + "\n")
        self.wfile.flush()

    def close_write(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_WR)
        except OSError:
            pass

    def close(self, *, join: bool = True) -> None:
        self.close_write()
        if join:
            self.thread.join(timeout=5)
        try:
            self.sock.close()
        except OSError:
            pass


class _Session:
    """One client connection's routing state: which worker each run
    went to, the header to replay on re-route, which runs are open."""

    def __init__(self, router: FleetRouter, emit):
        self.router = router
        self.emit = emit  # takes a RAW json line (str)
        self.lock = threading.Lock()
        self.upstreams: dict[str, _Upstream] = {}
        self.run_worker: dict[str, str] = {}
        self.run_header: dict[str, str] = {}
        self.open_runs: set[str] = set()

    def _emit_obj(self, d: dict) -> None:
        self.emit(json.dumps(d, separators=(",", ":")))

    def _upstream(self, spec: WorkerSpec) -> _Upstream:
        up = self.upstreams.get(spec.wid)
        if up is None:
            def emit_line(line: str, _wid=spec.wid):
                # a 'final' reply closes the run in our books
                try:
                    d = json.loads(line)
                except ValueError:
                    d = {}
                rid = d.get("run")
                if rid is not None and ("final" in d
                                        or "error" in d):
                    with self.lock:
                        self.open_runs.discard(str(rid))
                self.emit(line)
            up = _Upstream(spec, emit_line)
            self.upstreams[spec.wid] = up
        return up

    def _salvage_and_reroute(self, run_id: str, dead_wid: str,
                             *, reroute: bool) -> WorkerSpec | None:
        """The dead-worker path for one run: drop the dead upstream,
        emit the salvaged final, and (for a run with more lines
        coming) replay its header at the survivor so the suffix keeps
        streaming."""
        up = self.upstreams.pop(dead_wid, None)
        if up is not None:
            up.close(join=False)
        self.router.worker_failed(dead_wid)
        snap = self.router.salvage_final(dead_wid, run_id)
        final = (snap or {}).get("final")
        if final is not None:
            final = dict(final)
            final["finalized_by"] = "salvage"
            self._emit_obj({"run": run_id, "final": final})
        elif snap is not None:
            self._emit_obj({"run": run_id, "live": snap,
                            "salvaged": True})
        else:
            self._emit_obj(
                {"run": run_id,
                 "error": f"worker {dead_wid} died with no "
                          f"salvageable snapshot for this run"})
        with self.lock:
            self.open_runs.discard(run_id)
        if not reroute:
            return None
        spec = self.router.route(run_id)
        if spec is None:
            self._emit_obj({"run": run_id,
                            "error": "no live workers"})
            return None
        _M_REROUTED.inc(reason="rerouted-after-death")
        header = self.run_header.get(run_id)
        try:
            up2 = self._upstream(spec)
            if header:
                up2.send(header)
                _M_ROUTED.inc(worker=spec.wid)
            self.run_worker[run_id] = spec.wid
            with self.lock:
                self.open_runs.add(run_id)
        except OSError:
            self.router.worker_failed(spec.wid)
            return None
        return spec

    def handle_line(self, raw: str) -> None:
        try:
            d = json.loads(raw)
        except ValueError:
            self._emit_obj({"run": None,
                            "error": "line is not valid JSON"})
            return
        if d.get("drain") and "run" not in d:
            # broadcast: every worker this session touched drains
            for up in list(self.upstreams.values()):
                try:
                    up.send(raw)
                except OSError:
                    self.router.worker_failed(up.spec.wid)
            return
        run_id = str(d.get("run")) if d.get("run") is not None \
            else None
        if run_id is None:
            self._emit_obj({"run": None,
                            "error": "line carries no run id"})
            return
        is_header = "model" in d and "op" not in d
        if is_header and self.router.admission is not None:
            from .admission import scale_signal

            decision = self.router.admission.decide(
                scale_signal(self.router.aggregate_stats()))
            if decision == "shed":
                self._emit_obj({"run": run_id,
                                "overloaded": "admission"})
                return
            if decision == "spawn-worker" \
                    and self.router.on_spawn is not None:
                try:
                    self.router.on_spawn()
                except Exception:  # noqa: BLE001 — advisory only
                    log.warning("fleet: spawn hook failed",
                                exc_info=True)
        wid = self.run_worker.get(run_id)
        spec = self.router.worker(wid) if wid else None
        if wid is None or spec is None \
                or not self.router.is_live(wid):
            if wid is not None:
                # our worker died between lines: salvage, then route
                # the rest of this run at a survivor
                spec = self._salvage_and_reroute(run_id, wid,
                                                 reroute=True)
                if spec is None:
                    return
            else:
                spec = self.router.route(run_id)
                if spec is None:
                    self._emit_obj({"run": run_id,
                                    "error": "no live workers"})
                    return
                self.run_worker[run_id] = spec.wid
        if is_header:
            self.run_header[run_id] = raw
            with self.lock:
                self.open_runs.add(run_id)
            _M_ROUTED.inc(worker=spec.wid)
        try:
            self._upstream(spec).send(raw)
        except OSError:
            replacement = self._salvage_and_reroute(
                run_id, spec.wid, reroute=not d.get("end"))
            if replacement is not None and not is_header \
                    and "op" in d:
                # the op that hit the dead socket continues the run on
                # the survivor (the salvaged prefix is already final;
                # the survivor checks the suffix as its own run)
                try:
                    self._upstream(replacement).send(raw)
                except OSError:
                    pass

    def close(self) -> None:
        # EOF from the client: close write sides so workers finalize
        # (their serve_lines sees EOF -> end_all), then join pumps so
        # every final reaches the client before we hang up
        for up in self.upstreams.values():
            up.close_write()
        for up in self.upstreams.values():
            up.close()


class _RouterHandler(socketserver.StreamRequestHandler):
    def handle(self):
        from ..stream.service import _SCRAPE_RE

        srv = self.server
        router: FleetRouter = srv.router
        first = self.rfile.peek(16)
        m = _SCRAPE_RE.match(first)
        if m:
            try:
                while True:
                    line = self.rfile.readline()
                    if not line or line in (b"\r\n", b"\n"):
                        break
            except OSError:
                pass
            target = m.group(2).decode()
            if target == "/metrics":
                body = router.aggregate_metrics().encode()
                ctype = ("text/plain; version=0.0.4; "
                         "charset=utf-8")
            else:
                body = json.dumps(router.aggregate_stats()).encode()
                ctype = "application/json"
            try:
                self.wfile.write(
                    b"HTTP/1.0 200 OK\r\n"
                    + f"Content-Type: {ctype}\r\n".encode()
                    + f"Content-Length: {len(body)}\r\n".encode()
                    + b"Connection: close\r\n\r\n" + body)
            except OSError:
                pass
            return
        wlock = threading.Lock()

        def emit(line: str) -> None:
            with wlock:
                try:
                    self.wfile.write((line + "\n").encode())
                    self.wfile.flush()
                except OSError:
                    pass

        session = _Session(router, emit)
        try:
            for raw in self.rfile:
                raw = raw.decode("utf-8", "replace").strip()
                if raw:
                    session.handle_line(raw)
        except OSError:
            pass
        finally:
            session.close()


class _RouterServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def make_router_server(host: str, port: int,
                       router: FleetRouter) -> _RouterServer:
    srv = _RouterServer((host, port), _RouterHandler)
    srv.router = router
    return srv
