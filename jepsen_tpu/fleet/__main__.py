"""``python -m jepsen_tpu.fleet`` — boot a routed checking fleet.

Spawns N ``python -m jepsen_tpu.stream --listen`` worker processes
(each with its own fleet-cache segment and the shared persist dir),
warm-boots and admission-gates each one, then serves the stream line
protocol on the router port.  Scale-out is wired: when the admission
controller's signal says "spawn-worker", the supervisor forks another
worker (up to ``--max-workers``), warm-boots it, and adds it to the
ring — clients notice only that shedding stops.

SIGTERM drains the tier: workers get SIGTERM (their graceful-drain
handler finalizes open runs and exits 0), then the router stops.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import subprocess
import sys
import threading

log = logging.getLogger("jepsen_tpu.fleet")

_LISTEN_MARK = "stream service listening on "
_WARMUP_MARK = "stream service warmup:"


class WorkerProc:
    """One supervised worker subprocess + its parsed boot lines."""

    def __init__(self, wid: str, args, cmd: list[str]):
        self.wid = wid
        self.proc = subprocess.Popen(
            cmd, stderr=subprocess.PIPE, stdout=subprocess.DEVNULL,
            text=True)
        self.address: tuple[str, int] | None = None
        self.warmup: dict | None = None
        self._boot(timeout=args.boot_timeout)

    def _boot(self, *, timeout: float) -> None:
        from .warmup import parse_warmup_line

        def read_stderr():
            for line in self.proc.stderr:
                line = line.strip()
                if _WARMUP_MARK in line:
                    self.warmup = parse_warmup_line(line)
                elif line.startswith(_LISTEN_MARK):
                    host, _, port = line[len(_LISTEN_MARK):]\
                        .rpartition(":")
                    self.address = (host, int(port))
                    booted.set()
                else:
                    log.info("worker %s: %s", self.wid, line)

        booted = threading.Event()
        t = threading.Thread(target=read_stderr, daemon=True,
                             name=f"fleet-stderr-{self.wid}")
        t.start()
        if not booted.wait(timeout):
            self.proc.kill()
            raise RuntimeError(
                f"worker {self.wid} did not report a listen address "
                f"within {timeout}s")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m jepsen_tpu.fleet",
        description="Routed multi-worker checking fleet: N stream "
                    "workers behind a rendezvous-hash router with "
                    "health probes, dead-worker salvage, a shared "
                    "verdict-cache store, and warm-boot admission.")
    p.add_argument("--workers", type=int, default=2,
                   help="Initial worker count.")
    p.add_argument("--max-workers", type=int, default=8,
                   help="Scale-out ceiling for spawn-worker signals.")
    p.add_argument("--listen", metavar="HOST:PORT",
                   default="127.0.0.1:7777",
                   help="Router listen address (the client-facing "
                        "protocol + aggregated /metrics port).")
    p.add_argument("--cache-root", metavar="DIR", default=None,
                   help="Fleet verdict-cache store root "
                        "(fleet/cachestore.py layout); default: "
                        "store-managed.")
    p.add_argument("--persist-dir", metavar="DIR", default=None,
                   help="Shared persist dir for run snapshots — the "
                        "dead-worker salvage source.  Default: "
                        "<cache-root>/persist.")
    p.add_argument("--warmup", metavar="MANIFEST", default=None,
                   help="Warm-boot manifest or BENCH_trace_*.json "
                        "handed to every worker; admission requires "
                        "a verified report.")
    p.add_argument("--model", default=None,
                   help="Default model workers open headerless runs "
                        "with.")
    p.add_argument("--probe-interval", type=float, default=0.25)
    p.add_argument("--op-budget", type=int, default=None)
    p.add_argument("--idle-timeout", type=float, default=None)
    args = p.parse_args(argv)
    args.boot_timeout = 120.0
    logging.basicConfig(level=logging.INFO)

    from .. import store
    from .admission import AdmissionController
    from .router import FleetRouter, WorkerSpec, make_router_server

    cache_root = args.cache_root or os.path.join(
        store.BASE, "fleet_cache")
    persist = args.persist_dir or os.path.join(cache_root, "persist")
    os.makedirs(persist, exist_ok=True)

    state = {"n": 0, "procs": {}}
    lock = threading.Lock()

    def worker_cmd(wid: str) -> list[str]:
        cmd = [sys.executable, "-m", "jepsen_tpu.stream",
               "--listen", "127.0.0.1:0",
               "--fleet-cache", cache_root,
               "--worker-id", wid,
               "--persist-dir", persist]
        if args.warmup:
            cmd += ["--warmup", args.warmup]
        if args.model:
            cmd += ["--model", args.model]
        if args.op_budget is not None:
            cmd += ["--op-budget", str(args.op_budget)]
        if args.idle_timeout is not None:
            cmd += ["--idle-timeout", str(args.idle_timeout)]
        return cmd

    def spawn_worker() -> bool:
        with lock:
            if len(state["procs"]) >= args.max_workers:
                log.info("fleet: at max-workers=%d, not spawning",
                         args.max_workers)
                return False
            state["n"] += 1
            wid = f"w{state['n']}"
        log.info("fleet: spawning worker %s", wid)
        try:
            wp = WorkerProc(wid, args, worker_cmd(wid))
        except RuntimeError:
            log.warning("fleet: worker %s failed to boot", wid,
                        exc_info=True)
            return False
        spec = WorkerSpec(wid, wp.address[0], wp.address[1], persist)
        if not router.admit_worker(spec, warmup_report=wp.warmup):
            wp.proc.terminate()
            return False
        with lock:
            state["procs"][wid] = wp
        log.info("fleet: worker %s admitted at %s:%d (warmup=%s)",
                 wid, spec.host, spec.port, wp.warmup)
        return True

    router = FleetRouter(
        admission=AdmissionController(),
        probe_interval=args.probe_interval,
        require_warmup=bool(args.warmup),
        on_spawn=lambda: threading.Thread(
            target=spawn_worker, daemon=True).start())
    for _ in range(max(1, args.workers)):
        spawn_worker()
    if not router.workers():
        log.error("fleet: no worker passed admission; giving up")
        return 1
    router.start_probes()

    host, _, port = args.listen.rpartition(":")
    srv = make_router_server(host or "127.0.0.1", int(port), router)

    def _sigterm(_signo, _frame):
        def drain():
            log.info("fleet: draining %d workers",
                     len(state["procs"]))
            with lock:
                procs = dict(state["procs"])
            for wid, wp in procs.items():
                try:
                    wp.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
            for wid, wp in procs.items():
                try:
                    wp.proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    wp.proc.kill()
            srv.shutdown()
        threading.Thread(target=drain, name="fleet-drain",
                         daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _sigterm)
    except ValueError:
        pass
    print(f"fleet router listening on "
          f"{srv.server_address[0]}:{srv.server_address[1]} with "
          f"{len(router.workers())} worker(s)",
          file=sys.stderr, flush=True)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        srv.shutdown()
        _sigterm(None, None)
    router.stop_probes()
    return 0


if __name__ == "__main__":
    sys.exit(main())
