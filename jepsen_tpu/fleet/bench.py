"""The fleet bench tier — ``python bench.py --fleet-tier``.

Boots a 2-worker routed fleet IN PROCESS (real TCP between router and
workers, real sockets from the clients), warm-boots the steady-state
kernels first, then drives a synthetic client swarm at the router in
rungs (1, 2, 4, 8 concurrent clients) to find the **throughput knee**
— the rung past which adding clients stops buying events/sec.  Writes
``BENCH_fleet.json`` (numbers) and ``BENCH_trace_fleet.json`` (the
flight recording: ``device.compile`` spans prove the warmup did the
compiling and the steady state did none).

Three gates ride on the numbers (tools/obs_guard.py enforces them):

  * **parity** — a sample of routed finals is re-checked through a
    single in-process StreamService; verdict/engine/stream stats
    (minus cache counters) must match bit-for-bit.  A fleet that
    answers fast but differently from one service is broken, not fast.
  * **warmup verified** — the warm-boot report's zero-miss re-probe
    passed.
  * **zero steady-state compiles** — the kernel cache's miss counter
    does not move while the swarm runs: every kernel the steady state
    needed was compiled at boot.
"""

from __future__ import annotations

import json
import os
import random
import socket
import tempfile
import threading
import time

#: parity re-checks are a full second check each — sample, don't sweep
_PARITY_SAMPLE = 8


def _mk_history(seed: int, n_ops: int):
    from ..synth import register_history

    rng = random.Random(seed)
    return register_history(rng, n_ops=n_ops, n_procs=6, overlap=4,
                            quiesce_every=8, n_values=5, cas=False)


def _op_lines(run_id: str, h) -> list[str]:
    lines = [json.dumps({"run": run_id, "model": "register"})]
    lines += [json.dumps({"run": run_id, "op": op.to_dict()})
              for op in h]
    lines.append(json.dumps({"run": run_id, "end": True}))
    return lines


def _strip_cache(summary: dict) -> dict:
    """A final summary with the cache counters dropped — they depend
    on what else the fleet checked, not on this history."""
    out = dict(summary)
    stream = dict(out.get("stream") or {})
    for k in list(stream):
        if k.startswith("cache_"):
            stream.pop(k)
    out["stream"] = stream
    out.pop("finalized_by", None)
    return out


def _single_service_final(h) -> dict:
    """The oracle: the same history through ONE in-process service
    with a fresh in-memory cache."""
    from ..stream.service import StreamService

    svc = StreamService()
    replies: list[dict] = []
    rid = "parity"
    for line in _op_lines(rid, h):
        svc.handle_line(line, replies.append)
    final = [d for d in replies if "final" in d]
    assert final, "single service never finalized the parity run"
    return _strip_cache(final[-1]["final"])


def _stream_via_router(port: int, runs: list) -> dict:
    """One synthetic client: stream every (run_id, history) over one
    router connection; returns finals + shed/error counts."""
    out = {"finals": {}, "overloaded": 0, "errors": 0}
    s = socket.create_connection(("127.0.0.1", port), timeout=60)
    w = s.makefile("w", encoding="utf-8")
    r = s.makefile("r", encoding="utf-8")
    for rid, h in runs:
        for line in _op_lines(rid, h):
            w.write(line + "\n")
        w.flush()
    s.shutdown(socket.SHUT_WR)
    for raw in r:
        raw = raw.strip()
        if not raw:
            continue
        d = json.loads(raw)
        if "final" in d:
            out["finals"][d["run"]] = d["final"]
        elif "overloaded" in d:
            out["overloaded"] += 1
        elif "error" in d:
            out["errors"] += 1
    s.close()
    return out


def _default_warm_shapes(repo: str):
    """The steady-state shape set: the committed 1k trace's compile
    spans when present, plus the small-segment shapes the streaming
    folds actually use (quantized dims for short quiescence runs)."""
    from .warmup import WarmShape, load_shapes

    shapes = []
    trace = os.path.join(repo, "BENCH_trace_1k.json")
    if os.path.exists(trace):
        try:
            shapes = load_shapes(trace)
        except (OSError, ValueError):
            shapes = []
    seen = set(shapes)
    for n_det_pad in (64, 128, 256):
        for frontier in (64, 128):
            s = WarmShape(n_det_pad=n_det_pad, frontier=frontier)
            if s not in seen:
                seen.add(s)
                shapes.append(s)
    return shapes


def run_fleet_tier(repo: str, *, quick: bool = False) -> dict:
    from .. import obs as _obs
    from ..checker import linearizable as lin
    from ..stream.service import make_server
    from .cachestore import FleetCacheStore
    from .router import FleetRouter, WorkerSpec, make_router_server
    from .warmup import warm_boot

    _obs.enable(True)
    n_ops = 120 if quick else 400
    runs_per_client = 2 if quick else 3
    rungs = [1, 2, 4] if quick else [1, 2, 4, 8]
    out: dict = {"metric": "fleet tier: routed multi-worker checking",
                 "quick": quick, "workers": 2, "n_ops": n_ops,
                 "runs_per_client": runs_per_client}

    # --- warm boot ----------------------------------------------------
    shapes = _default_warm_shapes(repo)
    out["warmup"] = warm_boot(shapes)

    # --- the fleet: 2 workers + router, all in process ----------------
    tmp = tempfile.mkdtemp(prefix="fleet-bench-")
    cache_root = os.path.join(tmp, "cache")
    persist = os.path.join(tmp, "persist")
    servers = []
    specs = []
    caches = []
    for i in range(2):
        cache = FleetCacheStore(cache_root, worker_id=f"w{i}")
        caches.append(cache)
        srv = make_server("127.0.0.1", 0, cache=cache,
                          persist_dir=persist)
        threading.Thread(target=srv.serve_forever,
                         daemon=True).start()
        servers.append(srv)
        specs.append(WorkerSpec(f"w{i}", "127.0.0.1",
                                srv.server_address[1], persist))
    router = FleetRouter(specs)
    router.start_probes()
    rsrv = make_router_server("127.0.0.1", 0, router)
    threading.Thread(target=rsrv.serve_forever, daemon=True).start()
    rport = rsrv.server_address[1]

    # --- the swarm ramp ----------------------------------------------
    misses0 = lin.KERNEL_CACHE_STATS["misses"]
    ramp = []
    all_finals: dict = {}
    all_hist: dict = {}
    seed = 1000
    for clients in rungs:
        plans = []
        for c in range(clients):
            runs = []
            for j in range(runs_per_client):
                seed += 1
                rid = f"s{seed}"
                h = _mk_history(seed, n_ops)
                all_hist[rid] = h
                runs.append((rid, h))
            plans.append(runs)
        results: list = [None] * clients
        t0 = time.perf_counter()
        threads = [threading.Thread(
            target=lambda i=i, p=p: results.__setitem__(
                i, _stream_via_router(rport, p)))
            for i, p in enumerate(plans)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        events = sum(len(h) for p in plans for _rid, h in p)
        finals = {}
        shed = errors = 0
        for res in results:
            finals.update(res["finals"])
            shed += res["overloaded"]
            errors += res["errors"]
        all_finals.update(finals)
        expected = clients * runs_per_client
        ramp.append({
            "clients": clients,
            "runs": expected,
            "finals": len(finals),
            "events_total": events,
            "wall_s": round(wall, 4),
            "events_per_sec": round(events / wall, 1) if wall else None,
            "overloaded": shed,
            "errors": errors,
            "shed_rate": round(shed / max(1, shed + events), 4),
        })
    out["steady_state_compile_misses"] = (
        lin.KERNEL_CACHE_STATS["misses"] - misses0)

    # --- the knee -----------------------------------------------------
    best = max(ramp, key=lambda r: r["events_per_sec"] or 0)
    knee = ramp[0]
    for prev, cur in zip(ramp, ramp[1:]):
        if (cur["events_per_sec"] or 0) \
                < 1.15 * (prev["events_per_sec"] or 1):
            knee = prev
            break
        knee = cur
    out["ramp"] = ramp
    out["knee"] = {"clients": knee["clients"],
                   "events_per_sec": knee["events_per_sec"],
                   "peak_clients": best["clients"],
                   "peak_events_per_sec": best["events_per_sec"]}

    # --- parity vs one service (sampled) ------------------------------
    rng = random.Random(7)
    sample = rng.sample(sorted(all_finals),
                        min(_PARITY_SAMPLE, len(all_finals)))
    out["parity_sampled"] = len(sample)
    out["parity_total_runs"] = len(all_finals)  # not all re-checked
    parity = True
    for rid in sample:
        want = _single_service_final(all_hist[rid])
        got = _strip_cache(all_finals[rid])
        if got != want:
            parity = False
            out.setdefault("parity_diffs", []).append(
                {"run": rid, "routed": got, "single": want})
    out["parity"] = parity

    # --- aggregated scrape sanity ------------------------------------
    stats = router.aggregate_stats()
    out["scrape"] = {
        "n_workers": stats.get("n_workers"),
        "has_routed_counter":
            "jtpu_fleet_routed_total" in stats,
        "has_stream_ops":
            "jtpu_stream_ops_ingested_total" in stats,
    }

    # --- teardown -----------------------------------------------------
    router.stop_probes()
    rsrv.shutdown()
    rsrv.server_close()
    for srv in servers:
        srv.shutdown()
        srv.server_close()
    for cache in caches:
        cache.close()

    path = os.path.join(repo, "BENCH_fleet.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    _obs.write_trace(os.path.join(repo, "BENCH_trace_fleet.json"))
    out["trace"] = "BENCH_trace_fleet.json (device.compile spans: " \
                   "warmup pays the tax, steady state pays none)"
    print(json.dumps({
        "metric": "fleet: routed events/sec at the throughput knee "
                  f"(2 workers, {n_ops}-op runs)",
        "value": out["knee"]["events_per_sec"],
        "unit": "events/sec",
        "detail": out,
    }))
    return out
