"""Warm-boot: compile the steady-state bucket kernels BEFORE a worker
is admitted to the fleet.

A cold ``stream.service`` worker pays the 1.4-2.4s-per-kernel XLA
compile tax on its first runs — exactly the runs the router just
routed at it because it looked healthy.  The warm-boot gate inverts
that: at worker start, :func:`warm_boot` compiles every kernel shape
the steady state needs (shapes read from a recorded
``BENCH_trace_*.json``'s ``device.compile`` spans, or from an explicit
shape manifest) and **verifies** the warmth by re-requesting each
kernel and asserting a zero miss delta on
``checker.linearizable.KERNEL_CACHE_STATS``.  Only a verified worker
is admitted (fleet/__main__.py parses the report line stream/__main__
prints).

``jax.jit`` is lazy — merely building the jitted callable compiles
nothing.  Warm-boot therefore *invokes* each kernel once on a minimal
padded search (one step at the shape's full dims) and blocks until
ready; the resulting executable lands in the in-process kernel cache
and, when a persistent XLA compile cache is configured, on disk where
future worker boots skip the trace+compile entirely (the report's
``persistent_cache`` field says which regime you're in).

Shape manifest format (JSON)::

    {"shapes": [{"model": ["register", 0, 1], "n_det_pad": 1024,
                 "n_crash_pad": 32, "window": 32, "k": 4,
                 "frontier": 128}, ...]}

Optional per-shape fields ``batch`` (total lane count — warms the
vmapped batch kernel instead of the solo one) and ``shards`` (wraps it
in ``shard_map`` over that many local devices: the bucketed mesh
scheduler's steady-state shapes).  ``device.compile`` spans from
sharded runs carry both, so a recorded ``BENCH_trace_shard.json``
round-trips into exactly the kernel set the scheduler will request.

Trace format: a telemetry trace (``{"traceEvents": [...]}``) whose
``device.compile`` spans carry ``n_det_pad``/``frontier`` (always) and
``window``/``n_crash_pad``/``k`` (newer traces); missing fields fall
back to the steady-state defaults below.

Every loaded shape is validated against the **static cache-key model**
(:func:`jepsen_tpu.analyze.devlint.check_span_args` — the same K007
contract ``tools/obs_guard.py`` holds committed traces to).  A span or
manifest entry whose coordinates drifted from the kernel cache key
used to be *silently dropped or defaulted*, which surfaced much later
as an unexplained zero-miss-verify failure (warm boot compiled the
wrong kernel set and the steady state paid fresh compiles anyway).
Now it is a loud K007: the loaders raise ``ValueError`` naming the bad
span, or — when the caller passes ``diagnostics=[]`` — append
:class:`~jepsen_tpu.analyze.lint.Diagnostic` objects and skip only the
offending shapes.
"""

from __future__ import annotations

import dataclasses
import json
import time

#: steady-state defaults for trace spans predating the wider
#: compile-span args (window/n_crash_pad/k)
DEFAULT_WINDOW = 32
DEFAULT_N_CRASH_PAD = 32
DEFAULT_K = 4
DEFAULT_FRONTIER = 64
DEFAULT_MODEL = ("register", 0, 1)


@dataclasses.dataclass(frozen=True)
class WarmShape:
    """One kernel shape to compile at boot (mirrors SearchDims plus
    the model and phase-2 flags of the kernel cache key)."""

    model: tuple = DEFAULT_MODEL  # (name, init, width)
    n_det_pad: int = 64
    n_crash_pad: int = DEFAULT_N_CRASH_PAD
    window: int = DEFAULT_WINDOW
    k: int = DEFAULT_K
    frontier: int = DEFAULT_FRONTIER
    masked: bool = False
    masked_crash: bool = False
    dedup: bool = False
    vt: int = 8
    #: batch > 0 warms the vmapped BATCH kernel at that total lane
    #: count (0 = the solo kernel); shards > 0 additionally wraps it
    #: in shard_map over that many local devices — the steady-state
    #: shapes the bucketed mesh scheduler runs
    batch: int = 0
    shards: int = 0


def _shape_span_args(s: WarmShape) -> dict:
    """A WarmShape rendered as the ``device.compile`` span-args dict
    its warmed kernel will stamp — the shared currency between this
    loader and devlint's static cache-key model."""
    args = {
        "engine": "xla",
        "frontier": s.frontier, "n_det_pad": s.n_det_pad,
        "n_crash_pad": s.n_crash_pad, "window": s.window, "k": s.k,
        "masked": s.masked, "masked_crash": s.masked_crash,
        "dedup": s.dedup, "vt": s.vt,
        "model": s.model[0], "model_init": s.model[1],
        "model_width": s.model[2],
    }
    if s.batch:
        args["batch"] = s.batch
    if s.shards:
        args["sharded"] = True
        args["shards"] = s.shards
        # span convention: sharded spans record PER-SHARD lanes
        args["batch"] = max(1, s.batch // s.shards)
    return args


def _k007(diagnostics, where: str, errs: list[str]):
    """Report one shape's cache-key drift: append K007 diagnostics to
    ``diagnostics`` when the caller collects them, raise otherwise —
    the drift must never again surface only as a warm boot that
    compiles the wrong kernel set."""
    from ..analyze.lint import Diagnostic

    if diagnostics is None:
        raise ValueError(
            f"K007 {where}: cache-key coordinates drifted from the "
            f"static model (analyze/devlint.py): " + "; ".join(errs))
    for e in errs:
        diagnostics.append(Diagnostic("K007", "error", f"{where}: {e}"))


def validate_shapes(shapes, *,
                    diagnostics: list | None = None) -> list[WarmShape]:
    """Filter ``shapes`` to the ones whose coordinates satisfy the
    static cache-key model; drifted shapes raise (or, with
    ``diagnostics``, are reported as K007 and dropped)."""
    from ..analyze.devlint import check_span_args

    good = []
    for i, s in enumerate(shapes):
        errs = check_span_args(_shape_span_args(s), strict=True)
        if errs:
            _k007(diagnostics, f"warm shape #{i} ({s.model[0]})", errs)
            continue
        good.append(s)
    return good


def shapes_from_manifest(doc: dict, *,
                         diagnostics: list | None = None
                         ) -> list[WarmShape]:
    shapes = []
    for s in doc.get("shapes", []):
        m = s.get("model", list(DEFAULT_MODEL))
        shapes.append(WarmShape(
            model=(str(m[0]), int(m[1]) if len(m) > 1 else 0,
                   int(m[2]) if len(m) > 2 else 1),
            n_det_pad=int(s.get("n_det_pad", 64)),
            n_crash_pad=int(s.get("n_crash_pad",
                                  DEFAULT_N_CRASH_PAD)),
            window=int(s.get("window", DEFAULT_WINDOW)),
            k=int(s.get("k", DEFAULT_K)),
            frontier=int(s.get("frontier", DEFAULT_FRONTIER)),
            masked=bool(s.get("masked", False)),
            masked_crash=bool(s.get("masked_crash", False)),
            dedup=bool(s.get("dedup", False)),
            vt=int(s.get("vt", 8)),
            batch=int(s.get("batch", 0)),
            shards=int(s.get("shards", 0)),
        ))
    return validate_shapes(shapes, diagnostics=diagnostics)


def shapes_from_trace(doc: dict, *,
                      model: tuple = DEFAULT_MODEL,
                      diagnostics: list | None = None
                      ) -> list[WarmShape]:
    """The kernel shapes a recorded campaign actually compiled: every
    ``device.compile`` span in the trace, deduplicated.

    Spans whose cache-key coordinates fail the static model (including
    the pre-coordinate legacy spans the old loader skipped without a
    word) are K007: raised, or reported-and-skipped when the caller
    passes ``diagnostics``."""
    from ..analyze.devlint import check_span_args

    out = []
    seen = set()
    n_span = 0
    for ev in doc.get("traceEvents", []):
        if ev.get("name") != "device.compile":
            continue
        args = ev.get("args", {}) or {}
        n_span += 1
        # K007 gate: accept any documented cache-key generation (the
        # committed bench traces span several), but a span that fits
        # NO generation would reconstruct a kernel the steady state
        # never requests — report it, don't silently default it.
        # Trace spans predating the engine coordinate warmed the XLA
        # route; that default loses nothing (engine is not a dim).
        qargs = dict(args)
        qargs.setdefault("engine", "xla")
        errs = check_span_args(qargs, strict=False)
        if errs:
            _k007(diagnostics, f"device.compile span #{n_span}", errs)
            continue
        # sharded spans record PER-SHARD lanes + the shard count; the
        # batch kernel getter wants the total lane axis back
        shards = int(args.get("shards", 0) or 0)
        batch = int(args.get("batch", 0) or 0)
        # spans stamped with the model descriptor reconstruct against
        # the model that actually compiled; older spans fall back to
        # the caller-supplied default
        mdl = tuple(model)
        if "model" in args:
            mdl = (str(args["model"]),
                   int(args.get("model_init", 0)),
                   int(args.get("model_width", 1)))
        s = WarmShape(
            model=mdl,
            n_det_pad=int(args["n_det_pad"]),
            n_crash_pad=int(args.get("n_crash_pad",
                                     DEFAULT_N_CRASH_PAD)),
            window=int(args.get("window", DEFAULT_WINDOW)),
            k=int(args.get("k", DEFAULT_K)),
            frontier=int(args.get("frontier", DEFAULT_FRONTIER)),
            masked=bool(args.get("masked", False)),
            masked_crash=bool(args.get("masked_crash", False)),
            dedup=bool(args.get("dedup", False)),
            vt=int(args.get("vt", 8)),
            batch=batch * shards if shards else batch,
            shards=shards,
        )
        if s not in seen:
            seen.add(s)
            out.append(s)
    return out


def load_shapes(path: str, *,
                model: tuple = DEFAULT_MODEL,
                diagnostics: list | None = None) -> list[WarmShape]:
    """Sniff ``path``: a shape manifest (``{"shapes": [...]}``) or a
    recorded telemetry trace (``{"traceEvents": [...]}``).  Shapes are
    K007-validated against the static cache-key model — see the module
    docstring for the raise-vs-``diagnostics`` contract."""
    with open(path) as f:
        doc = json.load(f)
    if "shapes" in doc:
        return shapes_from_manifest(doc, diagnostics=diagnostics)
    if "traceEvents" in doc:
        return shapes_from_trace(doc, model=model,
                                 diagnostics=diagnostics)
    raise ValueError(
        f"{path}: neither a shape manifest ({{'shapes': [...]}}) nor "
        f"a telemetry trace ({{'traceEvents': [...]}})")


def _tiny_seq(model):
    """A minimal one-op history the model accepts — enough to invoke
    the kernel once at full padded dims."""
    from ..history import encode_ops, invoke_op, ok_op

    fc = model.f_codes
    try:
        names = list(fc)
    except TypeError:  # _AnyFCodes (noop model): accepts anything
        names = ["write"]
    for cand in ("write", "enqueue", "acquire"):
        if cand in names:
            f = cand
            break
    else:
        f = names[0]
    v = 1 if f in ("write", "enqueue") else None
    return encode_ops([invoke_op(0, f, v), ok_op(0, f, v)],
                      fc)


def _compile_one(shape: WarmShape, *, telemetry: bool):
    """Build + INVOKE one kernel at the shape's dims (jit is lazy —
    invocation is what compiles), blocking until the executable is
    ready.  Returns ``(dims, model, rerequest)`` where ``rerequest``
    re-asks the cache for the SAME kernel (warm_boot's verify pass)."""
    import jax
    import jax.numpy as jnp

    from ..checker import linearizable as lin
    from ..decompose.schedule import model_from_descriptor

    name, init, width = shape.model
    model = model_from_descriptor((name, (init,), width))
    dims = lin.SearchDims(
        n_det_pad=max(64, int(shape.n_det_pad)),
        n_crash_pad=max(32, int(shape.n_crash_pad)),
        window=max(32, int(shape.window)),
        k=max(1, int(shape.k)),
        state_width=model.state_width,
        frontier=max(8, int(shape.frontier)),
    )
    es = lin.encode_search(_tiny_seq(model))
    esp = lin.pad_search(es, dims.n_det_pad, dims.n_crash_pad)
    if shape.batch:
        b = max(1, int(shape.batch))
        mesh = axis = None
        if shape.shards:
            import numpy as np
            from jax.sharding import Mesh

            devs = jax.devices()
            if len(devs) >= shape.shards and b % shape.shards == 0:
                mesh = Mesh(np.array(devs[:shape.shards]), ("shard",))
                axis = "shard"
        if mesh is not None:
            def getter():
                return lin.get_sharded_batch_kernel(
                    model, dims, batch=b, mesh=mesh, axis=axis,
                    masked=shape.masked,
                    masked_crash=shape.masked_crash,
                    dedup=shape.dedup, vt=shape.vt,
                    telemetry=telemetry)
        else:
            def getter():
                return lin.get_batch_kernel(
                    model, dims, batch=b, allow_pallas=False,
                    masked=shape.masked,
                    masked_crash=shape.masked_crash,
                    dedup=shape.dedup, vt=shape.vt,
                    telemetry=telemetry)
        fn = getter()
        args = lin.stack_batch([esp] * b)
        carry = tuple(jnp.asarray(c)
                      for c in lin._init_batch_carry(b, dims, model))
        out = fn(*args, jnp.int32(64), jnp.int32(4), jnp.bool_(False),
                 *carry)
        jax.block_until_ready(out)
        return dims, model, getter

    def getter():
        return lin.get_kernel(model, dims, masked=shape.masked,
                              masked_crash=shape.masked_crash,
                              dedup=shape.dedup, vt=shape.vt,
                              telemetry=telemetry)

    fn = getter()
    args = lin.search_args(esp, es)
    carry = tuple(jnp.asarray(c) for c in lin._init_carry(dims, model))
    out = fn(*args, jnp.int32(64), jnp.int32(4), jnp.bool_(False),
             *carry)
    jax.block_until_ready(out)
    return dims, model, getter


def warm_boot(shapes, *, verify: bool = True) -> dict:
    """Compile every shape, then verify warmth: a second
    :func:`get_kernel` pass over the same shapes must be all hits
    (zero miss delta on ``KERNEL_CACHE_STATS``).

    Returns the admission-gate report::

        {"shapes": N, "compiled": n_misses, "hits": n_hits,
         "verified": bool, "persistent_cache": bool, "wall_s": float}

    Shapes that fail the static cache-key model (K007) are not warmed
    — the kernel they'd compile is one the steady state never requests
    — and the report carries their messages under ``"k007"`` with
    ``verified`` forced false, so the admission gate refuses the
    worker with a cause instead of admitting a boot that silently
    warmed the wrong kernel set."""
    from ..checker import linearizable as lin
    from ..obs import telemetry as _tele

    t0 = time.perf_counter()
    k007: list = []
    shapes = validate_shapes(list(shapes), diagnostics=k007)
    telemetry = _tele.enabled()
    before = dict(lin.KERNEL_CACHE_STATS)
    warmed = []
    for s in shapes:
        warmed.append((s, *_compile_one(s, telemetry=telemetry)))
    mid = dict(lin.KERNEL_CACHE_STATS)
    verified = True
    if verify:
        # re-request every kernel: each lookup must be a cache hit —
        # the executable, not just the builder, is resident
        for _s, _dims, _model, rerequest in warmed:
            rerequest()
        after = dict(lin.KERNEL_CACHE_STATS)
        verified = after["misses"] == mid["misses"]
    rep = {
        "shapes": len(shapes),
        "compiled": mid["misses"] - before["misses"],
        "hits": mid["hits"] - before["hits"],
        "verified": bool(verified) and not k007,
        "persistent_cache": _tele.persistent_cache_configured(),
        "wall_s": round(time.perf_counter() - t0, 6),
    }
    if k007:
        rep["k007"] = [d.message for d in k007]
    return rep


def parse_warmup_line(line: str) -> dict | None:
    """Parse the ``stream service warmup: ...`` stderr line a worker
    prints (stream/__main__.py) back into a report dict — the fleet
    admission gate's wire format."""
    marker = "stream service warmup:"
    if marker not in line:
        return None
    out = {}
    for tok in line.split(marker, 1)[1].split():
        if "=" not in tok:
            continue
        k, v = tok.split("=", 1)
        if v in ("true", "false"):
            out[k] = v == "true"
        else:
            try:
                out[k] = int(v)
            except ValueError:
                try:
                    out[k] = float(v)
                except ValueError:
                    out[k] = v
    return out or None
