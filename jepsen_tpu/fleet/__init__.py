"""Fleet tier: routed multi-worker checking service.

One front router process (``router.py``) spreads run namespaces across
N ``stream.service`` workers by rendezvous hashing, probes worker
health on ``reconnect.Backoff`` schedules, and re-routes a dead
worker's runs after salvaging their persisted verdicts.  Workers share
one verdict-cache store through per-worker write-ahead segments
(``cachestore.py``), warm-boot their steady-state kernels before
admission (``warmup.py``), and an admission controller turns shed
rate / open runs / fold backlog into accept / shed / spawn-worker
decisions (``admission.py``).

``python -m jepsen_tpu.fleet`` wires the pieces into a running tier;
``stream/bench.py --fleet-tier`` drives a synthetic client swarm
against it and records the throughput knee (BENCH_fleet.json).  See
docs/fleet.md for the walkthrough.
"""

from .admission import AdmissionController, AdmissionPolicy  # noqa: F401
from .cachestore import FleetCacheStore  # noqa: F401
from .router import (  # noqa: F401
    FleetRouter,
    WorkerSpec,
    route_run,
)
