"""Multi-writer fleet tier over the jsonl verdict cache.

decompose/cache.py's :class:`VerdictCache` is one jsonl file every
writer appends to — safe since the flock satellite, but every insert
from every worker contends on one file lock, and a single hot file is
an awkward unit for N workers on one shared store directory.  The
fleet tier splits the store:

.. code-block:: text

    <root>/
      verdicts.jsonl          # the compacted base (merge target)
      segments/<worker>.jsonl # one write-ahead segment PER WORKER
      .store.lock             # serializes spills (base rewrites)

Each worker appends only to its own segment — appends from different
workers never touch the same file, so the steady-state insert path is
contention-free.  A **spill** (:meth:`FleetCacheStore.compact`, auto-
armed when the worker's segment outgrows ``compact_bytes``) takes the
store lock, merge-reads the base plus *every* segment, atomically
rewrites the base, then truncates only the spiller's own segment.
Other workers' segments are never truncated by someone else: a line
another worker appends mid-spill stays in its segment and reaches the
base on a later spill — nothing is ever dropped.  Two concurrent
spills serialize on the store lock, so the second re-reads the first's
base and cannot resurrect or lose entries.

Loads read base + all segments, so hit ratios survive worker restarts
(a restarted worker sees everything the fleet ever decided, spilled or
not) and :meth:`refresh` lets a long-lived worker pick up its peers'
verdicts mid-campaign without restarting.
"""

from __future__ import annotations

import contextlib
import glob
import json
import os
import re
import threading

try:
    import fcntl
except ImportError:  # pragma: no cover — non-POSIX fallback
    fcntl = None  # type: ignore[assignment]

from ..decompose.cache import VerdictCache

_WID_RE = re.compile(r"[^A-Za-z0-9._-]+")

#: fleet segments are expected to spill far more often than the
#: single-file cache compacts — the base absorbs the volume
_DEFAULT_SEGMENT_BYTES = 8 << 20


def _safe_wid(worker_id: str | None) -> str:
    wid = worker_id if worker_id else f"w{os.getpid()}"
    return _WID_RE.sub("_", str(wid)) or f"w{os.getpid()}"


def store_paths(root: str) -> dict:
    """The store layout for ``root`` (tests, tooling)."""
    return {
        "base": os.path.join(root, "verdicts.jsonl"),
        "segments": os.path.join(root, "segments"),
        "lock": os.path.join(root, ".store.lock"),
    }


class FleetCacheStore(VerdictCache):
    """Per-worker write-ahead segment + shared compacted base.

    The public surface is the VerdictCache one (``get`` /
    ``put_verdict`` / ``put_states`` / ``compact`` / ``close``), so
    stream/service.py and the engines use it unchanged; only the
    persistence layout differs."""

    def __init__(self, root: str, worker_id: str | None = None,
                 compact_bytes: int | None = None):
        self.root = os.path.abspath(root)
        self.worker_id = _safe_wid(worker_id)
        p = store_paths(self.root)
        self.base_path = p["base"]
        self.segment_dir = p["segments"]
        self._store_lock_path = p["lock"]
        self._store_lockfh = None
        os.makedirs(self.segment_dir, exist_ok=True)
        seg = os.path.join(self.segment_dir,
                           f"{self.worker_id}.jsonl")
        super().__init__(
            seg,
            compact_bytes=_DEFAULT_SEGMENT_BYTES
            if compact_bytes is None else compact_bytes)
        # super().__init__ loaded our own (leftover) segment; fold in
        # the base and every peer segment for fleet-wide hit ratios
        self.refresh()

    # -- store-wide lock (spill serialization) -------------------------

    @contextlib.contextmanager
    def _store_locked(self):
        """Exclusive spill section across every worker on the store:
        flock on <root>/.store.lock.  Segment appends do NOT take it —
        they are single-writer per file by construction."""
        with self._tlock:
            if fcntl is None:  # pragma: no cover — non-POSIX
                yield
                return
            if self._store_lockfh is None:
                os.makedirs(self.root, exist_ok=True)
                self._store_lockfh = open(self._store_lock_path, "a")
            fcntl.flock(self._store_lockfh.fileno(), fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(self._store_lockfh.fileno(),
                            fcntl.LOCK_UN)

    # -- loading / peers -----------------------------------------------

    def _segment_paths(self) -> list[str]:
        return sorted(
            glob.glob(os.path.join(self.segment_dir, "*.jsonl")))

    def _read_into(self, path: str, dst: dict) -> int:
        """Merge a jsonl file into ``dst`` (setdefault — entries for a
        key are equal by determinism).  Returns lines read."""
        lines = 0
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    lines += 1
                    try:
                        e = json.loads(line)
                        dst.setdefault(e["k"], e)
                    except (ValueError, KeyError):
                        continue  # torn tail line
        except OSError:
            pass
        return lines

    def refresh(self) -> int:
        """Merge the base and every peer segment into memory — a
        worker picks up fleet-wide verdicts decided since its load.
        Returns how many new keys appeared."""
        before = len(self._d)
        self._read_into(self.base_path, self._d)
        for seg in self._segment_paths():
            if seg != self.path:
                self._read_into(seg, self._d)
        return len(self._d) - before

    # -- spill (the fleet compact) -------------------------------------

    def compact(self) -> int:
        """Spill: merge base + all segments into a fresh base, then
        truncate OUR segment only.  Returns superseded lines dropped
        across the files read."""
        if self.path is None:  # pragma: no cover — super() contract
            return 0
        with self._store_locked(), self._locked():
            merged = dict(self._d)
            lines = self._read_into(self.base_path, merged)
            for seg in self._segment_paths():
                lines += self._read_into(seg, merged)
            tmp = f"{self.base_path}.spill.{os.getpid()}"
            try:
                with open(tmp, "w") as f:
                    for e in merged.values():
                        f.write(json.dumps(e, separators=(",", ":"))
                                + "\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.base_path)
            except OSError:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return 0
            self._d = merged
            # truncate our own write-ahead segment: its lines are in
            # the base now.  Replace-with-empty keeps the inode-change
            # signal a restarted twin's _repoint_fh watches for.
            try:
                tmp2 = f"{self.path}.spill.{os.getpid()}"
                with open(tmp2, "w") as f:
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp2, self.path)
            except OSError:
                pass
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            # counters under the lock: concurrent spill/merge cycles
            # from two checker threads must not lose increments
            dropped = max(0, lines - len(merged))
            self.compactions += 1
            self.compacted_away += dropped
        return dropped

    def close(self) -> None:
        super().close()
        if self._store_lockfh is not None:
            self._store_lockfh.close()
            self._store_lockfh = None
