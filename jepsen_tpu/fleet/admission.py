"""Admission control and the fleet's scale signal.

The streaming tier already sheds per-run and per-connection overload
(op budgets, bounded ingest queues) — but a fleet needs a decision one
level up: *should this run be admitted at all, and is the tier sized
right?*  :class:`AdmissionController` folds the aggregated worker
stats (shed rate, open runs, fold backlog) into one of three
decisions (a cold fleet verdict cache additionally damps
``spawn-worker`` down to ``accept`` — see
``AdmissionPolicy.spawn_min_cache_hit_ratio``):

``accept``
    steady state — route the run.
``shed``
    the tier is past its ceiling: refuse the run at the door
    (the router answers the header with an ``overloaded`` reply)
    rather than letting it stall every run already admitted.
``spawn-worker``
    load is climbing but not critical — admit the run AND signal the
    supervisor (fleet/__main__.py, or an operator watching
    ``/api/stats``) to add a worker.  Spawn signals are damped
    (``min_spawn_interval_s``) so a burst doesn't fork a worker per
    request.

The controller is deliberately dumb-deterministic: thresholds in, a
decision out, every decision counted on
``jtpu_fleet_admission_total`` — an operator can replay why any run
was shed from the metrics alone.
"""

from __future__ import annotations

import dataclasses

from ..obs import metrics as obs_metrics

_M_ADMIT = obs_metrics.REGISTRY.counter(
    "jtpu_fleet_admission_total",
    "Fleet admission decisions (accept/shed/spawn-worker)",
    ("decision",))


@dataclasses.dataclass
class AdmissionPolicy:
    """Thresholds for the three-way decision.

    ``max_open_runs`` is the hard fleet-wide ceiling (shed past it);
    ``spawn_open_runs`` the soft one (scale signal).  ``shed_rate``
    thresholds read the workers' own shed counters as a fraction of
    ops ingested over the sampling window: workers already shedding
    means the tier is undersized long before open-runs says so.
    ``max_fold_backlog`` bounds the summed segment-fold queue depth
    (jtpu_stream_cells_open) the same way."""

    max_open_runs: int = 512
    spawn_open_runs: int = 64
    max_shed_rate: float = 0.5
    spawn_shed_rate: float = 0.02
    max_fold_backlog: int = 4096
    min_spawn_interval_s: float = 10.0
    #: verdict-cache damping: while the fleet cache's cumulative hit
    #: ratio sits below this, spawn signals downgrade to ``accept`` —
    #: a cold cache means the tier is still warming shapes, and a new
    #: worker would boot even colder (it re-misses everything the
    #: incumbents are busy inserting).  Only consulted once the cache
    #: has seen ``cache_signal_min_lookups`` lookups: an empty store
    #: at boot says nothing about sizing.
    spawn_min_cache_hit_ratio: float = 0.2
    cache_signal_min_lookups: int = 256


def scale_signal(merged: dict) -> dict:
    """Distill an aggregated ``/api/stats`` snapshot (router's merged
    worker scrape) into the controller's inputs."""

    def _num(v) -> float:
        if isinstance(v, dict):
            return float(sum(_num(x) for x in v.values()))
        try:
            return float(v)
        except (TypeError, ValueError):
            return 0.0

    def _label(v, key) -> float:
        # a labelled counter merges to {label_value: n}; a worker that
        # never fired it may report a bare 0
        return _num(v.get(key, 0)) if isinstance(v, dict) else 0.0

    values = merged.get("values", merged) or {}
    vc = values.get("jtpu_verdict_cache_total", 0)
    return {
        "open_runs": _num(values.get("jtpu_stream_runs_open", 0)),
        "fold_backlog": _num(values.get("jtpu_stream_cells_open", 0)),
        "shed_total": _num(values.get("jtpu_shed_total", 0)),
        "ops_total": _num(
            values.get("jtpu_stream_ops_ingested_total", 0)),
        # FleetCacheStore lookups ride the same verdict-cache counter
        # every VerdictCache feeds; hits/misses (not inserts) are the
        # warmth signal the spawn damping reads
        "cache_hits": _label(vc, "hit"),
        "cache_misses": _label(vc, "miss"),
    }


class AdmissionController:
    """Stateful three-way gate over successive :func:`scale_signal`
    samples.  Shed/ops totals are monotonic counters, so the shed
    *rate* is computed over the delta between samples."""

    def __init__(self, policy: AdmissionPolicy | None = None,
                 clock=None):
        import threading
        import time

        self.policy = policy or AdmissionPolicy()
        self._clock = clock or time.monotonic
        # decide() runs on every router connection-handler thread
        # (fleet/router.py _Session.handle_line): the rate window
        # (_last_shed/_last_ops), the spawn damper (_last_spawn) and
        # the decision counters are all read-modify-write state, so
        # one lock serializes the whole decision (T001)
        self._lock = threading.Lock()
        self._last_shed = 0.0
        self._last_ops = 0.0
        self._last_spawn = None
        self.decisions = {"accept": 0, "shed": 0, "spawn-worker": 0}

    def shed_rate(self, signal: dict) -> float:
        """Shed fraction over the window since the previous sample."""
        d_shed = max(0.0, signal.get("shed_total", 0.0)
                     - self._last_shed)
        d_ops = max(0.0, signal.get("ops_total", 0.0) - self._last_ops)
        denom = d_shed + d_ops
        return d_shed / denom if denom else 0.0

    def cache_hit_ratio(self, signal: dict) -> float | None:
        """Cumulative fleet verdict-cache hit ratio, or None while the
        cache has seen too few lookups to mean anything."""
        h = signal.get("cache_hits", 0.0)
        m = signal.get("cache_misses", 0.0)
        if h + m < self.policy.cache_signal_min_lookups:
            return None
        return h / (h + m)

    def decide(self, signal: dict) -> str:
        """One admission decision for the run knocking now.
        Thread-safe: concurrent handler threads serialize on the
        controller lock, so the rate window advances once per sample
        and the spawn damper can't double-fire in a burst."""
        p = self.policy
        with self._lock:
            rate = self.shed_rate(signal)
            self._last_shed = max(self._last_shed,
                                  signal.get("shed_total", 0.0))
            self._last_ops = max(self._last_ops,
                                 signal.get("ops_total", 0.0))
            open_runs = signal.get("open_runs", 0.0)
            backlog = signal.get("fold_backlog", 0.0)
            if (open_runs >= p.max_open_runs or rate >= p.max_shed_rate
                    or backlog >= p.max_fold_backlog):
                decision = "shed"
            elif open_runs >= p.spawn_open_runs \
                    or rate >= p.spawn_shed_rate:
                hit_ratio = self.cache_hit_ratio(signal)
                if hit_ratio is not None \
                        and hit_ratio < p.spawn_min_cache_hit_ratio:
                    # cold cache: the tier is still warming shapes, and
                    # a fresh worker boots colder still — admit, don't
                    # fork
                    decision = "accept"
                else:
                    now = self._clock()
                    if self._last_spawn is None or \
                            now - self._last_spawn \
                            >= p.min_spawn_interval_s:
                        self._last_spawn = now
                        decision = "spawn-worker"
                    else:
                        decision = "accept"  # damped: already sent
            else:
                decision = "accept"
            self.decisions[decision] += 1
        _M_ADMIT.inc(decision=decision)
        return decision
