"""Synthetic history generation — benchmark + self-test workloads.

Simulates N logically single-threaded processes (the reference's worker
model, core.clj:329-407) against an in-memory register/mutex, emitting a
history that is valid by construction: each op takes effect atomically at
its completion event, which is a legal linearization point.  Knobs:

  * ``overlap``  — target number of simultaneously pending ops; drives the
    real-time-order ambiguity the checker must search through (the
    generator analog of `delay-til` racing, generator.clj:134-157).
  * ``crash_p``  — probability a pending op crashes (:info) instead of
    completing; crashed effects are applied with probability .5, matching
    the "maybe happened" semantics the checker must cope with
    (core.clj:387-397).
  * ``corrupt_at`` — fraction; rewrites one ok read near that point of the
    history to a bogus value, which (almost always) makes the history
    non-linearizable so the checker must sweep the full state space.
"""

from __future__ import annotations

import random
from dataclasses import replace

from .history import Op, fail_op, info_op, invoke_op, ok_op


def register_history(rng: random.Random, *, n_ops: int, n_procs: int,
                     overlap: int = 4, crash_p: float = 0.0,
                     max_crashes: int = 16, n_values: int = 5,
                     cas: bool = True,
                     unique_writes: bool = False,
                     quiesce_every: int | None = None) -> list[Op]:
    """Concurrent CAS-register history, valid by construction.

    ``unique_writes`` draws every write value from a fresh counter
    (starting at 1, so it never collides with a register's initial 0)
    instead of ``[0, n_values)`` — the unique-writes register class the
    per-value block decomposition (decompose/partition.py) is exact
    on.

    ``quiesce_every`` drains every pending op after each that many
    invocations before invoking more — a *bursty* workload with
    guaranteed quiescent points every ~that many ops, the shape the
    quiescence cutter (and the streaming checker's online cuts) feeds
    on: segments of roughly that size at the full ``overlap`` width."""
    state = None
    h: list[Op] = []
    pending: dict[int, tuple] = {}
    n_crashed = 0
    done = 0
    next_v = 1  # unique_writes counter
    crashed_procs: set[int] = set()
    while done < n_ops or pending:
        free = [p for p in range(n_procs)
                if p not in pending and p not in crashed_procs]
        want_invoke = (done < n_ops and free
                       and (len(pending) < overlap or not pending)
                       and not (quiesce_every and done
                                and done % quiesce_every == 0
                                and pending))
        if want_invoke:
            p = rng.choice(free)
            fs = ["read", "write"] + (["cas"] if cas else [])
            f = rng.choice(fs)
            if f == "read":
                v = None
            elif f == "write":
                if unique_writes:
                    v = next_v
                    next_v += 1
                else:
                    v = rng.randrange(n_values)
            else:
                v = (rng.randrange(n_values), rng.randrange(n_values))
            h.append(invoke_op(p, f, v))
            pending[p] = (f, v)
            done += 1
            continue
        if not pending:
            break
        p = rng.choice(list(pending))
        f, v = pending.pop(p)
        if crash_p and rng.random() < crash_p and n_crashed < max_crashes:
            n_crashed += 1
            crashed_procs.add(p)  # a crashed process id is retired
            if rng.random() < 0.5:
                if f == "write":
                    state = v
                elif f == "cas" and state == v[0]:
                    state = v[1]
            h.append(info_op(p, f, v if f != "read" else None))
            continue
        if f == "read":
            h.append(ok_op(p, f, state))
        elif f == "write":
            state = v
            h.append(ok_op(p, f, v))
        else:
            if state == v[0]:
                state = v[1]
                h.append(ok_op(p, f, v))
            else:
                h.append(fail_op(p, f, v))
    return h


def swap_read_values(rng: random.Random, h: list[Op], *,
                     min_gap: int | None = None) -> list[Op]:
    """Swap the values of two ok reads of DIFFERENT values at least
    ``min_gap`` events apart (default: a quarter of the history).

    On a unique-writes history this forces block-order conflicts — a
    value current in two separated stretches would need two writes —
    which is the invalidity mode the per-value block decomposition's
    cross-block acyclicity test exists to catch.  (`corrupt_read`'s
    never-written value is rejected before any order reasoning.)"""
    idx = [i for i, op in enumerate(h)
           if op.type == "ok" and op.f == "read" and op.value is not None]
    if len(idx) < 2:
        return h
    gap = len(h) // 4 if min_gap is None else min_gap
    for _ in range(200):
        i, j = sorted(rng.sample(idx, 2))
        if j - i >= gap and h[i].value != h[j].value:
            h = list(h)
            h[i], h[j] = (replace(h[i], value=h[j].value),
                          replace(h[j], value=h[i].value))
            return h
    return h


def corrupt_read(rng: random.Random, h: list[Op], *,
                 at: float = 1.0) -> list[Op]:
    """Rewrite the ok read nearest fraction ``at`` of the way through to a
    value nothing wrote; the result is (almost certainly) invalid."""
    h = list(h)
    idx = [i for i, op in enumerate(h)
           if op.type == "ok" and op.f == "read" and op.value is not None]
    if not idx:
        return h
    target = int(at * (len(h) - 1))
    i = min(idx, key=lambda j: abs(j - target))
    h[i] = replace(h[i], value=(h[i].value or 0) + 1_000_003)
    return h


# ---------------------------------------------------------------------------
# Differential-test simulators (shared by tests/test_linearizable.py and
# tools/fuzz.py — one canonical copy, so a simulator fix lands once)
# ---------------------------------------------------------------------------


def sim_register_history(rng: random.Random, n_procs: int = 4,
                         n_ops: int = 40, *, crash_p: float = 0.0,
                         cas: bool = True,
                         max_crashes: int = 8) -> list[Op]:
    """Simulate processes against a real register; ops linearize at
    completion, so the emitted history is valid."""
    state = None  # register starts unset
    h: list[Op] = []
    pending: dict = {}  # process -> (f, value)
    n_crashed = 0
    done = 0
    while done < n_ops or pending:
        p = rng.randrange(n_procs)
        if p in pending:
            f, v = pending.pop(p)
            if crash_p and rng.random() < crash_p and \
                    n_crashed < max_crashes:
                n_crashed += 1
                # crashed: op takes effect iff coin flip says so
                if rng.random() < 0.5:
                    if f == "write":
                        state = v
                    elif f == "cas" and state == v[0]:
                        state = v[1]
                h.append(info_op(p, f, v if f != "read" else None))
                continue
            if f == "read":
                h.append(ok_op(p, f, state))
            elif f == "write":
                state = v
                h.append(ok_op(p, f, v))
            else:  # cas
                if state == v[0]:
                    state = v[1]
                    h.append(ok_op(p, f, v))
                else:
                    h.append(fail_op(p, f, v))
        elif done < n_ops:
            fs = ["read", "write"] + (["cas"] if cas else [])
            f = rng.choice(fs)
            if f == "read":
                v = None
            elif f == "write":
                v = rng.randrange(5)
            else:
                v = (rng.randrange(5), rng.randrange(5))
            h.append(invoke_op(p, f, v))
            pending[p] = (f, v)
            done += 1
    return h


def sim_mutex_history(rng: random.Random, n_ops: int = 40,
                      n_procs: int = 4, *,
                      crash_p: float = 0.0,
                      max_crashes: int = 48,
                      lease_p: float = 0.05) -> list[Op]:
    """Alternating acquire/release per process against a real lock.

    Always terminates: after the op budget is spent, completable pending
    ops are drained (the holder releases out-of-budget if needed) and
    anything still stuck — e.g. acquires blocked behind a crashed holder
    — becomes a crashed :info op, exactly what the harness records for
    ops whose fate is unknown (core.clj:387-397).

    A holder that crashes still holding the lock would deadlock every
    other process; like a real lock service, the lock's lease then
    expires (probability ``lease_p`` per scheduling step).  The emitted
    history stays valid: a crashed acquire is a :info op the checker may
    linearize or skip, and the skip branch always explains later
    acquires.  ``max_crashes`` caps :info ops so the engine's crash mask
    stays within its width."""
    holder = None
    holder_crashed = False
    h: list[Op] = []
    pending: dict = {}  # process -> f
    wants: dict = {}
    crashed: set = set()
    done = 0
    while done < n_ops:
        if len(crashed) >= n_procs:
            break  # everyone crashed; the history just ends short
        if holder_crashed and rng.random() < lease_p:
            holder = None  # lease expiry frees a dead holder's lock
            holder_crashed = False
        p = rng.randrange(n_procs)
        if p in crashed:
            continue
        if p in pending:
            f = pending[p]
            if crash_p and len(crashed) < max_crashes \
                    and rng.random() < crash_p:
                # coin flip: did the op take effect before the crash?
                if rng.random() < 0.5:
                    if f == "acquire" and holder is None:
                        holder = p
                    elif f == "release" and holder == p:
                        holder = None
                del pending[p]
                crashed.add(p)
                # a dead process still holding the lock (crashed acquire
                # that took effect, or crashed release that did NOT) must
                # be lease-expirable, or the simulation deadlocks; a
                # crash by a NON-holder must not touch the flag
                if holder == p:
                    holder_crashed = True
                h.append(info_op(p, f, None))
                continue
            if f == "acquire" and holder is None:
                holder = p
                holder_crashed = False
                del pending[p]
                h.append(ok_op(p, f, None))
            elif f == "release":
                del pending[p]
                if holder == p:
                    holder = None
                    holder_crashed = False
                    h.append(ok_op(p, f, None))
                else:
                    h.append(fail_op(p, f, None))
            continue
        f = "release" if wants.get(p) else "acquire"
        wants[p] = not wants.get(p)
        h.append(invoke_op(p, f, None))
        pending[p] = f
        done += 1

    # drain: free the lock if its holder is still schedulable, complete
    # what completes, and crash the rest
    if holder is not None and holder not in crashed \
            and holder not in pending:
        h.append(invoke_op(holder, "release", None))
        h.append(ok_op(holder, "release", None))
        holder = None
    for p, f in sorted(pending.items()):
        if f == "acquire" and holder is None:
            holder = p
            h.append(ok_op(p, f, None))
        elif f == "release":
            if holder == p:
                holder = None
                h.append(ok_op(p, f, None))
            else:
                h.append(fail_op(p, f, None))
        else:
            h.append(info_op(p, f, None))
    return h


def flip_read(rng: random.Random, h: list[Op]) -> list[Op]:
    """Flip one ok read's value; usually makes the history invalid."""
    h = list(h)
    idx = [i for i, op in enumerate(h)
           if op.type == "ok" and op.f == "read" and op.value is not None]
    if not idx:
        return h
    i = rng.choice(idx)
    h[i] = replace(h[i], value=(h[i].value or 0) + 7)
    return h


def mutate(rng: random.Random, h: list[Op]) -> list[Op]:
    """One random mutation: flip a read value, swap two completions, or
    duplicate a completion."""
    h = list(h)
    kind = rng.randrange(3)
    if kind == 0:
        return flip_read(rng, h)
    idx = [i for i, op in enumerate(h) if op.type == "ok"]
    if kind == 1 and len(idx) >= 2:
        i, j = rng.sample(idx, 2)
        h[i], h[j] = h[j], h[i]
    elif idx:
        h.insert(rng.choice(idx), h[rng.choice(idx)])
    return h


def sim_queue_history(rng: random.Random, n_ops: int = 40,
                      n_procs: int = 4, *,
                      crash_p: float = 0.0,
                      fifo: bool = False) -> list[Op]:
    """Enqueue/dequeue against a real in-memory multiset, valid by
    construction (ops take effect at completion; dequeues return an
    arbitrary present element — or the oldest when ``fifo``, making the
    history fifo-queue-valid).  Enqueued values are unique integers so
    corruptions are unambiguous.  Crashed enqueues apply their effect
    with probability .5 — but a crashed enqueue's value may then be
    dequeued later, which is still valid (the checker must consider the
    crashed op as possibly-linearized, core.clj:387-397)."""
    contents: list[int] = []
    h: list[Op] = []
    pending: dict = {}  # process -> (f, value-or-None)
    crashed: set = set()
    next_v = 0
    done = 0
    while done < n_ops or pending:
        live = [p for p in range(n_procs) if p not in crashed]
        if not live:
            break
        p = rng.choice(live)
        if p in pending:
            f, v = pending.pop(p)
            if crash_p and rng.random() < crash_p:
                if f == "enqueue" and rng.random() < 0.5:
                    contents.append(v)
                crashed.add(p)
                h.append(info_op(p, f, v))
                continue
            if f == "enqueue":
                contents.append(v)
                h.append(ok_op(p, f, v))
            else:  # dequeue completes only if something is present
                if contents:
                    got = contents.pop(
                        0 if fifo else rng.randrange(len(contents)))
                    h.append(ok_op(p, f, got))
                else:
                    h.append(fail_op(p, f, None))
        elif done < n_ops:
            if rng.random() < 0.55 or not contents:
                f, v = "enqueue", next_v
                next_v += 1
            else:
                f, v = "dequeue", None
            h.append(invoke_op(p, f, v))
            pending[p] = (f, v)
            done += 1
    return h


def swap_dequeues(rng: random.Random, h: list[Op]) -> list[Op]:
    """Swap two ok dequeues' values — reorders the service order, which a
    FIFO model must reject unless the two were concurrent."""
    idx = [i for i, op in enumerate(h)
           if op.type == "ok" and op.f == "dequeue"]
    if len(idx) < 2:
        return h
    i, j = rng.sample(idx, 2)
    h = list(h)
    h[i], h[j] = (replace(h[i], value=h[j].value),
                  replace(h[j], value=h[i].value))
    return h


def corrupt_dequeue(rng: random.Random, h: list[Op]) -> list[Op]:
    """Rewrite one ok dequeue's value to one never enqueued — a
    from-thin-air element no linearization can explain."""
    idx = [i for i, op in enumerate(h)
           if op.type == "ok" and op.f == "dequeue"]
    if not idx:
        return h
    i = rng.choice(idx)
    h = list(h)
    h[i] = replace(h[i], value=999_983)
    return h
