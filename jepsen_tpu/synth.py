"""Synthetic history generation — benchmark + self-test workloads.

Simulates N logically single-threaded processes (the reference's worker
model, core.clj:329-407) against an in-memory register/mutex, emitting a
history that is valid by construction: each op takes effect atomically at
its completion event, which is a legal linearization point.  Knobs:

  * ``overlap``  — target number of simultaneously pending ops; drives the
    real-time-order ambiguity the checker must search through (the
    generator analog of `delay-til` racing, generator.clj:134-157).
  * ``crash_p``  — probability a pending op crashes (:info) instead of
    completing; crashed effects are applied with probability .5, matching
    the "maybe happened" semantics the checker must cope with
    (core.clj:387-397).
  * ``corrupt_at`` — fraction; rewrites one ok read near that point of the
    history to a bogus value, which (almost always) makes the history
    non-linearizable so the checker must sweep the full state space.
"""

from __future__ import annotations

import random
from dataclasses import replace

from .history import Op, fail_op, info_op, invoke_op, ok_op


def register_history(rng: random.Random, *, n_ops: int, n_procs: int,
                     overlap: int = 4, crash_p: float = 0.0,
                     max_crashes: int = 16, n_values: int = 5,
                     cas: bool = True) -> list[Op]:
    """Concurrent CAS-register history, valid by construction."""
    state = None
    h: list[Op] = []
    pending: dict[int, tuple] = {}
    n_crashed = 0
    done = 0
    crashed_procs: set[int] = set()
    while done < n_ops or pending:
        free = [p for p in range(n_procs)
                if p not in pending and p not in crashed_procs]
        want_invoke = (done < n_ops and free
                       and (len(pending) < overlap or not pending))
        if want_invoke:
            p = rng.choice(free)
            fs = ["read", "write"] + (["cas"] if cas else [])
            f = rng.choice(fs)
            v = (None if f == "read"
                 else rng.randrange(n_values) if f == "write"
                 else (rng.randrange(n_values), rng.randrange(n_values)))
            h.append(invoke_op(p, f, v))
            pending[p] = (f, v)
            done += 1
            continue
        if not pending:
            break
        p = rng.choice(list(pending))
        f, v = pending.pop(p)
        if crash_p and rng.random() < crash_p and n_crashed < max_crashes:
            n_crashed += 1
            crashed_procs.add(p)  # a crashed process id is retired
            if rng.random() < 0.5:
                if f == "write":
                    state = v
                elif f == "cas" and state == v[0]:
                    state = v[1]
            h.append(info_op(p, f, v if f != "read" else None))
            continue
        if f == "read":
            h.append(ok_op(p, f, state))
        elif f == "write":
            state = v
            h.append(ok_op(p, f, v))
        else:
            if state == v[0]:
                state = v[1]
                h.append(ok_op(p, f, v))
            else:
                h.append(fail_op(p, f, v))
    return h


def corrupt_read(rng: random.Random, h: list[Op], *,
                 at: float = 1.0) -> list[Op]:
    """Rewrite the ok read nearest fraction ``at`` of the way through to a
    value nothing wrote; the result is (almost certainly) invalid."""
    h = list(h)
    idx = [i for i, op in enumerate(h)
           if op.type == "ok" and op.f == "read" and op.value is not None]
    if not idx:
        return h
    target = int(at * (len(h) - 1))
    i = min(idx, key=lambda j: abs(j - target))
    h[i] = replace(h[i], value=(h[i].value or 0) + 1_000_003)
    return h
