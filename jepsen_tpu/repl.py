"""Interactive exploration helpers.

Reference: jepsen/src/jepsen/repl.clj — `last-test` loads the most recent
run from the store for poking at histories offline (repl.clj:6-13).
"""

from __future__ import annotations

from . import store


def last_test(base: str | None = None):
    """The most recently run test, reloaded from disk (repl.clj:6-13)."""
    return store.latest(base)
