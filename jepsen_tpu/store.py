"""Results persistence (reference L7).

Reference: jepsen/src/jepsen/store.clj — runs persist under
``store/<test-name>/<start-time>/`` with the history, analysis results,
the full test map, and the run log; ``latest`` symlinks point at the most
recent run (store.clj:237-249); a load/browse API supports offline
re-analysis (store.clj:165-234).

Differences from the reference, by design: Fressian becomes JSON-lines for
the history (human-greppable, streamable) and JSON for results/test maps;
non-serializable test entries (clients, generators, checkers — function
objects) are dropped exactly like the reference's nonserializable-keys
(store.clj:155-163).
"""

from __future__ import annotations

import json
import logging
import os
import time as _time
from typing import Any, Iterable

from .history import Op

BASE = "store"

#: test-map keys that hold live objects and never serialize
#: (store.clj:155-163)
NONSERIALIZABLE_KEYS = [
    "db", "os", "net", "client", "checker", "nemesis", "generator", "model",
    "remote", "barrier", "active_histories", "sessions", "history",
]


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() or c in "-_. " else "_" for c in name)


def time_str(t: float | None = None) -> str:
    return _time.strftime("%Y%m%dT%H%M%S", _time.localtime(t))


def base_dir(test: dict) -> str:
    return test.get("store_base", BASE)


def path(test: dict, *more: str) -> str:
    """store/<name>/<start-time>/<more...> (store.clj:121-135)."""
    name = _sanitize(test.get("name") or "noname")
    t = test.get("start_time") or time_str()
    return os.path.join(base_dir(test), name, t, *[str(m) for m in more])


def path_mkdirs(test: dict, *more: str) -> str:
    p = path(test, *more)
    os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
    return p


def _jsonable(v: Any):
    if isinstance(v, Op):
        return v.to_dict()
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (set, frozenset)):
        return sorted(_jsonable(x) for x in v)
    try:
        import numpy as np

        if isinstance(v, np.generic):
            return v.item()
    except Exception:
        pass
    return repr(v)


def serializable_test(test: dict) -> dict:
    return {k: _jsonable(v) for k, v in test.items()
            if k not in NONSERIALIZABLE_KEYS}


#: chunk size for buffered history writes (util.clj:161-166's 16,384-op
#: parallel-writer threshold)
HISTORY_CHUNK = 16384


def write_history(test: dict, history: Iterable[Op],
                  fname: str = "history.jsonl") -> str:
    """One op per line (the analog of history.txt + history.edn,
    store.clj:267-279).

    Streams: ops are encoded one at a time (generators never
    materialize) and flushed in 16k-op chunks — the shape of
    util.clj:156-178's chunked history writer.  The reference
    parallelizes the per-chunk encode across JVM threads; CPython's
    json.dumps holds the GIL, so threads buy nothing here — histories
    big enough for encode throughput to matter ride the columnar OpSeq
    path instead."""
    p = path_mkdirs(test, fname)
    with open(p, "w") as f:
        buf: list[str] = []
        for op in history:
            d = op.to_dict() if isinstance(op, Op) else op
            buf.append(json.dumps(_jsonable(d)))
            if len(buf) >= HISTORY_CHUNK:
                f.write("\n".join(buf) + "\n")
                buf.clear()
        if buf:
            f.write("\n".join(buf) + "\n")
    return p


def read_history(p: str) -> list[Op]:
    with open(p) as f:
        return [Op.from_dict(json.loads(line)) for line in f if line.strip()]


def save_1(test: dict, history: Iterable[Op]) -> str:
    """Post-run save: history + test map (store.clj:281-292)."""
    write_history(test, history)
    p = path_mkdirs(test, "test.json")
    with open(p, "w") as f:
        json.dump(serializable_test(test), f, indent=2, default=repr)
    update_symlinks(test)
    return p


def save_2(test: dict, results: dict) -> str:
    """Post-analysis save: results.json (store.clj:294-304)."""
    p = path_mkdirs(test, "results.json")
    with open(p, "w") as f:
        json.dump(_jsonable(results), f, indent=2, default=repr)
    update_symlinks(test)
    return p


def update_symlinks(test: dict) -> None:
    """store/latest and store/<name>/latest (store.clj:237-249)."""
    run_dir = os.path.dirname(path(test, "x"))

    def relink(link: str, target: str):
        try:
            if os.path.islink(link):
                os.unlink(link)
            elif os.path.exists(link):
                return
            os.symlink(os.path.relpath(target, os.path.dirname(link)), link)
        except OSError:
            pass

    name_dir = os.path.dirname(run_dir)
    relink(os.path.join(name_dir, "latest"), run_dir)
    relink(os.path.join(base_dir(test), "latest"), run_dir)


def tests(name: str | None = None,
          base: str | None = None) -> dict:
    """Map of test name -> {start-time -> run dir} (store.clj:216-234).

    ``base`` defaults to BASE at call time, so module-level overrides
    (tests, store_base plumbing) are honored."""
    base = BASE if base is None else base
    out: dict = {}
    if not os.path.isdir(base):
        return out
    for n in sorted(os.listdir(base)):
        d = os.path.join(base, n)
        if not os.path.isdir(d) or n == "latest":
            continue
        if name is not None and n != name:
            continue
        runs = {t: os.path.join(d, t) for t in sorted(os.listdir(d))
                if t != "latest" and os.path.isdir(os.path.join(d, t))}
        out[n] = runs
    return out


def load(name: str, start_time: str,
         base: str | None = None) -> dict:
    """Reload a saved test: test map + history + results
    (store.clj:165-181)."""
    base = BASE if base is None else base
    d = os.path.join(base, name, start_time)
    out: dict = {}
    tj = os.path.join(d, "test.json")
    if os.path.exists(tj):
        out = json.load(open(tj))
    hj = os.path.join(d, "history.jsonl")
    if os.path.exists(hj):
        out["history"] = read_history(hj)
    rj = os.path.join(d, "results.json")
    if os.path.exists(rj):
        out["results"] = json.load(open(rj))
    return out


def latest(base: str | None = None) -> dict | None:
    """The most recent run, via the latest symlink (repl.clj:6-13)."""
    base = BASE if base is None else base
    link = os.path.join(base, "latest")
    if not os.path.exists(link):
        return None
    d = os.path.realpath(link)
    name = os.path.basename(os.path.dirname(d))
    return load(name, os.path.basename(d), base)


# ---------------------------------------------------------------------------
# logging (store.clj:306-328): console + per-test jepsen.log file
# ---------------------------------------------------------------------------

_handlers: dict = {}


def start_logging(test: dict) -> None:
    logger = logging.getLogger("jepsen")
    logger.setLevel(logging.INFO)
    if not logger.handlers:
        sh = logging.StreamHandler()
        sh.setFormatter(logging.Formatter(
            "%(asctime)s %(threadName)s %(levelname)s: %(message)s"))
        logger.addHandler(sh)
    if not test.get("name"):
        return  # unnamed tests don't persist anything
    p = path_mkdirs(test, "jepsen.log")
    fh = logging.FileHandler(p)
    fh.setFormatter(logging.Formatter(
        "%(asctime)s %(threadName)s %(levelname)s: %(message)s"))
    logger.addHandler(fh)
    _handlers[id(test)] = fh


def stop_logging(test: dict | None = None) -> None:
    logger = logging.getLogger("jepsen")
    if test is not None:
        fh = _handlers.pop(id(test), None)
        if fh:
            logger.removeHandler(fh)
            fh.close()
        return
    for fh in _handlers.values():
        logger.removeHandler(fh)
        fh.close()
    _handlers.clear()
