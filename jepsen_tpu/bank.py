"""Shared bank workload pieces (reference jepsen/src/jepsen/tests/bank.clj).

The reference hoists the bank generators into a reusable namespace
(bank.clj:36-66); the percona/postgres-rds/mysql-cluster/tidb suites all
re-plug the same read-all/conditional-transfer SQL body with tiny dialect
differences (lock clause, in-place vs read-modify-write).  This module is
that shared core: generators + the transaction body, parameterized by
cursor dialect, so an error-mapping fix lands once.
"""

from __future__ import annotations

import random
from dataclasses import replace


def bank_read(test, process):
    """bank.clj:36-39."""
    return {"type": "invoke", "f": "read", "value": None}


def bank_transfer(n: int, min_amount: int = 0, max_amount: int = 4):
    """Transfer between two *different* accounts (bank.clj:41-55's
    diff-transfer).  Default amount range matches bank.clj's
    (rand-int 5)."""

    def op(test, process):
        frm, to = random.sample(range(n), 2)
        return {"type": "invoke", "f": "transfer",
                "value": {"from": frm, "to": to,
                          "amount": random.randint(min_amount,
                                                   max_amount)}}

    return op


def sql_bank_body(cur, op, n: int, *, lock_type: str = "",
                  in_place: bool = False, lock_reads: bool = True):
    """One bank op against a DB-API cursor inside an open transaction
    (percona.clj:247-287 / postgres_rds.clj:163-204 / tidb bank.clj:33-90).

    read: every balance in one locked select.  transfer: read both
    balances (with the dialect's lock clause), refuse negatives
    (:fail — determinate), then write back either in place or by
    absolute value."""
    if op.f == "read":
        # percona locks its bank reads (percona.clj:247-250) but tidb
        # deliberately snapshot-reads (tidb bank.clj:36-38) — a locked
        # read would serialize against transfers and mask exactly the
        # fractured-total anomalies the checker hunts
        cur.execute("select id, balance from accounts"
                    + (lock_type if lock_reads else ""))
        rows = dict(cur.fetchall())
        return replace(op, type="ok",
                       value={i: rows.get(i) for i in range(n)})
    if op.f == "transfer":
        frm = op.value["from"]
        to = op.value["to"]
        amount = op.value["amount"]
        cur.execute("select balance from accounts where id = %s"
                    + lock_type, (frm,))
        b1 = cur.fetchone()[0] - amount
        cur.execute("select balance from accounts where id = %s"
                    + lock_type, (to,))
        b2 = cur.fetchone()[0] + amount
        if b1 < 0:
            return replace(op, type="fail", error=f"negative {frm} {b1}")
        if b2 < 0:
            return replace(op, type="fail", error=f"negative {to} {b2}")
        if in_place:
            cur.execute("update accounts set balance = balance - %s"
                        " where id = %s", (amount, frm))
            cur.execute("update accounts set balance = balance + %s"
                        " where id = %s", (amount, to))
        else:
            cur.execute("update accounts set balance = %s where id = %s",
                        (b1, frm))
            cur.execute("update accounts set balance = %s where id = %s",
                        (b2, to))
        return replace(op, type="ok")
    raise ValueError(f"unknown f {op.f!r}")
