"""Multi-host device-mesh plumbing — the DCN tier of the checker backend.

The reference's distributed backend is SSH from one control node
(jepsen/src/jepsen/control.clj); all *coordination* stays
control-node-centric and that design is kept (SURVEY.md §2.4).  What
actually scales out in this rebuild is the CHECKER: per-key history
batches ride a `jax.sharding.Mesh`, and when one host's chips aren't
enough the mesh must span hosts — JAX's runtime then lays collectives
over ICI within a host and DCN across hosts automatically, the XLA-native
equivalent of the NCCL/MPI tier in torch-style stacks.

Layout doctrine (matching the scaling-book recipe):

  * the independent-keys batch axis is pure data parallelism — no
    communication except the final verdict gather, so it can safely
    cross the DCN boundary: put the OUTER ("hosts") axis on keys;
  * the sharded-frontier axis (`search_opseq_sharded`) all_to_alls every
    level — keep it INSIDE a host's ICI domain.  `multihost_mesh`
    returns a 2-D (dcn, ici) mesh shaped that way.

Usage on each host of a slice (or each CPU pod in a test rig)::

    from jepsen_tpu import distributed as dist
    dist.init_from_env()               # no-op standalone; JAX_COORD_* set
    mesh = dist.multihost_mesh()       # ("keys", "shard") over all hosts
    results = search_batch(seqs, model,
                           sharding=dist.keys_sharding(mesh))

Every host must run the same program (SPMD): `search_batch` callers pass
the full key list everywhere; JAX partitions rows by the sharding.
"""

from __future__ import annotations

import os

import numpy as np

__all__ = ["init_from_env", "is_initialized", "multihost_mesh",
           "keys_sharding", "process_info"]

_INITIALIZED = False


def init_from_env(*, coordinator: str | None = None,
                  num_processes: int | None = None,
                  process_id: int | None = None) -> bool:
    """Initialize `jax.distributed` when a cluster is configured.

    Sources, in priority order: explicit arguments, then the
    ``JEPSEN_TPU_COORDINATOR`` / ``JEPSEN_TPU_NUM_PROCS`` /
    ``JEPSEN_TPU_PROC_ID`` environment, then JAX's own auto-detection
    (GKE/Cloud TPU metadata) if ``JAX_COORDINATOR_ADDRESS`` is set.
    Returns True when a multi-process runtime was brought up; standalone
    runs return False and everything downstream behaves single-host —
    tests and the tutorial path never need a cluster.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return True
    coordinator = coordinator or os.environ.get("JEPSEN_TPU_COORDINATOR")
    try:
        num = num_processes or int(
            os.environ.get("JEPSEN_TPU_NUM_PROCS", 0))
        pid = process_id if process_id is not None else \
            int(os.environ.get("JEPSEN_TPU_PROC_ID", -1))
    except ValueError as e:
        raise ValueError(
            "JEPSEN_TPU_NUM_PROCS / JEPSEN_TPU_PROC_ID must be "
            f"integers: {e}") from None
    pieces = {"JEPSEN_TPU_COORDINATOR": bool(coordinator),
              "JEPSEN_TPU_NUM_PROCS": num > 0,
              "JEPSEN_TPU_PROC_ID": pid >= 0}

    import jax

    if all(pieces.values()):
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num, process_id=pid)
        _INITIALIZED = True
        return True
    if any(pieces.values()):
        # silently degrading to standalone here would leave this host's
        # peers blocked in jax.distributed.initialize() forever, with no
        # error naming the misconfigured host
        missing = sorted(k for k, ok in pieces.items() if not ok)
        raise ValueError(
            f"partial cluster configuration: missing/invalid {missing}")
    if os.environ.get("JAX_COORDINATOR_ADDRESS"):
        jax.distributed.initialize()  # JAX-native auto-configuration
        _INITIALIZED = True
        return True
    return False


def is_initialized() -> bool:
    return _INITIALIZED


def process_info() -> dict:
    """This host's coordinates in the job (all zeros standalone)."""
    import jax

    return {"process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "local_devices": len(jax.local_devices()),
            "global_devices": len(jax.devices())}


def multihost_mesh(*, ici_axis: str = "shard", dcn_axis: str = "keys"):
    """A 2-D mesh over every device in the job: the outer axis spans
    hosts (DCN — give it the embarrassingly-parallel keys batch) and the
    inner axis stays within each host (ICI — the all_to_all frontier
    axis).  Standalone, the outer axis has size 1 and the mesh degrades
    to a plain single-host mesh."""
    import jax
    from jax.sharding import Mesh

    devs = list(jax.devices())
    hosts = jax.process_count()
    if len(devs) % hosts:
        raise ValueError(
            f"{len(devs)} global devices do not divide evenly over "
            f"{hosts} processes; a mesh row per host needs equal chip "
            "counts")
    per_host = len(devs) // hosts
    try:
        # topology-aware inside each host's ICI axis when available
        from jax.experimental import mesh_utils

        # shapes multiply per axis: ([1, per_host], [hosts, 1]) yields
        # a (hosts, per_host) array with the DCN granule on axis 0
        arr = mesh_utils.create_hybrid_device_mesh(
            [1, per_host], [hosts, 1], devices=devs)
        return Mesh(arr, (dcn_axis, ici_axis))
    except Exception:
        # fallback (e.g. CPU test rigs whose devices lack slice
        # attributes): group rows by owning process — jax.devices()
        # orders by device id, which is NOT guaranteed
        # process-contiguous, and an interleaved reshape would silently
        # put the all_to_all axis on DCN
        by_host: dict[int, list] = {}
        for d in devs:
            by_host.setdefault(d.process_index, []).append(d)
        if len(by_host) != hosts or any(len(v) != per_host
                                        for v in by_host.values()):
            raise ValueError(
                "devices are not evenly spread over processes: "
                f"{ {k: len(v) for k, v in by_host.items()} }")
        rows = [by_host[k] for k in sorted(by_host)]
        return Mesh(np.array(rows), (dcn_axis, ici_axis))


def keys_sharding(mesh, axis: str = "keys"):
    """NamedSharding that lays the leading (key) axis over the DCN axis,
    replicating along the intra-host axis."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(axis))
