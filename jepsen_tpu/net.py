"""Network manipulation (reference L1) — partitions, latency, loss.

Reference: jepsen/src/jepsen/net.clj + net/proto.clj.  Protocol Net with
drop!/heal!/slow!/flaky!/fast! (net.clj:14-25), an iptables
implementation (net.clj:57-109) with the optional PartitionAll batch fast
path (proto.clj:5-12, net.clj:100-109), an ipfilter implementation for
SmartOS (net.clj:111-143), and `tc netem` for latency/loss shaping.
"""

from __future__ import annotations

import logging

from . import control
from .control import RemoteError, lit
from .util import real_pmap

log = logging.getLogger("jepsen")

TC = "/sbin/tc"


class Net:
    """net.clj:14-25."""

    def drop(self, test: dict, src, dest) -> None:
        """Drop traffic from src as seen by dest."""
        raise NotImplementedError

    def heal(self, test: dict) -> None:
        raise NotImplementedError

    def slow(self, test: dict, mean_ms: int = 50, variance_ms: int = 10,
             distribution: str = "normal") -> None:
        raise NotImplementedError

    def flaky(self, test: dict) -> None:
        raise NotImplementedError

    def fast(self, test: dict) -> None:
        raise NotImplementedError


class PartitionAll:
    """Optional batch fast path (net/proto.clj:5-12)."""

    def drop_all(self, test: dict, grudge: dict) -> None:
        raise NotImplementedError


def drop_all(test: dict, grudge: dict) -> None:
    """Apply a grudge — {dst: [srcs to drop]} — via the test's net
    (net.clj:28-43)."""
    net = test["net"]
    if isinstance(net, PartitionAll):
        net.drop_all(test, grudge)
        return
    pairs = [(src, dst) for dst, srcs in grudge.items() for src in srcs]
    real_pmap(lambda p: net.drop(test, p[0], p[1]), pairs)


class _Noop(Net):
    def drop(self, test, src, dest):
        pass

    def heal(self, test):
        pass

    def slow(self, test, mean_ms=50, variance_ms=10, distribution="normal"):
        pass

    def flaky(self, test):
        pass

    def fast(self, test):
        pass


noop = _Noop()


def ip(sess: control.Session, host: str) -> str:
    """hostname -> IP via getent (control/net.clj:21-32)."""
    out = sess.exec("getent", "ahosts", host)
    for line in out.splitlines():
        parts = line.split()
        if len(parts) >= 2 and parts[1] == "STREAM":
            return parts[0]
    return out.split()[0]


def reachable(sess: control.Session, host: str) -> bool:
    """Can this node ping host? (control/net.clj:7-11)"""
    try:
        sess.exec("ping", "-w", "1", "-c", "1", host)
        return True
    except RemoteError:
        return False


class IPTables(Net, PartitionAll):
    """iptables DROP rules + tc netem (net.clj:57-109)."""

    def drop(self, test, src, dest):
        sess = control.session(dest, test).su()
        sess.exec("iptables", "-A", "INPUT", "-s", ip(sess, src),
                  "-j", "DROP", "-w")

    def heal(self, test):
        def f(t, node):
            s = control.session(node, t).su()
            s.exec("iptables", "-F", "-w")
            s.exec("iptables", "-X", "-w")
        control.on_nodes(test, f)

    def slow(self, test, mean_ms=50, variance_ms=10, distribution="normal"):
        def f(t, node):
            control.session(node, t).su().exec(
                TC, "qdisc", "add", "dev", "eth0", "root", "netem",
                "delay", f"{mean_ms}ms", f"{variance_ms}ms",
                "distribution", distribution)
        control.on_nodes(test, f)

    def flaky(self, test):
        def f(t, node):
            control.session(node, t).su().exec(
                TC, "qdisc", "add", "dev", "eth0", "root", "netem",
                "loss", "20%", "75%")
        control.on_nodes(test, f)

    def fast(self, test):
        def f(t, node):
            try:
                control.session(node, t).su().exec(
                    TC, "qdisc", "del", "dev", "eth0", "root")
            except RemoteError as e:
                if "No such file or directory" not in str(e):
                    raise
        control.on_nodes(test, f)

    def drop_all(self, test, grudge):
        """One iptables rule per dst with a joined source list
        (net.clj:100-109)."""
        def snub(t, node):
            srcs = grudge.get(node) or []
            if not srcs:
                return
            s = control.session(node, t).su()
            s.exec("iptables", "-A", "INPUT", "-s",
                   ",".join(ip(s, src) for src in srcs), "-j", "DROP", "-w")
        control.on_nodes(test, snub, list(grudge.keys()))


iptables = IPTables()


class IPFilter(Net):
    """SmartOS ipf (net.clj:111-143)."""

    def drop(self, test, src, dest):
        control.session(dest, test).su().exec(
            "echo", "block", "in", "from", src, "to", "any",
            lit("|"), "ipf", "-f", "-")

    def heal(self, test):
        control.on_nodes(
            test, lambda t, n: control.session(n, t).su().exec("ipf", "-Fa"))

    def slow(self, test, mean_ms=50, variance_ms=10, distribution="normal"):
        def f(t, node):
            control.session(node, t).su().exec(
                "tc", "qdisc", "add", "dev", "eth0", "root", "netem",
                "delay", f"{mean_ms}ms", f"{variance_ms}ms",
                "distribution", distribution)
        control.on_nodes(test, f)

    def flaky(self, test):
        def f(t, node):
            control.session(node, t).su().exec(
                "tc", "qdisc", "add", "dev", "eth0", "root", "netem",
                "loss", "20%", "75%")
        control.on_nodes(test, f)

    def fast(self, test):
        def f(t, node):
            control.session(node, t).su().exec(
                "tc", "qdisc", "del", "dev", "eth0", "root")
        control.on_nodes(test, f)


ipfilter = IPFilter()
