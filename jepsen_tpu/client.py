"""Client protocol — applies operations to the system under test.

Reference: jepsen/src/jepsen/client.clj:8-26.  Five-phase lifecycle:

  open(test, node)    -> connection-ready client (no logical state change)
  setup(test)         -> one-time database state preparation
  invoke(test, op)    -> completion Op (type ok/fail/info)
  teardown(test)      -> logical cleanup
  close(test)         -> connection cleanup

The worker loop (core.py) opens one client per worker, reopens after
crashes, and converts invoke exceptions into :info completions
(core.clj:248-281).
"""

from __future__ import annotations

from dataclasses import replace

from .history import Op


class Client:
    def open(self, test: dict, node) -> "Client":
        """Bind to a node; return a client ready for invoke (may be a new
        instance).  Must not change the logical state of the test."""
        return self

    def setup(self, test: dict) -> None:
        """One-time database state setup."""

    def invoke(self, test: dict, op: Op) -> Op:
        """Apply op; return the completion (type ok/fail/info)."""
        raise NotImplementedError

    def teardown(self, test: dict) -> None:
        """Tear down logical state when work is complete."""

    def close(self, test: dict) -> None:
        """Release the connection."""


class _Noop(Client):
    """Acks every op (client.clj:28-36)."""

    def invoke(self, test, op):
        return replace(op, type="ok")


noop = _Noop()
