"""The streaming bench tier — ``python bench.py --stream-tier``.

Three measurements, written to ``BENCH_stream.json`` (one JSON object)
and echoed as bench.py's usual single JSON line:

  * **time-to-first-verdict** — a quiescent register workload streamed
    op-by-op; wall clock (and event index) from the first ingest to the
    first folded segment, i.e. the moment the verdict stops being
    "open".  Post-hoc checking cannot answer before the last op by
    construction; this is the number that makes streaming a different
    execution mode rather than a faster one.
  * **violation-detection latency** — the same workload with a read
    corrupted near op k (~10% in): events and wall clock between
    ingesting the violating op and the stream flipping ``invalid``,
    plus the headroom to the end of the stream (how much run time the
    early verdict saves).
  * **sustained multiplexed ingest** — 4 concurrent synthetic streams
    through one :class:`~jepsen_tpu.stream.service.StreamService`
    namespace each, sharing one verdict cache; total ops/sec across
    the fleet, with the cache counters showing cross-stream reuse.

Every stream's final verdict is cross-checked against the post-hoc
direct engine (``parity`` in the output) — a throughput number from a
checker that disagrees with the oracle would be worthless.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time


def _mk_history(seed: int, n_ops: int, *, corrupt_at: float | None = None):
    from ..synth import corrupt_read, register_history

    rng = random.Random(seed)
    h = register_history(rng, n_ops=n_ops, n_procs=6, overlap=4,
                         quiesce_every=8, n_values=5, cas=False)
    violation_idx = None
    if corrupt_at is not None:
        h2 = corrupt_read(rng, h, at=corrupt_at)
        violation_idx = next(i for i, (a, b) in enumerate(zip(h, h2))
                             if a is not b)
        h = h2
    return h, violation_idx


def _stream_one(model, h, *, cache=None):
    """Stream a history op-by-op; returns (final result, timeline) where
    timeline records first-verdict and first-invalid wall/event marks."""
    from .checker import StreamChecker

    sc = StreamChecker(model, cache=cache)
    t0 = time.perf_counter()
    tl = {"t0": t0, "first_verdict": None, "first_invalid": None,
          "ingest_s": None}
    for i, op in enumerate(h):
        sc.ingest(op)
        if tl["first_verdict"] is None or tl["first_invalid"] is None:
            v = sc.verdict()
            if tl["first_verdict"] is None and v["status"] != "open":
                tl["first_verdict"] = (i, time.perf_counter() - t0)
            if tl["first_invalid"] is None and v["status"] == "invalid":
                tl["first_invalid"] = (i, time.perf_counter() - t0)
    tl["ingest_s"] = time.perf_counter() - t0
    return sc.finalize(), tl


def run_stream_tier(repo: str, *, quick: bool = False) -> dict:
    from ..checker.linear import check_opseq_linear
    from ..decompose.cache import VerdictCache
    from ..history import encode_ops
    from ..models import register

    n_ops = 400 if quick else 2000
    model = register(0)
    out: dict = {"metric": "streaming incremental checker",
                 "n_ops": n_ops, "quick": quick, "parity": True}

    def posthoc(h):
        seq = encode_ops(h, model.f_codes)
        t0 = time.perf_counter()
        r = check_opseq_linear(seq, model, lint=False)
        return r, time.perf_counter() - t0

    # --- tier 1: time-to-first-verdict on a valid stream -------------
    h, _ = _mk_history(11, n_ops)
    r, tl = _stream_one(model, h)
    ph, ph_s = posthoc(h)
    out["parity"] &= r["valid"] == ph["valid"]
    out["ttfv"] = {
        "events": len(h),
        "first_verdict_event": tl["first_verdict"][0]
        if tl["first_verdict"] else None,
        "first_verdict_s": round(tl["first_verdict"][1], 4)
        if tl["first_verdict"] else None,
        "stream_total_s": round(tl["ingest_s"], 4),
        "posthoc_s": round(ph_s, 4),
        "segments": r["stream"]["segments"],
        "valid": r["valid"],
    }

    # --- tier 2: violation-detection latency -------------------------
    h, k = _mk_history(12, n_ops, corrupt_at=0.1)
    r, tl = _stream_one(model, h)
    ph, _s = posthoc(h)
    out["parity"] &= r["valid"] == ph["valid"]
    inv = tl["first_invalid"]
    # wall clock between ingesting the violating event and the verdict
    # flipping (the op index delta is the protocol-level latency; the
    # headroom is how much of the run the early verdict saves)
    out["violation_latency"] = {
        "violation_event": k,
        "invalid_at_event": inv[0] if inv else None,
        "event_delta": (inv[0] - k) if inv else None,
        "invalid_at_s": round(inv[1], 4) if inv else None,
        "headroom_events": (len(h) - 1 - inv[0]) if inv else None,
        "detected_before_stream_end": bool(inv and inv[0] < len(h) - 1),
        "valid": r["valid"],
    }

    # --- tier 3: sustained ingest, 4 concurrent streams --------------
    cache = VerdictCache()  # in-memory, shared across the fleet
    streams = [(i, _mk_history(100 + (i % 2), n_ops)[0])
               for i in range(4)]  # two pairs share content: cache hits
    results: dict = {}

    def worker(i, h):
        results[i] = _stream_one(model, h, cache=cache)

    threads = [threading.Thread(target=worker, args=s) for s in streams]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    total_events = sum(len(h) for _i, h in streams)
    for i, h in streams:
        ph, _s = posthoc(h)
        out["parity"] &= results[i][0]["valid"] == ph["valid"]
    out["multiplexed"] = {
        "streams": len(streams),
        "events_total": total_events,
        "wall_s": round(wall, 4),
        "events_per_sec": round(total_events / wall, 1) if wall else None,
        "cache": {"hits": cache.hits, "misses": cache.misses,
                  "inserts": cache.inserts},
    }

    path = os.path.join(repo, "BENCH_stream.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({
        "metric": "stream: time-to-first-verdict (s) on a "
                  f"{n_ops}-op quiescent register stream",
        "value": out["ttfv"]["first_verdict_s"],
        "unit": "seconds",
        "detail": out,
    }))
    return out
