"""Streaming incremental checking — live verdicts while the test runs.

Everything else in this repo checks post-hoc: the runner finishes, then
the checker replays the whole history.  This subsystem turns the
quiescence-cut machinery (``decompose/partition.py``: segments compose
sequentially through reachable-state sets, P-compositionality,
arXiv:1504.00204) into an *online* checker:

  * :mod:`checker` — :class:`StreamChecker`, the op sink: incremental
    event pairing, online per-cell quiescence-cut detection, immediate
    folding of closed segments against the carried-forward
    reachable-state frontier (canonical-hash verdict cache first), and
    a live provisional verdict (``valid-so-far`` / final ``invalid`` /
    ``open``) the whole way.  ``finalize()`` emits a proof-carrying
    result identical to the post-hoc engines.
  * :mod:`device` — wide segment folds dispatched to the batched
    device engine (checker/bucket.py) via state-pinning pseudo-ops,
    the GPUexplore split (arXiv:1801.05857): accelerated search on
    device, cheap sequential composition on host.
  * :mod:`service` / ``python -m jepsen_tpu.stream`` — a long-running
    service multiplexing history JSONL from many concurrent runs over
    stdin or a socket, all sharing one verdict cache: the fleet only
    ever pays for novel segments.
  * :mod:`bench` — the streaming bench tier (``python bench.py
    --stream-tier``): time-to-first-verdict, violation-detection
    latency, sustained multiplexed ingest, written to
    BENCH_stream.json.

Wiring: ``core.prepare_test`` installs the sink next to the
StreamLinter behind ``JEPSEN_TPU_STREAM=1`` / CLI ``--stream``;
``core.run`` finalizes it on success AND on worker-abort paths (a
crashed run still yields the verdict of the prefix it recorded);
``web.py`` serves the live snapshot at ``/api/live/<run>`` and renders
the live panel; the streaming-applicability gate lives in
``analyze.plan.stream_plan`` / ``segment_fold_route`` so prediction
and execution cannot drift.
"""

from .checker import StreamChecker, stream_enabled
from .service import StreamService

__all__ = ["StreamChecker", "StreamService", "stream_enabled"]
