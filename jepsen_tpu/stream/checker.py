"""The streaming incremental checker — live verdicts while a test runs.

:class:`StreamChecker` is an op *sink*: the runner (or the service mode)
feeds it history events one at a time, in history order, and it keeps a
provisional verdict current the whole way:

  * events pair incrementally into retained rows (ok / :info; :fail
    drops), exactly the merge ``history.encode_ops`` performs post-hoc;
  * rows partition online into per-key cells (Herlihy–Wing locality,
    mirroring ``decompose.partition.partition_by_key``);
  * each cell watches for **online quiescence cuts**: the moment a new
    op invokes while the cell has nothing pending (and has never
    crashed), every earlier cell op has returned — the running prefix
    ends in a quiescent point, so the rows so far form a *closed
    segment* that composes with whatever follows purely through its
    reachable-state set (P-compositionality, arXiv:1504.00204);
  * closed segments are folded the moment they close — canonical-hash
    verdict cache first (``decompose/cache.py``: same keys the post-hoc
    engine writes, so repeat content across runs and fleets is never
    re-searched), then either the host fold
    (``decompose.engine.segment_states``) or, when the plan gate
    (``analyze.plan.segment_fold_route``) predicts the host fold is too
    expensive, the batched device path (``stream/device.py`` →
    ``checker/bucket.py``) on a background thread so ingest never
    blocks (the GPUexplore split, arXiv:1801.05857: accelerated search,
    cheap host composition);
  * an empty reachable set — or an :ok op on an unsteppable key — is
    **final**: no suffix can repair a closed segment (later ops invoke
    after every closed op returned, so they cannot interleave into it),
    and the stream flips to ``invalid`` seconds after the violating op,
    not minutes after teardown.

``finalize()`` closes the stream (open invokes become :info rows — the
crashed tail), checks each cell's final segment from its carried-in
state set, and emits a result dict with the same proof-carrying
certificate contract as the post-hoc engines: ``linearization`` (per-
cell chains threaded across segments, stitched by
``partition.merge_linearizations``) or ``witness_dropped``;
``final_ops`` or ``frontier_dropped``; auditable by
``analyze/audit.py``.  Online cuts are a *coarsening* of the post-hoc
cuts (an op that later :fails blocks an online cut but not an offline
one), and every stage is exact, so the final verdict is identical to
``check_opseq_decomposed`` / the direct engines on the same history —
enforced by the differential fuzz in tests/test_stream.py.  Anything
inconclusive (sub-search budget) falls back to one direct check of the
whole recorded history, mirroring the decomposed engine's contract:
streaming may only ever *hasten* a verdict, never change one.
"""

from __future__ import annotations

import json
import logging
import os
import queue as _queue
import threading
import time
from dataclasses import replace as _dc_replace

import numpy as np

from .. import obs
from ..history import INF_RET, INFO, INVOKE, NIL, OK, Op, OpSeq, ValueEncoder
from ..models import ModelSpec
from ..obs import metrics as obs_metrics

log = logging.getLogger("jepsen")

#: flight-recorder counters (module handles — ingest is the hot path)
_M_INGESTED = obs_metrics.REGISTRY.counter(
    "jtpu_stream_ops_ingested_total",
    "History events ingested by streaming checkers")
_M_FOLDED = obs_metrics.REGISTRY.counter(
    "jtpu_stream_segments_folded_total",
    "Closed quiescence segments folded, by route", ("route",))
_M_FORKS = obs_metrics.REGISTRY.counter(
    "jtpu_stream_forks_total",
    "Bounded :info lookahead forks, spawned vs capped", ("outcome",))
_M_FOLD_S = obs_metrics.REGISTRY.histogram(
    "jtpu_fold_seconds", "Wall seconds per streamed segment fold")

#: how often (events) the live snapshot is rewritten at most
_LIVE_EVERY = 64
_LIVE_MIN_S = 0.25


def stream_enabled() -> bool:
    """The fleet-wide opt-in knob (CLI ``--stream`` sets it): with
    JEPSEN_TPU_STREAM=1/true/on/yes, ``core.prepare_test`` installs a
    :class:`StreamChecker` op sink next to the StreamLinter."""
    return os.environ.get("JEPSEN_TPU_STREAM", "").strip().lower() in (
        "1", "true", "on", "yes")


class _Row:
    """One retained (or still-open) logical op."""

    __slots__ = ("inv", "ret", "process", "f", "v1", "v2", "status",
                 "op", "cell_key", "cell_pos", "g")

    def __init__(self, inv, process, f, v1, v2, op, cell_key):
        self.inv = inv
        self.ret = INF_RET
        self.process = process
        self.f = f
        self.v1 = v1
        self.v2 = v2
        self.status = "open"  # open | ok | info | fail
        self.op = op
        self.cell_key = cell_key
        self.cell_pos = None  # position in the cell's retained-row list
        self.g = None  # global row index, assigned at finalize


def _rows_opseq(rows: list[_Row], encoder, *, value_lane: bool) -> OpSeq:
    """Columnar OpSeq over retained rows (already inv-sorted).

    ``value_lane=True`` builds the *cell* shape of a multi-register
    projection (value moved from the v2 lane to v1, exactly
    ``partition.cells_from_rows``)."""
    n = len(rows)
    if value_lane:
        v1 = [r.v2 for r in rows]
        v2 = [NIL] * n
    else:
        v1 = [r.v1 for r in rows]
        v2 = [r.v2 for r in rows]
    return OpSeq(
        process=np.array([r.process for r in rows], np.int32).reshape(n),
        f=np.array([r.f for r in rows], np.int32).reshape(n),
        v1=np.array(v1, np.int32).reshape(n),
        v2=np.array(v2, np.int32).reshape(n),
        inv=np.array([r.inv for r in rows], np.int64).reshape(n),
        ret=np.array([r.ret for r in rows], np.int64).reshape(n),
        ok=np.array([r.status == "ok" for r in rows], bool).reshape(n),
        ops=[r.op for r in rows],
        encoder=encoder,
    )


class _Cell:
    """Per-key streaming state: the open segment buffer, the carried
    reachable-state frontier, and the witness chains threading it."""

    def __init__(self, key, init_state: tuple, witness: bool):
        self.key = key
        self.buf: list[_Row] = []  # rows of the still-open segment
        self.rows: list[_Row] = []  # retained rows of CLOSED segments
        self.pending = 0  # invoked, completion still unknown
        self.crashed = False  # an :info row suppresses all later cuts
        self.ok_in_buf = 0  # post-crash :ok rows (lookahead cadence)
        self.la_checked = 0  # ok_in_buf at the last speculative check
        self.states: set = {tuple(init_state)}
        # state -> cell-row chain reaching it; None once any stage drops
        self.chains: dict | None = {tuple(init_state): []} if witness \
            else None
        self.segments = 0  # closed segments folded so far
        self.fallback = False  # an inconclusive fold: direct at the end
        self.final_rows: list = []  # the unquiesced tail, at finalize


class StreamChecker:
    """Incremental checking engine; see the module docstring.

    model            the ModelSpec the history is checked against
    cache            VerdictCache, a jsonl path, or None
    witness          carry witness chains (certificate on valid)
    async_folds      fold closed segments on a background thread (the
                     runner wiring: ingest must never block a worker);
                     False folds inline at segment close (deterministic
                     — the tests' and service mode's default)
    sub_max_configs  per-sub-search budget, as the decomposed engine
    host_fold_max    override for the plan gate's host-fold cost cap
                     (``analyze.plan.segment_fold_route``)
    info_lookahead   bounded `:info` lookahead horizon: after this many
                     post-crash :ok rows accumulate at a pseudo-
                     quiescent point, the crashed cell's open segment
                     is speculatively fork-checked (each `:info` op
                     present at any frontier position vs absent) so a
                     kill-seeded violation flips the live verdict
                     mid-stream.  None = the plan default
                     (``analyze.plan.STREAM_INFO_LOOKAHEAD``); 0
                     disables (finalize-only).  Final verdicts are
                     identical either way: a speculative invalid is
                     sound (every fork fails, so no suffix can repair
                     the prefix), and anything else changes nothing.
    device_budget    config budget per device dispatch
    live_path        when set, a JSON snapshot of :meth:`verdict` is
                     rewritten there (atomically) as the stream moves —
                     the web UI's ``/api/live`` source
    run_id           label carried into the live snapshot
    """

    def __init__(self, model: ModelSpec, *,
                 cache=None, witness: bool = True,
                 async_folds: bool = False,
                 sub_max_configs: int = 50_000_000,
                 host_fold_max: int | None = None,
                 info_lookahead: int | None = None,
                 device_budget: int = 2_000_000,
                 live_path: str | None = None,
                 run_id: str | None = None,
                 hb: bool | None = None,
                 dpor: bool | None = None):
        from ..analyze.dpor import resolve_dpor
        from ..analyze.hb import resolve_hb
        from ..analyze.plan import STREAM_INFO_LOOKAHEAD
        from ..decompose.cache import VerdictCache

        self.model = model
        #: happens-before pre-pass (analyze/hb.py): closed crash-free
        #: segments in the decidable register class fold through the
        #: O(n log n) interval pass instead of the level sweep, and
        #: finalize's sub-searches inherit the same flag so streamed
        #: results stay bit-identical to the post-hoc engines
        self.hb = resolve_hb(hb)
        #: dynamic layer (analyze/dpor.py): finalize's sub-searches and
        #: the per-cell/whole-history direct fallbacks inherit it, so a
        #: streamed verdict's engines prune exactly like the post-hoc
        #: ones (bit-identical finals either way by construction)
        self.dpor = resolve_dpor(dpor)
        if isinstance(cache, str):
            cache = VerdictCache(cache)
        self.cache = cache
        # per-RUN cache counters, counted here rather than read off the
        # (possibly shared) VerdictCache object: concurrent streams on
        # one cache (the service, the bench fleet) must not zero or
        # inflate each other's stats
        self._cstats = {"hits": 0, "misses": 0, "inserts": 0}
        self.witness = witness
        self.sub_max_configs = sub_max_configs
        self.host_fold_max = host_fold_max
        self.info_lookahead = STREAM_INFO_LOOKAHEAD \
            if info_lookahead is None else max(0, int(info_lookahead))
        self.device_budget = device_budget
        self.live_path = live_path
        self.run_id = run_id

        # three demux modes, all the same cell machinery:
        #   single       one cell, cell model = the model
        #   multi        multi-register locality: per-key register cells
        #   independent  jepsen.independent [k v] workloads: per-key
        #                cells under the TEST model (detected on the
        #                first KV-valued client op — the streamed twin
        #                of independent.checker's subhistory split)
        self._multi = model.name == "multi-register"
        self._mode = "multi" if self._multi else "single"
        if self._multi:
            from ..models import register

            self._cell_model = register(int(model.init[0]))
        else:
            self._cell_model = model
        #: client ops whose key is not yet known (non-KV invoke in an
        #: independent stream): they block every cell's cuts until
        #: their completion reveals the key
        self._floating_n = 0
        #: running count of :ok rows admitted to cells — verdict() is
        #: called per ingested event, so it must not rescan the buffers
        self._ok_rows = 0
        self._enc = ValueEncoder()
        self._lock = threading.RLock()
        self._events = 0
        self._open: dict = {}  # process -> _Row awaiting completion
        self._cells: dict = {}
        #: independent mode: key -> full per-cell result (certificates
        #: over the cell's own rows), populated at finalize
        self.cell_results: dict = {}
        self._extra: list[_Row] = []  # unsteppable-key rows (no cell)
        self._bad_ok: list[_Row] = []  # :ok rows that decide invalid
        self._invalid: dict | None = None
        self._fallback = False
        self._finalized: dict | None = None
        self._seq: OpSeq | None = None
        self._stats = {"segments": 0, "configs_searched": 0,
                       "routes": {"host": 0, "device": 0, "hb": 0},
                       "checked_rows": 0, "lookahead_checks": 0}
        self._methods: set = set()
        self._drops = {"witness": None, "frontier": None}
        if not witness:
            self._drop("witness", "witness not requested (witness=False)")
        self._first_verdict_event: int | None = None
        self._invalid_event: int | None = None
        self._live_last = (0, 0.0)
        self._live_lock = threading.Lock()  # ingest + fold thread

        self._q: _queue.Queue | None = None
        self._worker: threading.Thread | None = None
        if async_folds:
            self._q = _queue.Queue()
            self._worker = threading.Thread(target=self._worker_loop,
                                            name="stream-fold",
                                            daemon=True)
            self._worker.start()

    # ------------------------------------------------------------------
    # ingest
    # ------------------------------------------------------------------

    def ingest(self, op: Op) -> None:
        """Feed the next history event (invoke or completion, client or
        nemesis — non-client events just consume their event index, so
        row ``inv``/``ret`` ranks match the post-hoc encoding)."""
        with self._lock:
            if self._finalized is not None:
                raise RuntimeError("stream already finalized")
            i = self._events
            self._events += 1
            _M_INGESTED.inc()
            if not isinstance(op.process, int):
                return  # nemesis journal entries are not client ops
            if op.type == INVOKE:
                self._on_invoke(op, i)
            else:
                self._on_complete(op, i)
        self._maybe_write_live()

    def _lanes_value(self, v):
        if isinstance(v, (tuple, list)) and len(v) == 2:
            return self._enc.encode(v[0]), self._enc.encode(v[1])
        return self._enc.encode(v), NIL

    def _lanes(self, op: Op):
        return self._lanes_value(op.value)

    @staticmethod
    def _is_kv(v) -> bool:
        from ..independent import is_tuple

        return is_tuple(v)

    def _cell(self, key) -> _Cell:
        c = self._cells.get(key)
        if c is None:
            c = _Cell(key, self._cell_model.init, self.witness)
            self._cells[key] = c
        return c

    def _cell_for(self, v1: int):
        """The cell a row belongs to, or None for an unsteppable key
        (multi-register NIL / out-of-range — ``key_partition_rows``)."""
        if not self._multi:
            key = None
        else:
            key = v1
            if key == NIL or not 0 <= key < self.model.state_width:
                return "__bad__", None
        return key, self._cell(key)

    def _admit(self, cell: _Cell, row: _Row) -> None:
        # the online cut: a fresh invoke against a cell with nothing
        # pending (and no op whose key is still unrevealed) means every
        # earlier cell op has returned — close the segment BEFORE
        # admitting the new row
        if cell.pending == 0 and not cell.crashed \
                and self._floating_n == 0 \
                and any(r.status == "ok" for r in cell.buf):
            self._close_segment(cell)
        cell.buf.append(row)
        cell.pending += 1

    def _on_invoke(self, op: Op, i: int) -> None:
        prev = self._open.pop(op.process, None)
        if prev is not None:
            # permissive double-invoke, as pair_index: the orphaned
            # invoke never pairs, i.e. it is a crashed op
            self._resolve(prev, INFO, i, None)
        if op.f not in self.model.f_codes:
            raise KeyError(f"op f={op.f!r} not in model f_codes "
                           f"{list(self.model.f_codes)}")
        fcode = self.model.f_codes[op.f]
        if self._mode == "single" and self._is_kv(op.value):
            # a jepsen.independent [k v] workload: per-key cells under
            # the test model — the streamed twin of
            # independent.checker's subhistory split
            if self._cells or self._extra:
                raise ValueError(
                    "independent [k v] op arrived after plain-valued "
                    "client ops; mixed histories are not streamable")
            self._mode = "independent"
        if self._mode == "independent":
            if self._is_kv(op.value):
                v1, v2 = self._lanes_value(op.value.value)
                row = _Row(i, op.process, fcode, v1, v2, op,
                           op.value.key)
                self._admit(self._cell(op.value.key), row)
            else:
                # key unknown until the completion reveals it: the op
                # floats, blocking every cell's cuts meanwhile
                row = _Row(i, op.process, fcode, NIL, NIL, op,
                           "__float__")
                self._floating_n += 1
        else:
            v1, v2 = self._lanes(op)
            key, cell = self._cell_for(v1)
            row = _Row(i, op.process, fcode, v1, v2, op, key)
            if cell is None:
                self._extra.append(row)
            else:
                self._admit(cell, row)
        self._open[op.process] = row

    def _on_complete(self, op: Op, i: int) -> None:
        row = self._open.pop(op.process, None)
        if row is None:
            return  # orphan completion: dropped, as pair_index does
        self._resolve(row, op.type, i, op)

    def _insert_floating(self, row: _Row) -> None:
        """Admit a just-keyed floating row into its cell's open segment
        at invocation order.  Sound because cuts need
        ``_floating_n == 0``: while this row floated no cell closed a
        segment, so every row already in a closed segment invoked (and
        returned) before this one invoked."""
        cell = self._cell(row.cell_key)
        pos = len(cell.buf)
        while pos > 0 and cell.buf[pos - 1].inv > row.inv:
            pos -= 1
        cell.buf.insert(pos, row)

    def _resolve(self, row: _Row, ctype: str, i: int, cop: Op | None):
        floating = row.cell_key == "__float__"
        cell = self._cells.get(row.cell_key) \
            if not floating and row.cell_key != "__bad__" else None
        if cell is not None:
            cell.pending -= 1
        if floating:
            self._floating_n -= 1
        if ctype == OK:
            row.status = "ok"
            row.ret = i
            if self._mode == "independent":
                if cop is None or not self._is_kv(cop.value):
                    if floating:
                        # an :ok op whose key was never revealed has no
                        # subhistory to land in — not streamable
                        raise ValueError(
                            "independent stream: :ok op without a "
                            "[k v] value")
                else:
                    row.v1, row.v2 = self._lanes_value(cop.value.value)
                    row.op = _dc_replace(row.op, value=cop.value)
                    if floating:
                        row.cell_key = cop.value.key
                        self._insert_floating(row)
            elif cop is not None and cop.value is not None:
                # the completion's value wins (history.complete: an
                # ok'd read's invocation carried nil)
                row.v1, row.v2 = self._lanes(cop)
                row.op = _dc_replace(row.op, value=cop.value)
            if row.cell_key not in ("__bad__", "__float__"):
                self._ok_rows += 1
            if row.cell_key == "__bad__":
                # an :ok op on an unsteppable key can never legally
                # step: the row itself IS the blocking frontier, and
                # the verdict is final right now
                self._bad_ok.append(row)
                if self._invalid is None:
                    self._mark_invalid({
                        "reason": "unsteppable key",
                        "cell": None, "event": i})
        elif ctype == INFO:
            row.status = "info"
            row.ret = INF_RET
            if cell is not None:
                cell.crashed = True
            # a crashed floating op never revealed its key: post-hoc it
            # is an always-legal NIL :info row in every subhistory —
            # verdict-neutral, so dropping it is exact
        else:  # fail: definitely didn't happen — drop the row
            row.status = "fail"
        c2 = self._cells.get(row.cell_key) \
            if row.cell_key not in ("__bad__", "__float__") else None
        if c2 is not None:
            if ctype == OK and c2.crashed:
                # the lookahead cadence counts POST-crash completions
                # only — the same basis stream_plan's
                # ``speculative_checks`` prediction uses
                c2.ok_in_buf += 1
            self._maybe_lookahead(c2)

    # ------------------------------------------------------------------
    # segment folding
    # ------------------------------------------------------------------

    def _close_segment(self, cell: _Cell) -> None:
        retained = [r for r in cell.buf if r.status == "ok"]
        cell.buf = []
        cell.ok_in_buf = 0
        cell.la_checked = 0
        for r in retained:
            r.cell_pos = len(cell.rows)
            cell.rows.append(r)
        if self._q is not None:
            self._q.put(("fold", cell, retained))
        else:
            self._fold(cell, retained)

    def _worker_loop(self) -> None:
        while True:
            task = self._q.get()
            if task is None:
                return
            kind, cell, rows = task
            if kind == "spec":
                try:
                    self._speculate(cell, rows)
                except Exception:  # noqa: BLE001 — speculation must
                    # never degrade the stream; finalize still decides
                    log.debug("stream: lookahead check crashed",
                              exc_info=True)
                self._maybe_write_live()
                continue
            try:
                self._fold(cell, rows)
            except Exception:  # noqa: BLE001 — one segment, not the run
                log.warning("stream: segment fold crashed; falling back",
                            exc_info=True)
                cell.fallback = True
                self._fallback = True
            self._maybe_write_live()

    def _fold(self, cell: _Cell, retained: list[_Row]) -> None:
        """Fold one closed, crash-free segment into the cell's carried
        state frontier — the streaming twin of the decomposed engine's
        quiescence loop."""
        t0 = time.perf_counter()
        with obs.span("stream.fold", cat="fold", run=self.run_id,
                      cell=str(cell.key), rows=len(retained)):
            self._fold_inner(cell, retained)
        _M_FOLD_S.observe(time.perf_counter() - t0)

    # threadlint: ok — single-owner: folds run only on the dedicated
    # "stream-fold" worker (or synchronously on the ingest thread when
    # async folds are off), so _stats/_cstats/cell fold-state have
    # exactly one writer until _drain_folds() joins the worker; all
    # cross-thread reads (verdict(), finalize()) take self._lock or
    # run post-join
    def _fold_inner(self, cell: _Cell, retained: list[_Row]) -> None:
        from ..decompose.canonical import canonical_payload
        from ..decompose.engine import _Inconclusive, _skey, segment_states

        if cell.fallback or self._fallback:
            return
        if self._invalid is not None and self._mode != "independent":
            # one invalid cell decides a single-object history, so
            # further folds are wasted work; independent keys keep
            # folding — the post-hoc checker reports EVERY key's
            # verdict, and so must the stream
            return
        sseq = _rows_opseq(retained, self._enc, value_lane=self._multi)
        self._methods.add("quiescence")
        skey = ren = None
        if self.cache is not None:
            payload, ren = canonical_payload(sseq, self._cell_model,
                                             instates=cell.states)
            skey = _skey(payload)
            e = self.cache.get(skey)
            if e is not None and "out" in e:
                self._cstats["hits"] += 1
                self._methods.add("cache")
                _M_FOLDED.inc(route="cache")
                states = set(ren.decode_states(e["out"]))
                if cell.chains is not None:
                    cell.chains = None
                    self._drop("witness", "segment state-set cache hit "
                               "(the cache stores states, not chains)")
                self._commit_fold(cell, retained, states, None,
                                  chains_known=False)
                return
            self._cstats["misses"] += 1
        from ..analyze.plan import segment_fold_route
        from ..history import max_concurrency

        wit = None
        states = None
        if self.hb:
            from ..analyze.hb import hb_fold_states

            out = hb_fold_states(sseq, self._cell_model, cell.states,
                                 witness=cell.chains is not None)
            if out is not None:
                if cell.chains is not None:
                    states, wit = out
                else:
                    states = out
                self._stats["routes"]["hb"] += 1
                _M_FOLDED.inc(route="hb")
                self._methods.add("hb-fold")
                if self.cache is not None:
                    self.cache.put_states(skey,
                                          ren.encode_states(states))
                    self._cstats["inserts"] += 1
                self._commit_fold(cell, retained, states, wit,
                                  chains_known=True)
                return
        route = segment_fold_route(len(sseq), max_concurrency(sseq),
                                   self._cell_model,
                                   host_fold_max=self.host_fold_max)
        if route == "device":
            from .device import device_fold_states

            out = device_fold_states(sseq, self._cell_model, cell.states,
                                     budget=self.device_budget)
            if out is not None:
                states, configs = out
                self._stats["routes"]["device"] += 1
                _M_FOLDED.inc(route="device")
                self._stats["configs_searched"] += configs
                self._methods.add("device")
                if cell.chains is not None:
                    cell.chains = None
                    self._drop("witness", "device-folded segment "
                               "carries states only")
        if states is None:
            self._stats["routes"]["host"] += 1
            _M_FOLDED.inc(route="host")
            try:
                if cell.chains is not None:
                    states, wit = segment_states(
                        sseq, self._cell_model, cell.states,
                        max_configs=self.sub_max_configs, witness=True)
                else:
                    states = segment_states(
                        sseq, self._cell_model, cell.states,
                        max_configs=self.sub_max_configs)
            except _Inconclusive:
                cell.fallback = True
                self._fallback = True
                return
        if self.cache is not None:
            self.cache.put_states(skey, ren.encode_states(states))
            self._cstats["inserts"] += 1
        self._commit_fold(cell, retained, states, wit, chains_known=True)

    def _commit_fold(self, cell: _Cell, retained, states, wit,
                     *, chains_known: bool) -> None:
        with self._lock:
            if chains_known and cell.chains is not None:
                if wit is None:
                    cell.chains = None
                    self._drop("witness",
                               "segment witness table exceeded its cap")
                else:
                    cell.chains = {
                        out_s: cell.chains[in_s]
                        + [retained[j].cell_pos for j in seg_chain]
                        for out_s, (in_s, seg_chain) in wit.items()}
            cell.states = states
            cell.segments += 1
            self._stats["segments"] += 1
            self._stats["checked_rows"] += len(retained)
            if not states:
                self._drop("frontier", "a quiescence segment has no "
                           "linearization (frontier not localized)")
                self._mark_invalid({
                    "reason": "segment has no linearization",
                    "cell": cell.key, "segment": cell.segments,
                    "event": self._events - 1})
            elif self._first_verdict_event is None:
                self._first_verdict_event = self._events - 1

    # ------------------------------------------------------------------
    # bounded `:info` lookahead (speculative fork check)
    # ------------------------------------------------------------------

    def _maybe_lookahead(self, cell: _Cell) -> None:
        """Schedule a speculative fork check of a crashed cell's open
        segment once a horizon's worth of post-crash :ok rows has
        accumulated at a pseudo-quiescent point (nothing pending, no
        floating keys) — the bounded-lookahead cut that lets a
        kill-seeded violation flip the live verdict mid-stream even
        though the `:info` op suppresses real quiescence cuts."""
        h = self.info_lookahead
        if not h or not cell.crashed or cell.pending != 0 \
                or self._floating_n != 0 or self._invalid is not None \
                or self._fallback or cell.fallback:
            return
        if cell.ok_in_buf - cell.la_checked < h:
            return
        cell.la_checked = cell.ok_in_buf
        from ..analyze.plan import info_fork_budget

        rows = [r for r in cell.buf if r.status in ("ok", "info")]
        n_infos = sum(1 for r in rows if r.status == "info")
        if not info_fork_budget(n_infos, len(rows)):
            # too costly to fork online — the POP-DPOR bound, now a
            # cost budget (pending infos x open-segment rows, the
            # sub-search's first-order cost) instead of a flat info
            # cap: the verdict still lands exactly at finalize
            _M_FORKS.inc(outcome="capped")
            return
        if self._q is not None:
            self._q.put(("spec", cell, rows))
        else:
            try:
                self._speculate(cell, rows)
            except Exception:  # noqa: BLE001 — speculation must never
                # degrade the stream (the op was already admitted;
                # raising here would poison ingest for a resolved row)
                log.debug("stream: lookahead check crashed",
                          exc_info=True)

    def _speculate(self, cell: _Cell, rows: list[_Row]) -> None:
        """The fork check itself: the crashed cell's open segment from
        every carried frontier state, with each `:info` op free to
        linearize at any position — or never (the sub-search already
        forks exactly present-at-each-position vs absent).  Sound as a
        FINAL verdict: later ops invoke after every retained op here
        returned, so they cannot interleave into this prefix, and the
        `:info` ops were given every placement including "later" — if
        no fork linearizes, no suffix can repair it.  A valid or
        inconclusive outcome changes nothing: the segment stays open
        and finalize folds it exactly as finalize-only mode would —
        final-verdict parity with lookahead off, by construction."""
        if self._invalid is not None or self._fallback or cell.fallback:
            return
        _M_FORKS.inc(outcome="spawned")
        with obs.span("stream.fork", cat="fold", run=self.run_id,
                      cell=str(cell.key), rows=len(rows)):
            self._speculate_inner(cell, rows)

    def _speculate_inner(self, cell: _Cell, rows: list[_Row]) -> None:
        sseq = _rows_opseq(rows, self._enc, value_lane=self._multi)
        sub = self._default_sub_check()
        with self._lock:
            self._stats["lookahead_checks"] += 1
            self._methods.add("lookahead")
        for s in sorted(cell.states):
            r = sub(sseq, _dc_replace(self._cell_model, init=tuple(s)),
                    max_configs=self.sub_max_configs)
            with self._lock:
                self._stats["configs_searched"] += int(
                    r.get("configs", 0) or 0)
            if r.get("valid") is not False:
                return  # some fork linearizes (or undecided): no news
        with self._lock:
            self._drop("frontier", "info-lookahead fork check found no "
                       "linearization (frontier spans the fork)")
            self._mark_invalid({
                "reason": "info-lookahead: no fork of the crashed "
                          "op(s) linearizes the prefix",
                "cell": cell.key, "event": self._events - 1,
                "infos": sum(1 for r in rows if r.status == "info")})

    def _mark_invalid(self, info: dict) -> None:
        if self._invalid is None:
            self._invalid = info
            self._invalid_event = self._events - 1

    def _drop(self, kind: str, reason: str) -> None:
        # first-writer-wins by design: any racing writer's reason is an
        # equally true first cause, and a lost overwrite is harmless —
        # the slot only ever goes None -> some-reason, never back
        if self._drops[kind] is None:
            self._drops[kind] = reason  # threadlint: ok — idempotent

    # ------------------------------------------------------------------
    # the live provisional verdict
    # ------------------------------------------------------------------

    def verdict(self) -> dict:
        """The current provisional verdict:

        ``status`` is ``"invalid"`` (final — a closed segment cannot
        linearize, or an :ok op can never step), ``"valid-so-far"``
        (every closed segment folded to a non-empty frontier), or
        ``"open"`` (nothing has quiesced yet: the whole prefix is the
        unquiesced tail)."""
        with self._lock:
            rows = self._ok_rows
            checked = self._stats["checked_rows"]
            if self._invalid is not None:
                status = "invalid"
            elif self._stats["segments"] > 0:
                status = "valid-so-far"
            else:
                status = "open"
            out = {
                "status": status,
                "run": self.run_id,
                "events": self._events,
                "rows": rows,
                "cells": len(self._cells),
                "segments_closed": self._stats["segments"],
                "checked_rows": checked,
                "open_rows": max(0, rows - checked),
                "routes": dict(self._stats["routes"]),
                "lookahead_checks": self._stats["lookahead_checks"],
                "fallback": self._fallback,
                "first_verdict_event": self._first_verdict_event,
                "invalid_event": self._invalid_event,
                "violation": dict(self._invalid) if self._invalid
                else None,
            }
            if self.cache is not None:
                out["cache"] = dict(self._cstats)
            return out

    def _maybe_write_live(self, force: bool = False,
                          final: dict | None = None) -> None:
        if self.live_path is None:
            return
        # one writer at a time: ingest and the fold thread both land
        # here, and two dumps into the shared tmp file would rename a
        # corrupt snapshot into place without any OSError to catch
        with self._live_lock:
            ev, t = self._live_last
            now = time.monotonic()
            # both constants are FLOORS: at least 64 events apart AND
            # at least 0.25s apart, so a hot stream never spends its
            # ingest path rewriting snapshots hundreds of times a second
            if not force and (self._events - ev < _LIVE_EVERY
                              or now - t < _LIVE_MIN_S):
                return
            self._live_last = (self._events, now)
            snap = self.verdict()
            if final is not None:
                snap["final"] = final
            tmp = self.live_path + ".tmp"
            try:
                os.makedirs(os.path.dirname(self.live_path) or ".",
                            exist_ok=True)
                with open(tmp, "w") as f:
                    json.dump(snap, f)
                os.replace(tmp, self.live_path)
            except OSError:
                log.debug("stream: live snapshot write failed",
                          exc_info=True)

    # ------------------------------------------------------------------
    # finalize
    # ------------------------------------------------------------------

    def seq(self) -> OpSeq:
        """The full columnar history as streamed (available after
        :meth:`finalize`) — identical in shape to what
        ``encode_ops(history, model.f_codes)`` would build post-hoc.
        (Independent mode: the flattened per-key rows — useful for row
        accounting, but certified per cell, not as one history.)"""
        if self._seq is None:
            raise RuntimeError("seq() is available after finalize()")
        return self._seq

    def cell_seq(self, key) -> OpSeq:
        """One cell's full subhistory as streamed (after finalize) —
        the OpSeq its :attr:`cell_results` certificate indexes."""
        return _rows_opseq(self._cells[key].rows, self._enc,
                           value_lane=self._multi)

    # threadlint: ok — callers (finalize, close) serialize on
    # self._lock / the single finalize path; after the join the fold
    # worker is gone, so nulling _q/_worker has one writer
    def _drain_folds(self) -> None:
        if self._q is not None:
            self._q.put(None)
            if self._worker is not None:
                self._worker.join()
            self._q = None
            self._worker = None

    def finalize(self, *, audit: bool | None = None) -> dict:
        """Close the stream and emit the final result dict (same shape
        and certificate contract as ``check_opseq_decomposed``).  Open
        invokes become :info rows — the crashed tail of an aborted run
        still yields its verdict."""
        with self._lock:
            if self._finalized is not None:
                return self._finalized
            # crashed tail: invokes the stream never saw complete
            for row in self._open.values():
                cell = self._cells.get(row.cell_key) \
                    if row.cell_key != "__bad__" else None
                if cell is not None:
                    cell.pending -= 1
                    cell.crashed = True
                row.status = "info"
                row.ret = INF_RET
            self._open.clear()
        self._drain_folds()
        with obs.span("stream.finalize", cat="check", run=self.run_id):
            out = self._finish(audit)
        self._finalized = out
        self._maybe_write_live(force=True, final={
            "valid": out.get("valid"), "engine": out.get("engine")})
        return out

    def _final_rows(self) -> list[_Row]:
        rows: list[_Row] = []
        for c in self._cells.values():
            rows.extend(c.rows)
        rows.extend(r for r in self._extra if r.status in ("ok", "info"))
        rows.sort(key=lambda r: r.inv)
        for g, r in enumerate(rows):
            r.g = g
        return rows

    def _finish(self, audit_flag) -> dict:
        from ..analyze.audit import maybe_audit
        from ..decompose.canonical import canonical_key, canonical_payload
        from ..decompose.engine import _skey
        from ..decompose.partition import merge_linearizations

        # final segments: whatever never quiesced (crashes included)
        for c in self._cells.values():
            final = [r for r in c.buf if r.status in ("ok", "info")]
            c.buf = []
            c.final_rows = final
            for r in final:
                r.cell_pos = len(c.rows)
                c.rows.append(r)
        rows = self._final_rows()
        self._seq = _rows_opseq(rows, self._enc, value_lane=False)
        if self._mode == "independent":
            self._methods.add("independent")
        elif self._multi and len(self._cells) > 1:
            self._methods.add("key-partition")

        stats = self._stats
        wkey = None
        if self.cache is not None and self._mode != "independent":
            # no whole-history key for independent streams: the
            # flattened [k v] rows canonically LOOK like a plain
            # register history, and caching the per-key-merged verdict
            # under that shape would poison real single-object lookups
            wkey = canonical_key(self._seq, self.model)

        # threadlint: ok — finalize path: runs strictly after
        # _drain_folds() joined the fold worker, so the process is
        # single-threaded over this state from here on
        def done(valid, extra: dict | None = None) -> dict:
            st = {
                "cells": max(1, len(self._cells)),
                "segments": stats["segments"]
                + sum(1 for c in self._cells.values() if c.final_rows),
                "rows": len(rows),
                "events": self._events,
                "checked_rows": stats["checked_rows"],
                "routes": dict(stats["routes"]),
                "lookahead_checks": stats["lookahead_checks"],
                "methods": sorted(self._methods),
                "first_verdict_event": self._first_verdict_event,
                "invalid_event": self._invalid_event,
                "fallback": self._fallback,
            }
            if stats.get("stitched"):
                st["stitched"] = True
            if self.cache is not None:
                if wkey is not None and valid in (True, False):
                    self.cache.put_verdict(wkey, valid)
                    self._cstats["inserts"] += 1
                st["cache_hits"] = self._cstats["hits"]
                st["cache_misses"] = self._cstats["misses"]
                st["cache_inserts"] = self._cstats["inserts"]
            out = {"valid": valid,
                   "configs": stats["configs_searched"],
                   "engine": "stream(%s)" % ",".join(st["methods"])
                   if self._methods else "stream",
                   "stream": st}
            if extra:
                out = {**extra, **out, "engine": out["engine"],
                       "stream": st}
            if out["valid"] is True and "linearization" not in out:
                out.setdefault("witness_dropped", self._drops["witness"]
                               or "streamed route produced no witness")
            if out["valid"] is False and "final_ops" not in out:
                out.setdefault("frontier_dropped", self._drops["frontier"]
                               or "streamed route produced no frontier")
            return maybe_audit(self._seq, self.model, out, audit_flag)

        if self._bad_ok:
            self._methods.add("key-partition")
            return done(False, extra={
                "final_ops": sorted(r.g for r in self._bad_ok)})
        if self._invalid is not None and not self._fallback \
                and self._mode != "independent":
            # final: a closed segment cannot linearize (independent
            # streams fall through — every key still gets its verdict)
            return done(False)
        if self._fallback and self._mode != "independent":
            # an inconclusive fold: one direct check of the whole
            # history (independent streams fall back per CELL below —
            # the flattened multi-key history is not one model's)
            return done(*self._finish_fallback(wkey))

        # each cell's final segment, checked from its carried frontier
        sub_check = self._default_sub_check()
        order = sorted(self._cells,
                       key=lambda k: (-len(self._cells[k].rows),
                                      str(k)))
        cell_lins: dict = {}
        invalid_frontier = None
        verdict = True
        has_unknown = False
        per_key: dict = {}
        for key in order:
            c = self._cells[key]
            v, lin, frontier = self._check_final(c, sub_check,
                                                 canonical_payload,
                                                 _skey)
            if v == "fallback":
                if self._mode == "independent":
                    v, lin, frontier = self._cell_direct(c)
                else:
                    return done(*self._finish_fallback(wkey))
            if self._mode == "independent":
                pk = {"valid": v}
                if lin is not None:
                    pk["witness_ops"] = len(lin)
                if v is False and frontier is not None:
                    pk["final_ops"] = sorted(c.rows[p].g
                                             for p in frontier)
                per_key[key] = pk
                self.cell_results[key] = {"valid": v,
                                          "linearization": lin,
                                          "final_ops": frontier}
            if v is False:
                verdict = False
                if frontier is not None and invalid_frontier is None:
                    invalid_frontier = [c.rows[p].g for p in frontier]
                if self._mode != "independent":
                    break
                continue
            if v not in (True, False):
                has_unknown = True
                continue
            if lin is not None:
                cell_lins[key] = [c.rows[p].g for p in lin]
            elif self.witness:
                self._drop("witness", self._drops["witness"]
                           or "a cell produced no witness")

        extra: dict = {}
        if self._mode == "independent":
            # the streamed twin of independent.checker's merge: False
            # wins, unknown is not a failure; certificates live per key
            if verdict is True and has_unknown:
                verdict = "unknown"
            extra["independent"] = {str(k): per_key[k] for k in order}
            self._drop("witness", "independent-key stream: witnesses "
                       "are per key (see `independent`)")
            if verdict is False and invalid_frontier is not None:
                extra["final_ops"] = sorted(invalid_frontier)
            else:
                self._drop("frontier", "independent-key stream: "
                           "frontiers are per key (see `independent`)")
            return done(verdict, extra=extra)
        if verdict is True and self.witness \
                and len(cell_lins) == len(self._cells):
            g = merge_linearizations(self._seq,
                                     [cell_lins[k] for k in order])
            if g is not None:
                extra["linearization"] = g
                if len(self._cells) > 1:
                    self._stats["stitched"] = True
            else:
                self._drop("witness", "cell-witness stitch found no "
                           "interleaving (engine bug; see W005)")
        if verdict is False and invalid_frontier is not None:
            extra["final_ops"] = sorted(invalid_frontier)
        return done(verdict, extra=extra or None)

    # threadlint: ok — finalize path (post-_drain_folds join):
    # single-threaded over _stats/_cstats/_drops by construction
    def _check_final(self, c: _Cell, sub_check, canonical_payload,
                     _skey):
        """-> (verdict | "fallback", cell-pos witness | None,
        cell-pos frontier | None) for one cell's final segment."""
        final = c.final_rows
        if c.fallback:
            return "fallback", None, None
        if not final:
            if not c.states:
                return False, None, None
            if c.chains is not None:
                return True, c.chains[min(sorted(c.states))], None
            return True, None, None
        fseq = _rows_opseq(final, self._enc, value_lane=self._multi)
        self._methods.add("sub-search")
        fkey = None
        if self.cache is not None:
            payload, _ren = canonical_payload(fseq, self._cell_model,
                                              instates=c.states)
            fkey = _skey(payload, b"fin")
            e = self.cache.get(fkey)
            if e is not None and "v" in e:
                self._cstats["hits"] += 1
                self._methods.add("cache")
                self._drop("witness", "final-segment verdict-cache hit")
                self._drop("frontier", "final-segment verdict-cache hit")
                return e["v"], None, None
            self._cstats["misses"] += 1
        v = False
        lin = frontier = None
        start = len(c.rows) - len(final)
        for s in sorted(c.states):
            r = sub_check(fseq,
                          _dc_replace(self._cell_model, init=tuple(s)),
                          max_configs=self.sub_max_configs)
            self._stats["configs_searched"] += int(r.get("configs", 0)
                                                   or 0)
            rv = r.get("valid")
            if rv is True:
                v = True
                flin = r.get("linearization")
                if c.chains is not None and flin is not None:
                    lin = c.chains[tuple(s)] + [start + j for j in flin]
                elif self.witness:
                    self._drop("witness", r.get(
                        "witness_dropped",
                        "final-segment sub-search produced no witness"))
                break
            if rv is not False:
                c.fallback = True
                return "fallback", None, None
            frontier = r.get("final_ops")
        if v is False and frontier is not None:
            frontier = [start + j for j in frontier]
        if self.cache is not None:
            self.cache.put_verdict(fkey, v)
            self._cstats["inserts"] += 1
        if v is False:
            self._drop("frontier", "final-segment sub-search produced "
                       "no frontier")
        return v, lin, (frontier if v is False else None)

    # threadlint: ok — finalize path (post-_drain_folds join):
    # single-threaded over _stats/_methods by construction
    def _cell_direct(self, c: _Cell):
        """Per-cell direct fallback (independent mode): one ordinary
        check of the cell's full recorded subhistory under the test
        model.  Row indices in the certificate are cell positions."""
        from ..checker.linear import DEFAULT_WITNESS_CAP, check_opseq_linear

        self._methods.add("direct")
        cseq = _rows_opseq(c.rows, self._enc, value_lane=False)
        r = check_opseq_linear(cseq, self._cell_model,
                               witness_cap=DEFAULT_WITNESS_CAP
                               if self.witness else 0, lint=False,
                               hb=self.hb, dpor=self.dpor)
        self._stats["configs_searched"] += int(r.get("configs", 0) or 0)
        v = r.get("valid", "unknown")
        return v, r.get("linearization"), \
            (r.get("final_ops") if v is False else None)

    # threadlint: ok — finalize path (post-_drain_folds join):
    # single-threaded over _stats/_cstats by construction
    def _finish_fallback(self, wkey):
        """One direct check of the whole recorded history — the
        streamed route hit a budget wall somewhere; the verdict must
        still be decided exactly as the post-hoc engine would."""
        from ..checker.linear import DEFAULT_WITNESS_CAP, check_opseq_linear

        self._methods.add("direct")
        r = check_opseq_linear(self._seq, self.model,
                               witness_cap=DEFAULT_WITNESS_CAP
                               if self.witness else 0, lint=False,
                               hb=self.hb, dpor=self.dpor)
        self._stats["configs_searched"] += int(r.get("configs", 0) or 0)
        if self.cache is not None and wkey is not None \
                and r.get("valid") in (True, False):
            self.cache.put_verdict(wkey, r["valid"])
            self._cstats["inserts"] += 1
        return r.get("valid", "unknown"), r

    def _default_sub_check(self):
        from ..checker.linear import DEFAULT_WITNESS_CAP, check_opseq_linear

        cap = DEFAULT_WITNESS_CAP if self.witness else 0

        def sub(sseq, smodel, *, max_configs):
            return check_opseq_linear(sseq, smodel,
                                      max_configs=max_configs,
                                      witness_cap=cap, lint=False,
                                      hb=self.hb, dpor=self.dpor)

        return sub

    def close(self) -> None:
        """Stop the fold worker without finalizing (abandoned stream)."""
        self._drain_folds()


class TotalFoldStream:
    """The total-queue (and set) fold route — streaming verdicts for
    the MODEL-LESS multiset families.

    The queue campaign families (``queue``, ``replicated-queue``)
    carry no ModelSpec: their post-hoc verdict is
    ``checker.basic.total_queue``'s multiset reduction, so until this
    class existed their cells could only ever grade
    ``detection.at="finalize"``.  This sink runs the constraint
    compiler's incremental edge form (:class:`analyze.constraints.
    MultisetFold`) per ingested event and flips the LIVE verdict the
    moment monotone evidence lands:

      * an :ok dequeue (or drained element) of a value no enqueue ever
        attempted — flagged at that event;
      * acked enqueues missing from every delivery once a drain has
        been observed at a point with no client op pending (the
        "drain-quiescent" cut — the lost-ack flip lands when the final
        drain returns short, mid-history, not at teardown).

    The mid-stream flip is *provisional* (a pathological suffix could
    re-attempt a value or deliver a missing one); :meth:`finalize`
    always recomputes the verdict with the post-hoc checker itself —
    ``total_queue`` for queues, ``set_checker`` for sets — so the
    final verdict is bit-identical to the post-hoc route by
    construction, and detection is only ever graded when finalize
    confirms.  Invalid finals carry a ``queue_evidence`` certificate
    (event rows) the independent audit re-justifies (W007).
    """

    def __init__(self, family: str = "total-queue", *,
                 live_path: str | None = None,
                 run_id: str | None = None):
        from ..analyze.constraints import MultisetFold

        self.family = family
        self.fold = MultisetFold(family)
        self.live_path = live_path
        self.run_id = run_id
        self._lock = threading.RLock()
        self._events = 0
        self._ops: list[Op] = []
        self._rows = 0
        self._invalid: dict | None = None
        self._invalid_event: int | None = None
        self._first_verdict_event: int | None = None
        self._finalized: dict | None = None
        self._live_last = (0, 0.0)
        self._live_lock = threading.Lock()

    def ingest(self, op: Op) -> None:
        with self._lock:
            if self._finalized is not None:
                raise RuntimeError("stream already finalized")
            i = self._events
            self._events += 1
            _M_INGESTED.inc()
            if not isinstance(op.process, int):
                return
            self._ops.append(op)
            if op.type != INVOKE:
                self._rows += 1
                if self._first_verdict_event is None:
                    self._first_verdict_event = i
            flip = self.fold.step(op, len(self._ops) - 1)
            if flip is not None and self._invalid is None:
                self._invalid = flip
                self._invalid_event = i
        self._maybe_write_live()

    def verdict(self) -> dict:
        with self._lock:
            if self._invalid is not None:
                status = "invalid"
            elif self._first_verdict_event is not None:
                status = "valid-so-far"
            else:
                status = "open"
            return {
                "status": status,
                "run": self.run_id,
                "family": self.family,
                "events": self._events,
                "rows": self._rows,
                "first_verdict_event": self._first_verdict_event,
                "invalid_event": self._invalid_event,
                "violation": dict(self._invalid)
                if self._invalid else None,
            }

    def _maybe_write_live(self, force: bool = False,
                          final: dict | None = None) -> None:
        if self.live_path is None:
            return
        with self._live_lock:
            ev, t = self._live_last
            now = time.monotonic()
            if not force and (self._events - ev < _LIVE_EVERY
                              or now - t < _LIVE_MIN_S):
                return
            self._live_last = (self._events, now)
            snap = self.verdict()
            if final is not None:
                snap["final"] = final
            tmp = self.live_path + ".tmp"
            try:
                os.makedirs(os.path.dirname(self.live_path) or ".",
                            exist_ok=True)
                with open(tmp, "w") as f:
                    json.dump(snap, f, default=str)
                os.replace(tmp, self.live_path)
            except OSError:
                log.debug("stream: live snapshot write failed",
                          exc_info=True)

    def finalize(self, *, audit: bool | None = None) -> dict:
        """Close the stream: the POST-HOC checker's verdict over
        exactly the recorded client ops (bit-identical to the
        authoritative route), plus the streamed detection stats and —
        on invalid — the W007-auditable evidence certificate."""
        from ..analyze.audit import maybe_audit_events
        from ..analyze.constraints import (
            analyze_queue_events,
            analyze_set_events,
        )
        from ..checker import basic

        with self._lock:
            if self._finalized is not None:
                return self._finalized
            ops = list(self._ops)
            with obs.span("stream.finalize", cat="check",
                          run=self.run_id, family=self.family):
                if self.family == "set":
                    checker = basic.set_checker()
                    evidence = analyze_set_events(ops)
                else:
                    checker = basic.total_queue()
                    evidence = analyze_queue_events(ops)
                try:
                    post = checker.check({}, ops)
                except Exception as e:  # noqa: BLE001 — same contract
                    # as check_safe: a checker crash (e.g. a crashed
                    # drain the expansion rejects) is unknown, never
                    # a stream crash
                    post = {"valid": "unknown",
                            "error": f"{type(e).__name__}: {e}"}
            out = dict(post)
            out["engine"] = f"stream({self.family})"
            out["stream"] = {
                "family": self.family,
                "events": self._events,
                "rows": self._rows,
                "segments": 1,
                "routes": {self.family: 1},
                "first_verdict_event": self._first_verdict_event,
                "invalid_event": self._invalid_event
                if out.get("valid") is False else None,
                "edges": evidence.get("edges"),
            }
            if out.get("valid") is False:
                # the RECOMPUTED full-history evidence, not the
                # provisional flip's: a mid-stream flip may have named
                # values a later drain delivered, and the certificate
                # must justify the FINAL verdict (W007 audits it)
                ev = evidence.get("evidence") or self._invalid
                if ev is not None:
                    out["queue_evidence"] = dict(ev)
            out = maybe_audit_events(ops, out, audit)
            self._finalized = out
        self._maybe_write_live(force=True, final={
            "valid": out.get("valid"), "engine": out.get("engine")})
        return out

    def close(self) -> None:
        """Nothing to stop (no fold worker); kept for sink parity."""
