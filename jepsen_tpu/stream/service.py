"""The long-running checking service — many runs, one warm cache.

``python -m jepsen_tpu.stream`` turns the incremental checker into a
service that ingests history JSONL from many concurrent test runs (over
stdin or a TCP socket) and answers with live verdicts.  All runs share
one :class:`~jepsen_tpu.decompose.cache.VerdictCache`, so a segment any
fleet member has ever folded is never re-searched — the sustained-
traffic architecture the ROADMAP names: pay only for novel segments.

Line protocol (one JSON object per line, newline-delimited):

  in   {"run": ID, "model": NAME, "init": N, "width": W}   open a run
  in   {"run": ID, "op": {process, type, f, value}}        one event
  in   {"process": .., "type": .., ...}                    single-run
                                                           shorthand
  in   {"run": ID, "end": true}                            finalize
  in   {"drain": true}                 graceful drain: finalize every
                                       open run, admit no new ones
  out  {"run": ID, "live": {...}}      status changed (open ->
                                       valid-so-far -> invalid)
  out  {"run": ID, "final": {...}}     the final verdict + stream stats
  out  {"run": ID, "error": "..."}     a malformed line / unknown run
  out  {"run": ID, "overloaded": ...}  backpressure: the op was SHED
                                       (per-run op budget exhausted, or
                                       the connection's bounded ingest
                                       queue is full)

Backpressure: thousands of concurrent connections must degrade
predictably, not by OOM or unbounded latency.  Two independent guards:

  * **per-run op budget** (``op_budget``): past the budget, further ops
    for that run are shed with an ``overloaded`` reply; the run still
    finalizes normally and its final summary reports ``shed`` — the
    verdict is for exactly the admitted prefix.
  * **bounded ingest queue** (``ingest_max`` in :func:`serve_lines`):
    each connection's reader never blocks on checking — lines queue up
    to the bound, and when the checker can't keep up the line is shed
    with an ``overloaded`` reply instead of stalling the socket (or
    buffering without limit).

Graceful drain (the fleet router's rolling-restart primitive): the
protocol ``{"drain": true}`` line — or ``SIGTERM`` in ``--listen``
mode (see __main__.py / :func:`drain_server`) — finalizes every open
run (finals carry ``finalized_by: "drain"``), then refuses new run
admissions with an ``{"overloaded": "draining"}`` reply; the process
exits 0 once drained.  Nothing admitted is ever discarded: every open
run yields its prefix verdict on the way out, exactly the
disconnect/EOF salvage contract.

Model names are the shard scheduler's descriptors
(``decompose.schedule.model_from_descriptor``): register,
cas-register, mutex, multi-register (width), unordered-queue-N,
fifo-queue-N.
"""

from __future__ import annotations

import json
import logging
import os
import re
import socketserver
import threading
import time

from .. import obs
from ..history import Op
from ..obs import metrics as obs_metrics

log = logging.getLogger("jepsen")

#: flight-recorder handles: backpressure sheds by reason, and how many
#: runs this process currently multiplexes (the fleet-health gauge the
#: /metrics scrape and /api/stats snapshot expose)
_M_SHED = obs_metrics.REGISTRY.counter(
    "jtpu_shed_total", "Ops/lines shed under backpressure, by reason",
    ("reason",))
_M_RUNS_OPEN = obs_metrics.REGISTRY.gauge(
    "jtpu_stream_runs_open",
    "Streaming runs currently open in this process")

#: default run id for the single-run (bare-op) shorthand
DEFAULT_RUN = "default"


def _safe_run_id(run_id: str) -> str:
    return re.sub(r"[^A-Za-z0-9._-]", "_", str(run_id))[:120]


def result_summary(result: dict, *, max_frontier: int = 16) -> dict:
    """The JSON-line form of a final result: verdict, engine, stream
    stats, and a bounded certificate summary (a 10k-op linearization
    does not belong on a protocol line)."""
    out = {"valid": result.get("valid"),
           "engine": result.get("engine"),
           "configs": result.get("configs"),
           "stream": result.get("stream")}
    lin = result.get("linearization")
    if lin is not None:
        out["witness_ops"] = len(lin)
    elif result.get("witness_dropped"):
        out["witness_dropped"] = result["witness_dropped"]
    fr = result.get("final_ops")
    if fr is not None:
        out["final_ops"] = list(fr[:max_frontier])
        out["frontier_ops"] = len(fr)
    elif result.get("frontier_dropped"):
        out["frontier_dropped"] = result["frontier_dropped"]
    if result.get("audit") is not None:
        out["audit"] = result["audit"]
    return out


class StreamService:
    """Multiplexes JSONL lines onto per-run :class:`StreamChecker`\\ s.

    One instance per connection namespace; the verdict cache (and its
    lock-free append-only jsonl) is shared across every instance the
    process creates — that is the fleet-reuse story."""

    def __init__(self, *, model=None, cache=None, witness: bool = True,
                 audit: bool | None = None,
                 host_fold_max: int | None = None,
                 info_lookahead: int | None = None,
                 op_budget: int | None = None,
                 persist_dir: str | None = None,
                 idle_timeout: float | None = None,
                 conn: str | None = None,
                 drain_parent=None):
        self.default_model = model
        #: anything with a truthy ``.draining`` attribute (the TCP
        #: server in --listen mode): a process-level drain covers
        #: every connection's service without touching each one
        self._drain_parent = drain_parent
        self._draining = False
        #: connection label for log attribution (TCP peer address);
        #: every service log line carries run_id=/conn= via obs.log_ctx
        #: so a multiplexed-run failure names its run and socket
        self.conn = conn
        self.cache = cache
        self.witness = witness
        self.audit = audit
        self.host_fold_max = host_fold_max
        self.info_lookahead = info_lookahead
        #: per-run admitted-op ceiling; None = unlimited
        self.op_budget = op_budget
        #: when set, each run keeps a live snapshot at
        #: persist_dir/<run>.json — finalize (normal, reaped, or the
        #: dropped-connection salvage) lands the final verdict there,
        #: so a verdict survives even a client that vanished
        self.persist_dir = persist_dir
        #: seconds of per-run silence before the reaper finalizes it
        #: (None = never): a client that opened a run and went away
        #: must not leak an open checker forever
        self.idle_timeout = idle_timeout
        self._runs: dict = {}
        self._status: dict = {}
        self._ops: dict = {}   # run -> admitted ops
        self._shed: dict = {}  # run -> ops shed past the budget
        self._last: dict = {}  # run -> monotonic last-activity
        self._lock = threading.RLock()  # handler vs reaper thread

    def _log(self, run_id: str | None = None) -> logging.LoggerAdapter:
        """The context-stamped logger for one run's lines."""
        return obs.log_ctx(log, run_id=run_id, conn=self.conn)

    @property
    def draining(self) -> bool:
        """New-run admission is closed — this namespace drained, or
        the owning server is draining process-wide."""
        return self._draining or bool(
            getattr(self._drain_parent, "draining", False))

    def drain(self, emit, *, reason: str = "drain") -> None:
        """Graceful drain: finalize every open run (finals labelled
        ``finalized_by: reason``) and stop admitting new ones.  The
        rolling-restart primitive — a drained worker owes nobody a
        verdict and can exit 0."""
        with self._lock:
            self._draining = True
        self.end_all(emit, reason=reason)

    def open_run(self, run_id: str, model) -> None:
        from .checker import StreamChecker

        if run_id not in self._runs:
            # re-opening an existing run replaces its checker below;
            # the open-runs gauge must count runs, not header lines
            _M_RUNS_OPEN.inc()
        live = None
        if self.persist_dir:
            live = os.path.join(self.persist_dir,
                                f"{_safe_run_id(run_id)}.json")
        self._runs[run_id] = StreamChecker(
            model, cache=self.cache, witness=self.witness,
            host_fold_max=self.host_fold_max,
            info_lookahead=self.info_lookahead, run_id=run_id,
            live_path=live)
        self._status[run_id] = "open"
        self._ops[run_id] = 0
        self._shed[run_id] = 0
        self._last[run_id] = time.monotonic()

    def _model_from(self, d: dict):
        from ..decompose.schedule import model_from_descriptor

        name = d["model"]
        init = int(d.get("init", 0))
        width = int(d.get("width", 1))
        return model_from_descriptor((name, (init,), width))

    def handle_line(self, line: str, emit) -> None:
        """Process one protocol line; ``emit(dict)`` writes a reply."""
        line = line.strip()
        if not line:
            return
        try:
            d = json.loads(line)
        except ValueError:
            emit({"run": None, "error": "malformed JSON line"})
            return
        if not isinstance(d, dict):
            emit({"run": None, "error": "expected a JSON object"})
            return
        with self._lock:
            self._handle(d, emit)

    def _handle(self, d: dict, emit) -> None:
        if d.get("drain") and "run" not in d and "op" not in d:
            self.drain(emit)
            return
        run_id = d.get("run", DEFAULT_RUN)
        self._last[run_id] = time.monotonic()
        try:
            if "model" in d:
                if self.draining:
                    _M_SHED.inc(reason="draining")
                    emit({"run": run_id, "overloaded": "draining"})
                    return
                self.open_run(run_id, self._model_from(d))
                return
            if d.get("end"):
                self.end_run(run_id, emit)
                return
            op = d.get("op")
            if op is None and "type" in d:
                op = d  # bare-op shorthand
            if op is None:
                emit({"run": run_id,
                      "error": "line carries neither model/op/end"})
                return
            chk = self._runs.get(run_id)
            if chk is None:
                if self.draining:
                    # a drained namespace admits nothing new — not even
                    # the bare-op shorthand's implicit open
                    _M_SHED.inc(reason="draining")
                    emit({"run": run_id, "overloaded": "draining"})
                    return
                if self.default_model is None:
                    emit({"run": run_id,
                          "error": f"unknown run {run_id!r} and no "
                                   f"default --model"})
                    return
                self.open_run(run_id, self.default_model)
                chk = self._runs[run_id]
            if self.op_budget is not None \
                    and self._ops.get(run_id, 0) >= self.op_budget:
                # shed, don't stall: the run keeps its verdict for the
                # admitted prefix; the client learns explicitly that
                # this op was dropped (first shed + every 1000th after,
                # so a hot run can't flood the reply stream either)
                shed = self._shed.get(run_id, 0) + 1
                self._shed[run_id] = shed
                _M_SHED.inc(reason="op-budget")
                if shed == 1 or shed % 1000 == 0:
                    emit({"run": run_id, "overloaded": "op-budget",
                          "budget": self.op_budget, "shed": shed})
                return
            self._ops[run_id] = self._ops.get(run_id, 0) + 1
            chk.ingest(Op.from_dict(op))
            v = chk.verdict()
            if v["status"] != self._status.get(run_id):
                self._status[run_id] = v["status"]
                emit({"run": run_id, "live": v})
        except Exception as e:  # noqa: BLE001 — one line, not the service
            self._log(run_id).warning("stream service: line failed: %s",
                                      e)
            emit({"run": run_id, "error": f"{type(e).__name__}: {e}"})

    def end_run(self, run_id: str, emit, *,
                reason: str | None = None,
                only_if_idle_for: float | None = None) -> None:
        with self._lock:
            if only_if_idle_for is not None:
                # the reaper decided on a stale snapshot; re-check
                # idleness under the SAME lock as the pop, so a run
                # whose client just resumed is never truncated
                t = self._last.get(run_id)
                if t is None or run_id not in self._runs \
                        or time.monotonic() - t <= only_if_idle_for:
                    return
            chk = self._runs.pop(run_id, None)
            if chk is not None:
                _M_RUNS_OPEN.dec()
            self._status.pop(run_id, None)
            self._ops.pop(run_id, None)
            self._last.pop(run_id, None)
            shed = self._shed.pop(run_id, 0)
        if chk is None:
            emit({"run": run_id, "error": f"unknown run {run_id!r}"})
            return
        result = chk.finalize(audit=self.audit)
        # with tracing on, every fold/fork span landed in this run's
        # ring buffer; the run is over, so the buffer must go — a
        # service multiplexing thousands of runs cannot keep one per
        # run id forever
        obs.drop_recorder(run_id)
        summary = result_summary(result)
        if shed:
            summary["shed"] = shed
        if reason:
            summary["finalized_by"] = reason
        emit({"run": run_id, "final": summary})

    def end_all(self, emit, *, reason: str | None = None) -> None:
        """EOF / disconnect: every still-open run yields its verdict for
        the prefix it recorded — nothing ingested is ever discarded."""
        for run_id in list(self._runs):
            self.end_run(run_id, emit, reason=reason)

    def abandon(self) -> None:
        """The connection died without finalizing (TCP reset, broken
        pipe): finalize every open run with NOBODY listening — the
        prefix verdict still lands in the verdict cache and, with
        ``persist_dir``, on disk — instead of leaking the run open."""
        self.end_all(lambda d: None, reason="connection-dropped")

    def reap_idle(self, emit, *, now: float | None = None) -> list:
        """Finalize runs silent for longer than ``idle_timeout``;
        returns the reaped run ids.  The idle-run reaper knob: a
        service holding thousands of concurrent runs must not let a
        vanished client pin a checker (and its memory) forever."""
        if self.idle_timeout is None:
            return []
        now = time.monotonic() if now is None else now
        with self._lock:
            stale = [r for r, t in self._last.items()
                     if r in self._runs and now - t > self.idle_timeout]
            for r in [r for r in self._last if r not in self._runs]:
                del self._last[r]
        reaped = []
        for run_id in stale:
            before = run_id in self._runs
            self.end_run(run_id, emit, reason="idle-reaper",
                         only_if_idle_for=self.idle_timeout)
            if before and run_id not in self._runs:
                self._log(run_id).info("stream service: reaped idle run")
                reaped.append(run_id)
        return reaped


def serve_lines(service: StreamService, lines, emit, *,
                ingest_max: int = 0) -> int:
    """Drain an iterable of protocol lines through the service; returns
    how many lines were shed.

    ``ingest_max=0`` processes inline (reader == checker: the socket
    itself is the backpressure).  ``ingest_max>0`` decouples them: the
    reader feeds a bounded queue a worker thread drains, and when the
    checker falls behind by more than the bound, the line is SHED with
    an explicit ``overloaded`` reply — bounded memory and a socket that
    never stalls, the degradation mode thousands of connections need.

    Every exit finalizes every open run: the normal EOF path emits the
    finals; an error path (reader died, client hung up mid-history)
    salvages them silently (:meth:`StreamService.abandon`) so the
    prefix verdict still lands in the cache/persist-dir instead of
    leaking the run open.  When the service carries an
    ``idle_timeout``, a reaper thread finalizes silent runs while the
    connection idles."""
    reaper_stop = None
    if service.idle_timeout is not None:
        reaper_stop = threading.Event()

        def _reap_loop() -> None:
            tick = max(0.05, min(1.0, service.idle_timeout / 4.0))
            while not reaper_stop.wait(tick):
                try:
                    service.reap_idle(emit)
                except Exception:  # noqa: BLE001 — reaper best-effort
                    log.debug("stream service: reaper failed",
                              exc_info=True)

        threading.Thread(target=_reap_loop, name="stream-reaper",
                         daemon=True).start()
    try:
        return _serve_lines(service, lines, emit,
                            ingest_max=ingest_max)
    except BaseException:
        # the connection died mid-history without finalizing: salvage
        # a prefix verdict for every open run, then surface the error
        service.abandon()
        raise
    finally:
        if reaper_stop is not None:
            reaper_stop.set()


def _serve_lines(service: StreamService, lines, emit, *,
                 ingest_max: int) -> int:
    if ingest_max <= 0:
        for line in lines:
            service.handle_line(line, emit)
        service.end_all(emit)
        return 0

    import queue as _queue

    q: _queue.Queue = _queue.Queue(maxsize=ingest_max)
    _EOF = object()
    broken: list = []  # the worker's fatal error, re-raised after join

    def worker() -> None:
        # a dead emit (client hung up) must not leave the reader
        # blocked on a full queue: keep draining, surface the error
        # after the join.  Lines already queued are still ADMITTED
        # (with nobody listening) — the client sent them before dying,
        # and the salvaged prefix verdict should cover them
        while True:
            item = q.get()
            if item is _EOF:
                return
            try:
                service.handle_line(
                    item, (lambda d: None) if broken else emit)
            except Exception as e:  # noqa: BLE001 — connection-fatal
                broken.append(e)

    t = threading.Thread(target=worker, name="stream-ingest",
                         daemon=True)
    t.start()
    shed = 0
    for line in lines:
        try:
            q.put_nowait(line)
        except _queue.Full:
            shed += 1
            _M_SHED.inc(reason="ingest-queue")
            if shed == 1 or shed % 1000 == 0:
                try:
                    emit({"run": None, "overloaded": "ingest-queue",
                          "queue": ingest_max, "shed": shed})
                except Exception as e:  # noqa: BLE001 — same contract
                    broken.append(e)
                    break
    q.put(_EOF)  # blocking put: drains behind whatever is queued
    t.join()
    if broken:
        raise broken[0]
    service.end_all(emit)
    return shed


def serve_stdio(service: StreamService, stdin, stdout, *,
                ingest_max: int = 0) -> None:
    """The stdin/stdout loop (one writer thread: replies are lines)."""
    lock = threading.Lock()

    def emit(d: dict) -> None:
        with lock:
            stdout.write(json.dumps(d, separators=(",", ":")) + "\n")
            stdout.flush()

    serve_lines(service, stdin, emit, ingest_max=ingest_max)


#: HTTP request lines the JSONL port also answers — a Prometheus
#: scraper (or curl) pointed at the service port gets its metrics
#: without a second listener to deploy
_SCRAPE_RE = re.compile(rb"^(GET|HEAD)\s+(/metrics|/api/stats)\b")


def _http_scrape(wfile, target: str) -> None:
    """One-shot HTTP/1.0 response on the protocol socket: the process
    registry as Prometheus text (``/metrics``) or the JSON snapshot
    (``/api/stats``)."""
    if target == "/metrics":
        body = obs_metrics.render().encode()
        ctype = "text/plain; version=0.0.4; charset=utf-8"
    else:
        body = json.dumps(obs_metrics.snapshot()).encode()
        ctype = "application/json"
    wfile.write(b"HTTP/1.0 200 OK\r\n"
                + f"Content-Type: {ctype}\r\n"
                  f"Content-Length: {len(body)}\r\n\r\n".encode()
                + body)


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        # each connection is its own run namespace (two fleets may both
        # call their run "r1"); the verdict cache is the shared part
        srv: _TCPServer = self.server
        conn = "%s:%s" % self.client_address[:2]
        clog = obs.log_ctx(log, conn=conn)
        try:
            first = self.rfile.readline()
        except (BrokenPipeError, ConnectionResetError, OSError):
            # a probe that connected and reset without a byte is not
            # worth a traceback (load balancers do this all day)
            clog.debug("stream service: connection reset before any "
                       "input")
            return
        m = _SCRAPE_RE.match(first)
        if m:
            # a metrics scrape, not a run: drain the request headers
            # (closing with unread bytes makes the kernel RST and can
            # truncate the reply mid-scrape), answer HTTP, hang up
            try:
                while True:
                    ln = self.rfile.readline()
                    if not ln or ln in (b"\r\n", b"\n"):
                        break
                _http_scrape(self.wfile, m.group(2).decode())
            except (BrokenPipeError, ConnectionResetError):
                pass
            return
        service = StreamService(model=srv.default_model,
                                cache=srv.cache, witness=srv.witness,
                                audit=srv.audit,
                                host_fold_max=srv.host_fold_max,
                                info_lookahead=srv.info_lookahead,
                                op_budget=srv.op_budget,
                                persist_dir=srv.persist_dir,
                                idle_timeout=srv.idle_timeout,
                                conn=conn, drain_parent=srv)
        lock = threading.Lock()

        def emit(d: dict) -> None:
            with lock:
                self.wfile.write(
                    (json.dumps(d, separators=(",", ":")) + "\n")
                    .encode())

        # registered so a process-level drain (SIGTERM ->
        # drain_server) can finalize THIS connection's open runs and
        # answer on its socket
        service._drain_emit = emit
        srv.services.add(service)

        import itertools

        lines = (raw.decode("utf-8", "replace")
                 for raw in itertools.chain([first] if first else [],
                                            self.rfile))
        try:
            serve_lines(service, lines, emit,
                        ingest_max=srv.ingest_max)
        except (BrokenPipeError, ConnectionResetError):
            # serve_lines already salvaged every open run's prefix
            # verdict (StreamService.abandon) before re-raising
            clog.debug("stream service: client dropped the connection")
        except OSError:
            # NOT a client hangup (disk trouble under --persist-dir,
            # socket weirdness): salvage still ran, but say so loudly
            clog.warning("stream service: connection failed",
                         exc_info=True)
        finally:
            srv.services.discard(service)
            service.abandon()  # no-op when end_all already ran


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True
    #: process-level drain flag every connection's StreamService reads
    #: (via drain_parent); flipped by drain_server
    draining = False


def drain_server(srv: "_TCPServer") -> int:
    """Gracefully drain a ``--listen`` server: stop admitting new runs
    on every connection (and every future one), finalize every open
    run with its final emitted on its own connection, then shut the
    server down.  Returns how many runs were finalized.  The SIGTERM
    handler (__main__.py) and the fleet router's rolling worker
    restarts call this; after it returns the process can exit 0."""
    srv.draining = True
    drained = 0
    for service in list(srv.services):
        emit = getattr(service, "_drain_emit", None) or (lambda d: None)
        before = len(service._runs)

        def safe_emit(d, _emit=emit):
            try:
                _emit(d)
            except Exception:  # noqa: BLE001 — client already gone
                pass

        try:
            service.drain(safe_emit)
        except Exception:  # noqa: BLE001 — drain is best-effort per conn
            log.warning("stream service: drain of one connection "
                        "failed", exc_info=True)
        drained += before - len(service._runs)
    srv.shutdown()
    return drained


def make_server(host: str, port: int, *, model=None, cache=None,
                witness: bool = True, audit: bool | None = None,
                host_fold_max: int | None = None,
                info_lookahead: int | None = None,
                op_budget: int | None = None,
                ingest_max: int = 0,
                persist_dir: str | None = None,
                idle_timeout: float | None = None) -> _TCPServer:
    srv = _TCPServer((host, port), _Handler)
    srv.draining = False
    srv.services = set()
    srv.default_model = model
    srv.cache = cache
    srv.witness = witness
    srv.audit = audit
    srv.host_fold_max = host_fold_max
    srv.info_lookahead = info_lookahead
    srv.op_budget = op_budget
    srv.ingest_max = ingest_max
    srv.persist_dir = persist_dir
    srv.idle_timeout = idle_timeout
    return srv
