"""Device-dispatched segment folds — bucket.py applied to the stream.

A mid-stream quiescence segment must be folded to its *reachable
final-state set* so the next segment can compose; ``segment_states`` is
an exact host sweep, but a wide segment makes it the one stage that
could stall ingest.  For the single-value register family the fold
reduces to ordinary linearizability checks the batched device engine
already runs well:

  * a **prepended pseudo-write** of candidate input state ``s_in``
    (interval ``[-2, -1]``: it returns before every real op invokes, so
    any linearization is forced to run it first — equivalent to
    starting the model in ``s_in``);
  * an **appended pseudo-read** of candidate output state ``s_out``
    (invoking after every real op returns: forced last, legal iff the
    register ends holding ``s_out``).

``(s_in, s_out)`` is feasible iff that decorated segment linearizes, so
the whole fold becomes one ``search_batch`` over the candidate pairs —
uniformly shaped variants of one segment, exactly what the
shape-bucketed scheduler (checker/bucket.py) pads tightest.  Candidate
outputs are the segment's state-changing values (every row is :ok in a
crash-free segment, so every write/successful cas linearizes and the
final state is the last one's value).

Returns None when the trick does not apply (no state-changing op — the
host fold is trivially cheap there anyway — or a candidate-pair blowup
past ``max_variants``, or any variant undecided under ``budget``); the
caller then folds on host.  Routing between the two lives in
``analyze.plan.segment_fold_route`` so the plan explainer and the
stream engine cannot drift.
"""

from __future__ import annotations

import numpy as np

from ..history import NIL, OpSeq
from ..models import R_CAS, R_READ, R_WRITE, ModelSpec

#: candidate (s_in, s_out) pairs above which the fold falls back to the
#: host sweep — each pair is one device-batched search
MAX_VARIANTS = 512


def _decorate(sseq: OpSeq, s_in: int, s_out: int) -> OpSeq:
    """The segment with the state-pinning pseudo-ops attached."""
    n = len(sseq)
    lo = int(np.min(sseq.inv)) if n else 0
    hi = int(np.max(sseq.ret)) if n else 0
    return OpSeq(
        process=np.concatenate([[np.int32(-1)], sseq.process,
                                [np.int32(-2)]]).astype(np.int32),
        f=np.concatenate([[R_WRITE], sseq.f, [R_READ]]).astype(np.int32),
        v1=np.concatenate([[s_in], sseq.v1, [s_out]]).astype(np.int32),
        v2=np.concatenate([[NIL], sseq.v2, [NIL]]).astype(np.int32),
        inv=np.concatenate([[lo - 2], sseq.inv, [hi + 1]]).astype(np.int64),
        ret=np.concatenate([[lo - 1], sseq.ret, [hi + 2]]).astype(np.int64),
        ok=np.concatenate([[True], sseq.ok, [True]]).astype(bool),
    )


def device_fold_states(sseq: OpSeq, model: ModelSpec, in_states, *,
                       budget: int = 2_000_000):
    """Reachable output states of a crash-free register-family segment,
    via the batched (bucketed) device engine.

    Returns ``(states, configs)`` — the exact set ``segment_states``
    would compute, plus the configs the searches billed — or ``None``
    when ineligible/undecided (the caller folds on host)."""
    if model.name not in ("register", "cas-register"):
        return None
    n = len(sseq)
    if n == 0 or not bool(np.asarray(sseq.ok).all()):
        return None
    f = np.asarray(sseq.f)
    changers = set()
    for i in range(n):
        fc = int(f[i])
        if fc == R_WRITE:
            changers.add(int(sseq.v1[i]))
        elif fc == R_CAS:
            changers.add(int(sseq.v2[i]))
        elif fc != R_READ:
            return None  # foreign op code: not this model family
    if not changers:
        # all-reads segment: the state never moves and the host fold is
        # linear — no device win to be had
        return None
    ins = sorted({int(s[0]) for s in in_states})
    outs = sorted(changers)
    pairs = [(a, b) for a in ins for b in outs]
    if not pairs or len(pairs) > MAX_VARIANTS:
        return None
    from ..checker.linearizable import search_batch

    variants = [_decorate(sseq, a, b) for a, b in pairs]
    results = search_batch(variants, model, budget=budget, lint=False)
    configs = sum(int(r.get("configs", 0) or 0) for r in results)
    states = set()
    for (_a, b), r in zip(pairs, results):
        v = r.get("valid")
        if v is True:
            states.add((b,))
        elif v is not False:
            return None  # undecided variant: the fold must stay exact
    return states, configs
