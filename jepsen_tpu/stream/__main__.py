"""``python -m jepsen_tpu.stream`` — the checking service's front door.

stdin mode (default) reads history JSONL from stdin and writes verdict
lines to stdout; ``--listen HOST:PORT`` serves the same line protocol
over TCP, one connection per run namespace.  See stream/service.py for
the protocol and docs/stream.md for the walkthrough.
"""

from __future__ import annotations

import argparse
import logging
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m jepsen_tpu.stream",
        description="Streaming incremental checking service: ingest "
                    "history JSONL from concurrent runs, answer with "
                    "live verdicts.")
    p.add_argument("--model", default=None,
                   help="Default model for runs that send no header "
                        "(register, cas-register, mutex, "
                        "multi-register, unordered-queue-N, "
                        "fifo-queue-N).")
    p.add_argument("--init", type=int, default=0,
                   help="Default model's initial value.")
    p.add_argument("--width", type=int, default=1,
                   help="Default model's state width (multi-register).")
    p.add_argument("--cache", metavar="PATH", default=None,
                   help="Shared verdict-cache jsonl; 'store' selects "
                        "the store-persisted default path.  Omit for "
                        "an in-memory per-process cache.")
    p.add_argument("--no-cache", action="store_true",
                   help="Disable the verdict cache entirely.")
    p.add_argument("--no-witness", action="store_true",
                   help="Skip witness chains (verdicts only; faster).")
    p.add_argument("--audit", action="store_true",
                   help="Replay every final certificate through the "
                        "independent audit (analyze/audit.py).")
    p.add_argument("--host-fold-max", type=int, default=None,
                   help="Override the plan gate's host-fold cost cap "
                        "(analyze.plan.STREAM_HOST_FOLD_MAX).")
    p.add_argument("--listen", metavar="HOST:PORT", default=None,
                   help="Serve the line protocol over TCP instead of "
                        "stdin/stdout.")
    p.add_argument("--op-budget", type=int, default=None, metavar="N",
                   help="Per-run admitted-op ceiling: past it, ops are "
                        "shed with an 'overloaded' reply and the run "
                        "finalizes on the admitted prefix.")
    p.add_argument("--ingest-queue", type=int, default=0, metavar="N",
                   help="Bounded per-connection ingest queue (0 = "
                        "process inline): when the checker falls this "
                        "many lines behind, further lines are shed "
                        "with an 'overloaded' reply instead of "
                        "stalling the socket.")
    p.add_argument("--info-lookahead", type=int, default=None,
                   metavar="N",
                   help="Bounded :info lookahead horizon: after N "
                        "post-crash ok ops at a pseudo-quiescent "
                        "point, speculatively fork-check the crashed "
                        "segment so kill-seeded violations flip the "
                        "live verdict mid-stream (default: "
                        "analyze.plan.STREAM_INFO_LOOKAHEAD; 0 "
                        "disables — finalize-only).")
    p.add_argument("--persist-dir", metavar="DIR", default=None,
                   help="Persist each run's live snapshot and final "
                        "verdict to DIR/<run>.json — a run whose "
                        "connection drops mid-history still leaves "
                        "its prefix verdict on disk.")
    p.add_argument("--idle-timeout", type=float, default=None,
                   metavar="S",
                   help="Reap (finalize) runs silent for S seconds: a "
                        "vanished client can't pin an open checker "
                        "forever.  Default: never.")
    p.add_argument("--fleet-cache", metavar="DIR", default=None,
                   help="Use the multi-writer fleet cache tier rooted "
                        "at DIR (fleet/cachestore.py: per-worker "
                        "write-ahead segments + merge-compaction) "
                        "instead of the single jsonl --cache.")
    p.add_argument("--worker-id", default=None,
                   help="Stable worker id for --fleet-cache segment "
                        "naming (default: w<pid>).")
    p.add_argument("--warmup", metavar="MANIFEST", default=None,
                   help="Warm-boot the steady-state kernels before "
                        "serving (fleet/warmup.py): MANIFEST is a "
                        "shape-manifest JSON or a recorded "
                        "BENCH_trace_*.json; prints a 'stream service "
                        "warmup:' line to stderr the fleet admission "
                        "gate parses.")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.WARNING)

    from ..decompose.cache import VerdictCache, default_cache_path
    from ..decompose.schedule import model_from_descriptor
    from .service import StreamService, make_server, serve_stdio

    model = None
    if args.model:
        model = model_from_descriptor(
            (args.model, (args.init,), args.width))
    cache = None
    if args.fleet_cache and not args.no_cache:
        from ..fleet.cachestore import FleetCacheStore

        cache = FleetCacheStore(args.fleet_cache,
                                worker_id=args.worker_id)
    elif not args.no_cache:
        path = args.cache
        if path == "store":
            path = default_cache_path()
        cache = VerdictCache(path)

    if args.warmup:
        # ahead-of-time kernel warmup BEFORE the listen line prints:
        # the fleet admission gate must not route traffic at a worker
        # still paying the 1.4-2.4s-per-kernel cold-start tax
        from ..fleet.warmup import load_shapes, warm_boot

        report = warm_boot(load_shapes(args.warmup))
        print("stream service warmup: shapes=%d compiled=%d "
              "verified=%s persistent_cache=%s wall_s=%.3f"
              % (report["shapes"], report["compiled"],
                 str(report["verified"]).lower(),
                 str(report["persistent_cache"]).lower(),
                 report["wall_s"]),
              file=sys.stderr, flush=True)

    if args.listen:
        import signal
        import threading

        from .service import drain_server

        host, _, port = args.listen.rpartition(":")
        srv = make_server(host or "127.0.0.1", int(port), model=model,
                          cache=cache,
                          witness=not args.no_witness,
                          audit=True if args.audit else None,
                          host_fold_max=args.host_fold_max,
                          info_lookahead=args.info_lookahead,
                          op_budget=args.op_budget,
                          ingest_max=args.ingest_queue,
                          persist_dir=args.persist_dir,
                          idle_timeout=args.idle_timeout)

        def _sigterm(_signo, _frame):
            # graceful drain: finalize every open run (finals still
            # answered on their own connections), refuse new ones,
            # then stop serve_forever — the process exits 0.  Run off
            # the signal frame: drain_server joins handler work and
            # shutdown() must not be called from the main loop's own
            # interrupt context.
            threading.Thread(target=drain_server, args=(srv,),
                             name="stream-drain", daemon=True).start()

        try:
            signal.signal(signal.SIGTERM, _sigterm)
        except ValueError:
            pass  # not the main thread (embedded use)
        print(f"stream service listening on "
              f"{srv.server_address[0]}:{srv.server_address[1]}",
              file=sys.stderr, flush=True)
        try:
            srv.serve_forever()
        except KeyboardInterrupt:
            srv.shutdown()
        if cache is not None:
            cache.close()
        return 0

    service = StreamService(model=model, cache=cache,
                            witness=not args.no_witness,
                            audit=True if args.audit else None,
                            host_fold_max=args.host_fold_max,
                            info_lookahead=args.info_lookahead,
                            op_budget=args.op_budget,
                            persist_dir=args.persist_dir,
                            idle_timeout=args.idle_timeout)
    serve_stdio(service, sys.stdin, sys.stdout,
                ingest_max=args.ingest_queue)
    return 0


if __name__ == "__main__":
    sys.exit(main())
