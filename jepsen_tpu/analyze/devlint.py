"""Device-contract lint — K-codes over staged kernel jaxprs.

The bench contract (PR 10 onward, executable since PR 15) catches
recompiles, mid-search host transfers, and cache-key drift *after* a
bench run regresses.  This module is the static counterpart: it
abstractly stages every kernel route the checker can dispatch —
single-device XLA, bucketed batch, mesh-sharded, pallas fused,
enumerated from :data:`jepsen_tpu.checker.linearizable.KERNEL_ROUTES`
— over representative :class:`SearchDims`, then walks the resulting
jaxprs for the device-contract violations the runtime gates would only
see as a regressed number.  ``jax.make_jaxpr`` traces without
compiling, so the whole sweep is a few seconds on CPU and runs in
tier-1 (tests/test_devlint.py) and as a ``bench.py --trace``
preflight.

K-code reference (docs/analyze.md has the prose version):

  K001  host callback primitive (pure_callback / io_callback) staged
        inside the level loop — every BFS level would sync to host
  K002  float64 / 64-bit dtype, or any float in an int-only route —
        dtype widening doubles device bytes and splits the cache key
  K003  weak-type input aval: a python scalar leaked into the traced
        operands, so numerically identical calls re-trace and split
        the kernel cache key
  K004  carry-donation policy break: the route's cache getter's
        ``jax.jit`` donates buffers the slice driver still needs (or a
        donate_carry=True route whose jit never donates)
  K005  dynamic-shape primitive — staging raised a concretization /
        data-dependent-shape error, so the kernel cannot stage at all
  K006  effectful host round-trip (debug prints, ordered callbacks)
        inside the scan body — a device→host transfer per level
  K007  compile-span cache-key coords missing or drifted versus the
        static model below — ``fleet/warmup.py`` warm-boot and the
        committed ``BENCH_trace_*.json`` recordings round-trip kernels
        through exactly these coords, so drift means silent zero-miss
        -verify failures

Suppression: the staged checks (K001/K002/K003/K006) attribute
findings to source lines via the jaxpr's ``source_info``; a
``devlint: ok`` comment on the flagged line suppresses it, same
contract as ``suite-lint: ok`` / ``threadlint: ok``.  K004 is
AST-level and honours the comment on the ``jax.jit`` call line.
Suppressions are for *documented* false positives only.

Wired into: ``python -m jepsen_tpu.analyze --devlint`` (CLI),
``tools/lint_suites.py --json`` (suite sweep), ``tools/obs_guard.py``
(K007 over committed trace compile spans), and ``bench.py --trace``
preflight.
"""

from __future__ import annotations

import ast
import importlib
import linecache
from typing import Any, Iterable

from .lint import Diagnostic

DEVLINT_CODES = {
    "K001": "host callback primitive inside the level loop",
    "K002": "float64/dtype-widening leak in kernel dataflow",
    "K003": "weak-type or python-scalar leak splitting the kernel "
            "cache key",
    "K004": "carry-donation policy break in the route's jit call",
    "K005": "dynamic-shape primitive (kernel fails to stage)",
    "K006": "device->host transfer inside the scan body",
    "K007": "compile-span cache-key coords missing/drifted vs the "
            "static model",
}

#: primitives that round-trip to the host per invocation — fatal
#: inside the level loop (K001)
_CALLBACK_PRIMS = {"pure_callback", "io_callback", "callback",
                   "python_callback"}
#: effectful primitives that imply a device->host transfer when staged
#: inside the loop body (K006) — debug prints are the common leak
_TRANSFER_PRIMS = {"debug_callback", "debug_print", "device_put"}
#: loop-body primitives: anything staged under one of these runs once
#: per BFS level (or per op), not once per kernel call
_LOOP_PRIMS = {"while", "scan"}


# ---------------------------------------------------------------------------
# K007 — the static cache-key model
# ---------------------------------------------------------------------------

#: coords every route's compile span must carry (newest generation):
#: the full kernel cache key, so a recorded span alone reconstructs
#: the exact compiled kernel (fleet/warmup.py warm boot)
BASE_COORDS = frozenset({
    "engine", "frontier", "n_det_pad", "n_crash_pad", "window", "k",
    "masked", "masked_crash", "dedup", "vt",
    "model", "model_init", "model_width",
})

#: attrs ``obs/telemetry.compile_span`` itself adds — runtime facts,
#: not cache-key coords, so excluded from the model comparison
RUNTIME_COORDS = frozenset({"cache", "persistent_cache"})

#: span_kind -> required coord set, newest generation.  span_kind is
#: declared per route (KernelRoute.span_kind) and recoverable from a
#: recorded span's args (see :func:`span_kind_for_args`).
CACHE_KEY_MODEL = {
    "solo": BASE_COORDS,
    "batch": BASE_COORDS | {"batch"},
    "batch-sharded": BASE_COORDS | {"batch", "sharded", "shards"},
    "window-sharded": BASE_COORDS | {"shards"},
}

#: coord sets earlier PRs emitted, oldest first — committed
#: ``BENCH_trace_*.json`` recordings predating the full model are
#: validated against these; LIVE staging (and any trace recorded from
#: now on) must match the newest generation exactly
LEGACY_GENERATIONS = (
    # PR 15: first span accounting — engine + two dims only
    frozenset({"engine", "frontier", "n_det_pad"}),
    # PR 16 fleet tier: warm-boot needed window/k/crash pad
    frozenset({"engine", "frontier", "n_det_pad", "n_crash_pad",
               "window", "k"}),
)


def span_kind_for_args(args: dict) -> str:
    """Classify a recorded ``device.compile`` span into the coord
    model's span_kind.  Legacy spans missing the batch/sharded markers
    classify as solo — their generation check still passes."""
    if args.get("engine") == "device-sharded":
        return "window-sharded"
    if "sharded" in args or args.get("shards") is not None:
        return "batch-sharded"
    if "batch" in args:
        return "batch"
    return "solo"


def _coord_domain_errors(args: dict) -> list[str]:
    """Value-domain checks for whatever coords are present — a coord
    carrying an impossible value is drift even when the key set
    matches."""
    errs = []

    def _int(k):
        v = args.get(k)
        if v is None:
            return None
        try:
            return int(v)
        except (TypeError, ValueError):
            errs.append(f"coord {k}={v!r} is not an integer")
            return None

    w = _int("window")
    if w is not None and (w <= 0 or w % 32):
        errs.append(f"window={w} not a positive multiple of 32")
    cp = _int("n_crash_pad")
    if cp is not None and (cp < 0 or cp % 32 or cp > 64):
        errs.append(f"n_crash_pad={cp} not a multiple of 32 in [0,64]")
    for k, lo in (("frontier", 1), ("n_det_pad", 1), ("k", 1),
                  ("batch", 1), ("shards", 1), ("model_width", 1)):
        v = _int(k)
        if v is not None and v < lo:
            errs.append(f"coord {k}={v} < {lo}")
    eng = args.get("engine")
    if eng is not None and eng not in ("xla", "pallas",
                                       "device-sharded"):
        errs.append(f"unknown engine {eng!r}")
    mdl = args.get("model")
    if mdl is not None and not isinstance(mdl, str):
        errs.append(f"coord model={mdl!r} is not a name")
    return errs


def check_span_args(args: dict, *, kind: str | None = None,
                    strict: bool = True) -> list[str]:
    """K007 core: validate one ``device.compile`` span's args against
    the static cache-key model.

    ``strict=True`` (live staging, bench preflight, newly recorded
    traces): the coord key set must equal the newest generation for
    its span_kind.  ``strict=False`` (committed historical traces): a
    legacy generation's key set is also accepted.  Returns a list of
    failure strings, empty when clean."""
    keys = frozenset(args) - RUNTIME_COORDS
    if kind is None:
        kind = span_kind_for_args(args)
    required = CACHE_KEY_MODEL.get(kind)
    if required is None:
        return [f"unknown span_kind {kind!r}"]
    failures = []
    if keys != required:
        legacy_ok = (not strict) and keys in LEGACY_GENERATIONS
        if not legacy_ok:
            missing = sorted(required - keys)
            extra = sorted(keys - required)
            parts = []
            if missing:
                parts.append(f"missing coords {missing}")
            if extra:
                parts.append(f"unmodelled coords {extra}")
            failures.append(f"[{kind}] " + ", ".join(parts))
    failures.extend(_coord_domain_errors(args))
    return failures


# ---------------------------------------------------------------------------
# staging + jaxpr walking
# ---------------------------------------------------------------------------


def representative_dims(model=None):
    """The SearchDims every route is staged at: small enough to trace
    in milliseconds, big enough to exercise padding, crash lanes and
    the windowed frontier."""
    from ..checker.linearizable import SearchDims
    from ..models import register

    m = model if model is not None else register(0)
    return m, SearchDims(n_det_pad=64, n_crash_pad=32, window=32, k=2,
                         state_width=m.state_width, frontier=8)


def _subjaxprs(eqn) -> Iterable[Any]:
    """Nested jaxprs inside one equation's params (while/scan bodies,
    cond branches, pjit/pallas callees)."""
    for v in eqn.params.values():
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for sub in vals:
            inner = getattr(sub, "jaxpr", sub)
            if hasattr(inner, "eqns"):
                yield inner


def walk_jaxpr(jaxpr, path=()):
    """Yield ``(eqn, path)`` for every equation, depth-first; ``path``
    is the tuple of enclosing primitive names (so ``"scan" in path``
    means inside a loop body)."""
    for eqn in jaxpr.eqns:
        yield eqn, path
        sub_path = path + (eqn.primitive.name,)
        for inner in _subjaxprs(eqn):
            yield from walk_jaxpr(inner, sub_path)


def _eqn_line(eqn) -> tuple[str, int] | None:
    """(filename, lineno) of the user frame that staged this equation,
    when jax kept one — the anchor for ``devlint: ok`` suppression."""
    try:
        from jax._src import source_info_util

        fr = source_info_util.user_frame(eqn.source_info)
    except Exception:  # pragma: no cover — internal API moved
        return None
    if fr is None:
        return None
    line = getattr(fr, "start_line", None) or getattr(fr, "line_num", 0)
    return fr.file_name, int(line or 0)


def _suppressed(eqn) -> bool:
    loc = _eqn_line(eqn)
    if loc is None:
        return False
    return "devlint: ok" in linecache.getline(loc[0], loc[1])


def _at(eqn) -> str:
    loc = _eqn_line(eqn)
    return f" at {loc[0]}:{loc[1]}" if loc else ""


def _in_loop(path) -> bool:
    return any(p in _LOOP_PRIMS for p in path)


def lint_jaxpr(jaxpr, *, route_name: str = "<kernel>",
               int_only: bool = True) -> list[Diagnostic]:
    """Walk one staged (closed or open) jaxpr for K001/K002/K003/K006.

    ``int_only`` is the route's dtype contract: the search kernels
    pack everything into int32/bool lanes, so ANY float is a widening
    leak; routes that legitimately carry floats only get the 64-bit
    check."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    diags: list[Diagnostic] = []

    # K003 — weak-type avals on the traced inputs: a python scalar
    # reached the operand list, so every numerically-distinct call
    # site re-traces under a different cache key
    for i, var in enumerate(inner.invars):
        aval = getattr(var, "aval", None)
        if aval is not None and getattr(aval, "weak_type", False):
            diags.append(Diagnostic(
                "K003", "error",
                f"{route_name}: traced input #{i} has a weak-type aval "
                f"({aval.dtype}) — a python scalar leaked into the "
                f"kernel operands and splits the jit cache key",
                index=i, f=route_name))

    for eqn, path in walk_jaxpr(inner):
        prim = eqn.primitive.name
        in_loop = _in_loop(path)
        if prim in _CALLBACK_PRIMS and in_loop:
            if not _suppressed(eqn):
                diags.append(Diagnostic(
                    "K001", "error",
                    f"{route_name}: host callback '{prim}' staged "
                    f"inside the level loop (path {'>'.join(path)})"
                    f"{_at(eqn)} — every BFS level syncs to host",
                    f=route_name))
            continue
        if prim in _TRANSFER_PRIMS and in_loop:
            if not _suppressed(eqn):
                diags.append(Diagnostic(
                    "K006", "error",
                    f"{route_name}: effectful '{prim}' inside the "
                    f"scan body{_at(eqn)} — a device->host transfer "
                    f"per level",
                    f=route_name))
            continue
        # K002 — dtype scan over the equation's outputs
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is None:
                continue
            name = str(dt)
            wide = name in ("float64", "int64", "uint64", "complex128")
            floaty = int_only and name.startswith(("float", "complex",
                                                   "bfloat"))
            if (wide or floaty) and not _suppressed(eqn):
                why = ("64-bit dtype" if wide
                       else "float dtype in an int-only route")
                diags.append(Diagnostic(
                    "K002", "error",
                    f"{route_name}: '{prim}' produces {name}{_at(eqn)}"
                    f" — {why} widens the device dataflow",
                    f=route_name))
                break  # one K002 per equation is enough signal
    return diags


def stage_route(route, model=None, dims=None):
    """Abstractly stage one route at representative dims.  Returns
    ``(closed_jaxpr | None, diagnostics)`` — staging failure IS the
    K005 finding."""
    import jax

    if model is None or dims is None:
        model, dims = representative_dims(model)
    try:
        fn, args = route.build(model, dims)
        jaxpr = jax.make_jaxpr(fn)(*args)
    except Exception as exc:  # ConcretizationTypeError & friends
        kind = type(exc).__name__
        msg = str(exc).splitlines()[0][:200]
        return None, [Diagnostic(
            "K005", "error",
            f"{route.name}: kernel fails to stage abstractly "
            f"({kind}: {msg}) — a data-dependent shape or python "
            f"control flow on traced values",
            f=route.name)]
    return jaxpr, []


# ---------------------------------------------------------------------------
# K004 — donation policy (AST over the route's cache getter)
# ---------------------------------------------------------------------------


def _jit_calls(fn_node: ast.AST):
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Call):
            f = node.func
            name = None
            if isinstance(f, ast.Attribute):
                name = f.attr
            elif isinstance(f, ast.Name):
                name = f.id
            if name == "jit":
                yield node


def check_donation(source: str, getter: str, *,
                   donate_carry: bool, route_name: str = "<route>",
                   filename: str = "<source>") -> list[Diagnostic]:
    """K004 over one module's source: find ``getter``'s ``jax.jit``
    calls and compare ``donate_argnums`` presence against the route's
    declared carry-donation policy.  Both directions are contract
    breaks: donating buffers the slice driver re-feeds after a
    frontier escalation (declared False, jit donates), and declaring
    donation that the jit never performs (declared True, no
    donate_argnums)."""
    try:
        tree = ast.parse(source)
    except SyntaxError as exc:
        return [Diagnostic(
            "K004", "warning",
            f"{route_name}: cannot parse {filename} for the donation "
            f"check ({exc})", f=route_name)]
    lines = source.splitlines()

    def suppressed(lineno: int) -> bool:
        return (0 < lineno <= len(lines)
                and "devlint: ok" in lines[lineno - 1])

    fn = next((n for n in ast.walk(tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
               and n.name == getter), None)
    if fn is None:
        return [Diagnostic(
            "K004", "warning",
            f"{route_name}: getter '{getter}' not found in {filename}",
            f=route_name)]
    diags = []
    donated_anywhere = False
    for call in _jit_calls(fn):
        donates = any(kw.arg in ("donate_argnums", "donate_argnames")
                      for kw in call.keywords)
        donated_anywhere = donated_anywhere or donates
        if donates and not donate_carry and not suppressed(call.lineno):
            diags.append(Diagnostic(
                "K004", "error",
                f"{route_name}: {getter}'s jax.jit at {filename}:"
                f"{call.lineno} donates buffers but the route declares "
                f"donate_carry=False — the slice driver re-feeds the "
                f"pre-overflow carry after a frontier escalation",
                index=call.lineno, f=route_name))
    if donate_carry and not donated_anywhere:
        diags.append(Diagnostic(
            "K004", "error",
            f"{route_name}: route declares donate_carry=True but no "
            f"jax.jit call in {getter} ({filename}) donates",
            f=route_name))
    return diags


def lint_route_source(route) -> list[Diagnostic]:
    """K004 for a registered route: load its module's source and run
    the donation check on the declared getter."""
    import inspect

    try:
        mod = importlib.import_module(route.module)
        source = inspect.getsource(mod)
        filename = inspect.getsourcefile(mod) or route.module
    except Exception as exc:
        return [Diagnostic(
            "K004", "warning",
            f"{route.name}: cannot load {route.module} source ({exc})",
            f=route.name)]
    return check_donation(source, route.getter,
                          donate_carry=route.donate_carry,
                          route_name=route.name, filename=filename)


# ---------------------------------------------------------------------------
# live span capture — K007 against the real cache getters
# ---------------------------------------------------------------------------

_DEVLINT_RUN = "__devlint__"


def capture_compile_spans(route, model=None, dims=None) -> list[dict]:
    """Request the route through its REAL cache getter under a private
    trace recorder and return the ``device.compile`` spans it emitted.
    An already-warm cache emits none (the miss path never runs) —
    callers treat that as vacuous, not clean."""
    from ..obs import trace as _trace

    if model is None or dims is None:
        model, dims = representative_dims(model)
    prev_forced = _trace._forced
    prev_run = _trace.current_run()
    _trace.enable(True)
    _trace.set_run(_DEVLINT_RUN)
    try:
        route.request(model, dims)
        rec = _trace.recorder(_DEVLINT_RUN)
        return [s for s in rec.spans() if s["name"] == "device.compile"]
    finally:
        _trace.set_run(prev_run)
        _trace.enable(prev_forced)
        _trace.drop_recorder(_DEVLINT_RUN)


def lint_compile_spans(route, spans: list[dict]) -> list[Diagnostic]:
    """K007 over live-captured spans: strict (newest-generation)
    coord check against the route's declared span_kind."""
    diags = []
    for s in spans:
        for fail in check_span_args(s.get("args", {}),
                                    kind=route.span_kind, strict=True):
            diags.append(Diagnostic(
                "K007", "error",
                f"{route.name}: device.compile span coords drift vs "
                f"the static cache-key model: {fail}",
                f=route.name))
    return diags


def lint_trace_spans(trace_obj: dict, *, name: str = "<trace>"
                     ) -> list[Diagnostic]:
    """K007 over one committed Chrome-trace JSON object
    (``BENCH_trace_*.json``): every ``device.compile`` event's args
    must match the static model, legacy generations allowed.  Traces
    with no compile spans pass vacuously (a fully warm recording)."""
    diags = []
    for ev in trace_obj.get("traceEvents", ()):
        if ev.get("name") != "device.compile":
            continue
        args = ev.get("args", {}) or {}
        for fail in check_span_args(args, strict=False):
            diags.append(Diagnostic(
                "K007", "error",
                f"{name}: committed compile span drifts vs the static "
                f"cache-key model: {fail}"))
    return diags


# ---------------------------------------------------------------------------
# the sweep
# ---------------------------------------------------------------------------


def lint_kernel_routes(routes=None, *, live: bool = False,
                       model=None) -> list[Diagnostic]:
    """Stage + walk every registered kernel route.  ``live=True`` also
    requests each route through its real getter and K007-checks the
    emitted compile spans (meaningful in a fresh process — warm caches
    emit no span)."""
    from ..checker.linearizable import kernel_routes

    if routes is None:
        routes = kernel_routes()
    m, dims = representative_dims(model)
    diags: list[Diagnostic] = []
    for name in sorted(routes):
        route = routes[name]
        jaxpr, stage_diags = stage_route(route, m, dims)
        diags.extend(stage_diags)
        if jaxpr is not None:
            diags.extend(lint_jaxpr(jaxpr, route_name=route.name,
                                    int_only=route.int_only))
        diags.extend(lint_route_source(route))
        if live:
            spans = capture_compile_spans(route, m, dims)
            diags.extend(lint_compile_spans(route, spans))
    return diags


def run_devlint(*, live: bool = False) -> dict:
    """The CLI/test entry: sweep all routes, return the result block
    ``{"routes": [names], "diagnostics": [...], "errors": n,
    "warnings": n}``."""
    from ..checker.linearizable import kernel_routes

    routes = kernel_routes()
    diags = lint_kernel_routes(routes, live=live)
    return {
        "routes": sorted(routes),
        "diagnostics": [d.to_dict() for d in diags],
        "errors": sum(1 for d in diags if d.severity == "error"),
        "warnings": sum(1 for d in diags if d.severity == "warning"),
    }
