"""Suite protocol lint — AST checks over ``jepsen_tpu/suites/*``.

The worker loop guarantees half of the client protocol at runtime
(``invoke_op`` asserts completion types, converts crashes to :info —
core.clj:248-281), but a suite bug can still poison a history in ways no
runtime assert sees: an ``except Exception`` that converts an
indeterminate crash into a determinate ``:ok``/``:fail`` teaches the
checker a lie it can never detect (a write that "failed" but actually
applied makes a LINEARIZABLE system look broken, and vice versa).  This
module lints the suite SOURCE for those patterns before any test runs.

S-codes (stable; documented in docs/analyze.md):

==== ======== ==========================================================
code severity meaning
==== ======== ==========================================================
S001 error    ``invoke`` can return None / fall off the end / return
              the invocation unchanged (must return a typed completion)
S002 error    broad/bare ``except`` in ``invoke`` converts a crash to
              ``:ok`` (a crash is indeterminate: must become ``:info``)
S003 error    broad/bare ``except`` in ``invoke`` unconditionally
              converts a crash to ``:fail`` (only sound when the op
              provably did not happen — guard the return with a test of
              the exception or ``op.f``, or complete as ``:info``)
S004 warning  ``setup``/``teardown`` (or ``open``/``close``) defined
              without its pair
S005 error    a Nemesis ``invoke`` returns a completion whose type is
              not ``info`` (core.py asserts this at runtime)
==== ======== ==========================================================

B-codes (``jepsen_tpu/live/`` backends; same gate, same suppression):

==== ======== ==========================================================
B001 error    a direct ``LiveBackend`` subclass is missing a protocol
              member (``name``/``server_argv``/``workload``) — the
              campaign runner would crash mid-matrix instead of at lint
              time
B002 error    broad/bare ``except`` anywhere in a live module whose
              handler unconditionally completes as ``:fail`` — a crash
              against a REAL process is indeterminate (the op may have
              applied before the connection died) and must become
              ``:info``
B003 error    a function writes a file and then ``os.replace``/
              ``os.rename``\\ s it without an ``fsync`` in between —
              the crash-safe journal contract (live/links.py,
              live/corpus.py) is durable-BEFORE-rename; a torn rename
              after a crash silently loses the journal
==== ======== ==========================================================

T-codes (thread/lock discipline over the service tiers —
``jepsen_tpu/fleet/``, ``stream/``, ``obs/``, ``decompose/cache.py``,
``checker/bucket.py`` — via :func:`lint_thread_tier`; a multi-file
pass that roots a name-based call graph at every
``threading.Thread(target=...)`` / ``executor.submit(...)`` /
socketserver ``handle()`` and lints the thread-reachable functions):

==== ======== ==========================================================
T001 error    module/instance state mutated read-modify-write
              (``+=``, self-referential assign, check-then-act) from a
              thread-reachable function without an enclosing lock —
              the admission/env-knob race class
T002 error    ``.acquire()`` / ``fcntl.flock(LOCK_EX)`` not covered by
              try/finally-release or a context manager — an exception
              between acquire and release deadlocks every other thread
T003 error    file written under a flock-style lock without
              ``os.fsync`` before release — the next holder (or a
              crash) can observe the torn tail the lock was supposed
              to serialize
T004 error    ``obs.span(...)`` emitted from a thread-reachable
              function without the ``run=`` pin — the span attributes
              to the process-wide current run, which a multiplexing
              service may have moved by the time the span closes (the
              PR 17 prep-span race)
==== ======== ==========================================================

N-codes (``JEPSEN_TPU_*`` knob threading, package-wide — via
:func:`lint_knobs`; every env knob the package READS must stay
reachable from the CLI and the docs, or it silently becomes a
load-bearing secret):

==== ======== ==========================================================
N001 error    a toggle knob (one read by a zero-arg ``*_enabled()``
              reader, the repo idiom for feature gates) is never
              mentioned in ``cli.py`` — the gate cannot be flipped
              per-run from the command line, only by editing the
              caller's environment
N002 error    a knob that ``cli.py`` claims to set is READ at module
              import time — the CLI applies env mappings after
              startup, so an import-time freeze turns the flag into a
              silent no-op depending on import order (env-only tuning
              constants that deliberately freeze into compile-cache
              keys are exempt because cli.py never claims them)
N003 warning  a knob the package reads appears in no ``docs/*.md`` —
              undocumented knobs rot into tribal knowledge
              (launcher-managed process-topology plumbing —
              ``PROC_ID``/``NUM_PROCS``/``COORDINATOR`` — is exempt:
              the fleet launcher sets it, users never should)
==== ======== ==========================================================

O-codes (``jtpu_*`` metrics contract — via :func:`lint_metrics`; the
observability surfaces must agree on which series exist):

==== ======== ==========================================================
O001 error    a ``jtpu_*`` series referenced by a consumer surface
              (``web.py``, ``tools/obs_guard.py``,
              ``obs_thresholds.json``) is registered nowhere in the
              package — the dashboard panel / guard threshold gates on
              a series that can never report
O002 warning  registered series no consumer surface references
              (aggregated into one finding) — orphans are not wrong,
              but each one is either a missing dashboard panel or dead
              instrumentation
==== ======== ==========================================================

R-codes (retry idempotency — via :func:`lint_retry`; the reconnect
layer retries automatically, so whatever it retries had better be
safe to run twice):

==== ======== ==========================================================
R001 error    a non-idempotent operation (name carries a mutation
              verb: write/put/add/enqueue/...) is retried by an
              automatic construct — a ``Backoff.run(fn)`` /
              ``with_conn(f)`` call, or a loop whose broad except
              handler silently goes around again — in a function with
              no ``"info"`` completion anywhere: a retransmitted
              mutation that already applied double-commits, and the
              history can't even say "maybe"
R002 error    a bounded retry loop whose broad except handler swallows
              the exception and whose function never re-raises after
              the loop — when the budget runs out the op silently
              becomes a no-op with no completion at all
==== ======== ==========================================================

(The model checker proves the dynamic twin of R001: MC201 in
docs/analyze.md §12 is this exact double-commit, caught by running the
live shell code under the simulated transport.)

False-positive escape hatch: a line containing ``suite-lint: ok``
suppresses S/B findings anchored on it; ``threadlint: ok`` suppresses
T findings; ``knoblint: ok`` suppresses N findings,
``metriclint: ok`` O findings and ``retrylint: ok`` R findings (use
sparingly, with a comment saying why the pattern is sound).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Sequence

from .lint import Diagnostic

#: exception names whose handlers catch crashes indiscriminately
BROAD_EXCEPTS = {"Exception", "BaseException"}

SUITE_CODES = {
    "S001": "invoke must return a typed completion on every path",
    "S002": "broad except converting a crash to :ok",
    "S003": "broad except unconditionally converting a crash to :fail",
    "S004": "setup/teardown (open/close) pairing",
    "S005": "nemesis completions must be :info",
    "B001": "LiveBackend subclass missing a protocol member",
    "B002": "broad except in a live module swallowing a crash to :fail",
    "B003": "file written and renamed without fsync in between",
    "T001": "shared state mutated from a thread without its lock",
    "T002": "lock acquired without try/finally or context manager",
    "T003": "file written under flock without fsync-before-release",
    "T004": "span emitted from a thread without the run= pin",
    "N001": "toggle knob (*_enabled reader) with no cli.py flag",
    "N002": "cli.py-claimed knob frozen by an import-time read",
    "N003": "env knob read by the package but absent from docs/",
    "O001": "consumer-referenced jtpu_* series registered nowhere",
    "O002": "registered jtpu_* series no consumer surface references",
    "R001": "non-idempotent op retried automatically without "
            ":info ambiguity handling",
    "R002": "bounded retry loop swallowing the final exception",
}

#: the LiveBackend protocol members a concrete family must provide
#: (live/backend.py raises NotImplementedError for the first two; a
#: family without them dies mid-campaign, not at lint time)
LIVE_PROTOCOL = ("server_argv", "workload")


def _base_names(cls: ast.ClassDef) -> list[str]:
    out = []
    for b in cls.bases:
        try:
            out.append(ast.unparse(b))
        except Exception:  # noqa: BLE001 — exotic base exprs: skip
            pass
    return out


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in elts:
        name = getattr(e, "id", getattr(e, "attr", None))
        if name in BROAD_EXCEPTS:
            return True
    return False


def _return_type_consts(ret: ast.Return) -> set:
    """Constant values passed as ``type=`` anywhere in the returned
    expression (IfExp alternatives all collected)."""
    out: set = set()
    if ret.value is None:
        return out
    for node in ast.walk(ret.value):
        if isinstance(node, ast.keyword) and node.arg == "type":
            for c in ast.walk(node.value):
                if isinstance(c, ast.Constant):
                    out.add(c.value)
    return out


def _always_exits(body: Sequence[ast.stmt]) -> bool:
    """Conservative: does this statement list definitely end in a
    return/raise on every path?  Uncertain constructs answer False at
    the leaf but callers only flag when the WHOLE body is certain to
    fall through — so uncertainty never produces a finding, only
    misses one."""
    if not body:
        return False
    last = body[-1]
    if isinstance(last, (ast.Return, ast.Raise)):
        return True
    if isinstance(last, ast.If):
        return bool(last.orelse) and _always_exits(last.body) \
            and _always_exits(last.orelse)
    if isinstance(last, ast.Try):
        handlers_exit = all(_always_exits(h.body)
                            for h in last.handlers) if last.handlers \
            else True
        body_exit = _always_exits(last.orelse) if last.orelse \
            else _always_exits(last.body)
        final_exit = _always_exits(last.finalbody) if last.finalbody \
            else False
        return final_exit or (body_exit and handlers_exit)
    if isinstance(last, ast.With):
        return _always_exits(last.body)
    if isinstance(last, ast.While):
        # while True with no top-level break never falls through
        is_true = isinstance(last.test, ast.Constant) and \
            bool(last.test.value)
        has_break = any(isinstance(n, ast.Break)
                        for n in ast.walk(last)
                        if not isinstance(n, (ast.FunctionDef,
                                              ast.AsyncFunctionDef)))
        return is_true and not has_break
    return False


def _own_returns(fn: ast.FunctionDef) -> list[ast.Return]:
    """Return statements belonging to ``fn`` itself (nested defs
    excluded — suites wrap invoke bodies in closures)."""
    out: list[ast.Return] = []

    def prune_walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(child, ast.Return):
                out.append(child)
            prune_walk(child)

    prune_walk(fn)
    return out


def _assigned_names(fn: ast.FunctionDef) -> set:
    names: set = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if isinstance(node.target, ast.Name):
                names.add(node.target.id)
    return names


def _handler_unguarded_returns(handler: ast.ExceptHandler
                               ) -> list[ast.Return]:
    """Returns sitting at the handler body's top level (not nested under
    an If/Try that could be testing the exception or the op)."""
    return [s for s in handler.body if isinstance(s, ast.Return)]


def _handler_raises(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) for n in ast.walk(handler))


def lint_source(src: str, filename: str = "<string>"
                ) -> list[Diagnostic]:
    """Lint one suite module's source.  Returns Diagnostics whose
    ``index`` is the 1-based source LINE."""
    diags: list[Diagnostic] = []
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError as e:
        return [Diagnostic("S001", "error",
                           f"{filename}: does not parse: {e}",
                           index=e.lineno)]
    lines = src.splitlines()

    def suppressed(lineno: int | None) -> bool:
        if lineno is None or not 1 <= lineno <= len(lines):
            return False
        return "suite-lint: ok" in lines[lineno - 1]

    def add(code, sev, msg, lineno, **kw):
        if not suppressed(lineno):
            diags.append(Diagnostic(code, sev, f"{filename}:{lineno}: "
                                    f"{msg}", index=lineno, **kw))

    for cls in [n for n in ast.walk(tree)
                if isinstance(n, ast.ClassDef)]:
        bases = _base_names(cls)
        is_client = any(b.endswith("Client") for b in bases) or (
            cls.name.endswith("Client") and not bases)
        is_nemesis = any(b.endswith("Nemesis") for b in bases)
        is_db = any(b.endswith("DB") or b.endswith("db_mod.DB")
                    for b in bases)
        methods = {m.name: m for m in cls.body
                   if isinstance(m, ast.FunctionDef)}

        # --- S004: lifecycle pairing ------------------------------------
        # DB classes own node state: a setup without a teardown leaks it
        # across runs.  CLIENT setup-without-teardown is idiomatic here
        # (logical state is wiped by the DB teardown), so clients are
        # only checked for the connection pair (open without close).
        if is_db:
            for a, b in (("setup", "teardown"),):
                if (a in methods) != (b in methods):
                    have, miss = (a, b) if a in methods else (b, a)
                    add("S004", "warning",
                        f"{cls.name} defines {have}() without {miss}() "
                        f"(lifecycle pairing — state made in one phase "
                        f"should be unmade in its pair)",
                        methods[have].lineno)
        elif is_client and "open" in methods and "close" not in methods:
            # only flag when open() plausibly acquires a resource (it
            # does more than construct-and-return)
            opens = methods["open"]
            if len(opens.body) > 1:
                add("S004", "warning",
                    f"{cls.name} defines open() that builds client "
                    f"state but no close() — if open() acquires a "
                    f"connection or server-side session it leaks on "
                    f"every crash/reopen cycle", opens.lineno)

        if not (is_client or is_nemesis) or "invoke" not in methods:
            continue
        fn = methods["invoke"]
        args = [a.arg for a in fn.args.args]
        op_name = args[2] if len(args) > 2 else "op"
        reassigned = _assigned_names(fn)
        returns = _own_returns(fn)

        # --- S001: every return is a typed completion -------------------
        for ret in returns:
            if ret.value is None or (isinstance(ret.value, ast.Constant)
                                     and ret.value.value is None):
                add("S001", "error",
                    f"{cls.name}.invoke returns None — it must return "
                    f"a completion Op with type ok/fail/info",
                    ret.lineno)
            elif isinstance(ret.value, ast.Name) and \
                    ret.value.id == op_name and op_name not in reassigned:
                add("S001", "error",
                    f"{cls.name}.invoke returns the invocation "
                    f"unchanged — complete it with an explicit type",
                    ret.lineno)
        if not _always_exits(fn.body):
            add("S001", "error",
                f"{cls.name}.invoke can fall off the end (implicit "
                f"None) — every path must return a typed completion "
                f"or raise", fn.lineno)

        # --- S005: nemesis completions are :info ------------------------
        if is_nemesis:
            for ret in returns:
                consts = _return_type_consts(ret)
                bad = consts - {"info"}
                if bad:
                    add("S005", "error",
                        f"{cls.name}.invoke returns type={sorted(bad)!r}"
                        f" — nemesis completions must be :info "
                        f"(core.py asserts this at runtime)",
                        ret.lineno)
            continue  # S002/S003 are about client determinism

        # --- S002/S003: crash-to-determinate conversion -----------------
        for handler in [n for n in ast.walk(fn)
                        if isinstance(n, ast.ExceptHandler)]:
            if not _is_broad(handler):
                continue
            for ret in [r for r in returns
                        if handler.lineno <= r.lineno <=
                        (handler.end_lineno or r.lineno)]:
                consts = _return_type_consts(ret)
                if "ok" in consts:
                    add("S002", "error",
                        f"{cls.name}.invoke converts a broad-except "
                        f"crash to :ok — a crash is indeterminate and "
                        f"must complete as :info",
                        ret.lineno)
            if _handler_raises(handler):
                continue  # narrow cases re-raised: the rest is vetted
            for ret in _handler_unguarded_returns(handler):
                consts = _return_type_consts(ret)
                if consts == {"fail"}:
                    add("S003", "error",
                        f"{cls.name}.invoke unconditionally converts a "
                        f"broad-except crash to :fail — :fail asserts "
                        f"the op definitely did NOT happen; guard on "
                        f"the exception/op.f or complete as :info",
                        ret.lineno)
    return diags


def _fn_calls(fn: ast.FunctionDef) -> list[ast.Call]:
    out = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            out.append(node)
    return out


def _call_name(c: ast.Call) -> str:
    try:
        return ast.unparse(c.func)
    except Exception:  # noqa: BLE001 — exotic callee exprs
        return ""


def lint_live_source(src: str, filename: str = "<string>"
                     ) -> list[Diagnostic]:
    """B-code lint for one ``jepsen_tpu/live/`` module (run on top of
    :func:`lint_source`, whose Client/Nemesis S-codes apply to live
    wire shims unchanged)."""
    diags: list[Diagnostic] = []
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError as e:
        return [Diagnostic("B001", "error",
                           f"{filename}: does not parse: {e}",
                           index=e.lineno)]
    lines = src.splitlines()

    def suppressed(lineno: int | None) -> bool:
        if lineno is None or not 1 <= lineno <= len(lines):
            return False
        return "suite-lint: ok" in lines[lineno - 1]

    def add(code, msg, lineno):
        if not suppressed(lineno):
            diags.append(Diagnostic(code, "error",
                                    f"{filename}:{lineno}: {msg}",
                                    index=lineno))

    classes = [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]

    # --- B001: LiveBackend protocol conformance ----------------------
    # A class that SETS a family `name` declares itself a concrete
    # campaign family: it must define (or inherit through an in-file
    # base chain) the protocol members LiveBackend only raises for.
    # Classes without `name` are abstract intermediates (e.g. the
    # replicated consensus core) and are exempt; chains through bases
    # defined in other modules are unprovable here and skipped.
    by_name = {c.name: c for c in classes}

    def own(cls):
        members = {m.name for m in cls.body
                   if isinstance(m, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        assigns = {t.id for m in cls.body if isinstance(m, ast.Assign)
                   for t in m.targets if isinstance(t, ast.Name)}
        assigns |= {m.target.id for m in cls.body
                    if isinstance(m, ast.AnnAssign)
                    and isinstance(m.target, ast.Name)
                    and m.value is not None}
        return members, assigns

    def chain_has(cls, member: str):
        """True / False / None (= unprovable) walking in-file bases,
        stopping at LiveBackend (whose defs just raise)."""
        seen = set()
        stack = [cls]
        unprovable = False
        while stack:
            c = stack.pop()
            if c.name in seen:
                continue
            seen.add(c.name)
            if c.name != "LiveBackend" and member in own(c)[0]:
                return True
            for b in _base_names(c):
                leaf = b.split(".")[-1]
                if leaf == "LiveBackend":
                    continue
                if leaf in by_name:
                    stack.append(by_name[leaf])
                else:
                    unprovable = True
        return None if unprovable else False

    def is_backend(cls) -> bool:
        seen = set()
        stack = [cls]
        while stack:
            c = stack.pop()
            if c.name in seen:
                continue
            seen.add(c.name)
            for b in _base_names(c):
                leaf = b.split(".")[-1]
                if leaf == "LiveBackend":
                    return True
                if leaf in by_name:
                    stack.append(by_name[leaf])
        return False

    for cls in classes:
        if not is_backend(cls):
            continue
        members, assigns = own(cls)
        if "name" not in assigns:
            if all(m in members for m in LIVE_PROTOCOL):
                add("B001",
                    f"{cls.name} implements the LiveBackend protocol "
                    f"but does not set `name` — campaign cell keys "
                    f"would collide on '?'", cls.lineno)
            continue  # no name: an abstract intermediate
        for req in LIVE_PROTOCOL:
            if chain_has(cls, req) is False:
                add("B001",
                    f"{cls.name} subclasses LiveBackend but neither "
                    f"defines nor inherits {req}() — the campaign "
                    f"runner would raise NotImplementedError "
                    f"mid-matrix", cls.lineno)

    # --- B002: crash swallowed into :fail anywhere in a live module --
    # The S003 beat covers *Client.invoke; live modules also complete
    # ops in helpers and ported shims, where the same conversion is the
    # same lie (a crash against a real process may have applied).
    client_invokes = set()
    for cls in classes:
        bases = _base_names(cls)
        is_client = any(b.endswith("Client") for b in bases) or (
            cls.name.endswith("Client") and not bases)
        if is_client:
            for m in cls.body:
                if isinstance(m, ast.FunctionDef) and \
                        m.name == "invoke":
                    client_invokes.add(id(m))
    for fn in [n for n in ast.walk(tree)
               if isinstance(n, ast.FunctionDef)]:
        if id(fn) in client_invokes:
            continue  # S003's beat — don't double-report
        for handler in [n for n in ast.walk(fn)
                        if isinstance(n, ast.ExceptHandler)]:
            if not _is_broad(handler) or _handler_raises(handler):
                continue
            for ret in _handler_unguarded_returns(handler):
                if _return_type_consts(ret) == {"fail"}:
                    add("B002",
                        f"{fn.name}() unconditionally converts a "
                        f"broad-except crash to :fail — against a real "
                        f"process the op may have applied; complete as "
                        f":info or guard on the exception", ret.lineno)

    # --- B003: rename without fsync ----------------------------------
    # The journal contract (live/links.py rules.jsonl, live/corpus.py
    # pool.jsonl, oplog.py): bytes are durable BEFORE the rename
    # publishes them.  Flag any function that opens a file for writing
    # and renames/replaces one without an os.fsync between.
    for fn in [n for n in ast.walk(tree)
               if isinstance(n, ast.FunctionDef)]:
        calls = _fn_calls(fn)
        renames = [c for c in calls
                   if _call_name(c) in ("os.replace", "os.rename")]
        if not renames:
            continue
        writes = []
        for c in calls:
            if _call_name(c) != "open" or len(c.args) < 2:
                continue
            mode = c.args[1]
            if isinstance(mode, ast.Constant) and \
                    isinstance(mode.value, str) and \
                    ("w" in mode.value or "a" in mode.value):
                writes.append(c)
        if not writes:
            continue
        fsyncs = [c for c in calls if _call_name(c) == "os.fsync"]
        for rn in renames:
            covered = any(w.lineno < f.lineno < rn.lineno
                          for w in writes for f in fsyncs)
            if not covered:
                add("B003",
                    f"{fn.name}() writes a file and then "
                    f"{_call_name(rn)}()s without an os.fsync in "
                    f"between — a crash can publish a torn or empty "
                    f"journal (durable-before-rename contract)",
                    rn.lineno)
    return diags


def lint_file(path: str | Path) -> list[Diagnostic]:
    p = Path(path)
    src = p.read_text()
    diags = lint_source(src, filename=str(p))
    if p.parent.name == "live":
        diags = diags + lint_live_source(src, filename=str(p))
    return diags


def lint_paths(paths: Sequence[str | Path] | None = None
               ) -> dict[str, list[Diagnostic]]:
    """Lint suite files.  ``paths`` may mix files and directories;
    default: the bundled ``jepsen_tpu/suites`` AND ``jepsen_tpu/live``
    (files under a ``live`` directory additionally get the B-code
    backend lint).  Returns {filename: diagnostics} for files with
    findings only."""
    if not paths:
        pkg = Path(__file__).resolve().parent.parent
        paths = [pkg / "suites", pkg / "live"]
    files: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(sorted(p.glob("*.py")))
        else:
            files.append(p)
    out: dict[str, list[Diagnostic]] = {}
    for f in files:
        diags = lint_file(f)
        if diags:
            out[str(f)] = diags
    return out


# ---------------------------------------------------------------------------
# T-codes — thread/lock discipline over the service tiers
# ---------------------------------------------------------------------------
#
# The fleet/stream tiers (PRs 16–17) grew threads fast: socketserver
# connection handlers, probe/pump/reaper loops, the bucket scheduler's
# prep pipeline.  The races they invite (unlocked read-modify-write of
# admission state, env-knob caches, span attribution to a moved
# current-run) are exactly the ones the runtime gates can't see — a
# torn counter doesn't fail a bench.  This pass is deliberately
# tier-LEVEL, not file-level: thread reachability crosses files (a
# router handler thread calls into admission.py), so the call graph is
# built over the whole tier at once, name-based and over-approximate
# (a lint, not an alias analysis).

#: the default tier: every package that runs code on threads, relative
#: to the jepsen_tpu package root
THREAD_TIER = ("fleet", "stream", "obs", "decompose/cache.py",
               "checker/bucket.py")

#: substrings marking a with-item's context expr as a lock
_LOCKISH = ("lock", "mutex", "locked")

#: method names too generic to be call-graph edges — ``self._runs.get``
#: must not make every function named ``get`` thread-reachable (the
#: name-based graph has no receiver types, so ubiquitous
#: container/stdlib names are excluded from edges entirely)
_GENERIC_NAMES = frozenset({
    "get", "put", "set", "add", "pop", "append", "extend", "update",
    "clear", "copy", "close", "open", "read", "write", "send", "recv",
    "start", "join", "submit", "result", "items", "keys", "values",
    "setdefault", "discard", "remove", "insert", "index", "count",
    "inc", "observe", "acquire", "release", "wait", "notify", "run",
})


def _is_lockish(expr: str) -> bool:
    e = expr.lower()
    return any(t in e for t in _LOCKISH)


def _last_seg(expr_str: str) -> str:
    return expr_str.split(".")[-1].split("(")[0].strip()


def _target_name(node) -> str | None:
    """Callable-reference name: ``Name`` / ``Attribute`` last segment."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class _FnInfo:
    __slots__ = ("node", "filename", "lines", "cls")

    def __init__(self, node, filename, lines, cls=None):
        self.node = node
        self.filename = filename
        self.lines = lines
        self.cls = cls


def thread_tier_files() -> list[Path]:
    pkg = Path(__file__).resolve().parent.parent
    files: list[Path] = []
    for rel in THREAD_TIER:
        p = pkg / rel
        if p.is_dir():
            files.extend(sorted(p.glob("*.py")))
        elif p.exists():
            files.append(p)
    return files


def _index_tier(files: Sequence[Path]):
    """One parse pass: function defs by bare name, thread-root names,
    and the name-based call graph."""
    fns: dict[str, list[_FnInfo]] = {}
    roots: set[str] = set()
    calls: dict[int, set[str]] = {}  # id(fn node) -> callee names
    trees = []
    for path in files:
        src = Path(path).read_text()
        try:
            tree = ast.parse(src, filename=str(path))
        except SyntaxError:
            continue
        lines = src.splitlines()
        trees.append((path, tree, lines))
        # class membership for handler-root detection
        cls_of: dict[int, ast.ClassDef] = {}
        for cls in [n for n in ast.walk(tree)
                    if isinstance(n, ast.ClassDef)]:
            for m in cls.body:
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    cls_of[id(m)] = cls
        for fn in [n for n in ast.walk(tree)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]:
            info = _FnInfo(fn, str(path), lines, cls_of.get(id(fn)))
            fns.setdefault(fn.name, []).append(info)
            callees = set()
            for c in [n for n in ast.walk(fn)
                      if isinstance(n, ast.Call)]:
                leaf = _last_seg(_call_name(c))
                if leaf and leaf not in _GENERIC_NAMES:
                    callees.add(leaf)
            calls[id(fn)] = callees
        for c in [n for n in ast.walk(tree) if isinstance(n, ast.Call)]:
            cname = _call_name(c)
            leaf = _last_seg(cname)
            if leaf == "Thread":
                for kw in c.keywords:
                    if kw.arg == "target":
                        t = _target_name(kw.value)
                        if t:
                            roots.add(t)
            elif leaf == "submit" and c.args:
                t = _target_name(c.args[0])
                if t:
                    roots.add(t)
        # socketserver: ThreadingTCPServer runs each connection's
        # handler on its own thread — handle() is a thread root
        for cls in [n for n in ast.walk(tree)
                    if isinstance(n, ast.ClassDef)]:
            if any("RequestHandler" in b for b in _base_names(cls)):
                for m in cls.body:
                    if isinstance(m, ast.FunctionDef) and \
                            m.name == "handle":
                        roots.add("handle")
    return fns, roots, calls, trees


def _reachable_names(fns, roots, calls) -> set[str]:
    seen: set[str] = set()
    stack = [r for r in roots if r in fns]
    while stack:
        name = stack.pop()
        if name in seen:
            continue
        seen.add(name)
        for info in fns[name]:
            for callee in calls.get(id(info.node), ()):
                if callee in fns and callee not in seen:
                    stack.append(callee)
    return seen


def _fn_call_edges(fn) -> list[tuple[str, bool]]:
    """(callee name, call site is inside a lock context) for every
    call in ``fn`` — the raw material for the caller-holds-lock
    fixpoint (a function whose every in-tier call site holds a lock is
    as protected as one that takes the lock itself)."""
    out: list[tuple[str, bool]] = []

    def exprs_calls(node, in_lock):
        if node is None:
            return
        for c in [n for n in ast.walk(node) if isinstance(n, ast.Call)]:
            leaf = _last_seg(_call_name(c))
            if leaf and leaf not in _GENERIC_NAMES:
                out.append((leaf, in_lock))

    def scan(stmts, in_lock):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                locky = False
                for it in st.items:
                    exprs_calls(it.context_expr, in_lock)
                    try:
                        locky = locky or _is_lockish(
                            ast.unparse(it.context_expr))
                    except Exception:  # noqa: BLE001
                        pass
                scan(st.body, in_lock or locky)
            elif isinstance(st, ast.Try):
                scan(st.body, in_lock)
                for h in st.handlers:
                    scan(h.body, in_lock)
                scan(st.orelse, in_lock)
                scan(st.finalbody, in_lock)
            elif isinstance(st, ast.If):
                exprs_calls(st.test, in_lock)
                scan(st.body, in_lock)
                scan(st.orelse, in_lock)
            elif isinstance(st, ast.While):
                exprs_calls(st.test, in_lock)
                scan(st.body, in_lock)
                scan(st.orelse, in_lock)
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                exprs_calls(st.iter, in_lock)
                scan(st.body, in_lock)
                scan(st.orelse, in_lock)
            else:
                exprs_calls(st, in_lock)

    scan(fn.body, False)
    return out


def _lock_covered(fns, roots, edges: list[tuple[str, str, bool]]
                  ) -> set[str]:
    """Greatest fixpoint of "every in-tier call site holds a lock":
    start from every called name, drop thread roots (they start on a
    bare thread), then drop any callee with an unlocked call site from
    an uncovered caller, until stable."""
    covered = {callee for _, callee, _ in edges} - set(roots)
    changed = True
    while changed:
        changed = False
        for caller, callee, locked in edges:
            if callee in covered and not locked \
                    and caller not in covered:
                covered.discard(callee)
                changed = True
    return covered


def _is_acquire(call: ast.Call) -> bool:
    name = _call_name(call)
    if name.endswith(".acquire"):
        return True
    if _last_seg(name) == "flock":
        return any("LOCK_EX" in ast.unparse(a) for a in call.args)
    return False


def _try_releases(node: ast.Try) -> bool:
    for st in node.finalbody:
        for c in [n for n in ast.walk(st) if isinstance(n, ast.Call)]:
            name = _call_name(c)
            if name.endswith(".release") or (
                    _last_seg(name) == "flock"
                    and any("LOCK_UN" in ast.unparse(a)
                            for a in c.args)):
                return True
    return False


def _scan_thread_fn(info: _FnInfo, reachable: bool, add, *,
                    covered: bool = False) -> None:
    """Walk one function's statements tracking lock context; emit
    T001/T002/T003/T004 through ``add(code, msg, lineno)``.
    ``covered`` means every in-tier call site holds a lock, so the
    T001 shared-state checks are moot."""
    fn = info.node
    global_names = {n for node in ast.walk(fn)
                    if isinstance(node, ast.Global) for n in node.names}
    # T002 release heuristic is function-scoped: a lock taken in one
    # branch and released in an enclosing finally (depth-counted CMs
    # like VerdictCache._locked) is disciplined even though the
    # acquire's own statement list has no Try sibling
    fn_releases = any(_try_releases(t) for t in ast.walk(fn)
                      if isinstance(t, ast.Try))

    def stmt_calls(st):
        return [n for n in ast.walk(st) if isinstance(n, ast.Call)]

    def is_shared_target(t) -> tuple[bool, str]:
        """(is shared state, display name) — instance/class attrs and
        declared-global module names; subscripts of those too."""
        if isinstance(t, ast.Subscript):
            return is_shared_target(t.value)
        if isinstance(t, ast.Attribute):
            try:
                return True, ast.unparse(t)
            except Exception:  # noqa: BLE001
                return True, t.attr
        if isinstance(t, ast.Name) and t.id in global_names:
            return True, t.id
        return False, ""

    def check_t001(st, in_lock, if_tests):
        if not reachable or in_lock or covered:
            return
        if isinstance(st, ast.AugAssign):
            shared, name = is_shared_target(st.target)
            if shared:
                add("T001",
                    f"{fn.name}() read-modify-writes {name} from a "
                    f"thread-reachable path without holding a lock — "
                    f"concurrent updates lose increments", st.lineno)
            return
        if isinstance(st, ast.Assign) and len(st.targets) == 1:
            shared, name = is_shared_target(st.targets[0])
            if not shared:
                return
            try:
                val = ast.unparse(st.value)
            except Exception:  # noqa: BLE001
                val = ""
            rmw = name in val
            check_act = any(name in test for test in if_tests)
            if rmw or check_act:
                how = ("self-referential assign" if rmw
                       else "check-then-act")
                add("T001",
                    f"{fn.name}() {how} on {name} from a "
                    f"thread-reachable path without holding a lock — "
                    f"two threads can interleave between read and "
                    f"write", st.lineno)

    def check_t004(st, in_lock):
        if not reachable:
            return
        for c in stmt_calls(st):
            if _last_seg(_call_name(c)) != "span":
                continue
            if not any(kw.arg == "run" for kw in c.keywords):
                add("T004",
                    f"{fn.name}() emits a span from a thread-reachable "
                    f"path without the run= pin — it attributes to the "
                    f"process-wide current run, which another thread "
                    f"may have moved", c.lineno)

    def check_t003_with(st: ast.With | ast.AsyncWith):
        """Write under a flock-style lock without fsync before the
        lock releases at the with-exit."""
        ctxs = []
        for it in st.items:
            try:
                ctxs.append(ast.unparse(it.context_expr))
            except Exception:  # noqa: BLE001
                pass
        if not any("flock" in c.lower() or "locked" in c.lower()
                   for c in ctxs):
            return
        writes = []
        has_fsync = False
        for sub in st.body:
            for c in stmt_calls(sub):
                name = _call_name(c)
                if name.endswith((".write", ".writelines")):
                    writes.append(c)
                if _last_seg(name) == "fsync":
                    has_fsync = True
        if writes and not has_fsync:
            add("T003",
                f"{fn.name}() writes a file under {ctxs[0]} without "
                f"os.fsync before the lock releases — the next holder "
                f"(or a crash) can observe the torn tail the lock was "
                f"meant to serialize", writes[0].lineno)

    def scan(stmts, in_lock, if_tests, protected):
        for i, st in enumerate(stmts):
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue  # nested defs are their own entries
            # T002: bare acquire must be covered by try/finally
            acquires = [c for c in stmt_calls(st)
                        if isinstance(st, ast.Expr) and _is_acquire(c)]
            for c in acquires:
                nxt = stmts[i + 1] if i + 1 < len(stmts) else None
                ok = protected or fn_releases or (
                    isinstance(nxt, ast.Try) and _try_releases(nxt))
                if not ok:
                    add("T002",
                        f"{fn.name}() acquires a lock with no "
                        f"try/finally release and no context manager "
                        f"— an exception here deadlocks every other "
                        f"thread", c.lineno)
            if isinstance(st, (ast.With, ast.AsyncWith)):
                locky = any(_is_lockish(ast.unparse(it.context_expr))
                            for it in st.items)
                check_t003_with(st)
                check_t004(st, in_lock)
                scan(st.body, in_lock or locky, if_tests, protected)
            elif isinstance(st, ast.Try):
                body_protected = protected or _try_releases(st)
                scan(st.body, in_lock, if_tests, body_protected)
                for h in st.handlers:
                    scan(h.body, in_lock, if_tests, protected)
                scan(st.orelse, in_lock, if_tests, protected)
                scan(st.finalbody, in_lock, if_tests, protected)
            elif isinstance(st, ast.If):
                try:
                    test = ast.unparse(st.test)
                except Exception:  # noqa: BLE001
                    test = ""
                scan(st.body, in_lock, if_tests + [test], protected)
                scan(st.orelse, in_lock, if_tests, protected)
            elif isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
                scan(st.body, in_lock, if_tests, protected)
                scan(st.orelse, in_lock, if_tests, protected)
            else:
                check_t001(st, in_lock, if_tests)
                check_t004(st, in_lock)

    scan(fn.body, False, [], False)


def lint_thread_tier(paths: Sequence[str | Path] | None = None
                     ) -> dict[str, list[Diagnostic]]:
    """The T-code pass: build the tier-wide call graph, mark
    thread-reachable functions, lint them for lock discipline.
    Returns {filename: diagnostics} for files with findings only."""
    files = ([Path(p) for p in paths] if paths
             else thread_tier_files())
    all_files: list[Path] = []
    for p in files:
        if p.is_dir():
            all_files.extend(sorted(p.glob("*.py")))
        else:
            all_files.append(p)
    fns, roots, calls, _trees = _index_tier(all_files)
    reachable = _reachable_names(fns, roots, calls)
    edges = [(name, callee, locked)
             for name, infos in fns.items()
             for info in infos
             for callee, locked in _fn_call_edges(info.node)
             if callee in fns]
    covered = _lock_covered(fns, roots, edges)
    out: dict[str, list[Diagnostic]] = {}
    for name, infos in fns.items():
        for info in infos:
            lines = info.lines

            def add(code, msg, lineno, _info=info, _lines=lines):
                # line suppression, or a whole-function suppression on
                # the def line or in the contiguous comment block just
                # above it — single-owner-thread functions document
                # their ownership argument once, not per statement
                cand = [lineno, _info.node.lineno]
                ln = _info.node.lineno - 1
                while 1 <= ln <= len(_lines) \
                        and _lines[ln - 1].lstrip().startswith("#"):
                    cand.append(ln)
                    ln -= 1
                for ln in cand:
                    if 1 <= ln <= len(_lines) and \
                            "threadlint: ok" in _lines[ln - 1]:
                        return
                out.setdefault(_info.filename, []).append(Diagnostic(
                    code, "error", f"{_info.filename}:{lineno}: {msg}",
                    index=lineno))
            _scan_thread_fn(info, name in reachable, add,
                            covered=name in covered)
    for f in out:
        out[f].sort(key=lambda d: d.index or 0)
    return out


# ---------------------------------------------------------------------------
# N-codes — JEPSEN_TPU_* knob threading (package-wide)
# ---------------------------------------------------------------------------
#
# The knob surface grew one env var at a time; nothing ever checked
# that a knob stayed reachable from cli.py, overridable per-run, and
# documented.  This pass rebuilds the contract from the source: every
# os.environ read of a JEPSEN_TPU_* literal is located and classified
# (toggle reader / import-time freeze / plain read), then checked
# against cli.py and docs/*.md.  Name-based and literal-only by
# design — a knob whose name is computed at runtime is already a
# deeper problem than this lint can state.

#: every package knob starts with this prefix (telemetry scrapes the
#: whole prefix; the lint only tracks full literal names)
KNOB_PREFIX = "JEPSEN_TPU_"

#: launcher-managed process-topology plumbing: the fleet launcher sets
#: these for child processes, users never should — exempt from N003
KNOB_INTERNAL = frozenset({
    "JEPSEN_TPU_PROC_ID",
    "JEPSEN_TPU_NUM_PROCS",
    "JEPSEN_TPU_COORDINATOR",
})


def _env_read(node) -> str | None:
    """The knob name when ``node`` READS a ``JEPSEN_TPU_*`` env var
    (``os.environ.get``/``os.getenv``/``os.environ[...]`` in Load
    context / ``"X" in os.environ``), else None.  Writes (assignment,
    ``setdefault``, ``pop``, ``del``) are not reads."""
    def knob_const(n) -> str | None:
        if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                and n.value.startswith(KNOB_PREFIX):
            return n.value
        return None

    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr in ("get", "getenv") and node.args:
        name = knob_const(node.args[0])
        if name is not None:
            recv = ast.unparse(node.func.value)
            if "environ" in recv or recv.split(".")[-1] == "os":
                return name
    if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
        name = knob_const(node.slice)
        if name is not None and "environ" in ast.unparse(node.value):
            return name
    if isinstance(node, ast.Compare) and len(node.ops) == 1 \
            and isinstance(node.ops[0], (ast.In, ast.NotIn)):
        name = knob_const(node.left)
        if name is not None and "environ" in ast.unparse(
                node.comparators[0]):
            return name
    return None


def _knob_reads(tree) -> list[tuple]:
    """All knob reads in a module: ``(name, lineno, enclosing_fn)``
    where ``enclosing_fn`` is the innermost FunctionDef (None for a
    module-import-time read; class bodies without a function count as
    import time too)."""
    enclosing: dict[int, object] = {}

    def assign(node, fn):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                enclosing[id(child)] = fn
                assign(child, child)
            else:
                enclosing[id(child)] = fn
                assign(child, fn)

    assign(tree, None)
    out = []
    for n in ast.walk(tree):
        name = _env_read(n)
        if name is not None:
            out.append((name, getattr(n, "lineno", None),
                        enclosing.get(id(n))))
    return out


def _is_toggle_reader(fn) -> bool:
    """The repo idiom for a feature gate: a zero-arg ``*_enabled()``
    function whose body reads the knob."""
    if fn is None or not fn.name.endswith("_enabled"):
        return False
    a = fn.args
    return not (a.posonlyargs or a.args or a.kwonlyargs
                or a.vararg or a.kwarg)


def _package_py_files(pkg_root: Path) -> list[Path]:
    return sorted(p for p in pkg_root.rglob("*.py")
                  if "__pycache__" not in p.parts)


def lint_knobs(pkg_root: str | Path | None = None,
               cli_text: str | None = None,
               docs_text: str | None = None
               ) -> dict[str, list[Diagnostic]]:
    """The N-code knob-threading lint over every module in the
    package.  ``cli_text``/``docs_text`` are injectable for tests;
    defaults read ``jepsen_tpu/cli.py`` and concatenate ``docs/*.md``
    from the repo root.  Returns {filename: diagnostics} for files
    with findings only; a line containing ``knoblint: ok`` suppresses
    findings anchored on it."""
    pkg = Path(pkg_root) if pkg_root else \
        Path(__file__).resolve().parent.parent
    if cli_text is None:
        cli = pkg / "cli.py"
        cli_text = cli.read_text() if cli.exists() else ""
    if docs_text is None:
        docs = pkg.parent / "docs"
        docs_text = "\n".join(p.read_text()
                              for p in sorted(docs.glob("*.md"))) \
            if docs.is_dir() else ""

    out: dict[str, list[Diagnostic]] = {}
    documented: set[str] = set()  # first-anchor dedup for N003
    for f in _package_py_files(pkg):
        src = f.read_text()
        if KNOB_PREFIX not in src:
            continue
        try:
            tree = ast.parse(src, filename=str(f))
        except SyntaxError:
            continue  # the S-lint owns parse errors
        lines = src.splitlines()

        def suppressed(lineno):
            return (lineno is not None and 1 <= lineno <= len(lines)
                    and "knoblint: ok" in lines[lineno - 1])

        diags: list[Diagnostic] = []
        for name, lineno, fn in _knob_reads(tree):
            if suppressed(lineno):
                continue
            if _is_toggle_reader(fn) and name not in cli_text:
                diags.append(Diagnostic(
                    "N001", "error",
                    f"{f}:{lineno}: toggle knob {name} is read by "
                    f"{fn.name}() but never mentioned in cli.py — the "
                    f"gate cannot be flipped per-run from the command "
                    f"line", index=lineno))
            if fn is None and name in cli_text:
                diags.append(Diagnostic(
                    "N002", "error",
                    f"{f}:{lineno}: {name} is set by cli.py but read "
                    f"at import time — the flag silently no-ops when "
                    f"this module imports first", index=lineno))
            if name not in KNOB_INTERNAL and name not in docs_text \
                    and name not in documented:
                documented.add(name)
                diags.append(Diagnostic(
                    "N003", "warning",
                    f"{f}:{lineno}: {name} is read here but appears "
                    f"in no docs/*.md", index=lineno))
        if diags:
            out[str(f)] = diags
    return out


# ---------------------------------------------------------------------------
# O-codes — jtpu_* metrics contract (registration vs consumer surfaces)
# ---------------------------------------------------------------------------

#: consumer surfaces, relative to the REPO root (pkg_root.parent):
#: the dashboard, the scrape guard, and the alert thresholds — a
#: series one of these names must exist, or the panel/threshold gates
#: on nothing
METRIC_CONSUMERS = ("jepsen_tpu/web.py", "tools/obs_guard.py",
                    "obs_thresholds.json")

_METRIC_RE = re.compile(r"\bjtpu_[a-z0-9_]+\b")

#: prometheus exposition suffixes a histogram/counter family implies —
#: a consumer referencing jtpu_x_seconds_bucket is consuming the
#: registered jtpu_x_seconds
_METRIC_SUFFIXES = ("_bucket", "_sum", "_count", "_total")


def registered_metrics(pkg_root: str | Path | None = None
                       ) -> dict[str, tuple]:
    """Every ``jtpu_*`` series the package registers:
    {name: (filename, lineno)} from literal first arguments of
    ``.counter(...)``/``.gauge(...)``/``.histogram(...)`` calls."""
    pkg = Path(pkg_root) if pkg_root else \
        Path(__file__).resolve().parent.parent
    out: dict[str, tuple] = {}
    for f in _package_py_files(pkg):
        src = f.read_text()
        if "jtpu_" not in src:
            continue
        try:
            tree = ast.parse(src, filename=str(f))
        except SyntaxError:
            continue
        for n in ast.walk(tree):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in ("counter", "gauge",
                                        "histogram") \
                    and n.args and isinstance(n.args[0], ast.Constant) \
                    and isinstance(n.args[0].value, str) \
                    and n.args[0].value.startswith("jtpu_"):
                out.setdefault(n.args[0].value, (str(f), n.lineno))
    return out


def lint_metrics(pkg_root: str | Path | None = None,
                 consumers: Sequence[str | Path] | None = None
                 ) -> dict[str, list[Diagnostic]]:
    """The O-code metrics-contract lint.  ``consumers`` overrides the
    default surface list (absolute paths; for tests).  Returns
    {filename: diagnostics}; ``metriclint: ok`` on a consumer line
    suppresses O001 findings anchored on it."""
    pkg = Path(pkg_root) if pkg_root else \
        Path(__file__).resolve().parent.parent
    if consumers is None:
        consumers = [pkg.parent / c for c in METRIC_CONSUMERS]
    registered = registered_metrics(pkg)

    def base_name(name: str) -> str:
        for suf in _METRIC_SUFFIXES:
            if name.endswith(suf) and name[:-len(suf)] in registered:
                return name[:-len(suf)]
        return name

    out: dict[str, list[Diagnostic]] = {}
    referenced: set[str] = set()
    for c in consumers:
        c = Path(c)
        if not c.exists():
            continue
        diags: list[Diagnostic] = []
        seen_here: set[str] = set()
        for lineno, line in enumerate(c.read_text().splitlines(), 1):
            for m in _METRIC_RE.finditer(line):
                name = base_name(m.group(0))
                referenced.add(name)
                if name in registered or name in seen_here \
                        or "metriclint: ok" in line:
                    continue
                seen_here.add(name)
                diags.append(Diagnostic(
                    "O001", "error",
                    f"{c}:{lineno}: {m.group(0)} is referenced here "
                    f"but registered nowhere in the package — the "
                    f"panel/threshold gates on a series that can "
                    f"never report", index=lineno))
        if diags:
            out[str(c)] = diags

    orphans = sorted(set(registered) - referenced)
    if orphans:
        shown = ", ".join(orphans[:6]) + \
            (f", … ({len(orphans)} total)" if len(orphans) > 6 else "")
        f0, l0 = registered[orphans[0]]
        out.setdefault(f0, []).append(Diagnostic(
            "O002", "warning",
            f"{len(orphans)} registered jtpu_* series no consumer "
            f"surface (web.py / obs_guard / thresholds) references: "
            f"{shown}", index=l0))
    return out


# ---------------------------------------------------------------------------
# R-codes — retry idempotency (reconnect.Backoff / with_conn / retry loops)
# ---------------------------------------------------------------------------
#
# The reconnect layer makes retries AUTOMATIC: Backoff.run(fn) calls
# fn up to max_attempts times, with_conn reopens under the caller's
# loop, and ad-hoc `while ...: try: op() except Exception: continue`
# loops go around on any crash.  A retried READ is harmless.  A
# retried MUTATION that already applied on the server is a duplicate
# commit — exactly the bug the model checker's MC201 certificate
# exhibits dynamically (a timed-out ADDJOB retransmitted after its
# first copy was delivered).  The static contract this pass enforces:
# an automatically retried mutation must live in a function that can
# complete the ambiguous outcome as :info (the repo idiom — a string
# constant "info" somewhere in the function), or carry a
# ``retrylint: ok`` waiver explaining why the op is idempotent (e.g.
# a server-side reqId dedup cache).

#: identifier segments that mark a callable as a mutation — matched
#: against whole ``_``/camelCase segments, never substrings ("address"
#: does not contain the verb "add")
RETRY_MUTATION_VERBS = frozenset({
    "write", "put", "add", "addjob", "enqueue", "dequeue", "insert",
    "update", "delete", "ack", "ackjob", "cas", "commit", "post",
    "send", "execute", "push", "create", "set", "transfer", "upsert",
})

#: wrapper callables whose FIRST argument is the thing actually
#: retried — the lint digs through them one level
_RETRY_WRAPPERS = ("with_conn", "run")


def _ident_segments(name: str) -> list[str]:
    """``addJob_once`` → ["add", "job", "once"] — underscore and
    camelCase boundaries both split."""
    snake = re.sub(r"(?<=[a-z0-9])(?=[A-Z])", "_", name)
    return [s.lower() for s in re.split(r"[_\W]+", snake) if s]


def _is_mutation_name(name: str) -> bool:
    return any(seg in RETRY_MUTATION_VERBS
               for seg in _ident_segments(name))


def _callable_names(node) -> list[tuple[str, int]]:
    """Names a retried callable argument could invoke: a bare
    Name/Attribute is itself; a Lambda is every call in its body."""
    if isinstance(node, ast.Name):
        return [(node.id, node.lineno)]
    if isinstance(node, ast.Attribute):
        return [(node.attr, node.lineno)]
    if isinstance(node, ast.Lambda):
        out = []
        for c in ast.walk(node.body):
            if isinstance(c, ast.Call):
                n = _call_name(c).split(".")[-1]
                if n:
                    out.append((n, c.lineno))
        return out
    return []


def _retried_names_in_call(call: ast.Call) -> list[tuple[str, int]]:
    """For a retry-construct call, the names of what it retries.

    ``<backoffish>.run(fn)`` and ``*.with_conn(f)`` retry their first
    argument; anything else retries nothing."""
    if not isinstance(call.func, ast.Attribute) or not call.args:
        return []
    attr = call.func.attr
    if attr == "with_conn":
        return _callable_names(call.args[0])
    if attr == "run":
        try:
            recv = ast.unparse(call.func.value).lower()
        except Exception:  # noqa: BLE001 — exotic receiver exprs
            return []
        if "backoff" in recv:
            return _callable_names(call.args[0])
    return []


def _own_stmt_nodes(root) -> list:
    """Nodes belonging to ``root`` itself — nested function/class
    bodies excluded (they get their own scan)."""
    out: list = []

    def rec(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            out.append(child)
            rec(child)

    rec(root)
    return out


def _handler_retries(handler: ast.ExceptHandler) -> bool:
    """Does this broad handler just go around the loop again?  Any
    raise/return/break anywhere in it means the loop has an explicit
    failure path — conservative: uncertainty never produces a
    finding."""
    return not any(isinstance(n, (ast.Raise, ast.Return, ast.Break))
                   for n in ast.walk(handler))


def _handler_captured(handler: ast.ExceptHandler) -> str | None:
    """The name the handler saves the exception under
    (``except Exception as e: last = e`` → "last"), or None.  A loop
    that keeps the last error is retry-shaped, and the kept name being
    USED after the loop is the non-swallowing exit path R002 wants."""
    if not handler.name:
        return None
    for n in ast.walk(handler):
        if isinstance(n, ast.Assign) \
                and isinstance(n.value, ast.Name) \
                and n.value.id == handler.name:
            for t in n.targets:
                if isinstance(t, ast.Name):
                    return t.id
    return None


def _loop_is_retry(loop) -> bool:
    """Attempt-shaped loop header: ``for attempt in range(...)``,
    ``while not bo.exhausted()``, anything mentioning the retry
    vocabulary.  Plain per-item scans (``for f in files``) are NOT
    retry loops — a broad `continue` there skips a bad item, it does
    not re-run one."""
    parts = [loop.target, loop.iter] if isinstance(loop, ast.For) \
        else [loop.test]
    try:
        header = " ".join(ast.unparse(p) for p in parts).lower()
    except Exception:  # noqa: BLE001 — exotic header exprs
        return False
    return any(k in header for k in ("attempt", "retr", "backoff",
                                     "exhaust"))


def lint_retry_source(src: str, filename: str = "<string>"
                      ) -> list[Diagnostic]:
    """R-code lint for one module's source (see the module docstring's
    R-code table).  ``retrylint: ok`` on the anchored line
    suppresses."""
    diags: list[Diagnostic] = []
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError:
        return []  # the S-lint owns parse errors
    lines = src.splitlines()

    def suppressed(lineno: int | None) -> bool:
        return (lineno is not None and 1 <= lineno <= len(lines)
                and "retrylint: ok" in lines[lineno - 1])

    def add(code, msg, lineno):
        if not suppressed(lineno):
            diags.append(Diagnostic(code, "error",
                                    f"{filename}:{lineno}: {msg}",
                                    index=lineno))

    #: scan units: every function, plus the module top level
    units = [n for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    units.append(tree)

    for fn in units:
        fn_name = getattr(fn, "name", "<module>")
        if fn_name == "run" and isinstance(fn, ast.FunctionDef):
            # Backoff.run itself IS the retry machinery — it re-raises
            # the last error internally; linting it against itself
            # would flag the mechanism, not a use of it
            continue
        own = _own_stmt_nodes(fn)
        # the repo idiom for acknowledged ambiguity: the function
        # completes (or can complete) the op as :info somewhere
        has_info = any(isinstance(n, ast.Constant) and n.value == "info"
                       for n in ast.walk(fn))

        # --- construct A: Backoff.run(fn) / with_conn(f) -------------
        for call in [n for n in own if isinstance(n, ast.Call)]:
            for name, lineno in _retried_names_in_call(call):
                if _is_mutation_name(name) and not has_info:
                    add("R001",
                        f"{fn_name}() auto-retries {name}() (reconnect "
                        f"schedule) but can never complete :info — a "
                        f"retransmitted mutation that already applied "
                        f"double-commits; complete ambiguous outcomes "
                        f"as :info or mark the op idempotent with "
                        f"`retrylint: ok`", lineno)

        # --- construct B: retry loop + try + broad handler that
        # goes around again --------------------------------------------
        for loop in [n for n in own if isinstance(n, (ast.For,
                                                      ast.While))]:
            for tr in [n for n in ast.walk(loop)
                       if isinstance(n, ast.Try)]:
                retry_handlers = [h for h in tr.handlers
                                  if _is_broad(h) and
                                  _handler_retries(h)]
                if not retry_handlers:
                    continue
                kept = [k for k in map(_handler_captured,
                                       retry_handlers) if k]
                if not _loop_is_retry(loop) and not kept:
                    continue  # a per-item scan, not a retry loop
                # R001: a mutation inside the retried try body
                if not has_info:
                    seen: set = set()
                    for c in [n for st in tr.body
                              for n in ast.walk(st)
                              if isinstance(n, ast.Call)]:
                        names = _retried_names_in_call(c) or \
                            [(_call_name(c).split(".")[-1], c.lineno)]
                        for name, lineno in names:
                            if _is_mutation_name(name) and \
                                    name not in seen:
                                seen.add(name)
                                add("R001",
                                    f"{fn_name}() retries {name}() in "
                                    f"a broad-except loop but can "
                                    f"never complete :info — a crash "
                                    f"after the op applied retries a "
                                    f"committed mutation; complete "
                                    f"ambiguous outcomes as :info or "
                                    f"waive with `retrylint: ok`",
                                    lineno)
                # R002: a bounded loop whose budget can run out with
                # the last error discarded and never re-raised
                unbounded = isinstance(loop, ast.While) and \
                    isinstance(loop.test, ast.Constant) and \
                    bool(loop.test.value)
                if unbounded:
                    continue  # while True never exits by exhaustion
                loop_end = getattr(loop, "end_lineno", loop.lineno)
                reraises_after = any(
                    isinstance(n, ast.Raise) and n.lineno > loop_end
                    for n in ast.walk(fn))
                # the kept last-error being read after the loop is the
                # other legitimate exit: completing :info/:fail WITH
                # the error instead of raising it
                kept_used = any(
                    isinstance(n, ast.Name) and n.id in kept
                    and isinstance(n.ctx, ast.Load)
                    and n.lineno > loop_end
                    for n in ast.walk(fn))
                if not reraises_after and not kept_used:
                    h0 = retry_handlers[0]
                    add("R002",
                        f"{fn_name}()'s bounded retry loop swallows "
                        f"every crash and never re-raises after the "
                        f"loop — when the budget runs out the op "
                        f"silently becomes a no-op; keep the last "
                        f"error and raise it (Backoff.run semantics)",
                        h0.lineno)
    return diags


def lint_retry(pkg_root: str | Path | None = None
               ) -> dict[str, list[Diagnostic]]:
    """The R-code retry-idempotency lint over every module in the
    package.  Returns {filename: diagnostics} for files with findings
    only; a line containing ``retrylint: ok`` suppresses findings
    anchored on it."""
    pkg = Path(pkg_root) if pkg_root else \
        Path(__file__).resolve().parent.parent
    out: dict[str, list[Diagnostic]] = {}
    for f in _package_py_files(pkg):
        src = f.read_text()
        diags = lint_retry_source(src, filename=str(f))
        if diags:
            out[str(f)] = diags
    return out
