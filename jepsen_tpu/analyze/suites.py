"""Suite protocol lint — AST checks over ``jepsen_tpu/suites/*``.

The worker loop guarantees half of the client protocol at runtime
(``invoke_op`` asserts completion types, converts crashes to :info —
core.clj:248-281), but a suite bug can still poison a history in ways no
runtime assert sees: an ``except Exception`` that converts an
indeterminate crash into a determinate ``:ok``/``:fail`` teaches the
checker a lie it can never detect (a write that "failed" but actually
applied makes a LINEARIZABLE system look broken, and vice versa).  This
module lints the suite SOURCE for those patterns before any test runs.

S-codes (stable; documented in docs/analyze.md):

==== ======== ==========================================================
code severity meaning
==== ======== ==========================================================
S001 error    ``invoke`` can return None / fall off the end / return
              the invocation unchanged (must return a typed completion)
S002 error    broad/bare ``except`` in ``invoke`` converts a crash to
              ``:ok`` (a crash is indeterminate: must become ``:info``)
S003 error    broad/bare ``except`` in ``invoke`` unconditionally
              converts a crash to ``:fail`` (only sound when the op
              provably did not happen — guard the return with a test of
              the exception or ``op.f``, or complete as ``:info``)
S004 warning  ``setup``/``teardown`` (or ``open``/``close``) defined
              without its pair
S005 error    a Nemesis ``invoke`` returns a completion whose type is
              not ``info`` (core.py asserts this at runtime)
==== ======== ==========================================================

B-codes (``jepsen_tpu/live/`` backends; same gate, same suppression):

==== ======== ==========================================================
B001 error    a direct ``LiveBackend`` subclass is missing a protocol
              member (``name``/``server_argv``/``workload``) — the
              campaign runner would crash mid-matrix instead of at lint
              time
B002 error    broad/bare ``except`` anywhere in a live module whose
              handler unconditionally completes as ``:fail`` — a crash
              against a REAL process is indeterminate (the op may have
              applied before the connection died) and must become
              ``:info``
B003 error    a function writes a file and then ``os.replace``/
              ``os.rename``\\ s it without an ``fsync`` in between —
              the crash-safe journal contract (live/links.py,
              live/corpus.py) is durable-BEFORE-rename; a torn rename
              after a crash silently loses the journal
==== ======== ==========================================================

False-positive escape hatch: a line containing ``suite-lint: ok``
suppresses findings anchored on it (use sparingly, with a comment saying
why the pattern is sound).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Sequence

from .lint import Diagnostic

#: exception names whose handlers catch crashes indiscriminately
BROAD_EXCEPTS = {"Exception", "BaseException"}

SUITE_CODES = {
    "S001": "invoke must return a typed completion on every path",
    "S002": "broad except converting a crash to :ok",
    "S003": "broad except unconditionally converting a crash to :fail",
    "S004": "setup/teardown (open/close) pairing",
    "S005": "nemesis completions must be :info",
    "B001": "LiveBackend subclass missing a protocol member",
    "B002": "broad except in a live module swallowing a crash to :fail",
    "B003": "file written and renamed without fsync in between",
}

#: the LiveBackend protocol members a concrete family must provide
#: (live/backend.py raises NotImplementedError for the first two; a
#: family without them dies mid-campaign, not at lint time)
LIVE_PROTOCOL = ("server_argv", "workload")


def _base_names(cls: ast.ClassDef) -> list[str]:
    out = []
    for b in cls.bases:
        try:
            out.append(ast.unparse(b))
        except Exception:  # noqa: BLE001 — exotic base exprs: skip
            pass
    return out


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in elts:
        name = getattr(e, "id", getattr(e, "attr", None))
        if name in BROAD_EXCEPTS:
            return True
    return False


def _return_type_consts(ret: ast.Return) -> set:
    """Constant values passed as ``type=`` anywhere in the returned
    expression (IfExp alternatives all collected)."""
    out: set = set()
    if ret.value is None:
        return out
    for node in ast.walk(ret.value):
        if isinstance(node, ast.keyword) and node.arg == "type":
            for c in ast.walk(node.value):
                if isinstance(c, ast.Constant):
                    out.add(c.value)
    return out


def _always_exits(body: Sequence[ast.stmt]) -> bool:
    """Conservative: does this statement list definitely end in a
    return/raise on every path?  Uncertain constructs answer False at
    the leaf but callers only flag when the WHOLE body is certain to
    fall through — so uncertainty never produces a finding, only
    misses one."""
    if not body:
        return False
    last = body[-1]
    if isinstance(last, (ast.Return, ast.Raise)):
        return True
    if isinstance(last, ast.If):
        return bool(last.orelse) and _always_exits(last.body) \
            and _always_exits(last.orelse)
    if isinstance(last, ast.Try):
        handlers_exit = all(_always_exits(h.body)
                            for h in last.handlers) if last.handlers \
            else True
        body_exit = _always_exits(last.orelse) if last.orelse \
            else _always_exits(last.body)
        final_exit = _always_exits(last.finalbody) if last.finalbody \
            else False
        return final_exit or (body_exit and handlers_exit)
    if isinstance(last, ast.With):
        return _always_exits(last.body)
    if isinstance(last, ast.While):
        # while True with no top-level break never falls through
        is_true = isinstance(last.test, ast.Constant) and \
            bool(last.test.value)
        has_break = any(isinstance(n, ast.Break)
                        for n in ast.walk(last)
                        if not isinstance(n, (ast.FunctionDef,
                                              ast.AsyncFunctionDef)))
        return is_true and not has_break
    return False


def _own_returns(fn: ast.FunctionDef) -> list[ast.Return]:
    """Return statements belonging to ``fn`` itself (nested defs
    excluded — suites wrap invoke bodies in closures)."""
    out: list[ast.Return] = []

    def prune_walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(child, ast.Return):
                out.append(child)
            prune_walk(child)

    prune_walk(fn)
    return out


def _assigned_names(fn: ast.FunctionDef) -> set:
    names: set = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if isinstance(node.target, ast.Name):
                names.add(node.target.id)
    return names


def _handler_unguarded_returns(handler: ast.ExceptHandler
                               ) -> list[ast.Return]:
    """Returns sitting at the handler body's top level (not nested under
    an If/Try that could be testing the exception or the op)."""
    return [s for s in handler.body if isinstance(s, ast.Return)]


def _handler_raises(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) for n in ast.walk(handler))


def lint_source(src: str, filename: str = "<string>"
                ) -> list[Diagnostic]:
    """Lint one suite module's source.  Returns Diagnostics whose
    ``index`` is the 1-based source LINE."""
    diags: list[Diagnostic] = []
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError as e:
        return [Diagnostic("S001", "error",
                           f"{filename}: does not parse: {e}",
                           index=e.lineno)]
    lines = src.splitlines()

    def suppressed(lineno: int | None) -> bool:
        if lineno is None or not 1 <= lineno <= len(lines):
            return False
        return "suite-lint: ok" in lines[lineno - 1]

    def add(code, sev, msg, lineno, **kw):
        if not suppressed(lineno):
            diags.append(Diagnostic(code, sev, f"{filename}:{lineno}: "
                                    f"{msg}", index=lineno, **kw))

    for cls in [n for n in ast.walk(tree)
                if isinstance(n, ast.ClassDef)]:
        bases = _base_names(cls)
        is_client = any(b.endswith("Client") for b in bases) or (
            cls.name.endswith("Client") and not bases)
        is_nemesis = any(b.endswith("Nemesis") for b in bases)
        is_db = any(b.endswith("DB") or b.endswith("db_mod.DB")
                    for b in bases)
        methods = {m.name: m for m in cls.body
                   if isinstance(m, ast.FunctionDef)}

        # --- S004: lifecycle pairing ------------------------------------
        # DB classes own node state: a setup without a teardown leaks it
        # across runs.  CLIENT setup-without-teardown is idiomatic here
        # (logical state is wiped by the DB teardown), so clients are
        # only checked for the connection pair (open without close).
        if is_db:
            for a, b in (("setup", "teardown"),):
                if (a in methods) != (b in methods):
                    have, miss = (a, b) if a in methods else (b, a)
                    add("S004", "warning",
                        f"{cls.name} defines {have}() without {miss}() "
                        f"(lifecycle pairing — state made in one phase "
                        f"should be unmade in its pair)",
                        methods[have].lineno)
        elif is_client and "open" in methods and "close" not in methods:
            # only flag when open() plausibly acquires a resource (it
            # does more than construct-and-return)
            opens = methods["open"]
            if len(opens.body) > 1:
                add("S004", "warning",
                    f"{cls.name} defines open() that builds client "
                    f"state but no close() — if open() acquires a "
                    f"connection or server-side session it leaks on "
                    f"every crash/reopen cycle", opens.lineno)

        if not (is_client or is_nemesis) or "invoke" not in methods:
            continue
        fn = methods["invoke"]
        args = [a.arg for a in fn.args.args]
        op_name = args[2] if len(args) > 2 else "op"
        reassigned = _assigned_names(fn)
        returns = _own_returns(fn)

        # --- S001: every return is a typed completion -------------------
        for ret in returns:
            if ret.value is None or (isinstance(ret.value, ast.Constant)
                                     and ret.value.value is None):
                add("S001", "error",
                    f"{cls.name}.invoke returns None — it must return "
                    f"a completion Op with type ok/fail/info",
                    ret.lineno)
            elif isinstance(ret.value, ast.Name) and \
                    ret.value.id == op_name and op_name not in reassigned:
                add("S001", "error",
                    f"{cls.name}.invoke returns the invocation "
                    f"unchanged — complete it with an explicit type",
                    ret.lineno)
        if not _always_exits(fn.body):
            add("S001", "error",
                f"{cls.name}.invoke can fall off the end (implicit "
                f"None) — every path must return a typed completion "
                f"or raise", fn.lineno)

        # --- S005: nemesis completions are :info ------------------------
        if is_nemesis:
            for ret in returns:
                consts = _return_type_consts(ret)
                bad = consts - {"info"}
                if bad:
                    add("S005", "error",
                        f"{cls.name}.invoke returns type={sorted(bad)!r}"
                        f" — nemesis completions must be :info "
                        f"(core.py asserts this at runtime)",
                        ret.lineno)
            continue  # S002/S003 are about client determinism

        # --- S002/S003: crash-to-determinate conversion -----------------
        for handler in [n for n in ast.walk(fn)
                        if isinstance(n, ast.ExceptHandler)]:
            if not _is_broad(handler):
                continue
            for ret in [r for r in returns
                        if handler.lineno <= r.lineno <=
                        (handler.end_lineno or r.lineno)]:
                consts = _return_type_consts(ret)
                if "ok" in consts:
                    add("S002", "error",
                        f"{cls.name}.invoke converts a broad-except "
                        f"crash to :ok — a crash is indeterminate and "
                        f"must complete as :info",
                        ret.lineno)
            if _handler_raises(handler):
                continue  # narrow cases re-raised: the rest is vetted
            for ret in _handler_unguarded_returns(handler):
                consts = _return_type_consts(ret)
                if consts == {"fail"}:
                    add("S003", "error",
                        f"{cls.name}.invoke unconditionally converts a "
                        f"broad-except crash to :fail — :fail asserts "
                        f"the op definitely did NOT happen; guard on "
                        f"the exception/op.f or complete as :info",
                        ret.lineno)
    return diags


def _fn_calls(fn: ast.FunctionDef) -> list[ast.Call]:
    out = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            out.append(node)
    return out


def _call_name(c: ast.Call) -> str:
    try:
        return ast.unparse(c.func)
    except Exception:  # noqa: BLE001 — exotic callee exprs
        return ""


def lint_live_source(src: str, filename: str = "<string>"
                     ) -> list[Diagnostic]:
    """B-code lint for one ``jepsen_tpu/live/`` module (run on top of
    :func:`lint_source`, whose Client/Nemesis S-codes apply to live
    wire shims unchanged)."""
    diags: list[Diagnostic] = []
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError as e:
        return [Diagnostic("B001", "error",
                           f"{filename}: does not parse: {e}",
                           index=e.lineno)]
    lines = src.splitlines()

    def suppressed(lineno: int | None) -> bool:
        if lineno is None or not 1 <= lineno <= len(lines):
            return False
        return "suite-lint: ok" in lines[lineno - 1]

    def add(code, msg, lineno):
        if not suppressed(lineno):
            diags.append(Diagnostic(code, "error",
                                    f"{filename}:{lineno}: {msg}",
                                    index=lineno))

    classes = [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]

    # --- B001: LiveBackend protocol conformance ----------------------
    # A class that SETS a family `name` declares itself a concrete
    # campaign family: it must define (or inherit through an in-file
    # base chain) the protocol members LiveBackend only raises for.
    # Classes without `name` are abstract intermediates (e.g. the
    # replicated consensus core) and are exempt; chains through bases
    # defined in other modules are unprovable here and skipped.
    by_name = {c.name: c for c in classes}

    def own(cls):
        members = {m.name for m in cls.body
                   if isinstance(m, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        assigns = {t.id for m in cls.body if isinstance(m, ast.Assign)
                   for t in m.targets if isinstance(t, ast.Name)}
        assigns |= {m.target.id for m in cls.body
                    if isinstance(m, ast.AnnAssign)
                    and isinstance(m.target, ast.Name)
                    and m.value is not None}
        return members, assigns

    def chain_has(cls, member: str):
        """True / False / None (= unprovable) walking in-file bases,
        stopping at LiveBackend (whose defs just raise)."""
        seen = set()
        stack = [cls]
        unprovable = False
        while stack:
            c = stack.pop()
            if c.name in seen:
                continue
            seen.add(c.name)
            if c.name != "LiveBackend" and member in own(c)[0]:
                return True
            for b in _base_names(c):
                leaf = b.split(".")[-1]
                if leaf == "LiveBackend":
                    continue
                if leaf in by_name:
                    stack.append(by_name[leaf])
                else:
                    unprovable = True
        return None if unprovable else False

    def is_backend(cls) -> bool:
        seen = set()
        stack = [cls]
        while stack:
            c = stack.pop()
            if c.name in seen:
                continue
            seen.add(c.name)
            for b in _base_names(c):
                leaf = b.split(".")[-1]
                if leaf == "LiveBackend":
                    return True
                if leaf in by_name:
                    stack.append(by_name[leaf])
        return False

    for cls in classes:
        if not is_backend(cls):
            continue
        members, assigns = own(cls)
        if "name" not in assigns:
            if all(m in members for m in LIVE_PROTOCOL):
                add("B001",
                    f"{cls.name} implements the LiveBackend protocol "
                    f"but does not set `name` — campaign cell keys "
                    f"would collide on '?'", cls.lineno)
            continue  # no name: an abstract intermediate
        for req in LIVE_PROTOCOL:
            if chain_has(cls, req) is False:
                add("B001",
                    f"{cls.name} subclasses LiveBackend but neither "
                    f"defines nor inherits {req}() — the campaign "
                    f"runner would raise NotImplementedError "
                    f"mid-matrix", cls.lineno)

    # --- B002: crash swallowed into :fail anywhere in a live module --
    # The S003 beat covers *Client.invoke; live modules also complete
    # ops in helpers and ported shims, where the same conversion is the
    # same lie (a crash against a real process may have applied).
    client_invokes = set()
    for cls in classes:
        bases = _base_names(cls)
        is_client = any(b.endswith("Client") for b in bases) or (
            cls.name.endswith("Client") and not bases)
        if is_client:
            for m in cls.body:
                if isinstance(m, ast.FunctionDef) and \
                        m.name == "invoke":
                    client_invokes.add(id(m))
    for fn in [n for n in ast.walk(tree)
               if isinstance(n, ast.FunctionDef)]:
        if id(fn) in client_invokes:
            continue  # S003's beat — don't double-report
        for handler in [n for n in ast.walk(fn)
                        if isinstance(n, ast.ExceptHandler)]:
            if not _is_broad(handler) or _handler_raises(handler):
                continue
            for ret in _handler_unguarded_returns(handler):
                if _return_type_consts(ret) == {"fail"}:
                    add("B002",
                        f"{fn.name}() unconditionally converts a "
                        f"broad-except crash to :fail — against a real "
                        f"process the op may have applied; complete as "
                        f":info or guard on the exception", ret.lineno)

    # --- B003: rename without fsync ----------------------------------
    # The journal contract (live/links.py rules.jsonl, live/corpus.py
    # pool.jsonl, oplog.py): bytes are durable BEFORE the rename
    # publishes them.  Flag any function that opens a file for writing
    # and renames/replaces one without an os.fsync between.
    for fn in [n for n in ast.walk(tree)
               if isinstance(n, ast.FunctionDef)]:
        calls = _fn_calls(fn)
        renames = [c for c in calls
                   if _call_name(c) in ("os.replace", "os.rename")]
        if not renames:
            continue
        writes = []
        for c in calls:
            if _call_name(c) != "open" or len(c.args) < 2:
                continue
            mode = c.args[1]
            if isinstance(mode, ast.Constant) and \
                    isinstance(mode.value, str) and \
                    ("w" in mode.value or "a" in mode.value):
                writes.append(c)
        if not writes:
            continue
        fsyncs = [c for c in calls if _call_name(c) == "os.fsync"]
        for rn in renames:
            covered = any(w.lineno < f.lineno < rn.lineno
                          for w in writes for f in fsyncs)
            if not covered:
                add("B003",
                    f"{fn.name}() writes a file and then "
                    f"{_call_name(rn)}()s without an os.fsync in "
                    f"between — a crash can publish a torn or empty "
                    f"journal (durable-before-rename contract)",
                    rn.lineno)
    return diags


def lint_file(path: str | Path) -> list[Diagnostic]:
    p = Path(path)
    src = p.read_text()
    diags = lint_source(src, filename=str(p))
    if p.parent.name == "live":
        diags = diags + lint_live_source(src, filename=str(p))
    return diags


def lint_paths(paths: Sequence[str | Path] | None = None
               ) -> dict[str, list[Diagnostic]]:
    """Lint suite files.  ``paths`` may mix files and directories;
    default: the bundled ``jepsen_tpu/suites`` AND ``jepsen_tpu/live``
    (files under a ``live`` directory additionally get the B-code
    backend lint).  Returns {filename: diagnostics} for files with
    findings only."""
    if not paths:
        pkg = Path(__file__).resolve().parent.parent
        paths = [pkg / "suites", pkg / "live"]
    files: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(sorted(p.glob("*.py")))
        else:
            files.append(p)
    out: dict[str, list[Diagnostic]] = {}
    for f in files:
        diags = lint_file(f)
        if diags:
            out[str(f)] = diags
    return out
