"""Dynamic partial-order reduction — the runtime half of state-space
reduction, phase 2.

PR 12/13 built the STATIC half: the happens-before order-solver
(analyze/hb.py) and the model-generic constraint compiler
(analyze/constraints.py) decide easy histories outright and hand the
engines a must-order prune computed once before any search.  This
module adds the three reductions that live AT runtime, in the spirit of
Parsimonious Optimal DPOR (arXiv:2405.11128) — bound what commuting
operations can do instead of enumerating their interleavings — and of
GPUexplore's cheap on-chip filtering (arXiv:1801.05857):

**Duplicate-op canonical edges** (static in cost, dynamic in reach):
two rows with IDENTICAL content — same ``(f, v1, v2)`` and the same
``ok`` flag — are fully interchangeable in any linearization, because
swapping their LABELS leaves the op-content sequence unchanged.  When
additionally their intervals form a staircase (``inv_a <= inv_b`` and
``ret_a <= ret_b``), forcing a before b is exchange-safe:

    Given any valid linearization with b at position i and a at a
    later position j, relabel: a takes position i, b takes j.  Model
    legality is untouched (identical content, identical sequence).
    Real time holds too: a at i needs ``ret_a >= max_inv(before i)``,
    which follows from a's own validity at j (``ret_a >= max_inv
    (before j) >= max_inv(before i)``); b at j needs ``ret_b >=
    max_inv'(before j)``, and the swap only replaced ``inv_b`` by the
    smaller ``inv_a`` among those, so ``ret_b >= ret_a >= max_inv
    (before j) >= max_inv'(before j)``.  Intermediate positions only
    see constraints relax.

  This is sound for EVERY model family (content equality is
  model-agnostic) and covers exactly what the HB solver's canonical
  read-order cannot: duplicate writes and cas rows on ``tainted``
  (non-unique-writes) keys, duplicate enqueues, duplicate mutex
  acquires.  The edges merge into the same must-order predecessor map
  the engines already consume — host DFS, `linear` frames, and (new
  in this PR) the device kernels' ``expand_mask`` planes.

**Dynamic sleep sets** (the host DFS): at each configuration, after a
candidate's subtree is fully explored, later siblings carry it in a
*sleep set* — provided the pair COMMUTES at the concrete state
(``step(step(s,a),b) == step(step(s,b),a)``, both-illegal counting as
equal).  A sleeping op is skipped as the immediate next linearization:
its continuation was already covered through the sibling explored
first (state equality from commutation makes the coverage exact, and
coverage is state-based, so it propagates).  Sleep sets compose with
the visited memo through a per-state ANTICHAIN of sleep masks: a
revisit is skipped only when some prior visit explored with a SUBSET
sleep set — the classic state-caching fix (Godefroid), the same
subset-antichain trick `checker/linear.py` uses for crash masks.
Observed commutativity is tested at runtime against the model's own
``pystep`` (memoized; reads and identical rows short-circuit
statically), so cas/mutex pairs prune exactly where their concrete
states allow.

**Canonical-state frontier dedup** lives in
``decompose/canonical.py`` (:func:`~jepsen_tpu.decompose.canonical.
dead_value_cutoffs`) and in the engines: register-family states whose
value no remaining op compares against are observation-equivalent, so
they rewrite to one DEAD token and collapse in the level dedup —
symmetric interleavings that differ only in which dead value they
left behind merge BEFORE expansion instead of being expanded apart.

Knob family: default ON; ``dpor=False`` per call on every wired
engine, ``JEPSEN_TPU_DPOR=0`` fleet-wide, ``--no-dpor`` on the CLI.
Verdict-identical by construction: duplicate-op edges are exchange-
safe, sleep sets only skip covered work, and the dead-token rewrite
is an exact bisimulation quotient — proven by the all-route
differential fuzz in tests/test_dpor.py.
"""

from __future__ import annotations

import os

import numpy as np

from ..history import OpSeq
from ..models import R_READ, ModelSpec
from ..obs.metrics import REGISTRY

_M_SLEEP = REGISTRY.counter(
    "jtpu_dpor_sleep_prunes_total",
    "Host-DFS candidates skipped because they were sleeping "
    "(covered by an already-explored commuting sibling)")
_M_DEDUP = REGISTRY.counter(
    "jtpu_dpor_dedup_total",
    "Canonical-state frontier dedup events, by site/kind "
    "(rewrite = a successor state collapsed onto the dead token; "
    "hit = a rewritten config merged with an existing frontier row)",
    ("site", "event"))
_M_MASK = REGISTRY.counter(
    "jtpu_dpor_mask_total",
    "Must-order mask effects, by site (lanes/candidates killed on "
    "host frames and the DFS; masked rows shipped to device planes)",
    ("site",))
_M_EDGES = REGISTRY.counter(
    "jtpu_dpor_dup_edges_total",
    "Duplicate-op canonical must-order edges inferred")

#: per-dst cap on duplicate-op chain edges (mirrors hb.EDGE_CAP_*)
DUP_EDGE_CAP_FACTOR = 2
DUP_EDGE_CAP_MIN = 128

#: sleep-set bookkeeping caps: masks past this popcount stop growing
#: (a truncated sleep set prunes less, never wrongly)
SLEEP_SCAN_CAP = 24
#: commute-memo bound — beyond it the memo resets (correctness
#: unaffected; the test is deterministic per (state, a, b))
COMMUTE_MEMO_CAP = 200_000


def dpor_enabled() -> bool:
    """The fleet knob: on unless JEPSEN_TPU_DPOR=0/false/off/no."""
    return os.environ.get("JEPSEN_TPU_DPOR", "").strip().lower() not in (
        "0", "false", "off", "no")


def resolve_dpor(flag: bool | None) -> bool:
    return dpor_enabled() if flag is None else bool(flag)


# ---------------------------------------------------------------------------
# Duplicate-op canonical edges
# ---------------------------------------------------------------------------


def duplicate_op_edges(seq: OpSeq, cap: int | None = None
                       ) -> list[tuple[int, int, str]]:
    """Staircase chains over identical-content rows, as must-order
    edges ``(src, dst, "dup")`` — src forced before dst, exchange-safe
    by the label-swap argument in the module docstring.

    Rows group by ``(f, v1, v2, ok)``; each group is chained exactly
    like hb._canon_edges: sorted by invocation, consecutive members
    whose returns also do not decrease get an edge (rt-implied pairs
    are skipped — the engines enforce real time natively).  Crashed
    duplicates all share ``ret = +inf``, so the whole group chains.
    """
    n = len(seq)
    if n < 2:
        return []
    if cap is None:
        cap = max(DUP_EDGE_CAP_MIN, DUP_EDGE_CAP_FACTOR * n)
    f = np.asarray(seq.f)
    v1 = np.asarray(seq.v1)
    v2 = np.asarray(seq.v2)
    ok = np.asarray(seq.ok, dtype=bool)
    inv = [int(x) for x in seq.inv]
    ret = [int(x) for x in seq.ret]
    groups: dict[tuple, list[int]] = {}
    for i in range(n):
        groups.setdefault(
            (int(f[i]), int(v1[i]), int(v2[i]), bool(ok[i])),
            []).append(i)
    out: list[tuple[int, int, str]] = []
    for rows in groups.values():
        if len(rows) < 2:
            continue
        chain = sorted(rows, key=lambda i: (inv[i], i))
        prev = chain[0]
        for nxt in chain[1:]:
            if ret[nxt] >= ret[prev]:
                if not ret[prev] < inv[nxt]:  # rt gives it anyway
                    out.append((prev, nxt, "dup"))
                    if len(out) >= cap:
                        return out
                prev = nxt
    return out


def merge_dup_edges(seq: OpSeq, model: ModelSpec, hb,
                    flag: bool | None = None):
    """Merge duplicate-op edges into an :class:`~jepsen_tpu.analyze.
    hb.HBAnalysis`'s must-order predecessor map — the unified prepass
    transport every consumer (host DFS, linear frames, batch disposal,
    device planes) already reads.  No-op when dpor is off, the history
    is decided, or no duplicate rows exist.  Returns ``hb`` (mutated in
    place) for chaining."""
    if hb is None or hb.decided is not None or not resolve_dpor(flag):
        return hb
    edges = duplicate_op_edges(seq)
    st = hb.stats.setdefault("dpor", {})
    st["dup_edges"] = len(edges)
    st["enabled"] = True
    if not edges:
        return hb
    _M_EDGES.inc(len(edges))
    must = {d: list(s) for d, s in hb.must_pred.items()}
    for (src, dst, _k) in edges:
        must.setdefault(int(dst), []).append(int(src))
    hb.must_pred = {d: tuple(sorted(set(s))) for d, s in must.items()}
    hb.applies = True
    return hb


# ---------------------------------------------------------------------------
# Dynamic sleep sets (host DFS)
# ---------------------------------------------------------------------------


class SleepSets:
    """Commutation oracle + sleep-mask bookkeeping for one DFS run.

    ``commutes(state, a, b)`` tests the two rows' transitions at one
    concrete state: both orders produce the same outcome (the same
    state, or both illegal).  Static short-circuits: two plain reads
    are state-transparent (always commute), identical-content rows
    trivially commute.  Everything else runs the model's ``pystep``
    four ways, memoized per (state, a, b).
    """

    __slots__ = ("_f", "_v1", "_v2", "_pystep", "_read", "_ident",
                 "_memo", "prunes")

    def __init__(self, seq: OpSeq, model: ModelSpec):
        self._f = [int(x) for x in seq.f]
        self._v1 = [int(x) for x in seq.v1]
        self._v2 = [int(x) for x in seq.v2]
        self._pystep = model.pystep
        fam = model.name in ("register", "cas-register",
                             "multi-register")
        # state-transparent rows: plain reads never change state and
        # their legality ignores the other read
        self._read = [fam and fi == R_READ for fi in self._f]
        self._ident = {}
        for i in range(len(self._f)):
            self._ident.setdefault(
                (self._f[i], self._v1[i], self._v2[i]), []).append(i)
        self._memo: dict = {}
        self.prunes = 0

    def commutes(self, state, a: int, b: int) -> bool:
        if self._read[a] and self._read[b]:
            return True
        if (self._f[a], self._v1[a], self._v2[a]) == \
                (self._f[b], self._v1[b], self._v2[b]):
            return True
        if a > b:
            a, b = b, a
        key = (state, a, b)
        r = self._memo.get(key)
        if r is not None:
            return r
        step = self._pystep
        sa = step(state, self._f[a], self._v1[a], self._v2[a])
        sb = step(state, self._f[b], self._v1[b], self._v2[b])
        sab = step(sa, self._f[b], self._v1[b], self._v2[b]) \
            if sa is not None else None
        sba = step(sb, self._f[a], self._v1[a], self._v2[a]) \
            if sb is not None else None
        r = sab == sba
        if len(self._memo) > COMMUTE_MEMO_CAP:
            self._memo.clear()
        self._memo[key] = r
        return r

    def child_sleep(self, state, taken: int, base: int) -> int:
        """The sleep mask a child inherits after linearizing ``taken``:
        members of ``base`` (parent sleep + siblings explored first)
        that commute with ``taken`` at the parent state.  Scan is
        popcount-capped — truncation only weakens the prune."""
        out = 0
        scanned = 0
        z = base
        while z and scanned < SLEEP_SCAN_CAP:
            bit = z & -z
            z ^= bit
            scanned += 1
            if self.commutes(state, bit.bit_length() - 1, taken):
                out |= bit
        return out

    def record_prune(self, n: int = 1) -> None:
        self.prunes += n
        _M_SLEEP.inc(n)


def sleep_visit(visited: dict, key, sleep: int) -> int | None:
    """Sleep-aware visited check — the state-caching fix for sleep
    sets (Godefroid), in its tight *missing-transitions* form.

    ``visited[key]`` holds ONE sleep mask: the intersection of every
    sleep set the state was expanded under (what is still guaranteed
    unexplored from it).  An arrival with sleep ``Z``:

      * first visit — record ``Z``, return 0 (expand everything not
        in ``Z``);
      * stored ``Z1 ⊆ Z`` — every transition this arrival would take
        was already taken: covered, return None (skip);
      * otherwise — only ``missing = Z1 \\ Z`` was never taken from
        this state: return it (the caller expands ONLY those
        transitions) and shrink the stored mask to ``Z1 ∩ Z``.  Each
        re-expansion strictly shrinks the stored mask, so a state
        re-expands at most popcount-of-mask times, and only over its
        previously-sleeping transitions.

    With dpor off every sleep is 0 and this degenerates to the plain
    visited set (one visit, never again)."""
    z1 = visited.get(key)
    if z1 is None:
        visited[key] = sleep
        return 0
    if z1 & ~sleep == 0:  # z1 ⊆ sleep: prior visits covered more
        return None
    missing = z1 & ~sleep
    visited[key] = z1 & sleep
    return missing


# ---------------------------------------------------------------------------
# Plan integration (analyze/plan.py's explain() consumes this)
# ---------------------------------------------------------------------------


def plan_block(seq: OpSeq, model: ModelSpec, raw_bound: int,
               hb_analysis=None) -> dict:
    """The static ``dpor`` block for explain(): what the dynamic layer
    would do — duplicate-op edge count, device-mask coverage once those
    edges join the HB map, the dead-value dedup's predicted hit-rate,
    and a sleep-set size bound from static commutation density.  Pure
    description: nothing here touches the live counters."""
    from ..decompose.canonical import dead_value_cutoffs
    from .hb import _TLS, _window_effective, analyze_hb

    n = len(seq)
    out: dict = {"enabled": dpor_enabled(), "applies": n > 0}
    edges = duplicate_op_edges(seq) if n else []
    out["dup_edges"] = len(edges)

    # device-mask coverage: rows carrying >= 1 must-order predecessor
    # once HB edges and duplicate-op edges merge (exactly the rows the
    # device planes will mask).  analyze_hb, not maybe_hb: describing
    # a plan must not feed the live prepass metrics (hb.plan_block's
    # rule).  ``hb_analysis`` lets explain()/explain_batch share one
    # solve instead of re-running it per block.
    hb = (hb_analysis if hb_analysis is not None
          else analyze_hb(seq, model)) if n else None
    must = dict(hb.must_pred) if hb is not None else {}
    for (s, d, _k) in edges:
        must.setdefault(int(d), ())
    out["masked_rows"] = len(must)
    out["mask_coverage"] = round(len(must) / n, 4) if n else 0.0

    # dead-value dedup: the fraction of possible register states whose
    # value dies before the history ends — the dedup hit-rate proxy
    # (a state is collapsible for the whole suffix past its cutoff)
    dv = dead_value_cutoffs(seq, model)
    if dv is None:
        out["dedup"] = {"applies": False}
    else:
        n_det = int(np.asarray(seq.ok, dtype=bool).sum())
        vals = [c for c in dv.cutoffs.values()]
        dead = [c for c in vals if c < n_det]
        out["dedup"] = {
            "applies": True,
            "values": len(vals),
            "dead_values": len(dead),
            "hit_rate_prediction": round(
                sum(max(0, n_det - c) for c in dead)
                / max(1, n_det * max(1, len(vals))), 4),
        }

    # sleep-set size bound: max simultaneously-open state-transparent
    # rows (reads) — the static floor of what the dynamic sets carry
    fam = model.name in ("register", "cas-register", "multi-register")
    if fam and n:
        f = np.asarray(seq.f)
        reads = np.nonzero(f == R_READ)[0]
        events = []
        for i in reads:
            events.append((int(seq.inv[i]), 1))
            events.append((int(seq.ret[i]), -1))
        events.sort()
        cur = peak = 0
        for _t, d in events:
            cur += d
            peak = max(peak, cur)
        out["sleep_set_bound"] = peak
    else:
        out["sleep_set_bound"] = 0

    # pruned-vs-raw bound with the dup edges included (hb reports its
    # own bound; this one adds what the dynamic layer's static edges
    # buy on top)
    if edges and hb is not None and hb.applies and n:
        _TLS.inv = [int(x) for x in seq.inv]
        _TLS.ret = [int(x) for x in seq.ret]
        try:
            all_edges = edges + [
                (s, d, "hb") for d, ss in hb.must_pred.items()
                for s in ss]
            _w_raw, w_eff = _window_effective(seq, all_edges)
        finally:
            _TLS.inv = _TLS.ret = None
        ok = np.asarray(seq.ok, dtype=bool)
        nd = int(ok.sum())
        pruned = min((nd + 1) << (max(0, w_eff - 1) + (n - nd)),
                     raw_bound)
        out["pruned_upper_bound"] = pruned
        out["prune_ratio"] = (round(pruned / raw_bound, 6)
                              if raw_bound else None)
    else:
        out["pruned_upper_bound"] = raw_bound
        out["prune_ratio"] = 1.0
    return out
