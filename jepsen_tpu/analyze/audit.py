"""Certificate audit — independent replay of proof-carrying verdicts.

Every engine route now emits a *certificate* alongside its verdict
(docs/analyze.md "Certificate format"):

  * a ``valid`` result carries ``linearization`` — row indices of the
    checked OpSeq in linearization order — or an explicit
    ``witness_dropped: <reason>`` when the route cannot produce one
    (device BFS keeps no parent chains, cache hits store verdicts only,
    a witness table hit its cap, ...);
  * an ``invalid`` result carries ``final_ops`` — the blocking frontier
    the search exhausted — or an explicit ``frontier_dropped: <reason>``.

This module is the *independent* half of that contract: a pure-Python,
JAX-free O(n) replay of the certificate against the model, sharing no
code with the search engines (the GPUexplore pattern, arXiv:1801.05857:
the accelerated search earns trust by pairing with a cheap host-side
validation of its answer).  A certificate that fails audit means an
engine bug — a kernel miscompile, a bad bucket pad, a wrong cell stitch
in decompose/engine.py — that the verdict alone could never reveal.

W-codes (stable; documented in docs/analyze.md):

==== =================================================================
W001 certificate references an op not in the history (row out of range)
W002 duplicate or missing op (an :ok row absent from the witness, a row
     linearized twice, or a decided verdict with no certificate AND no
     explicit drop reason)
W003 witness violates real-time order (an op linearized before another
     op that returned before it invoked)
W004 model step rejects a witness transition (the linearization is not
     a legal run of the model)
W005 stitched witness violates cross-cell precedence (the decomposed
     merge interleaved two cells against the parent history's real-time
     order)
W006 HB-cycle certificate fails independent validation (an edge is
     unjustified, the chain does not close, or a precondition of the
     unique-writes block algebra does not hold on this history)
==== =================================================================

``audit(history, model, result)`` never raises on a bad certificate —
it *reports*; :func:`maybe_audit` applies the wiring policy (attach the
audit to the result, raise :class:`AuditError` on any W-code) behind the
``audit=True`` / ``JEPSEN_TPU_AUDIT=1`` / CLI ``--audit`` opt-in.
"""

from __future__ import annotations

import os

from ..history import OpSeq, encode_ops
from .lint import Diagnostic

AUDIT_CODES = {
    "W001": "certificate references an op not in the history",
    "W002": "duplicate or missing op in the certificate",
    "W003": "witness violates real-time order",
    "W004": "model step rejects a witness transition",
    "W005": "stitched witness violates cross-cell precedence",
    "W006": "HB-cycle certificate fails independent validation",
    "W007": "queue/set multiset evidence fails independent validation "
            "(lost-acked-enqueue / unexpected-dequeue rows unjustified)",
    "W008": "queue order certificate fails independent validation "
            "(duplicate-delivery or FIFO-inversion/rf-cycle edges "
            "unjustified)",
}


class AuditError(ValueError):
    """A certificate failed its independent audit.  ``diagnostics``
    carries every W-code finding; ``audit`` the full audit dict."""

    def __init__(self, audit: dict):
        self.audit = audit
        self.diagnostics = list(audit.get("diagnostics", ()))
        head = "; ".join(str(d) for d in self.diagnostics[:5])
        more = (f" (+{len(self.diagnostics) - 5} more)"
                if len(self.diagnostics) > 5 else "")
        super().__init__(f"certificate failed audit: {head}{more}")


def audit_enabled() -> bool:
    """The opt-in knob: JEPSEN_TPU_AUDIT=1/true/on/yes turns the
    certificate audit on fleet-wide (engines also take ``audit=``)."""
    return os.environ.get("JEPSEN_TPU_AUDIT", "").strip().lower() in (
        "1", "true", "on", "yes")


def _as_seq(history, model) -> OpSeq:
    if isinstance(history, OpSeq):
        return history
    return encode_ops(history, model.f_codes)


def _audit_witness(seq: OpSeq, model, result: dict, diags: list) -> None:
    """Replay a ``linearization`` certificate: coverage (W001/W002),
    real-time order (W003/W005), model legality (W004)."""
    lin = result["linearization"]
    n = len(seq)
    # W005 needs a row -> cell map; for the key-partitioned (stitched)
    # route the cell IS the key lane, so it is derivable from the
    # history itself — the result does not have to ship a row map
    stitched = bool((result.get("decompose") or {}).get("stitched"))
    cell_of = None
    if stitched and getattr(model, "name", "") == "multi-register":
        cell_of = [int(x) for x in seq.v1]

    seen: set[int] = set()
    rows: list[int] = []
    for pos, r in enumerate(lin):
        if not isinstance(r, int) or isinstance(r, bool) \
                or not 0 <= r < n:
            diags.append(Diagnostic(
                "W001", "error",
                f"witness position {pos} references row {r!r}, not a "
                f"row of this {n}-op history", index=pos))
            continue
        if r in seen:
            diags.append(Diagnostic(
                "W002", "error",
                f"row {r} appears more than once in the witness "
                f"(position {pos})", index=r))
            continue
        seen.add(r)
        rows.append(r)

    ok = seq.ok
    missing = [i for i in range(n) if bool(ok[i]) and i not in seen]
    for i in missing[:8]:
        diags.append(Diagnostic(
            "W002", "error",
            f":ok row {i} is missing from the witness (every ok op "
            f"must linearize)", index=i))
    if len(missing) > 8:
        diags.append(Diagnostic(
            "W002", "error",
            f"...and {len(missing) - 8} more :ok rows missing"))

    # real-time: no witness op may precede an op that returned before
    # it invoked.  One pass tracking the running max invocation rank
    # (and which row holds it): a later row returning below that max
    # was really ordered after its own return.
    inv = [int(x) for x in seq.inv]
    ret = [int(x) for x in seq.ret]
    max_inv = -1
    max_inv_row = -1
    for r in rows:
        if ret[r] < max_inv:
            code, extra = "W003", ""
            if cell_of is not None and cell_of[r] != cell_of[max_inv_row]:
                code = "W005"
                extra = (f" (cells {cell_of[max_inv_row]} vs "
                         f"{cell_of[r]}: the stitch broke cross-cell "
                         f"precedence)")
            diags.append(Diagnostic(
                code, "error",
                f"row {r} (returns at rank {ret[r]}) is linearized "
                f"after row {max_inv_row} (invokes at rank "
                f"{inv[max_inv_row]}) although it returned first"
                f"{extra}", index=r))
        if inv[r] > max_inv:
            max_inv, max_inv_row = inv[r], r

    # model replay — the independent legality check (plain pystep; no
    # engine encodings, no JAX)
    pystep = model.pystep
    state = model.init
    f = seq.f
    v1 = seq.v1
    v2 = seq.v2
    for r in rows:
        ns = pystep(state, int(f[r]), int(v1[r]), int(v2[r]))
        if ns is None:
            op = seq.ops[r] if seq.ops else None
            what = (f"{op.process} {op.f} {op.value!r}" if op is not None
                    else f"f={int(f[r])} v1={int(v1[r])} v2={int(v2[r])}")
            diags.append(Diagnostic(
                "W004", "error",
                f"model {model.name!r} rejects witness step at row {r} "
                f"({what}) from state {tuple(state)}", index=r))
            break  # later steps run from a state that never existed
        state = ns


def _audit_hb_cycle(seq: OpSeq, model, result: dict,
                    diags: list) -> None:
    """Independently re-justify an HB-cycle certificate (analyze/hb.py)
    edge by edge — sharing no code with the solver that emitted it.

    The certificate claims a cycle of FORCED order: each edge must hold
    in every valid linearization, and the chain must close.  Edge
    kinds:

      rt    ret[src] < inv[dst] (real time; self-evident)
      rf    src is THE unique write of value v, dst an :ok read of v
      ww    src's value-block must wholly precede dst's, witnessed by
            ``via=[a, b]`` — a in src's block, b in dst's block,
            ret[a] < inv[b] (block contiguity under unique writes)
      init  src is an :ok read of the initial value (never written),
            dst a member of an anchored write block

    Preconditions re-checked here (W006 when violated): register-family
    model, no cas rows, unique non-NIL non-init writes for every value
    the certificate touches, anchored blocks for ww edges.
    """
    from ..models import R_CAS, R_READ, R_WRITE

    cyc = result["hb_cycle"]
    n = len(seq)

    def bad(msg, index=None):
        diags.append(Diagnostic("W006", "error", msg, index=index))

    if not isinstance(cyc, (list, tuple)) or len(cyc) < 2:
        bad("hb_cycle must be a chain of at least two edges")
        return
    name = getattr(model, "name", "")
    multi = name == "multi-register"
    if name not in ("register", "cas-register", "multi-register"):
        bad(f"model {name!r} is outside the unique-writes block "
            f"algebra the certificate relies on")
        return
    f = [int(x) for x in seq.f]
    if any(x == R_CAS for x in f) and name == "cas-register":
        bad("history contains cas ops: writes are not unique and the "
            "block algebra does not apply")
        return
    inv = [int(x) for x in seq.inv]
    ret = [int(x) for x in seq.ret]
    ok = [bool(x) for x in seq.ok]
    key = [int(x) for x in seq.v1] if multi else [0] * n
    val = [int(x) for x in (seq.v2 if multi else seq.v1)]
    init_of = (lambda k: int(model.init[k])
               if 0 <= k < model.state_width else None) if multi \
        else (lambda k: int(model.init[0]))

    # value -> write rows, for uniqueness + membership checks
    writes: dict = {}
    for i in range(n):
        if f[i] == R_WRITE:
            writes.setdefault((key[i], val[i]), []).append(i)

    def block_of(i):
        """(key, value) block of a row, or None when the row cannot
        belong to one (NIL value, foreign op)."""
        if f[i] not in (R_READ, R_WRITE):
            return None
        from ..history import NIL

        if val[i] == NIL:
            return None
        return (key[i], val[i])

    def block_sound(b, index):
        """Unique, non-init, anchored write block."""
        from ..history import NIL

        ws = writes.get(b, [])
        if len(ws) != 1:
            bad(f"value {b[1]} has {len(ws)} writes — block reasoning "
                f"needs exactly one", index=index)
            return False
        if b[1] == NIL or b[1] == init_of(b[0]):
            bad(f"value {b[1]} collides with NIL/initial value — "
                f"blocks do not apply", index=index)
            return False
        w = ws[0]
        if not ok[w] and not any(
                f[i] == R_READ and ok[i] and block_of(i) == b
                for i in range(n)):
            bad(f"block of value {b[1]} is not anchored (crashed "
                f"write, no :ok read): it need not linearize at all",
                index=index)
            return False
        return True

    rows_ok = True
    for e in cyc:
        for fld in ("src", "dst"):
            r = e.get(fld)
            if not isinstance(r, int) or isinstance(r, bool) \
                    or not 0 <= r < n:
                diags.append(Diagnostic(
                    "W001", "error",
                    f"hb_cycle edge references row {r!r}, not a row "
                    f"of this {n}-op history"))
                rows_ok = False
    if not rows_ok:
        return
    for i, e in enumerate(cyc):
        nxt = cyc[(i + 1) % len(cyc)]
        src, dst, kind = e["src"], e["dst"], e.get("kind")
        if dst != nxt["src"]:
            bad(f"edge {i} ends at row {dst} but edge "
                f"{(i + 1) % len(cyc)} starts at row {nxt['src']} — "
                f"the chain does not close", index=dst)
        if kind == "rt":
            if not ret[src] < inv[dst]:
                bad(f"rt edge {src}->{dst} unjustified: row {src} did "
                    f"not return before row {dst} invoked", index=src)
        elif kind == "rf":
            b = block_of(dst)
            if f[dst] != R_READ or not ok[dst] or b is None:
                bad(f"rf edge {src}->{dst}: row {dst} is not an :ok "
                    f"read of a concrete value", index=dst)
            elif not block_sound(b, src):
                pass
            elif writes[b][0] != src:
                bad(f"rf edge {src}->{dst}: row {src} is not the "
                    f"write of value {b[1]}", index=src)
        elif kind == "ww":
            via = e.get("via") or (src, dst)
            a, b2 = int(via[0]), int(via[1])
            bs, bd = block_of(src), block_of(dst)
            if bs is None or bd is None or bs == bd:
                bad(f"ww edge {src}->{dst}: rows are not members of "
                    f"two distinct value blocks", index=src)
                continue
            if not (block_sound(bs, src) and block_sound(bd, dst)):
                continue
            if block_of(a) != bs or block_of(b2) != bd or \
                    (f[a] == R_READ and not ok[a]) or \
                    (f[b2] == R_READ and not ok[b2]):
                bad(f"ww edge {src}->{dst}: via pair ({a},{b2}) does "
                    f"not witness these blocks", index=src)
            elif not ret[a] < inv[b2]:
                bad(f"ww edge {src}->{dst}: via pair ({a},{b2}) is "
                    f"not a real-time edge", index=a)
        elif kind == "init":
            iv = init_of(key[src])
            from ..history import NIL

            if f[src] != R_READ or not ok[src] or iv is None \
                    or iv == NIL or val[src] != iv:
                bad(f"init edge {src}->{dst}: row {src} is not an "
                    f":ok read of the initial value", index=src)
                continue
            if writes.get((key[src], iv)):
                bad(f"init edge {src}->{dst}: the initial value "
                    f"{iv} is re-written, so init reads are not "
                    f"forced first", index=src)
                continue
            bd = block_of(dst)
            if bd is None or bd[0] != key[src] or bd not in writes \
                    or not block_sound(bd, dst):
                bad(f"init edge {src}->{dst}: row {dst} is not a "
                    f"member of an anchored write block on the same "
                    f"key", index=dst)
        else:
            bad(f"edge {i} has unknown kind {kind!r}", index=src)


def _queue_fs(model) -> tuple[int, int]:
    from ..models import Q_DEQ, Q_ENQ

    return Q_ENQ, Q_DEQ


def _audit_queue_order(seq: OpSeq, model, result: dict,
                       diags: list) -> None:
    """Independently re-justify a queue ORDER certificate
    (analyze/constraints.py) — ``queue_cycle`` (rf/rt/fifo forced-edge
    chain) or ``queue_dup`` (duplicate delivery) — sharing no code
    with the compiler that emitted it.  W008 on any unjustified edge,
    open chain, or incomplete row set."""
    name = getattr(model, "name", "") or ""

    def bad(msg, index=None):
        diags.append(Diagnostic("W008", "error", msg, index=index))

    if not (name.startswith("unordered-queue-")
            or name.startswith("fifo-queue-")):
        bad(f"model {name!r} is outside the queue multiset algebra "
            f"the certificate relies on")
        return
    Q_ENQ, Q_DEQ = _queue_fs(model)
    n = len(seq)
    f = [int(x) for x in seq.f]
    v1 = [int(x) for x in seq.v1]
    ok = [bool(x) for x in seq.ok]
    inv = [int(x) for x in seq.inv]
    ret = [int(x) for x in seq.ret]
    from ..history import NIL

    enq_of: dict = {}
    deq_ok_of: dict = {}
    for i in range(n):
        if v1[i] == NIL:
            continue
        if f[i] == Q_ENQ:
            enq_of.setdefault(v1[i], []).append(i)
        elif f[i] == Q_DEQ and ok[i]:
            deq_ok_of.setdefault(v1[i], []).append(i)

    dup = result.get("queue_dup")
    if dup is not None:
        deqs = sorted(int(r) for r in dup.get("dequeues", ()))
        enqs = sorted(int(r) for r in dup.get("enqueues", ()))
        if any(not 0 <= r < n for r in (*deqs, *enqs)):
            diags.append(Diagnostic(
                "W001", "error",
                f"queue_dup references a row outside this {n}-op "
                f"history"))
            return
        if not deqs:
            bad("queue_dup names no dequeue rows")
            return
        val = v1[deqs[0]]
        if deqs != sorted(deq_ok_of.get(val, ())):
            bad(f"queue_dup dequeue rows are not exactly the :ok "
                f"dequeues of value {val}", index=deqs[0])
        elif enqs != sorted(enq_of.get(val, ())):
            bad(f"queue_dup enqueue rows are not exactly the enqueue "
                f"rows of value {val}", index=deqs[0])
        elif len(deqs) <= len(enqs):
            bad(f"value {val} has {len(enqs)} enqueue row(s) for "
                f"{len(deqs)} :ok dequeue(s) — no duplicate delivery",
                index=deqs[0])
        return

    cyc = result.get("queue_cycle")
    if not isinstance(cyc, (list, tuple)) or len(cyc) < 2:
        bad("queue_cycle must be a chain of at least two edges")
        return
    for e in cyc:
        for fld in ("src", "dst"):
            r = e.get(fld)
            if not isinstance(r, int) or isinstance(r, bool) \
                    or not 0 <= r < n:
                diags.append(Diagnostic(
                    "W001", "error",
                    f"queue_cycle edge references row {r!r}, not a row "
                    f"of this {n}-op history"))
                return
    for i, e in enumerate(cyc):
        nxt = cyc[(i + 1) % len(cyc)]
        src, dst, kind = e["src"], e["dst"], e.get("kind")
        if dst != nxt["src"]:
            bad(f"edge {i} ends at row {dst} but edge "
                f"{(i + 1) % len(cyc)} starts at row {nxt['src']} — "
                f"the chain does not close", index=dst)
        if kind == "rt":
            if not ret[src] < inv[dst]:
                bad(f"rt edge {src}->{dst} unjustified: row {src} did "
                    f"not return before row {dst} invoked", index=src)
        elif kind == "rf":
            val = v1[dst]
            if f[dst] != Q_DEQ or not ok[dst] or val == NIL:
                bad(f"rf edge {src}->{dst}: row {dst} is not an :ok "
                    f"dequeue of a concrete value", index=dst)
            elif enq_of.get(val, []) != [src]:
                bad(f"rf edge {src}->{dst}: row {src} is not the "
                    f"unique enqueue of value {val}", index=src)
        elif kind == "fifo":
            if not name.startswith("fifo-queue-"):
                bad(f"fifo edge {src}->{dst} on non-FIFO model "
                    f"{name!r}", index=src)
                continue
            via = e.get("via") or ()
            if len(via) != 2:
                bad(f"fifo edge {src}->{dst} carries no enqueue "
                    f"witness pair", index=src)
                continue
            ei, ej = int(via[0]), int(via[1])
            if not (0 <= ei < n and 0 <= ej < n):
                diags.append(Diagnostic(
                    "W001", "error",
                    f"fifo edge via pair ({ei},{ej}) is outside this "
                    f"{n}-op history"))
                continue
            vi, vj = v1[src], v1[dst]
            if f[src] != Q_DEQ or not ok[src] or f[dst] != Q_DEQ \
                    or not ok[dst] or vi == NIL or vj == NIL \
                    or vi == vj:
                bad(f"fifo edge {src}->{dst}: rows are not :ok "
                    f"dequeues of two distinct values", index=src)
            elif enq_of.get(vi, []) != [ei] \
                    or enq_of.get(vj, []) != [ej]:
                bad(f"fifo edge {src}->{dst}: via pair ({ei},{ej}) is "
                    f"not the unique enqueues of values {vi}/{vj}",
                    index=ei)
            elif not ret[ei] < inv[ej]:
                bad(f"fifo edge {src}->{dst}: enqueue {ei} did not "
                    f"return before enqueue {ej} invoked — FIFO forces "
                    f"nothing", index=ei)
        else:
            bad(f"edge {i} has unknown kind {kind!r}", index=src)


def _audit_queue_evidence_seq(seq: OpSeq, model, result: dict,
                              diags: list) -> None:
    """W007 over an OpSeq-level ``queue_evidence`` certificate: each
    named row must be an :ok dequeue whose value no enqueue row (of any
    status) could have produced."""
    ev = result.get("queue_evidence") or {}
    Q_ENQ, Q_DEQ = _queue_fs(model)
    n = len(seq)
    f = [int(x) for x in seq.f]
    v1 = [int(x) for x in seq.v1]
    ok = [bool(x) for x in seq.ok]
    from ..history import NIL

    enq_vals = {v1[i] for i in range(n) if f[i] == Q_ENQ}
    if ev.get("kind") != "unexpected-dequeue":
        diags.append(Diagnostic(
            "W007", "error",
            f"OpSeq queue evidence of kind {ev.get('kind')!r} is not "
            f"independently checkable (expected unexpected-dequeue)"))
        return
    rows = ev.get("rows") or ()
    if not rows:
        diags.append(Diagnostic(
            "W007", "error", "queue_evidence names no rows"))
        return
    for r in rows:
        if not isinstance(r, int) or isinstance(r, bool) \
                or not 0 <= r < n:
            diags.append(Diagnostic(
                "W001", "error",
                f"queue_evidence references row {r!r}, not a row of "
                f"this {n}-op history"))
            continue
        if f[r] != Q_DEQ or not ok[r] or v1[r] == NIL:
            diags.append(Diagnostic(
                "W007", "error",
                f"row {r} is not an :ok dequeue of a concrete value",
                index=r))
        elif v1[r] in enq_vals:
            diags.append(Diagnostic(
                "W007", "error",
                f"row {r} dequeues value {v1[r]}, which some enqueue "
                f"row could have produced — not unexpected", index=r))


def _audit_multiset_evidence(ops, result: dict, diags: list) -> None:
    """W007 over EVENT-level multiset evidence (the streamed
    total-queue/set fold's certificate): re-derive lost / unexpected
    from the raw history — independently of both the fold and the
    post-hoc checker — and check every named event row justifies the
    claimed kind."""
    ev = result.get("queue_evidence") or {}
    kind = ev.get("kind")
    rows = list(ev.get("rows") or ())
    n = len(ops)

    def bad(msg, index=None):
        diags.append(Diagnostic("W007", "error", msg, index=index))

    if not rows:
        bad("multiset evidence names no rows")
        return
    for r in rows:
        if not isinstance(r, int) or isinstance(r, bool) \
                or not 0 <= r < n:
            diags.append(Diagnostic(
                "W001", "error",
                f"multiset evidence references event {r!r}, not an "
                f"event of this {n}-event history"))
            return
    from collections import Counter

    attempts: set = set()
    acked: Counter = Counter()      # :ok enqueues per value
    delivered: Counter = Counter()  # :ok dequeues/drained per value
    last_read: set | None = None
    for op in ops:
        if not isinstance(op.process, int):
            continue
        if op.type == "invoke" and op.f in ("enqueue", "add"):
            attempts.add(op.value)
        elif op.type == "ok" and op.f == "enqueue":
            acked[op.value] += 1
        elif op.type == "ok" and op.f == "dequeue":
            delivered[op.value] += 1
        elif op.type == "ok" and op.f == "drain" \
                and isinstance(op.value, (list, tuple)):
            delivered.update(op.value)
        elif op.type == "ok" and op.f == "read":
            last_read = set(op.value or ())
    if kind == "unexpected-dequeue":
        for r in rows:
            op = ops[r]
            if op.type != "ok" or op.f not in ("dequeue", "drain"):
                bad(f"event {r} is not an :ok dequeue/drain", index=r)
                continue
            got = op.value if op.f == "dequeue" \
                else list(op.value or ())
            vals = got if isinstance(got, list) else [got]
            if all(v in attempts for v in vals):
                bad(f"event {r}'s value(s) were all attempted by some "
                    f"enqueue — not unexpected", index=r)
    elif kind == "lost-acked-enqueue":
        for r in rows:
            op = ops[r]
            if op.type != "ok" or op.f != "enqueue":
                bad(f"event {r} is not an :ok enqueue", index=r)
            elif delivered[op.value] >= acked[op.value]:
                # multiset semantics, as the checker counts: a value
                # is lost only while its acked copies outnumber its
                # delivered ones (a duplicate payload with one copy
                # delivered and one lost IS lost)
                bad(f"event {r}'s value {op.value!r} was delivered as "
                    f"often as it was acked — not lost", index=r)
    elif kind == "unexpected-member":
        if last_read is None:
            bad("unexpected-member evidence on a history with no :ok "
                "read")
            return
        if not (last_read - attempts):
            bad("every member of the final read was attempted by some "
                "add — not unexpected")
    elif kind == "lost-acked-add":
        if last_read is None:
            bad("lost-acked-add evidence on a history with no :ok read")
            return
        for r in rows:
            op = ops[r]
            if op.type != "ok" or op.f != "add":
                bad(f"event {r} is not an :ok add", index=r)
            elif op.value in last_read:
                bad(f"event {r}'s value {op.value!r} appears in the "
                    f"final read — not lost", index=r)
    else:
        bad(f"unknown multiset evidence kind {kind!r}")


def audit_events(history, result: dict) -> dict:
    """Audit one MODEL-LESS (event-level, multiset-semantics) result —
    the streamed total-queue/set fold's certificate contract.  Same
    return shape as :func:`audit`.  Lenient where the multiset
    checkers themselves carry no certificate: an invalid verdict with
    no ``queue_evidence`` is reported as unchecked, not failed."""
    ops = list(history or ())
    diags: list[Diagnostic] = []
    out: dict = {"ok": True, "checked": "undecided", "codes": [],
                 "diagnostics": diags, "witness_ops": None}
    if result.get("valid") is False:
        if result.get("queue_evidence") is not None:
            out["checked"] = "queue_evidence"
            _audit_multiset_evidence(ops, result, diags)
        else:
            out["checked"] = "no_certificate"
    elif result.get("valid") is True:
        out["checked"] = "multiset"
    out["codes"] = sorted({d.code for d in diags})
    out["ok"] = not diags
    return out


def maybe_audit_events(history, result: dict,
                       audit_flag: bool | None = None) -> dict:
    """The event-level twin of :func:`maybe_audit` (the streamed fold's
    postamble): same opt-in, same attach-and-raise policy."""
    if not (audit_flag if audit_flag is not None else audit_enabled()):
        return result
    a = audit_events(history, result)
    result["audit"] = _summary(a)
    if not a["ok"]:
        raise AuditError(a)
    return result


def audit(history, model, result: dict) -> dict:
    """Audit one engine result's certificate.  Returns::

        {"ok": bool, "checked": what-was-audited, "codes": [...],
         "diagnostics": [Diagnostic...], "witness_ops": n | None}

    ``checked`` is ``"linearization"`` (full replay ran),
    ``"witness_dropped"`` / ``"frontier_dropped"`` (explicit drop reason
    accepted, nothing to replay), ``"final_ops"`` (frontier rows
    range-checked), or ``"undecided"``.  Never raises on a bad
    certificate — :func:`maybe_audit` applies the raising policy.
    """
    if model is None:
        # model-less (multiset-semantics) result: the event-level
        # audit owns it — there is no OpSeq encoding to replay
        return audit_events(history, result)
    seq = _as_seq(history, model)
    diags: list[Diagnostic] = []
    v = result.get("valid")
    out: dict = {"ok": True, "checked": "undecided", "codes": [],
                 "diagnostics": diags, "witness_ops": None}

    if v is True:
        lin = result.get("linearization")
        if lin is None:
            out["checked"] = "witness_dropped"
            reason = result.get("witness_dropped")
            if reason is None:
                diags.append(Diagnostic(
                    "W002", "error",
                    "valid verdict carries neither `linearization` nor "
                    "a `witness_dropped` reason — the certificate "
                    "contract requires one of the two"))
            else:
                out["witness_dropped"] = reason
        else:
            out["checked"] = "linearization"
            out["witness_ops"] = len(lin)
            _audit_witness(seq, model, result, diags)
    elif v is False:
        frontier = result.get("final_ops")
        if result.get("hb_cycle") is not None:
            out["checked"] = "hb_cycle"
            _audit_hb_cycle(seq, model, result, diags)
        elif result.get("queue_cycle") is not None \
                or result.get("queue_dup") is not None:
            out["checked"] = "queue_order"
            _audit_queue_order(seq, model, result, diags)
        elif result.get("queue_evidence") is not None:
            out["checked"] = "queue_evidence"
            _audit_queue_evidence_seq(seq, model, result, diags)
        elif frontier is None:
            out["checked"] = "frontier_dropped"
            reason = result.get("frontier_dropped")
            if reason is None:
                diags.append(Diagnostic(
                    "W002", "error",
                    "invalid verdict carries neither `final_ops`, an "
                    "`hb_cycle`, nor a `frontier_dropped` reason — the "
                    "certificate contract requires one of the three"))
            else:
                out["frontier_dropped"] = reason
        else:
            out["checked"] = "final_ops"
            n = len(seq)
            for r in frontier:
                if not isinstance(r, int) or isinstance(r, bool) \
                        or not 0 <= r < n:
                    diags.append(Diagnostic(
                        "W001", "error",
                        f"blocking frontier references row {r!r}, not a "
                        f"row of this {n}-op history"))

    out["codes"] = sorted({d.code for d in diags})
    out["ok"] = not diags
    return out


def _summary(a: dict) -> dict:
    """The JSON-serializable form attached to result dicts."""
    out = {"ok": a["ok"], "checked": a["checked"], "codes": a["codes"]}
    if a.get("witness_ops") is not None:
        out["witness_ops"] = a["witness_ops"]
    if not a["ok"]:
        out["diagnostics"] = [d.to_dict() for d in a["diagnostics"]]
    return out


def maybe_audit(seq, model, result: dict,
                audit_flag: bool | None = None) -> dict:
    """The engines' shared audit postamble: resolve the three-state
    ``audit`` flag (None follows JEPSEN_TPU_AUDIT, default off), run the
    audit, attach the summary as ``result["audit"]``, and raise
    :class:`AuditError` on any W-code — a certificate its own engine
    cannot replay is an engine bug, and opting into the audit means
    wanting it loud.  ONE home for the policy, mirroring
    ``lint.maybe_lint``."""
    if not (audit_flag if audit_flag is not None else audit_enabled()):
        return result
    a = audit(seq, model, result)
    result["audit"] = _summary(a)
    if not a["ok"]:
        raise AuditError(a)
    return result
