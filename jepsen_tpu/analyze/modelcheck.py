"""Bounded model checker for the live backends — exhaustive
interleaving exploration with sleep-set reduction and schedule
certificates.

The live campaign only ever *samples* interleavings: real processes,
real clocks, a nemesis rolling dice.  This module lifts the SAME
state machines the daemons run — :class:`~jepsen_tpu.live.
replicated_server.ReplicaCore`, :class:`~jepsen_tpu.live.
replicated_queue.QueueCore`, and a localnode-style lock store — into
a single-threaded deterministic scheduler and explores every
schedule at a bounded scope (nodes x client ops x crashes x
partitions x max events), in the GPUexplore spirit
(arXiv:1801.05857): a cheap exhaustive search finds the violation, a
slow independent validator (the linearizability engine + audit)
confirms it.

**The event model.**  A schedule is a sequence of atomic events:

  ``hb i``        leader i runs one heartbeat round (step-down on an
                  expired lease, else ping fan-out + lease renewal)
  ``campaign i``  the logical clock jumps to node i's election-timer
                  expiry and i runs one full election round (ballots
                  + win/lose, winner heartbeats once)
  ``op i``        node i serves the NEXT client op of the scoped
                  program (enabled only while i believes it serves)
  ``crash i``     kill -9: node i's process state vanishes
  ``restart i``   node i boots a fresh core and replays the shared
                  oplog (which the volatile seeded mode left empty)
  ``isolate i``   the partitioner cuts every link touching i
  ``heal``        all links restored

An RPC round (ballots, ping fan-out, append replication) executes
atomically inside its event — the abstraction under-approximates
message-level interleavings but keeps every schedule the *process*
scheduler and the nemesis can produce, which is exactly the space
the live campaign samples.  Time is a logical clock that only
``campaign`` advances (to the precise instant the timer fires): the
scheduler can starve a leader's heartbeat past its lease, which is
the pause/partition behaviour the lease protocol must survive.

**Invariants** (stable MC1xx codes, :data:`MC_CODES`): election
safety under the leader lease, durability of majority-acked writes,
at-least-once redelivery without invention, no-double-grant for
locks.  State-level violations are completed into *client-visible*
histories by probe ops (a read at each offending leader, a drain at
a lossy queue leader), so every certificate renders as a jepsen
history the linearizability engine independently re-checks invalid
and ``analyze/audit.py`` confirms.

**Schedule certificates.**  Every violation emits::

    {"code": "MC1xx", "family": ..., "mode": ..., "scope": {...},
     "schedule": [["campaign", 0], ["op", 0], ...],
     "history": [op dicts], "shrunk": {ddmin stats},
     "confirm": {engine + audit verdicts}, "state": fingerprint-id}

replayable via ``python -m jepsen_tpu.analyze --mc --replay CERT``
(deterministic: same schedule, same world, same violation) and
banked into live/corpus.py.  ``analyze/shrink.py``'s generic
:func:`~jepsen_tpu.analyze.shrink.ddmin_list` minimizes the schedule
first — the lifecycle is explore -> confirm -> shrink -> bank.

**Reduction.**  Sleep sets with concrete commutation (clone the
world, execute both orders, compare fingerprints), composed with the
visited memo through :func:`~jepsen_tpu.analyze.dpor.sleep_visit` —
the same state-caching antichain the engine DFS uses.  Sleep sets
prune *transitions*, never states, so the violation set is provably
identical with the reduction off (``dpor=False``) — the soundness
test asserts bit-identity.  Clean runs emit the explored-scope block
(states, schedules, prune ratio, completeness) and ``jtpu_mc_*``
metrics: a clean verdict names exactly what it proved.
"""

from __future__ import annotations

import hashlib
import json
from collections import OrderedDict
from dataclasses import asdict, dataclass, field, replace

from ..history import Op, fail_op, info_op, invoke_op, ok_op
from ..live.replicated_queue import QueueCore
from ..live.replicated_server import ReplicaCore
from ..obs.metrics import REGISTRY
from .dpor import resolve_dpor, sleep_visit

MC_CODES = {
    "MC101": "election safety: two serving leaders answer with "
             "divergent state on an acked key",
    "MC102": "durability: a serving leader's state lost or rewrote "
             "a majority-acked write",
    "MC103": "stale read: a client read returned a value outside "
             "the possible set (acked + indeterminate writes)",
    "MC104": "lost enqueue: a client-acked job vanished from the "
             "serving leader (not acked, not pending, not claimed)",
    "MC105": "invented delivery: a dequeue returned a job that was "
             "never added or was already acked",
    "MC106": "double grant: the lock server granted while another "
             "client still holds an unreleased grant",
    "MC201": "non-idempotent retry: one client op (one request id) "
             "committed twice across a retransmission",
    "MC202": "acked reply lost then lied: a committed write's retry "
             "was answered with a failure",
    "MC203": "proxy loop: a forwarded client request was re-forwarded "
             "past every node in the mesh",
    "MC204": "session leak: a connection reset left server-side "
             "session state (a claim) owned by a dead connection",
    "MC205": "stale-leader serving: a deposed leader answered a "
             "proxied/direct read outside the possible set",
}

_M_STATES = REGISTRY.counter(
    "jtpu_mc_states_total",
    "Model-checker states expanded across all runs")
_M_SCHED = REGISTRY.counter(
    "jtpu_mc_schedules_total",
    "Model-checker maximal schedules completed (depth bound, "
    "quiescence, or violation)")
_M_VIOL = REGISTRY.counter(
    "jtpu_mc_violations_total",
    "Model-checker invariant violations found, by MC code",
    ("code",))
_M_PRUNE = REGISTRY.counter(
    "jtpu_mc_sleep_prunes_total",
    "Model-checker transitions skipped by sleep sets (covered by an "
    "already-explored commuting sibling)")
_M_RATIO = REGISTRY.gauge(
    "jtpu_mc_prune_ratio",
    "Sleep-set prune ratio of the last model-checker run "
    "(prunes / (prunes + executed transitions))")

#: logical-time nudge past a timer threshold (strict inequalities in
#: election_due)
EPS = 1e-3

FAMILIES = ("replicated", "rqueue", "lock")
MODES = {
    "replicated": ("clean", "volatile", "split-brain"),
    "rqueue": ("clean", "volatile"),
    "lock": ("clean", "volatile"),
}

#: the shell-layer scope (analyze/simnet.py): the daemons' actual
#: request-dispatch code paths under a simulated transport.  Seeded
#: modes re-open the retry-idempotency / session-lifecycle bugs the
#: live shells fix; clean modes prove the fixed shells hold at the
#: same bounds.
SHELL_FAMILIES = ("shell-kv", "shell-queue", "shell-replicated",
                  "shell-rqueue")
SHELL_MODES = {
    "shell-kv": ("clean", "volatile"),
    "shell-queue": ("clean", "volatile", "session-leak"),
    "shell-replicated": ("clean", "proxy-loop", "stale-proxy"),
    "shell-rqueue": ("clean", "volatile"),
}
ALL_FAMILIES = FAMILIES + SHELL_FAMILIES
ALL_MODES = {**MODES, **SHELL_MODES}

#: the one key the kv program exercises — a single register is where
#: every seeded backend defect already shows
KEY = "x"

#: how an absent key renders in a certificate history.  A nil-valued
#: read is a WILDCARD to the cas-register model (knossos: unknown
#: value), so a lost write probed as None would confirm engine-valid;
#: rendering absence as the concrete 0 against ``register(initial=0)``
#: makes it count — which is why kv program write values must be
#: non-zero
ABSENT = 0


@dataclass(frozen=True)
class Scope:
    """The exploration bounds — the certificate's 'what was proven'
    block.  ``ops`` is the client program: ``("w", v)`` / ``("r",)``
    for the kv family, ``("add", body)`` / ``("get",)`` / ``("ack",)``
    for the queue, ``("lock", client)`` / ``("unlock", client)`` for
    the lock family (lock clients run their own sub-programs and
    interleave; the other families serve one sequential program)."""

    nodes: int = 3
    ops: tuple = field(default_factory=tuple)
    crashes: int = 0
    partitions: int = 0
    max_events: int = 6
    #: which nodes may crash / be isolated: "leader" bites the
    #: interesting node, "any" widens the space
    crash_targets: str = "leader"
    isolate_targets: str = "leader"
    #: exploration budget; past it the run reports complete=False
    max_states: int = 200_000

    def to_dict(self) -> dict:
        d = asdict(self)
        d["ops"] = [list(o) for o in self.ops]
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Scope":
        d = dict(d)
        d["ops"] = tuple(tuple(o) for o in d.get("ops", ()))
        known = {f for f in cls.__dataclass_fields__}
        return cls(**{k: v for k, v in d.items() if k in known})


def default_scope(family: str, mode: str) -> Scope:
    """The bounded scope each seeded defect is reachable in (and the
    clean twin must clear): hand-derived from the shortest known
    violating schedule per mode, one event of slack."""
    if family == "lock":
        return Scope(nodes=1,
                     ops=(("lock", 0), ("unlock", 0), ("lock", 1)),
                     crashes=1, max_events=6)
    if family == "rqueue":
        return Scope(nodes=3, ops=(("add", 1),), crashes=1,
                     max_events=6)
    if family == "shell-kv":
        # drop(reply) + retry: one partition token, depth 7
        return Scope(nodes=1, ops=(("cas", 1, 2),), crashes=0,
                     partitions=1, max_events=7)
    if family == "shell-queue":
        # MC204 needs: add acked (3) + get claimed (2) + reset (1)
        # + retry/deliver/deliver (3) = 9 events
        return Scope(nodes=1, ops=(("add", 1), ("get",)), crashes=1,
                     partitions=1, max_events=9)
    if family == "shell-rqueue":
        return Scope(nodes=2, ops=(("add", 1),), crashes=0,
                     partitions=1, max_events=7)
    if family == "shell-replicated":
        if mode == "proxy-loop":
            # elect, learn, elect, op — two leadership moves
            return Scope(nodes=3, ops=(("w", 1),), crashes=2,
                         max_events=6)
        return Scope(nodes=3, ops=(("w", 1), ("w", 2), ("r",)),
                     crashes=1, max_events=6)
    if mode == "split-brain":
        return Scope(nodes=3, ops=(("w", 1), ("w", 2)), crashes=0,
                     partitions=1, max_events=6)
    return Scope(nodes=3, ops=(("w", 1),), crashes=1, max_events=6)


# ---------------------------------------------------------------------------
# Worlds: the lifted state machines behind one scheduling protocol
# (enabled / execute / clone / fingerprint)
# ---------------------------------------------------------------------------


class ClusterWorld:
    """The replicated kv / queue cluster under the deterministic
    scheduler: N live cores, a shared in-memory oplog standing in for
    the fsync'd file (appends skipped in volatile mode, exactly like
    ``DurableLog``), a symmetric link-cut set, and the client-visible
    ledger the invariants are phrased over."""

    def __init__(self, family: str, mode: str, scope: Scope):
        self.family = family
        self.mode = mode
        self.scope = scope
        self.volatile = mode == "volatile"
        self.split_brain = mode == "split-brain"
        self.core_cls = QueueCore if family == "rqueue" \
            else ReplicaCore
        n = scope.nodes
        self.alive = [True] * n
        self.log: list[dict] = []
        self.log_pos = [0] * n
        self.cut: frozenset = frozenset()
        self.clock = 0.0
        self.op_i = 0
        self.crashes_used = 0
        self.partitions_used = 0
        self.history: list[Op] = []
        self.t = 0
        # the client-visible write ledger the invariants close over
        self.committed: dict = {}   # key -> last acked write value
        self.maybes: dict = {}      # key -> :info writes since it
        self.added_ok: dict = {}    # jid -> body, client-acked adds
        self.added_info: dict = {}  # jid -> body, indeterminate adds
        self.acked: set = set()     # jids the server acked as done
        self.last_jid: str | None = None
        self.cores = [self._fresh_core(i) for i in range(n)]

    # -- construction / cloning ---------------------------------------

    def _fresh_core(self, i: int):
        core = self.core_cls(
            i, self.scope.nodes, lease_s=1.0, volatile=self.volatile,
            split_brain=self.split_brain, now=self.clock)
        self._bind(core, i)
        return core

    def _bind(self, core, i: int) -> None:
        """The core's injected catch_up: replay the shared-log tail —
        the model-checker twin of Replica._catch_up_locked."""

        def catch_up() -> int:
            applied = 0
            while self.log_pos[i] < len(self.log):
                e = self.log[self.log_pos[i]]
                self.log_pos[i] += 1
                if core.wants(e):
                    core.apply(e)
                    applied += 1
            return applied

        core.catch_up = catch_up

    def _clone_core(self, core):
        c = object.__new__(type(core))
        c.__dict__.update(core.__dict__)
        c.state = dict(core.state)
        if isinstance(core, QueueCore):
            c.pending = OrderedDict(core.pending)
            c.claimed = dict(core.claimed)
        return c

    def clone(self) -> "ClusterWorld":
        w = object.__new__(type(self))
        w.__dict__.update(self.__dict__)
        w.alive = list(self.alive)
        w.log = list(self.log)  # entries are append-only, share refs
        w.log_pos = list(self.log_pos)
        w.history = list(self.history)
        w.committed = dict(self.committed)
        w.maybes = {k: list(v) for k, v in self.maybes.items()}
        w.added_ok = dict(self.added_ok)
        w.added_info = dict(self.added_info)
        w.acked = set(self.acked)
        w.cores = [self._clone_core(c) for c in self.cores]
        for i, c in enumerate(w.cores):
            w._bind(c, i)
        return w

    def fingerprint(self) -> tuple:
        """Hashable machine + ledger state.  Dead cores collapse to
        None (a restart rebuilds from the log, so their frozen state
        cannot influence any future) — which is also what lets a
        crash commute with events on the surviving majority."""
        return (
            tuple(c.snapshot() if a else None
                  for c, a in zip(self.cores, self.alive)),
            tuple(sorted(tuple(sorted(p)) for p in self.cut)),
            round(self.clock, 6), self.op_i,
            self.crashes_used, self.partitions_used, len(self.log),
            self.last_jid,
            tuple(sorted(self.committed.items())),
            tuple(sorted((k, tuple(v))
                         for k, v in self.maybes.items())),
            tuple(sorted(self.added_ok.items())),
            tuple(sorted(self.added_info.items())),
            tuple(sorted(self.acked)),
        )

    # -- scheduling protocol ------------------------------------------

    def _connected(self, i: int):
        return [j for j in range(len(self.cores))
                if j != i and self.alive[j]
                and frozenset((i, j)) not in self.cut]

    def _next_verb(self):
        if self.op_i < len(self.scope.ops):
            return self.scope.ops[self.op_i]
        return None

    def enabled(self) -> list[tuple]:
        evs: list[tuple] = []
        s = self.scope
        verb = self._next_verb()
        for i, core in enumerate(self.cores):
            if not self.alive[i]:
                evs.append(("restart", i))
                continue
            if core.role == "leader":
                evs.append(("hb", i))
            else:
                evs.append(("campaign", i))
            if verb is not None and core.leader_serving(self.clock) \
                    and not (verb[0] == "ack" and self.last_jid is None):
                evs.append(("op", i))
            if self.crashes_used < s.crashes and (
                    s.crash_targets == "any" or core.role == "leader"):
                evs.append(("crash", i))
            if not self.cut and self.partitions_used < s.partitions \
                    and (s.isolate_targets == "any"
                         or core.role == "leader"):
                evs.append(("isolate", i))
        if self.cut:
            evs.append(("heal", 0))
        return evs

    def execute(self, ev: tuple) -> dict | None:
        """Run one event; returns a violation dict or None.  Probe
        ops completing a state-level violation into a client-visible
        history are appended before returning."""
        kind, i = ev
        v = None
        if kind == "hb":
            self._exec_hb(i)
        elif kind == "campaign":
            self._exec_campaign(i)
        elif kind == "crash":
            self.alive[i] = False
            self.crashes_used += 1
        elif kind == "restart":
            self.alive[i] = True
            self.log_pos[i] = 0
            self.cores[i] = self._fresh_core(i)
            self.cores[i].catch_up()
        elif kind == "isolate":
            self.cut = frozenset(
                frozenset((i, j)) for j in range(len(self.cores))
                if j != i)
            self.partitions_used += 1
        elif kind == "heal":
            self.cut = frozenset()
        elif kind == "op":
            v = self._exec_op(i)
        return v or self._state_violation()

    # -- cluster event bodies -----------------------------------------

    def _exec_hb(self, i: int) -> None:
        core = self.cores[i]
        if core.step_leader_expiry(self.clock):
            return
        term = core.term
        acks = 1
        for j in self._connected(i):
            r = self.cores[j].on_ping(term, i, core.seq, self.clock)
            if r.get("granted"):
                acks += 1
        if acks >= core.majority():
            core.heartbeat_ack(term, self.clock)

    def _exec_campaign(self, i: int) -> None:
        core = self.cores[i]
        due = core.lease_until + core.election_timeout() \
            - core.lease_s + EPS
        self.clock = max(self.clock, due)
        if not core.election_due(self.clock):
            return
        term, seq = core.begin_campaign()
        votes = 1
        for j in self._connected(i):
            r = self.cores[j].on_vote(term, i, seq, self.clock)
            if r.get("granted"):
                votes += 1
        if votes >= core.majority():
            if core.win_campaign(term, self.clock):
                self._exec_hb(i)  # the shell heartbeats on a win
        else:
            core.lose_campaign(self.clock, 0.0)

    def _commit(self, i: int, entry: dict) -> bool:
        """The commit protocol under the scheduler: shared-log append
        (skipped when volatile — DurableLog's no-op), replication
        fan-out over uncut links, majority required."""
        core = self.cores[i]
        if not self.volatile:
            self.log.append(entry)
        acks = 1
        for j in self._connected(i):
            st, _ = self.cores[j].on_append(entry, self.clock)
            if st < 400:
                acks += 1
        if acks >= core.majority():
            core.apply(entry)
            return True
        return False

    # -- client ops + history rendering -------------------------------

    def _h(self, ctor, process, f, value=None) -> None:
        self.history.append(ctor(process, f, value, time=self.t))
        self.t += 1

    def _possible(self, k) -> set:
        poss = set(self.maybes.get(k, ()))
        poss.add(self.committed.get(k))  # None before any acked write
        return poss

    def _exec_op(self, i: int) -> dict | None:
        verb = self.scope.ops[self.op_i]
        self.op_i += 1
        core = self.cores[i]
        if verb[0] == "w":
            val = verb[1]
            if val == ABSENT:
                raise ValueError("kv write values must be non-zero "
                                 "(0 renders key absence)")
            self._h(invoke_op, 0, "write", val)
            st, _body, entry = core.put_prepare(KEY, val, None,
                                                self.clock)
            if entry is None:
                self._h(fail_op, 0, "write", val)
            elif self._commit(i, entry):
                self.committed[KEY] = val
                self.maybes[KEY] = []
                self._h(ok_op, 0, "write", val)
            else:
                self.maybes.setdefault(KEY, []).append(val)
                self._h(info_op, 0, "write", val)
            return None
        if verb[0] == "r":
            self._h(invoke_op, 0, "read")
            st, body = core.get(KEY, self.clock)
            if st == 503:
                self._h(fail_op, 0, "read")
                return None
            val = None if st == 404 else body["node"]["value"]
            self._h(ok_op, 0, "read",
                    ABSENT if val is None else val)
            if val not in self._possible(KEY):
                return {"code": "MC103",
                        "detail": f"node {i} served read {val!r}; "
                                  f"possible was "
                                  f"{sorted(map(repr, self._possible(KEY)))}"}
            return None
        if verb[0] == "add":
            body_v = verb[1]
            self._h(invoke_op, 0, "enqueue", body_v)
            st, jid, entry = core.addjob_prepare(body_v, 10.0,
                                                 self.clock)
            if entry is None:
                self._h(fail_op, 0, "enqueue", body_v)
            elif self._commit(i, entry):
                self.added_ok[jid] = body_v
                self._h(ok_op, 0, "enqueue", body_v)
            else:
                self.added_info[jid] = body_v
                self._h(info_op, 0, "enqueue", body_v)
            return None
        if verb[0] == "get":
            core.expire_claims(self.clock)
            got = core.claim(self.clock)
            self._h(invoke_op, 0, "dequeue")
            if got is None:
                self._h(fail_op, 0, "dequeue")
                return None
            jid, body_v = got
            self.last_jid = jid
            self._h(ok_op, 0, "dequeue", body_v)
            if jid in self.acked or (jid not in self.added_ok
                                     and jid not in self.added_info):
                return {"code": "MC105",
                        "detail": f"node {i} delivered {jid} "
                                  f"(acked or never added)"}
            return None
        if verb[0] == "ack":
            jid = self.last_jid
            st, _n, entry = core.ackjob_prepare(jid, self.clock)
            if entry is not None and self._commit(i, entry):
                self.acked.add(jid)
            return None
        raise ValueError(f"unknown program verb {verb!r}")

    # -- invariants ----------------------------------------------------

    def _probe_read(self, i: int) -> None:
        val = self.cores[i].state.get(KEY)
        self._h(invoke_op, 0, "read")
        self._h(ok_op, 0, "read", ABSENT if val is None else val)

    def _probe_drain(self, i: int) -> None:
        core = self.cores[i]
        bodies = [b for b, _ in core.pending.values()] \
            + [b for b, _r, _t in core.claimed.values()]
        self._h(invoke_op, 0, "drain")
        self._h(ok_op, 0, "drain", bodies)

    def _state_violation(self) -> dict | None:
        serving = [i for i in range(len(self.cores))
                   if self.alive[i]
                   and self.cores[i].leader_serving(self.clock)]
        if self.family == "rqueue":
            for i in serving:
                core = self.cores[i]
                lost = [j for j in self.added_ok
                        if j not in self.acked
                        and j not in core.pending
                        and j not in core.claimed]
                if lost:
                    self._probe_drain(i)
                    return {"code": "MC104",
                            "detail": f"leader {i} lost acked "
                                      f"job(s) {sorted(lost)}"}
            return None
        # kv family
        if len(serving) > 1:
            for k in self.committed:
                vals = {self.cores[i].state.get(k) for i in serving}
                if len(vals) > 1:
                    for i in serving:
                        self._probe_read(i)
                    return {"code": "MC101",
                            "detail": f"serving leaders {serving} "
                                      f"diverge on {k!r}: "
                                      f"{sorted(map(repr, vals))}"}
        for i in serving:
            for k in set(self.committed) | set(self.maybes):
                val = self.cores[i].state.get(k)
                if val not in self._possible(k):
                    self._probe_read(i)
                    return {"code": "MC102",
                            "detail": f"leader {i} holds {val!r} for "
                                      f"{k!r}; possible was "
                                      f"{sorted(map(repr, self._possible(k)))}"}
        return None


class LockWorld:
    """The localnode-style lock server under the scheduler: one
    store, a durable grant log (skipped when volatile — the seeded
    forget-on-kill defect), and per-client programs that interleave.
    Client ops stay enabled against a dead server (connection
    refused -> :fail), which is also what lets a no-op BUSY attempt
    commute with a crash."""

    family = "lock"

    def __init__(self, family: str, mode: str, scope: Scope):
        self.mode = mode
        self.scope = scope
        self.volatile = mode == "volatile"
        self.alive = True
        self.holder = None
        self.log: list[tuple] = []
        self.crashes_used = 0
        self.progs: dict[int, list] = {}
        for verb, client in scope.ops:
            self.progs.setdefault(int(client), []).append(verb)
        self.prog_i = {c: 0 for c in self.progs}
        self.believed: set = set()  # clients holding an :ok grant
        self.history: list[Op] = []
        self.t = 0

    def clone(self) -> "LockWorld":
        w = object.__new__(type(self))
        w.__dict__.update(self.__dict__)
        w.log = list(self.log)
        w.prog_i = dict(self.prog_i)
        w.believed = set(self.believed)
        w.history = list(self.history)
        return w

    def fingerprint(self) -> tuple:
        return (self.alive, self.holder, len(self.log),
                self.crashes_used,
                tuple(sorted(self.prog_i.items())),
                tuple(sorted(self.believed)))

    def enabled(self) -> list[tuple]:
        evs = [("op", c) for c in sorted(self.progs)
               if self.prog_i[c] < len(self.progs[c])]
        if self.alive:
            if self.crashes_used < self.scope.crashes:
                evs.append(("crash", 0))
        else:
            evs.append(("restart", 0))
        return evs

    def _h(self, ctor, process, f, value=None) -> None:
        self.history.append(ctor(process, f, value, time=self.t))
        self.t += 1

    def execute(self, ev: tuple) -> dict | None:
        kind, c = ev
        if kind == "crash":
            self.alive = False
            self.holder = None  # in-memory grant table gone
            self.crashes_used += 1
            return None
        if kind == "restart":
            self.alive = True
            self.holder = None
            for rec in self.log:  # durable replay; empty if volatile
                if rec[0] == "L":
                    self.holder = rec[1]
                elif rec[0] == "U":
                    self.holder = None
            return None
        verb = self.progs[c][self.prog_i[c]]
        self.prog_i[c] += 1
        if verb == "lock":
            self._h(invoke_op, c, "acquire")
            if not self.alive or self.holder is not None:
                self._h(fail_op, c, "acquire")
                return None
            if not self.volatile:
                self.log.append(("L", c))
            self.holder = c
            self._h(ok_op, c, "acquire")
            others = self.believed - {c}
            self.believed.add(c)
            if others:
                return {"code": "MC106",
                        "detail": f"granted to client {c} while "
                                  f"client(s) {sorted(others)} still "
                                  f"hold unreleased grants"}
            return None
        if verb == "unlock":
            self._h(invoke_op, c, "release")
            if not self.alive or self.holder != c:
                self._h(fail_op, c, "release")
                return None
            if not self.volatile:
                self.log.append(("U",))
            self.holder = None
            self.believed.discard(c)
            self._h(ok_op, c, "release")
            return None
        raise ValueError(f"unknown lock verb {verb!r}")


def make_world(family: str, mode: str, scope: Scope):
    if family not in ALL_FAMILIES:
        raise ValueError(f"unknown family {family!r}")
    if mode not in ALL_MODES[family]:
        raise ValueError(f"mode {mode!r} invalid for {family!r}")
    if family in SHELL_FAMILIES:
        from . import simnet
        cls = {"shell-kv": simnet.ShellKVWorld,
               "shell-queue": simnet.ShellQueueWorld,
               "shell-replicated": simnet.ShellReplWorld,
               "shell-rqueue": simnet.ShellRqueueWorld}[family]
        return cls(family, mode, scope)
    if family == "lock":
        return LockWorld(family, mode, scope)
    return ClusterWorld(family, mode, scope)


# ---------------------------------------------------------------------------
# Exploration: DFS + sleep sets over the world protocol
# ---------------------------------------------------------------------------


def _fp_id(code: str, fp: tuple) -> str:
    return hashlib.sha256(repr((code, fp)).encode()).hexdigest()[:16]


def explore(family: str, mode: str, scope: Scope, *,
            dpor: bool = True, max_violations: int = 64) -> dict:
    """Enumerate every schedule of the scoped world up to
    ``scope.max_events``, dedup states through the sleep-set
    antichain, and collect the violation set (deduped on
    (code, violating-state fingerprint) — the identity the dpor
    on/off soundness guard compares)."""
    universe: dict[tuple, int] = {}
    events: list[tuple] = []

    def bit(ev: tuple) -> int:
        b = universe.get(ev)
        if b is None:
            b = len(universe)
            universe[ev] = b
            events.append(ev)
        return b

    visited: dict = {}
    commute_memo: dict = {}
    stats = {"states": 0, "schedules": 0, "events": 0,
             "sleep_prunes": 0, "dedup": 0}
    violations: list[dict] = []
    seen: set = set()
    complete = True

    def commutes(world, a: tuple, b: tuple) -> bool:
        """Concrete commutation: both orders enabled, landing on the
        same fingerprint, and VIOLATION-FREE — a violating transition
        ends its DFS path, so its subtree never covers the sibling
        order and sleeping on it would prune a distinct violating
        state.  Conservative False on anything else."""
        key = (world.fingerprint(), a, b) if a <= b \
            else (world.fingerprint(), b, a)
        hit = commute_memo.get(key)
        if hit is not None:
            return hit
        out = False
        wa = world.clone()
        if a in wa.enabled():
            va = wa.execute(a)
            if va is None and b in wa.enabled():
                vab = wa.execute(b)
                wb = world.clone()
                if vab is None and b in wb.enabled():
                    vb = wb.execute(b)
                    if vb is None and a in wb.enabled():
                        vba = wb.execute(a)
                        out = vba is None \
                            and wa.fingerprint() == wb.fingerprint()
        commute_memo[key] = out
        return out

    def record(world, code: str, detail, schedule: list) -> None:
        vid = _fp_id(code, world.fingerprint())
        if vid in seen:
            return
        seen.add(vid)
        violations.append({"code": code, "detail": detail,
                           "schedule": list(schedule), "state": vid})
        _M_VIOL.inc(code=code)

    def dfs(world, depth: int, sleep: int, schedule: list) -> None:
        nonlocal complete
        if stats["states"] >= scope.max_states \
                or len(violations) >= max_violations:
            complete = False
            return
        key = (world.fingerprint(), depth)
        mask = sleep_visit(visited, key, sleep)
        if mask is None:
            stats["dedup"] += 1
            return
        evs = world.enabled()
        if depth >= scope.max_events or not evs:
            stats["schedules"] += 1
            return
        stats["states"] += 1
        sleep_cur = sleep
        for ev in evs:
            b = bit(ev)
            if mask and not (mask >> b) & 1:
                continue  # covered by a prior visit of this state
            if (sleep_cur >> b) & 1:
                stats["sleep_prunes"] += 1
                continue
            child_sleep = 0
            if dpor:
                scan = sleep_cur
                while scan:
                    low = scan & -scan
                    s_bit = low.bit_length() - 1
                    if commutes(world, events[s_bit], ev):
                        child_sleep |= low
                    scan &= scan - 1
            child = world.clone()
            v = child.execute(ev)
            stats["events"] += 1
            schedule.append(ev)
            if v is not None:
                stats["schedules"] += 1
                record(child, v["code"], v.get("detail"), schedule)
            else:
                dfs(child, depth + 1, child_sleep, schedule)
            schedule.pop()
            if dpor:
                sleep_cur |= 1 << b
        del evs

    dfs(make_world(family, mode, scope), 0, 0, [])
    _M_STATES.inc(stats["states"])
    _M_SCHED.inc(stats["schedules"])
    _M_PRUNE.inc(stats["sleep_prunes"])
    denom = stats["events"] + stats["sleep_prunes"]
    ratio = stats["sleep_prunes"] / denom if denom else 0.0
    _M_RATIO.set(ratio)
    return {
        "violations": violations,
        "explored": {**stats, "prune_ratio": round(ratio, 4),
                     "complete": complete},
    }


# ---------------------------------------------------------------------------
# Certificates: replay -> confirm -> shrink -> bank
# ---------------------------------------------------------------------------


def replay(family: str, mode: str, scope: Scope,
           schedule) -> tuple:
    """Deterministically re-execute a schedule -> (world,
    violation-or-None).  An event that is not enabled at its turn
    aborts (None violation): a valid certificate never hits this; a
    ddmin candidate that breaks an enabling chain is simply
    rejected."""
    world = make_world(family, mode, scope)
    for ev in schedule:
        ev = tuple(ev) if not isinstance(ev, tuple) else ev
        ev = (ev[0], int(ev[1]))
        if ev not in world.enabled():
            return world, None
        v = world.execute(ev)
        if v is not None:
            return world, v
    return world, None


def replay_certificate(cert: dict) -> dict:
    """Replay a banked/emitted certificate dict; returns
    ``{"reproduced": bool, "code": ..., "detail": ...}``."""
    scope = Scope.from_dict(cert.get("scope") or {})
    _w, v = replay(cert["family"], cert["mode"], scope,
                   cert.get("schedule") or ())
    return {
        "reproduced": v is not None and v["code"] == cert.get("code"),
        "code": v["code"] if v else None,
        "detail": v.get("detail") if v else None,
    }


def _shrink_schedule(family: str, mode: str, scope: Scope,
                     schedule: list, code: str) -> dict:
    from .shrink import ddmin_list

    def still(sub) -> bool:
        _w, v = replay(family, mode, scope, sub)
        return v is not None and v["code"] == code

    return ddmin_list([tuple(e) for e in schedule], still)


def _confirm_engine(ops: list, model) -> dict:
    """The independent validation loop for engine-route histories:
    the linearizability engine must answer invalid and the audit
    must accept its certificate."""
    from ..checker.seq import check_opseq
    from ..history import encode_ops
    from .audit import audit

    seq = encode_ops(ops, model.f_codes)
    res = check_opseq(seq, model, lint=False)
    a = audit(ops, model, res)
    return {"route": "engine", "engine_valid": res.get("valid"),
            "audit_ok": bool(a.get("ok")),
            "audit_checked": a.get("checked")}


def _confirm_kv_lock(family: str, ops: list) -> dict:
    from ..models import mutex, register

    return _confirm_engine(
        ops, mutex() if family == "lock" else register(ABSENT))


def _confirm_queue_engine(ops: list) -> dict:
    """The MC201 route: duplicate delivery is invisible to the
    tolerant total-queue multiset (at-least-once admits duplicates),
    so double-commits confirm through the ENGINE over an unordered
    queue — a dequeue with no remaining enqueue to justify it has no
    linearization."""
    from ..checker.basic import expand_queue_drain_ops
    from ..models import unordered_queue

    flat = expand_queue_drain_ops(ops)
    n_enq = sum(1 for op in flat
                if op.f == "enqueue" and op.type == "invoke")
    return _confirm_engine(flat, unordered_queue(max(2, n_enq + 1)))


def _confirm_queue(ops: list) -> dict:
    """Queue certificates confirm through multiset semantics: the
    total-queue replay answers invalid, and the W007 evidence audit
    independently re-derives the loss from the raw history."""
    from ..live.corpus import replay_queue
    from .audit import audit

    res = dict(replay_queue(ops))
    acked: dict = {}
    delivered: list = []
    for op in ops:
        if op.type != "ok":
            continue
        if op.f == "enqueue":
            acked.setdefault(op.value, []).append(True)
        elif op.f == "dequeue":
            delivered.append(op.value)
        elif op.f == "drain" and isinstance(op.value, (list, tuple)):
            delivered.extend(op.value)
    lost = {v for v in acked
            if len(acked[v]) > delivered.count(v)}
    rows = [i for i, op in enumerate(ops)
            if op.type == "ok" and op.f == "enqueue"
            and op.value in lost]
    if rows:
        res["queue_evidence"] = {"family": "queue",
                                 "kind": "lost-acked-enqueue",
                                 "rows": rows}
    a = audit(ops, None, res)
    return {"route": "queue", "engine_valid": res.get("valid"),
            "audit_ok": bool(a.get("ok")),
            "audit_checked": a.get("checked")}


def confirm_certificate(family: str, ops: list, code: str | None = None,
                        replayed: bool | None = None) -> dict:
    """Route a certificate's history to its independent validator.
    Shell codes pick their route by invariant (MC201 → engine over an
    unordered queue, MC202/MC205 → engine over a register, MC204 →
    total-queue multiset); MC203 has no invalid client history — a
    loop amplifies without lying to anyone — so deterministic replay
    IS its confirmation (route "loop")."""
    if code == "MC201":
        return _confirm_queue_engine(ops)
    if code == "MC202":
        from ..models import cas_register

        return _confirm_engine(ops, cas_register(1))
    if code == "MC203":
        return {"route": "loop", "engine_valid": False,
                "audit_ok": bool(replayed),
                "audit_checked": "loop-replay"}
    if code == "MC204":
        return _confirm_queue(ops)
    if code == "MC205":
        from ..models import register

        return _confirm_engine(ops, register(ABSENT))
    if family == "rqueue":
        return _confirm_queue(ops)
    return _confirm_kv_lock(family, ops)


def bank_certificate(family: str, mode: str, ops: list,
                     base: str) -> dict:
    """Bank the certificate's rendered history into the live corpus
    (the same pool campaign failures land in, so the corpus replayer
    regression-checks model-checker finds too)."""
    from ..live import corpus
    from ..models import cas_register, mutex, register

    if family in ("rqueue", "shell-queue", "shell-rqueue"):
        model = None  # the queue families bank through total-queue
    elif family == "lock":
        model = mutex()
    elif family == "shell-kv":
        model = cas_register(1)
    else:
        model = register(ABSENT)
    entries = corpus.entries_from_test(
        {"history": ops, "model": model},
        {"family": f"mc-{family}", "nemesis": f"mc-{mode}",
         "seeded": mode != "clean", "valid": False})
    out = corpus.bank(entries, base)
    return {"entries": len(entries), **{k: out[k] for k in out
                                        if k in ("banked", "pool")}}


def run_mc(family: str, mode: str, *, scope: Scope | None = None,
           dpor: bool | None = None, confirm: bool = True,
           shrink: bool = True, bank_base: str | None = None,
           max_violations: int = 64, max_certificates: int = 4) -> dict:
    """One model-checking run: explore the bounded scope, then take
    each violation through the confirm -> shrink -> bank lifecycle.
    Returns the result block ``--mc --json`` prints (``ok`` True
    exactly when no violation was found)."""
    dpor = resolve_dpor(dpor)
    if scope is None:
        scope = default_scope(family, mode)
    res = explore(family, mode, scope, dpor=dpor,
                  max_violations=max_violations)
    certs = []
    for v in res["violations"][:max_certificates]:
        cert = {"code": v["code"], "mc": MC_CODES[v["code"]],
                "detail": v["detail"], "family": family,
                "mode": mode, "scope": scope.to_dict(),
                "state": v["state"],
                "schedule": [list(e) for e in v["schedule"]]}
        schedule = v["schedule"]
        if shrink:
            d = _shrink_schedule(family, mode, scope, schedule,
                                 v["code"])
            schedule = d["items"]
            cert["schedule"] = [list(e) for e in schedule]
            cert["shrunk"] = {k: d[k] for k in
                              ("n_from", "n_to", "checks", "minimal")}
        world, rv = replay(family, mode, scope, schedule)
        cert["replayed"] = rv is not None and rv["code"] == v["code"]
        cert["history"] = [op.to_dict() for op in world.history]
        if confirm:
            cert["confirm"] = confirm_certificate(
                family, world.history, code=v["code"],
                replayed=cert["replayed"])
        if bank_base:
            cert["banked"] = bank_certificate(family, mode,
                                              world.history,
                                              bank_base)
        certs.append(cert)
    return {
        "family": family, "mode": mode, "dpor": dpor,
        "scope": scope.to_dict(),
        "explored": res["explored"],
        "n_violations": len(res["violations"]),
        "violations": certs,
        "ok": not res["violations"],
    }


def run_mc_sweep(families=FAMILIES, *, modes: dict | None = None,
                 dpor: bool | None = None, scope: Scope | None = None,
                 bank_base: str | None = None) -> dict:
    """The clean+seeded matrix: every family x mode at its default
    (or one shared) scope.  ``ok`` is True when every clean mode is
    violation-free AND every seeded mode is caught — the tier-1
    acceptance shape."""
    runs = []
    ok = True
    for family in families:
        for mode in (modes or ALL_MODES)[family]:
            r = run_mc(family, mode, scope=scope, dpor=dpor,
                       bank_base=bank_base if mode != "clean"
                       else None)
            runs.append(r)
            if mode == "clean":
                ok = ok and r["ok"]
            else:
                ok = ok and not r["ok"] \
                    and all(c.get("replayed") for c in r["violations"])
    return {"ok": ok, "runs": runs}


def scope_from_args(family: str, mode: str, *, nodes=None, ops=None,
                    crashes=None, partitions=None, max_events=None,
                    max_states=None) -> Scope:
    """CLI overlay: start from the family/mode default and replace
    only what was given."""
    s = default_scope(family, mode)
    over = {k: v for k, v in dict(
        nodes=nodes, ops=ops, crashes=crashes, partitions=partitions,
        max_events=max_events, max_states=max_states).items()
        if v is not None}
    return replace(s, **over) if over else s


def mc_plan_block(family: str, mode: str,
                  scope: Scope | None = None) -> dict:
    """The static 'what would --mc do' block for explain()/plan
    output: the scope bounds and invariant set, no exploration."""
    scope = scope or default_scope(family, mode)
    if family == "shell-replicated":
        events = ["op", "elect", "learn"]
    elif family in SHELL_FAMILIES:
        events = ["send", "deliver", "drop", "dup", "reset", "retry",
                  "giveup"]
    else:
        events = ["hb", "campaign", "op", "crash", "restart",
                  "isolate", "heal"]
    return {"family": family, "mode": mode, "scope": scope.to_dict(),
            "codes": sorted(MC_CODES), "events": events}


def load_certificate(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        return json.load(f)
