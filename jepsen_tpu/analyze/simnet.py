"""Simulated transport under the model checker — the SHELL layer of
the live daemons, explored schedule-by-schedule.

``modelcheck.py`` lifts the *cores* (election, replication,
durability).  This module lifts the layer above them: the
request-dispatch shells the daemons serve clients through —
:func:`jepsen_tpu.live.kv_server.dispatch`,
:func:`jepsen_tpu.live.queue_server.dispatch`,
:func:`jepsen_tpu.live.replicated_queue.dispatch_resp`, and
:func:`jepsen_tpu.live.replicated_server.handle_client_request` — by
substituting an in-memory message soup for the socket layer.  The
checked code path IS the served code path (the shell-lifting
contract, docs/analyze.md §12): the worlds here call the exact
functions the TCP handlers call, and the parity tests in
tests/test_modelcheck_shell.py hold the real daemons to the same
client-visible histories on fault-free schedules.

**The transport event model** (all events are ``(kind, int)`` pairs,
so modelcheck's replay/shrink machinery applies unchanged):

  ``send 0``        the client transmits its NEXT program op
                    (request message enters the soup)
  ``deliver mid``   message ``mid`` arrives: a request runs the real
                    dispatch function; a reply completes the client
                    op it answers (stale replies — an earlier attempt
                    of the op — are discarded, exactly what a client
                    that already tore down that connection does)
  ``drop mid``      the network eats message ``mid`` (budget:
                    ``scope.partitions``, shared with ``dup``)
  ``dup mid``       the network duplicates a REQUEST in flight — the
                    retransmission-race MC201 lives in
  ``reset 0``       the connection dies mid-request: every in-flight
                    message is lost and the server shell observes the
                    send failure (budget: ``scope.crashes``)
  ``retry 0``       the client retransmits the current op through
                    ``reconnect.Backoff`` (``step()``; enabled only
                    while the schedule has attempts left and the
                    current attempt is provably dead)
  ``giveup 0``      the client abandons the op: :info for mutations
                    (it may have happened), :fail for pure reads

Delivery order is unconstrained — delivering an arbitrary in-flight
``mid`` subsumes explicit reorder events.  The replicated-server
world (:class:`ShellReplWorld`) has no message soup: its ops execute
request→reply atomically through ``handle_client_request`` and the
interesting nondeterminism is leadership (``elect``/``learn``), which
is where the proxy-loop and stale-proxy defects live.

**Invariants** (MC2xx, registered in modelcheck.MC_CODES):

  MC201  non-idempotent retry double-commit: one client ADDJOB
         (one REQID) minted two jobs
  MC202  acked-reply-lost-then-lied: a committed PUT whose reply was
         lost answered the retry with a failure
  MC203  proxy loop: a forwarded request re-forwarded past every node
  MC204  session leak: a connection reset left a claim dead-owned,
         hiding an acked job from every consumer
  MC205  stale-leader serving: a read answered from a deposed
         leader's state, outside the possible set

State-level detections are completed into client-visible histories by
probe ops (a pending-only drain for MC204 — the leaked claim is the
invisibility being proven; pending+claimed for MC201 — claims
redeliver, so both copies count as deliveries), and every certificate
re-confirms through an independent route (modelcheck.confirm_
certificate): the linearizability engine over ``unordered_queue`` /
``cas_register`` / ``register``, the total-queue multiset replay, or
— for MC203, which produces no invalid client history, only an
amplification — deterministic replay itself.
"""

from __future__ import annotations

import json
import random
import threading
from collections import OrderedDict

from ..history import Op, fail_op, info_op, invoke_op, ok_op
from ..live import kv_server, queue_server
from ..live.replicated_queue import dispatch_resp
from ..live.replicated_server import PREFIX, handle_client_request
from ..reconnect import Backoff

#: the one key the shell kv programs exercise (modelcheck.KEY twin)
KEY = "x"
#: how key absence renders (see modelcheck.ABSENT)
ABSENT = 0

#: client retry budget per op: Backoff(max_attempts=3) allows the
#: original send plus two retransmissions — enough for every seeded
#: defect, small enough to keep the bounded scopes exhaustive
MAX_ATTEMPTS = 3


# ---------------------------------------------------------------------------
# No-file stores: the REAL Store classes minus the oplog fsync
# ---------------------------------------------------------------------------


class SimKVStore(kv_server.Store):
    """kv_server.Store with durability stubbed: same lock discipline,
    same put/get/dispatch code paths, no filesystem.  ``volatile``
    keeps its real meaning (reply-dedup cache skipped — the seeded
    MC202 mode)."""

    def __init__(self, volatile: bool = False):
        self.lock = threading.Lock()
        self.volatile = volatile
        self.state: dict[str, str] = {}
        self.replies: dict[str, tuple[int, dict]] = {}

    def _durable(self, entry: dict) -> None:  # no oplog in the sim
        pass

    def clone(self) -> "SimKVStore":
        s = SimKVStore(self.volatile)
        s.state = dict(self.state)
        s.replies = {k: (st, dict(b))
                     for k, (st, b) in self.replies.items()}
        return s

    def fingerprint(self) -> tuple:
        return (tuple(sorted(self.state.items())),
                tuple(sorted(
                    (k, st, json.dumps(b, sort_keys=True))
                    for k, (st, b) in self.replies.items())))


class SimQueueStore(queue_server.Store):
    """queue_server.Store with durability stubbed and the clock frozen
    at 0: claims never expire inside a schedule, so redelivery is an
    explicit transport event (reset→unclaim) instead of a wall-clock
    race, and ``getjob(0)`` polls instead of blocking."""

    def __init__(self, volatile: bool = False):
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.now = lambda: 0.0
        self.volatile = volatile
        self.next_id = 0
        self.pending: OrderedDict[str, tuple[str, float]] = OrderedDict()
        self.claimed: dict[str, tuple[str, float, float]] = {}
        self.replies: dict[str, str] = {}

    def _durable(self, line: str) -> None:  # no oplog in the sim
        pass

    def clone(self) -> "SimQueueStore":
        s = SimQueueStore(self.volatile)
        s.next_id = self.next_id
        s.pending = OrderedDict(self.pending)
        s.claimed = dict(self.claimed)
        s.replies = dict(self.replies)
        return s

    def fingerprint(self) -> tuple:
        return (self.next_id, tuple(self.pending.items()),
                tuple(sorted(self.claimed.items())),
                tuple(sorted(self.replies.items())))


# ---------------------------------------------------------------------------
# The message-soup transport base
# ---------------------------------------------------------------------------


class _TransportWorld:
    """One client driving one daemon shell through an in-memory
    message soup.  Subclasses provide ``_request`` (program verb →
    request message fields), ``_serve`` (request → reply message via
    the REAL dispatch function) and ``_complete`` (reply → history
    completion + invariant checks)."""

    def __init__(self, family: str, mode: str, scope):
        self.family = family
        self.mode = mode
        self.scope = scope
        self.volatile = mode == "volatile"
        self.op_i = 0
        #: the op awaiting completion: {"op", "verb", "attempt"}
        self.cur: dict | None = None
        self.inflight: dict[int, dict] = {}
        self.next_mid = 0
        #: connection generation; bumped by reset
        self.epoch = 0
        self.drops_used = 0
        self.resets_used = 0
        #: the real client-side retry schedule (jitter 0 keeps the
        #: rng stream inert; max_attempts bounds the retry events)
        self.backoff = Backoff(base=0.05, cap=2.0, factor=2.0,
                               max_attempts=MAX_ATTEMPTS, jitter=0.0,
                               rng=random.Random(7))
        #: op index -> commit tokens the SERVER minted for it (jids /
        #: "commit" markers) — what the retry-idempotency invariants
        #: are phrased over
        self.ledger: dict[int, set] = {}
        self.history: list[Op] = []
        self.t = 0

    # -- cloning / fingerprint ----------------------------------------

    def clone(self):
        w = object.__new__(type(self))
        w.__dict__.update(self.__dict__)
        w.cur = dict(self.cur) if self.cur is not None else None
        w.inflight = {m: dict(v) for m, v in self.inflight.items()}
        w.ledger = {k: set(v) for k, v in self.ledger.items()}
        w.history = list(self.history)
        w.backoff = self.backoff.clone()
        self._clone_into(w)
        return w

    def _clone_into(self, w) -> None:
        w.store = self.store.clone()

    def _store_fp(self) -> tuple:
        return self.store.fingerprint()

    def fingerprint(self) -> tuple:
        cur = None if self.cur is None \
            else (self.cur["op"], self.cur["attempt"])
        return (
            self.op_i, cur,
            tuple(sorted(
                (m, tuple(sorted(v.items())))
                for m, v in self.inflight.items())),
            self.next_mid, self.epoch, self.drops_used,
            self.resets_used, self.backoff.attempt,
            tuple(sorted((k, tuple(sorted(v)))
                         for k, v in self.ledger.items())),
            self._store_fp(),
        )

    # -- history rendering --------------------------------------------

    def _h(self, ctor, process, f, value=None) -> None:
        self.history.append(ctor(process, f, value, time=self.t))
        self.t += 1

    # -- scheduling protocol ------------------------------------------

    def _attempt_live(self) -> bool:
        c = self.cur
        return any(m["op"] == c["op"] and m["attempt"] == c["attempt"]
                   for m in self.inflight.values())

    def enabled(self) -> list[tuple]:
        evs: list[tuple] = []
        if self.cur is None and self.op_i < len(self.scope.ops):
            evs.append(("send", 0))
        for mid in sorted(self.inflight):
            evs.append(("deliver", mid))
            if self.drops_used < self.scope.partitions:
                evs.append(("drop", mid))
                if self.inflight[mid]["kind"] == "req":
                    evs.append(("dup", mid))
        if self.inflight and self.resets_used < self.scope.crashes:
            evs.append(("reset", 0))
        if self.cur is not None and not self._attempt_live():
            if not self.backoff.exhausted():
                evs.append(("retry", 0))
            evs.append(("giveup", 0))
        return evs

    def execute(self, ev: tuple) -> dict | None:
        kind, mid = ev
        if kind == "send":
            verb = self.scope.ops[self.op_i]
            self.cur = {"op": self.op_i, "verb": verb, "attempt": 0}
            self.op_i += 1
            self.backoff.reset()
            self._invoke(verb)
            self._post_request()
            return None
        if kind == "retry":
            self.backoff.step()
            self.cur["attempt"] += 1
            self._post_request()
            return None
        if kind == "giveup":
            self._giveup()
            return None
        if kind == "dup":
            m = dict(self.inflight[mid])
            self.inflight[self.next_mid] = m
            self.next_mid += 1
            self.drops_used += 1
            return None
        if kind == "drop":
            self.inflight.pop(mid)
            self.drops_used += 1
            return None
        if kind == "reset":
            killed = list(self.inflight.values())
            self.inflight.clear()
            self.epoch += 1
            self.resets_used += 1
            return self._on_reset(killed)
        if kind == "deliver":
            m = self.inflight.pop(mid)
            if m["kind"] == "req":
                return self._serve(m)
            return self._complete(m)
        raise ValueError(f"unknown transport event {ev!r}")

    def _post_request(self) -> None:
        """Put the current attempt's request into the soup."""
        c = self.cur
        m = {"kind": "req", "op": c["op"], "attempt": c["attempt"]}
        m.update(self._request(c["verb"], c["op"]))
        self.inflight[self.next_mid] = m
        self.next_mid += 1

    def _reply(self, m: dict, **fields) -> None:
        """Queue the reply to request ``m`` (same op/attempt tags —
        what lets the client discard stale answers)."""
        r = {"kind": "reply", "op": m["op"], "attempt": m["attempt"]}
        r.update(fields)
        self.inflight[self.next_mid] = r
        self.next_mid += 1

    def _stale(self, m: dict) -> bool:
        c = self.cur
        return c is None or m["op"] != c["op"] \
            or m["attempt"] != c["attempt"]

    def _finish(self, ctor, f, value=None) -> None:
        """Complete the current op and reset the retry schedule."""
        self._h(ctor, 0, f, value)
        self.cur = None
        self.backoff.reset()

    def _giveup(self) -> None:
        verb = self.cur["verb"]
        f, value = self._render(verb)
        if verb[0] in ("r", "get"):
            self._finish(fail_op, f, value)
        else:
            # a mutation the client stops waiting for may still have
            # happened: indeterminate, never :fail
            self._finish(info_op, f, value)

    def _on_reset(self, killed: list[dict]) -> dict | None:
        return None

    # -- subclass hooks -----------------------------------------------

    def _invoke(self, verb: tuple) -> None:
        f, value = self._render(verb)
        self._h(invoke_op, 0, f, value)

    def _render(self, verb: tuple) -> tuple:
        raise NotImplementedError

    def _request(self, verb: tuple, op_index: int) -> dict:
        raise NotImplementedError

    def _serve(self, m: dict) -> dict | None:
        raise NotImplementedError

    def _complete(self, m: dict) -> dict | None:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# shell-kv: the etcd-v2 shell (kv_server.dispatch) under retry
# ---------------------------------------------------------------------------


class ShellKVWorld(_TransportWorld):
    """One client retrying PUT/GET against the real
    ``kv_server.dispatch``.  Requests carry a ``reqId`` that is
    CONSTANT across retries — the idempotency key the reply-dedup
    cache closes MC202 with; ``volatile`` skips the cache (the seeded
    mode)."""

    def __init__(self, family: str, mode: str, scope):
        super().__init__(family, mode, scope)
        self.store = SimKVStore(volatile=self.volatile)
        # a CAS needs something to compare against
        self.store.state[KEY] = "1"

    def _render(self, verb: tuple) -> tuple:
        if verb[0] == "cas":
            return "cas", [verb[1], verb[2]]
        if verb[0] == "w":
            return "write", verb[1]
        return "read", None

    def _request(self, verb: tuple, op_index: int) -> dict:
        if verb[0] == "r":
            return {"method": "GET", "path": PREFIX + KEY, "body": b""}
        qs = f"reqId=op{op_index}"
        if verb[0] == "cas":
            qs = f"prevValue={verb[1]}&" + qs
        new = verb[2] if verb[0] == "cas" else verb[1]
        return {"method": "PUT", "path": f"{PREFIX}{KEY}?{qs}",
                "body": f"value={new}".encode()}

    def _serve(self, m: dict) -> dict | None:
        status, body = kv_server.dispatch(
            self.store, m["method"], m["path"], m["body"])
        if m["method"] == "PUT" and status == 200:
            self.ledger.setdefault(m["op"], set()).add("commit")
        self._reply(m, status=status,
                    body=json.dumps(body, sort_keys=True))
        return None

    def _probe_read(self) -> None:
        val = self.store.state.get(KEY)
        self._h(invoke_op, 0, "read")
        self._h(ok_op, 0, "read",
                ABSENT if val is None else int(val))

    def _complete(self, m: dict) -> dict | None:
        if self._stale(m):
            return None
        verb = self.cur["verb"]
        st = m["status"]
        if verb[0] == "r":
            if st == 200:
                val = int(json.loads(m["body"])["node"]["value"])
                self._finish(ok_op, "read", val)
            else:
                self._finish(ok_op, "read", ABSENT)
            return None
        f, value = self._render(verb)
        opi = self.cur["op"]
        if st == 200:
            self._finish(ok_op, f, value)
            return None
        self._finish(fail_op, f, value)
        if self.ledger.get(opi):
            # the server committed this op on an earlier attempt, lost
            # the reply, and just told the client it failed
            self._probe_read()
            return {"code": "MC202",
                    "detail": f"op {opi} ({f} {value!r}) committed "
                              f"server-side but the retry was answered "
                              f"{st} — the client recorded :fail for "
                              f"an applied write"}
        return None


# ---------------------------------------------------------------------------
# shell-queue: the disque-shaped shell (queue_server.dispatch)
# ---------------------------------------------------------------------------


class ShellQueueWorld(_TransportWorld):
    """One client retrying ADDJOB/GETJOB against the real
    ``queue_server.dispatch``.  ``reset`` replays the connection
    handler's reply-send-failure path: a claim whose reply died is
    returned to pending (``Store.unclaim``) — except in the seeded
    ``session-leak`` mode, which keeps the pre-fix behaviour and
    leaks the claim (MC204)."""

    def __init__(self, family: str, mode: str, scope):
        super().__init__(family, mode, scope)
        self.leak = mode == "session-leak"
        self.store = SimQueueStore(volatile=self.volatile)
        #: jid -> connection epoch that claimed it
        self.claim_epochs: dict[str, int] = {}
        #: jids whose ADDJOB ack reached the client
        self.acked_adds: set = set()

    def _clone_into(self, w) -> None:
        super()._clone_into(w)
        w.claim_epochs = dict(self.claim_epochs)
        w.acked_adds = set(self.acked_adds)

    def _store_fp(self) -> tuple:
        return (self.store.fingerprint(),
                tuple(sorted(self.claim_epochs.items())),
                tuple(sorted(self.acked_adds)))

    def _render(self, verb: tuple) -> tuple:
        if verb[0] == "add":
            return "enqueue", verb[1]
        return "dequeue", None

    def _request(self, verb: tuple, op_index: int) -> dict:
        if verb[0] == "add":
            args = ("ADDJOB", "jepsen", str(verb[1]), "0",
                    "REQID", f"op{op_index}")
        else:
            args = ("GETJOB", "TIMEOUT", "0", "COUNT", "1",
                    "FROM", "jepsen")
        return {"args": args}

    def _probe_drain(self, *, include_claimed: bool) -> None:
        bodies = [int(b) for b, _ in self.store.pending.values()]
        if include_claimed:
            bodies += [int(b) for b, _r, _t
                       in self.store.claimed.values()]
        self._h(invoke_op, 0, "drain")
        self._h(ok_op, 0, "drain", sorted(bodies))

    def _close_cur_info(self) -> None:
        """Render the open op indeterminate before probing (the
        violation fires mid-request; the client never hears back)."""
        if self.cur is not None:
            f, value = self._render(self.cur["verb"])
            self._finish(info_op, f, value)

    def _serve(self, m: dict) -> dict | None:
        payload, claimed = queue_server.dispatch(
            self.store, list(m["args"]))
        if claimed is not None:
            self.claim_epochs[claimed] = self.epoch
        self._reply(m, payload=payload, claimed=claimed or "")
        if m["args"][0] == "ADDJOB" and payload.startswith(b"+"):
            jid = payload[1:].split(b"\r")[0].decode()
            jids = self.ledger.setdefault(m["op"], set())
            jids.add(jid)
            if len(jids) > 1:
                # one client op, one REQID — two jobs minted
                self._close_cur_info()
                self._probe_drain(include_claimed=True)
                return {"code": "MC201",
                        "detail": f"ADDJOB op {m['op']} minted "
                                  f"{sorted(jids)} across retries — "
                                  f"non-idempotent retry double-"
                                  f"commit"}
        return None

    def _on_reset(self, killed: list[dict]) -> dict | None:
        for m in killed:
            if m["kind"] == "reply" and m.get("claimed"):
                if self.leak:
                    continue  # the pre-fix bug: claim stays dead-owned
                self.store.unclaim(m["claimed"])
                self.claim_epochs.pop(m["claimed"], None)
        return None

    def _zombie_claims(self) -> list[str]:
        return sorted(
            j for j, e in self.claim_epochs.items()
            if e < self.epoch and j in self.store.claimed
            and j in self.acked_adds)

    def _complete(self, m: dict) -> dict | None:
        if self._stale(m):
            return None
        verb = self.cur["verb"]
        payload = m["payload"]
        if verb[0] == "add":
            f, value = self._render(verb)
            if payload.startswith(b"+"):
                self.acked_adds.add(
                    payload[1:].split(b"\r")[0].decode())
                self._finish(ok_op, f, value)
            else:
                self._finish(fail_op, f, value)
            return None
        # GETJOB
        if payload == b"*-1\r\n":
            self._finish(fail_op, "dequeue", None)
            zombies = self._zombie_claims()
            if zombies:
                # an acked job exists but no consumer can see it: its
                # claim belongs to a connection that no longer exists
                self._probe_drain(include_claimed=False)
                return {"code": "MC204",
                        "detail": f"acked job(s) {zombies} are "
                                  f"claimed by a dead connection "
                                  f"(epoch < {self.epoch}) — invisible "
                                  f"to every consumer"}
            return None
        body = payload.split(b"\r\n")[7].decode()
        self._finish(ok_op, "dequeue", int(body))
        return None


# ---------------------------------------------------------------------------
# shell-rqueue: the replicated-queue RESP shell (dispatch_resp) with
# the follower->leader JPROXY relay in the loop
# ---------------------------------------------------------------------------


class _NoForward:
    def __call__(self, lid, args):
        raise RuntimeError("a proxied command must not re-forward")


class SimRqueueNode:
    """Duck-types the QueueReplica surface ``dispatch_resp`` and
    ``_forward_to_leader`` touch (id/lock/volatile/leader_id/
    reply_cache + addjob/getjob/ackjob) over the world's shared
    queue state — node 0 is the stable leader, node 1 the follower
    the client talks to, so every client command rides the JPROXY
    relay and the leader-side REQID dedup."""

    def __init__(self, world: "ShellRqueueWorld", node_id: int):
        self.world = world
        self.id = node_id
        self.lock = threading.Lock()
        self.volatile = world.volatile
        self.reply_cache: dict[str, bytes] = {}

    @property
    def leader_id(self) -> int:
        return self.world.beliefs[self.id]

    def addjob(self, body: str, retry_s: float):
        w = self.world
        if self.id != w.leader:
            return "noleader", None
        jid = f"D-{self.id}-{w.next_seq}"
        w.next_seq += 1
        w.pending[jid] = (body, retry_s)
        return "ok", jid

    def getjob(self, timeout_ms: int):
        w = self.world
        if self.id != w.leader:
            return "noleader", None
        if not w.pending:
            return "ok", None
        jid, (body, retry_s) = w.pending.popitem(last=False)
        w.claimed[jid] = (body, retry_s)
        return "ok", (jid, body)

    def ackjob(self, jid: str):
        w = self.world
        if self.id != w.leader:
            return "noleader", None
        known = jid in w.pending or jid in w.claimed
        w.pending.pop(jid, None)
        w.claimed.pop(jid, None)
        return "ok", 1 if known else 0


class ShellRqueueWorld(_TransportWorld):
    """The replicated queue's SHELL under the transport: the client's
    commands land on the FOLLOWER, whose real ``dispatch_resp`` relays
    them to the leader as JPROXY commands (the forward leg runs the
    leader's ``dispatch_resp`` with ``proxied=True`` — one atomic
    RPC, the same under-approximation the core checker makes).  The
    REQID dedup lives on the leader; ``volatile`` skips it — retried
    ADDJOBs then double-commit through the proxy (MC201)."""

    def __init__(self, family: str, mode: str, scope):
        super().__init__(family, mode, scope)
        self.leader = 0
        self.beliefs = [0] * scope.nodes
        self.next_seq = 0
        self.pending: OrderedDict[str, tuple[str, float]] = OrderedDict()
        self.claimed: dict[str, tuple[str, float]] = {}
        self.nodes = [SimRqueueNode(self, i)
                      for i in range(scope.nodes)]
        self.store = None  # shared state lives on the world

    def _clone_into(self, w) -> None:
        w.beliefs = list(self.beliefs)
        w.pending = OrderedDict(self.pending)
        w.claimed = dict(self.claimed)
        w.nodes = [SimRqueueNode(w, i)
                   for i in range(self.scope.nodes)]
        for old, new in zip(self.nodes, w.nodes):
            new.reply_cache = dict(old.reply_cache)

    def _store_fp(self) -> tuple:
        return (self.next_seq, tuple(self.pending.items()),
                tuple(sorted(self.claimed.items())),
                tuple(self.beliefs),
                tuple(tuple(sorted(n.reply_cache.items()))
                      for n in self.nodes))

    def _render(self, verb: tuple) -> tuple:
        if verb[0] == "add":
            return "enqueue", verb[1]
        return "dequeue", None

    def _request(self, verb: tuple, op_index: int) -> dict:
        if verb[0] == "add":
            args = ("ADDJOB", "jepsen", str(verb[1]), "0",
                    "REQID", f"op{op_index}")
        else:
            args = ("GETJOB", "TIMEOUT", "0", "COUNT", "1",
                    "FROM", "jepsen")
        return {"args": args}

    def _forward(self, lid: int, args: list[str]) -> bytes:
        return dispatch_resp(self.nodes[lid], list(args),
                             proxied=True, forward=_NoForward())

    def _probe_drain(self) -> None:
        bodies = sorted(
            [int(b) for b, _ in self.pending.values()]
            + [int(b) for b, _ in self.claimed.values()])
        self._h(invoke_op, 0, "drain")
        self._h(ok_op, 0, "drain", bodies)

    def _close_cur_info(self) -> None:
        if self.cur is not None:
            f, value = self._render(self.cur["verb"])
            self._finish(info_op, f, value)

    def _serve(self, m: dict) -> dict | None:
        entry = self.nodes[min(1, len(self.nodes) - 1)]
        payload = dispatch_resp(entry, list(m["args"]),
                                proxied=False, forward=self._forward)
        self._reply(m, payload=payload)
        if m["args"][0] == "ADDJOB" and payload.startswith(b"+"):
            jid = payload[1:].split(b"\r")[0].decode()
            jids = self.ledger.setdefault(m["op"], set())
            jids.add(jid)
            if len(jids) > 1:
                self._close_cur_info()
                self._probe_drain()
                return {"code": "MC201",
                        "detail": f"proxied ADDJOB op {m['op']} "
                                  f"minted {sorted(jids)} across "
                                  f"retries — the leader-side REQID "
                                  f"dedup did not hold"}
        return None

    def _complete(self, m: dict) -> dict | None:
        if self._stale(m):
            return None
        verb = self.cur["verb"]
        payload = m["payload"]
        f, value = self._render(verb)
        if verb[0] == "add":
            if payload.startswith(b"+"):
                self._finish(ok_op, f, value)
            elif payload.startswith(b"-NOREPL"):
                self._finish(info_op, f, value)
            else:
                self._finish(fail_op, f, value)
            return None
        if payload == b"*-1\r\n":
            self._finish(fail_op, "dequeue", None)
        elif payload.startswith(b"-NOREPL"):
            self._finish(info_op, "dequeue", None)
        elif payload.startswith(b"-"):
            self._finish(fail_op, "dequeue", None)
        else:
            body = payload.split(b"\r\n")[7].decode()
            self._finish(ok_op, "dequeue", int(body))
        return None


# ---------------------------------------------------------------------------
# shell-replicated: handle_client_request + the proxy mesh
# ---------------------------------------------------------------------------


class SimReplNode:
    """Duck-types the Replica surface ``handle_client_request``
    touches (id/lock/leader_id + get/put) over the world's
    leadership model: ``serving`` is the lease the shell trusts,
    ``beliefs[i]`` is node i's possibly-stale leader view, and only
    the ACTUAL leader can commit — a deposed-but-still-serving node
    (the seeded ``stale-proxy`` mode) answers reads from its frozen
    state and writes with 504 (it cannot reach quorum)."""

    def __init__(self, world: "ShellReplWorld", node_id: int):
        self.world = world
        self.id = node_id
        self.lock = threading.Lock()

    @property
    def leader_id(self) -> int | None:
        return self.world.beliefs[self.id]

    def get(self, key: str) -> tuple[int, dict]:
        w = self.world
        if not w.serving[self.id]:
            return 503, {"errorCode": 300, "message": "not leader"}
        val = w.states[self.id].get(key)
        if val is None:
            return 404, {"errorCode": 100,
                         "message": "Key not found", "cause": key}
        return 200, {"action": "get",
                     "node": {"key": f"/{key}", "value": val}}

    def put(self, key: str, value: str,
            prev: str | None = None) -> tuple[int, dict]:
        w = self.world
        if not w.serving[self.id]:
            return 503, {"errorCode": 300, "message": "not leader"}
        if self.id != w.actual:
            # a stale leader can accept the request but not assemble a
            # quorum: indeterminate, never a lie
            return 504, {"errorCode": 301, "message": "no quorum"}
        if prev is not None and w.states[self.id].get(key) != prev:
            return 412, {"errorCode": 101, "message": "Compare failed"}
        w.states[self.id][key] = value
        w.log_state[key] = value
        return 200, {"action": "set",
                     "node": {"key": f"/{key}", "value": value}}


class ShellReplWorld:
    """The replicated-server SHELL — the follower→leader proxy
    decision inside ``handle_client_request`` — under a leadership
    model the scheduler perturbs.  Events:

      ``op i``     the client sends its next program op to node i;
                   the request resolves atomically (local serve or
                   proxy hop via the node's leader belief)
      ``elect j``  leadership moves to node j (j catches up from the
                   replicated state); the old leader's lease is
                   revoked — except in ``stale-proxy`` mode, where it
                   keeps serving (the seeded MC205 bug)
      ``learn i``  node i refreshes its leader belief

    ``proxy-loop`` mode strips the proxied marker off forwarded
    requests (the seeded MC203 bug): two confused beliefs then
    re-forward forever; the transport raises after nodes+1 hops and
    the world reports the amplification."""

    def __init__(self, family: str, mode: str, scope):
        self.family = family
        self.mode = mode
        self.scope = scope
        n = scope.nodes
        self.states: list[dict] = [{} for _ in range(n)]
        self.log_state: dict = {}
        self.serving = [i == 0 for i in range(n)]
        self.beliefs = [0] * n
        self.actual = 0
        self.elects_used = 0
        self.op_i = 0
        self.committed: dict = {}
        self.maybes: dict = {}
        self.loop_overflow = False
        self.max_hops = 0
        self.nodes = [SimReplNode(self, i) for i in range(n)]
        self.history: list[Op] = []
        self.t = 0

    def clone(self) -> "ShellReplWorld":
        w = object.__new__(type(self))
        w.__dict__.update(self.__dict__)
        w.states = [dict(s) for s in self.states]
        w.log_state = dict(self.log_state)
        w.serving = list(self.serving)
        w.beliefs = list(self.beliefs)
        w.committed = dict(self.committed)
        w.maybes = {k: list(v) for k, v in self.maybes.items()}
        w.nodes = [SimReplNode(w, i)
                   for i in range(self.scope.nodes)]
        w.history = list(self.history)
        return w

    def fingerprint(self) -> tuple:
        return (
            tuple(tuple(sorted(s.items())) for s in self.states),
            tuple(sorted(self.log_state.items())),
            tuple(self.serving), tuple(self.beliefs),
            self.actual, self.elects_used, self.op_i,
            tuple(sorted(self.committed.items())),
            tuple(sorted((k, tuple(v))
                         for k, v in self.maybes.items())),
            self.loop_overflow,
        )

    def enabled(self) -> list[tuple]:
        evs: list[tuple] = []
        n = self.scope.nodes
        if self.op_i < len(self.scope.ops):
            evs.extend(("op", i) for i in range(n))
        if self.elects_used < self.scope.crashes:
            evs.extend(("elect", j) for j in range(n)
                       if j != self.actual)
        evs.extend(("learn", i) for i in range(n)
                   if self.beliefs[i] != self.actual)
        return evs

    def _h(self, ctor, process, f, value=None) -> None:
        self.history.append(ctor(process, f, value, time=self.t))
        self.t += 1

    def _possible(self, k) -> set:
        poss = set(self.maybes.get(k, ()))
        poss.add(self.committed.get(k))
        return poss

    def _deliver(self, i: int, method: str, path: str,
                 raw_body: bytes | None, hops: list,
                 proxied: bool) -> tuple[int, dict]:
        hops.append(i)
        self.max_hops = max(self.max_hops, len(hops))
        if len(hops) > self.scope.nodes + 1:
            # a correct proxy mesh touches at most two nodes per
            # request; past every node it can only be looping
            self.loop_overflow = True
            raise OSError("proxy loop suspected")

        def forward(lid, m, p, b):
            return self._deliver(
                lid, m, p, b, hops,
                proxied=self.mode != "proxy-loop")

        return handle_client_request(
            self.nodes[i], method, path, raw_body,
            proxied=proxied, forward=forward)

    def execute(self, ev: tuple) -> dict | None:
        kind, i = ev
        if kind == "elect":
            self.elects_used += 1
            old = self.actual
            self.actual = i
            self.serving[i] = True
            # the new leader catches up from the replicated log
            self.states[i] = dict(self.log_state)
            if self.mode != "stale-proxy":
                self.serving[old] = False
            return None
        if kind == "learn":
            self.beliefs[i] = self.actual
            return None
        # op
        verb = self.scope.ops[self.op_i]
        self.op_i += 1
        self.loop_overflow = False
        self.max_hops = 0
        hops: list = []
        if verb[0] == "w":
            val = verb[1]
            if val == ABSENT:
                raise ValueError("kv write values must be non-zero "
                                 "(0 renders key absence)")
            self._h(invoke_op, 0, "write", val)
            path = PREFIX + KEY
            body = f"value={val}".encode()
            status, _b = self._deliver(i, "PUT", path, body, hops,
                                       proxied=False)
            if status == 200:
                self.committed[KEY] = val
                self.maybes[KEY] = []
                self._h(ok_op, 0, "write", val)
            elif status == 504:
                self.maybes.setdefault(KEY, []).append(val)
                self._h(info_op, 0, "write", val)
            else:
                self._h(fail_op, 0, "write", val)
        else:  # ("r",)
            self._h(invoke_op, 0, "read")
            status, b = self._deliver(i, "GET", PREFIX + KEY, None,
                                      hops, proxied=False)
            if status == 200:
                val = int(b["node"]["value"])
                self._h(ok_op, 0, "read", val)
                if val not in self._possible(KEY):
                    return {"code": "MC205",
                            "detail": f"read at node {i} answered "
                                      f"{val!r} via {hops} — a deposed "
                                      f"leader served outside the "
                                      f"possible set "
                                      f"{sorted(map(repr, self._possible(KEY)))}"}
            elif status == 404:
                self._h(ok_op, 0, "read", ABSENT)
                if None not in self._possible(KEY):
                    return {"code": "MC205",
                            "detail": f"read at node {i} answered "
                                      f"absent via {hops}; possible "
                                      f"was "
                                      f"{sorted(map(repr, self._possible(KEY)))}"}
            elif status == 504:
                self._h(info_op, 0, "read")
            else:
                self._h(fail_op, 0, "read")
        if self.loop_overflow:
            return {"code": "MC203",
                    "detail": f"request to node {i} was re-forwarded "
                              f"through {hops} — the proxied marker "
                              f"did not stop the relay"}
        return None
