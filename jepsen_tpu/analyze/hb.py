"""Happens-before constraint analysis — the static order-solver.

One cheap host pass over a history, BEFORE any search, that builds the
forced-order (happens-before) structure the engines otherwise rediscover
config by config:

  * **real time** — ``ret[i] < inv[j]`` forces i before j (the interval
    order every engine already enforces natively);
  * **read-from** — under unique writes, an :ok read of value v forces
    the (single) write of v before it;
  * **block order** — unique-writes register semantics make each value's
    ops a contiguous *block* in any linearization (between w(v) and a
    read of v no other write may land), so ANY real-time edge between
    members of two blocks orients the whole blocks — Gibbons & Korach's
    cluster argument, the reason atomic-register histories decide in
    O(n log n) instead of exponentially;
  * **init order** — a read of the initial value must precede every
    write (unique writes never re-create the initial value).

Three passes consume that structure:

**Decide-fast.**  A cycle among forced edges is an immediate ``invalid``
verdict carrying an *HB-cycle certificate* — an op-level edge list the
independent audit (analyze/audit.py, W006) re-justifies edge by edge
without re-running this solver.  For all-:ok read/write histories the
interval pass decides *completely*: acyclic block spans + clean
read-from structure yield ``valid`` with a constructive linearization
witness (cluster topological order, blocks emitted contiguously,
NIL reads re-inserted by real time), self-verified by model replay
before it is ever emitted — a wrong verdict is structurally impossible,
only a missed decision is.  Multi-register histories decide per key and
stitch the witness through ``partition.merge_linearizations``
(Herlihy–Wing locality).

**Constraint-propagate.**  Partially-decided histories (crashed rows,
cas ops out of the decidable class) still yield forced edges — read-from
off anchored crashed writes, block order between anchored clusters —
saturated against real time so only edges real time does NOT already
imply are kept.

**Prune.**  The forced edges, plus a *canonical-order* relation over
concurrent same-value reads (two reads of the same value on the same
register are state-transparent and interchangeable; when both inv and
ret are ordered the exchange is always legal, so restricting the search
to inv-canonical orders preserves the verdict — the sleep-set-flavored
commutativity prune of Parsimonious Optimal DPOR, arXiv:2405.11128,
done statically), are exported as a must-order predecessor map.  The
host engines mask candidates whose must-predecessors are not yet
linearized; the batch scheduler disposes decided keys before they ever
reach the device.

Soundness invariants (what keeps this verdict-identical by
construction):

  * decide-``valid`` only ever fires after the constructed witness
    replays clean against the model AND real time;
  * decide-``invalid`` only ever fires on independently re-checkable
    evidence (a forced-edge cycle, or an :ok read of a value no write
    and no initial state can produce);
  * must-order edges are either *forced* (hold in every valid
    linearization) or *canonical* (every valid linearization can be
    exchanged into one that satisfies them), so masking them can never
    flip a verdict;
  * anything outside the gates returns "undecided" and the engines run
    exactly as before.

Knobs: ``hb=False`` per call on every wired engine, or
``JEPSEN_TPU_HB=0`` fleet-wide (default ON).
"""

from __future__ import annotations

import bisect
import os
import threading
from dataclasses import dataclass, field

import numpy as np

from ..history import INF_RET, NIL, OpSeq
from ..models import R_CAS, R_READ, R_WRITE, ModelSpec
from ..obs.metrics import REGISTRY

_M_PREPASS = REGISTRY.counter(
    "jtpu_hb_prepass_total",
    "HB pre-pass outcomes (decided_valid/decided_invalid/undecided/"
    "skipped)", ("outcome",))
_M_EDGES = REGISTRY.counter(
    "jtpu_hb_edges_total",
    "Forced/canonical HB edges inferred beyond real time, by kind",
    ("kind",))
_M_RATIO = REGISTRY.gauge(
    "jtpu_hb_prune_ratio",
    "pruned/raw config-bound ratio of the most recent HB pre-pass "
    "(0 = decided without search)")
_M_FOLDS = REGISTRY.counter(
    "jtpu_hb_fold_total",
    "Streamed/decomposed segment folds answered by the HB interval "
    "pass")

#: cap on enumerated inferred edges — the prune degrades gracefully
#: (fewer mask edges) instead of going quadratic on pathological
#: cluster structures
EDGE_CAP_FACTOR = 4
EDGE_CAP_MIN = 256

#: NIL (unknown-value) reads are re-inserted into the constructed
#: witness one linear scan each; past this many the decision is ceded
#: to the engines rather than going quadratic
NIL_INSERT_CAP = 512

#: instates a segment fold will run the per-instate interval pass for
#: before ceding to the generic fold
FOLD_INSTATE_CAP = 8
#: distinct reachable out-states the fold will build witness chains for
FOLD_WITNESS_STATES = 8


def hb_enabled() -> bool:
    """The fleet knob: on unless JEPSEN_TPU_HB=0/false/off/no."""
    return os.environ.get("JEPSEN_TPU_HB", "").strip().lower() not in (
        "0", "false", "off", "no")


def resolve_hb(flag: bool | None) -> bool:
    return hb_enabled() if flag is None else bool(flag)


@dataclass
class HBAnalysis:
    """The pre-pass output one engine entry consumes."""

    n: int
    applies: bool
    #: engine-style result dict (verdict + certificate) or None
    decided: dict | None
    #: row -> tuple of must-predecessor rows (beyond real time)
    must_pred: dict = field(default_factory=dict)
    #: json-able summary for result["hb"] / plan["hb"]
    stats: dict = field(default_factory=dict)


# ---------------------------------------------------------------------------
# Cluster scan — the one structure every pass reads
# ---------------------------------------------------------------------------


def _family(model: ModelSpec) -> str | None:
    if model.name in ("register", "cas-register"):
        return "register"
    if model.name == "multi-register":
        return "multi"
    return None


class _Cluster:
    """One value's block on one key: the (unique) write plus the :ok
    reads of that value.  ``anchored`` = the block must appear in every
    linearization (ok write, or a crashed write some :ok read saw)."""

    __slots__ = ("val", "write", "write_ok", "ok_reads", "s", "e")

    def __init__(self, val: int, write: int, write_ok: bool):
        self.val = val
        self.write = write
        self.write_ok = write_ok
        self.ok_reads: list[int] = []

    @property
    def anchored(self) -> bool:
        return self.write_ok or bool(self.ok_reads)

    def members(self) -> list[int]:
        return [self.write, *self.ok_reads]


class _KeyScan:
    __slots__ = ("key", "init_val", "clusters", "init_reads",
                 "nil_reads", "impossible", "tainted", "crashed_reads",
                 "read_classes")

    def __init__(self, key: int, init_val: int):
        self.key = key
        self.init_val = init_val
        self.clusters: dict[int, _Cluster] = {}   # val -> cluster
        self.init_reads: list[int] = []           # :ok reads of init
        self.nil_reads: list[int] = []            # :ok reads of NIL
        self.impossible: list[int] = []           # :ok reads, no writer
        self.tainted = False                      # no rf/block inference
        self.crashed_reads: list[int] = []
        #: value-class -> rows (ok+crashed reads), for the canonical
        #: read-read exchange chains; NIL reads class under key NIL
        self.read_classes: dict[int, list[int]] = {}


class _Scan:
    __slots__ = ("keys", "all_ok", "has_cas", "n")

    def __init__(self):
        self.keys: dict[int, _KeyScan] = {}
        self.all_ok = True
        self.has_cas = False
        self.n = 0


def _scan(seq: OpSeq, model: ModelSpec) -> _Scan | None:
    """One O(n) pass building per-key cluster structure; None when the
    model family is out of scope or an unencodable row appears."""
    fam = _family(model)
    if fam is None:
        return None
    n = len(seq)
    f = np.asarray(seq.f)
    v1 = np.asarray(seq.v1)
    v2 = np.asarray(seq.v2)
    ok = np.asarray(seq.ok, dtype=bool)

    sc = _Scan()
    sc.n = n
    sc.all_ok = bool(ok.all())
    if bool((f == R_CAS).any()) and model.name == "cas-register":
        # a cas both reads and writes: the unique-writes block algebra
        # (rf/ww/init edges, decide-fast) does not apply — but the
        # canonical same-value read-order exchange still does (reads
        # are state-transparent whatever writes them), so the scan
        # keeps collecting read classes and taints everything else
        sc.has_cas = True

    if fam == "multi":
        keys = v1
        vals = v2
        if bool((keys == NIL).any()):
            return None  # un-keyed row: the model rejects it anyway
        init_of = {int(k): int(model.init[int(k)])
                   if 0 <= int(k) < model.state_width else 0
                   for k in np.unique(keys)}
    else:
        keys = np.zeros(n, dtype=np.int64)
        vals = v1
        init_of = {0: int(model.init[0])}

    for i in range(n):
        k = int(keys[i])
        ks = sc.keys.get(k)
        if ks is None:
            ks = sc.keys[k] = _KeyScan(k, init_of.get(k, 0))
        fi = int(f[i])
        val = int(vals[i])
        if fi == R_WRITE:
            if val == NIL or val == ks.init_val or val in ks.clusters:
                ks.tainted = True  # NIL/init/duplicate write: no algebra
                if val in ks.clusters:
                    pass
            if val not in ks.clusters:
                ks.clusters[val] = _Cluster(val, i, bool(ok[i]))
        elif fi == R_READ:
            if val == NIL:
                (ks.nil_reads if ok[i] else ks.crashed_reads).append(i)
                ks.read_classes.setdefault(NIL, []).append(i)
            else:
                ks.read_classes.setdefault(val, []).append(i)
                if not ok[i]:
                    ks.crashed_reads.append(i)
                elif val == ks.init_val:
                    ks.init_reads.append(i)
        elif fi == R_CAS and sc.has_cas:
            continue  # canon-only mode: cas rows carry no read class
        else:
            return None  # foreign op code: out of scope
    if sc.has_cas:
        for ks in sc.keys.values():
            ks.tainted = True
        return sc
    # second half: attach ok reads to clusters / find impossible reads
    for ks in sc.keys.values():
        for val, rows in ks.read_classes.items():
            if val == NIL or val == ks.init_val:
                continue
            cl = ks.clusters.get(val)
            for i in rows:
                if not ok[i]:
                    continue
                if cl is None:
                    ks.impossible.append(i)
                else:
                    cl.ok_reads.append(i)
        if ks.init_val != NIL and ks.init_val in ks.clusters:
            # a write re-creates the initial value: init reads lose
            # their "before every write" force
            ks.tainted = True
    return sc


# ---------------------------------------------------------------------------
# Forced-edge checks (complete for the forced-edge system; see module doc)
# ---------------------------------------------------------------------------


def _edge(src: int, dst: int, kind: str, via=None) -> dict:
    e = {"src": int(src), "dst": int(dst), "kind": kind}
    if via is not None:
        e["via"] = [int(via[0]), int(via[1])]
    return e


def _spans(ks: _KeyScan) -> list[tuple[int, int, _Cluster]]:
    """(s, e, cluster) for each ANCHORED cluster: s = min member
    return, e = max member invocation.  An edge u -> v (block u wholly
    before block v) is forced iff s(u) < e(v)."""
    inv, ret = _ranks()
    out = []
    for cl in ks.clusters.values():
        if not cl.anchored:
            continue
        mem = cl.members()
        s = min(int(ret[i]) for i in mem)
        e = max(int(inv[i]) for i in mem)
        cl.s, cl.e = s, e
        out.append((s, e, cl))
    return out


# per-THREAD rank views set for the duration of one analysis (stream
# folds and campaign cells analyze concurrently on worker threads, so
# plain module globals would clobber each other)
_TLS = threading.local()


def _ranks():
    return _TLS.inv, _TLS.ret


def _find_cycle(seq: OpSeq, sc: _Scan) -> list[dict] | None:
    """Complete cycle search over the forced-edge system (rt + rf +
    block + init), per key.  Returns an op-level edge cycle or None.

    Completeness: real time alone is acyclic (an interval order); a
    forced cycle therefore visits >= 1 inferred edge, inferred edges
    connect cluster members of ONE key, and rt is numerically
    transitive — so every cycle projects to (a) an intra-cluster
    read-before-its-write, (b) an init-read block inversion, or (c) a
    2-cycle between anchored block spans (a longer span cycle always
    contains a 2-cycle: take the min-s cluster on the cycle)."""
    inv, ret = _ranks()
    for ks in sc.keys.values():
        if ks.tainted:
            continue
        # (a) a read real-time-before its own (unique) write
        for cl in ks.clusters.values():
            w = cl.write
            for r in cl.ok_reads:
                if ret[r] < inv[w]:
                    return [_edge(w, r, "rf"), _edge(r, w, "rt")]
        spans = _spans(ks)
        # (b) init reads are forced before every anchored write; a
        # cluster member real-time-before an init read inverts that
        if ks.init_reads:
            ri_by_inv = max(ks.init_reads, key=lambda i: inv[i])
            for s, _e, cl in spans:
                if s < inv[ri_by_inv]:
                    x = min(cl.members(), key=lambda i: ret[i])
                    ri = next(i for i in ks.init_reads
                              if ret[x] < inv[i])
                    cyc = []
                    if x != cl.write:
                        cyc.append(_edge(cl.write, x, "rf"))
                    cyc.append(_edge(x, ri, "rt"))
                    cyc.append(_edge(ri, cl.write, "init"))
                    return cyc
        # (c) overlapping anchored block spans: blocks each forced
        # wholly before the other.  Sweep in s order; for the current
        # span find a previous one with s(prev) < e(cur) and
        # e(prev) > s(cur) via a prefix-max over the s-sorted list.
        spans.sort(key=lambda t: t[0])
        pref: list[tuple[int, _Cluster]] = []  # (prefix max e, argmax)
        ss = []
        for s, e, cl in spans:
            if pref:
                # rightmost previous span with s(prev) < e(cur)
                hi = bisect.bisect_left(ss, e)
                if hi > 0 and pref[hi - 1][0] > s:
                    u = pref[hi - 1][1]
                    # concrete member witnesses for both directions
                    a1 = min(u.members(), key=lambda i: ret[i])
                    b1 = next(i for i in cl.members()
                              if ret[a1] < inv[i])
                    a2 = min(cl.members(), key=lambda i: ret[i])
                    b2 = next(i for i in u.members()
                              if ret[a2] < inv[i])
                    return [_edge(a1, b1, "ww", via=(a1, b1)),
                            _edge(b1, a1, "ww", via=(a2, b2))]
            best = max(pref[-1][0], e) if pref else e
            pref.append((best, cl if not pref or e >= pref[-1][0]
                         else pref[-1][1]))
            ss.append(s)
    return None


# ---------------------------------------------------------------------------
# Decide-valid: the Gibbons–Korach interval construction
# ---------------------------------------------------------------------------


def _topo_clusters(spans: list[tuple[int, int, _Cluster]]
                   ) -> list[_Cluster] | None:
    """Topological order of anchored blocks under `u -> v iff
    s(u) < e(v)`, O(C log C) via lazy heaps.  None when no source
    exists (a cycle — callers treat it as undecided; the cycle pass
    already ran)."""
    import heapq

    C = len(spans)
    if C <= 1:
        return [cl for _s, _e, cl in spans]
    hs = [(s, i) for i, (s, _e, _c) in enumerate(spans)]
    he = [(e, i) for i, (_s, e, _c) in enumerate(spans)]
    heapq.heapify(hs)
    heapq.heapify(he)
    done = [False] * C
    out: list[_Cluster] = []
    INF = INF_RET + 1
    for _ in range(C):
        while hs and done[hs[0][1]]:
            heapq.heappop(hs)
        while he and done[he[0][1]]:
            heapq.heappop(he)
        s1, u1 = hs[0]
        # second-min s: pop the head, peek the next live entry, push
        # the head back — O(log C), not a scan
        heapq.heappop(hs)
        while hs and done[hs[0][1]]:
            heapq.heappop(hs)
        s2 = hs[0][0] if hs else INF
        heapq.heappush(hs, (s1, u1))
        e1, v1 = he[0]
        pick = None
        if v1 != u1 and e1 <= s1:
            pick = v1
        elif v1 == u1 and e1 <= s2:
            pick = v1
        elif v1 != u1 and spans[u1][1] <= s2:
            pick = u1
        if pick is None:
            return None
        done[pick] = True
        out.append(spans[pick][2])
    return out


def _insert_by_rt(order: list[int], rows: list[int]) -> list[int] | None:
    """Insert NIL (state-transparent) reads into an rt-consistent
    order: each goes right after its last rt predecessor.  None past
    the work cap."""
    if not rows:
        return order
    if len(rows) > NIL_INSERT_CAP:
        return None
    inv, ret = _ranks()
    for x in sorted(rows, key=lambda i: inv[i]):
        pos = 0
        for j, y in enumerate(order):
            if ret[y] < inv[x]:
                pos = j + 1
        order.insert(pos, x)
    return order


def _gk_key_order(ks: _KeyScan) -> list[int] | None:
    """Constructive linearization of ONE all-:ok key that already
    passed the cycle checks: init reads, then blocks in topological
    order (write first, reads by invocation), NIL reads re-inserted by
    real time.  None = cede to the engines."""
    inv, _ret = _ranks()
    spans = _spans(ks)
    topo = _topo_clusters(sorted(spans, key=lambda t: t[0]))
    if topo is None:
        return None
    order: list[int] = sorted(ks.init_reads, key=lambda i: inv[i])
    for cl in topo:
        order.append(cl.write)
        order.extend(sorted(cl.ok_reads, key=lambda i: inv[i]))
    return _insert_by_rt(order, ks.nil_reads)


def _verify_witness(seq: OpSeq, model: ModelSpec,
                    order: list[int]) -> bool:
    """Self-check before any decide-valid leaves this module: the
    witness covers every :ok row once, respects real time, and replays
    through the model."""
    n = len(seq)
    ok = np.asarray(seq.ok, dtype=bool)
    if sorted(order) != sorted(int(i) for i in range(n) if ok[i]):
        return False
    inv = [int(x) for x in seq.inv]
    ret = [int(x) for x in seq.ret]
    max_inv = -1
    for r in order:
        if ret[r] < max_inv:
            return False
        max_inv = max(max_inv, inv[r])
    state = model.init
    pystep = model.pystep
    f = seq.f
    v1 = seq.v1
    v2 = seq.v2
    for r in order:
        state = pystep(state, int(f[r]), int(v1[r]), int(v2[r]))
        if state is None:
            return False
    return True


# ---------------------------------------------------------------------------
# Must-order edges (the prune)
# ---------------------------------------------------------------------------


def _forced_edges(sc: _Scan, cap: int) -> list[tuple[int, int, str]]:
    """rf / block / init edges NOT already implied by real time,
    budget-capped."""
    inv, ret = _ranks()
    out: list[tuple[int, int, str]] = []

    def rt(a: int, b: int) -> bool:
        return ret[a] < inv[b]

    for ks in sc.keys.values():
        if ks.tainted:
            continue
        spans = _spans(ks)
        for _s, _e, cl in spans:
            for r in cl.ok_reads:
                if not rt(cl.write, r):
                    out.append((cl.write, r, "rf"))
                    if len(out) >= cap:
                        return out
        # init reads precede every anchored write
        for ri in ks.init_reads:
            for _s, _e, cl in spans:
                if not rt(ri, cl.write):
                    out.append((ri, cl.write, "init"))
                    if len(out) >= cap:
                        return out
        # block order: pairs u -> v forced one way only (both ways is
        # a cycle, found by the cycle pass before this runs).  The
        # pair scan is work-bounded too: rt-implied pairs cost budget
        # without emitting, so a pathological cluster structure cannot
        # go quadratic
        spans.sort(key=lambda t: t[0])
        budget = 8 * cap
        for j, (s_v, e_v, cv) in enumerate(spans):
            for (s_u, e_u, cu) in spans:
                if s_u >= e_v or budget <= 0:
                    break
                budget -= 1
                if cu is cv or s_v < e_u:
                    continue  # self, or mutual (cycle pass territory)
                # u wholly before v: reads of u precede w(v)
                if not rt(cu.write, cv.write):
                    out.append((cu.write, cv.write, "ww"))
                for r in cu.ok_reads:
                    if not rt(r, cv.write):
                        out.append((r, cv.write, "ww"))
                if len(out) >= cap:
                    return out
            if budget <= 0:
                break
    return out


def _canon_edges(sc: _Scan, cap: int) -> list[tuple[int, int, str]]:
    """Canonical-order chains over same-key same-value reads: a
    staircase (inv AND ret both non-decreasing) is exchange-safe, so
    forcing it loses no linearization — but masks the frontier's
    read-permutation blowup."""
    inv, ret = _ranks()
    out: list[tuple[int, int, str]] = []
    for ks in sc.keys.values():
        for _val, rows in ks.read_classes.items():
            if len(rows) < 2:
                continue
            chain = sorted(rows, key=lambda i: (inv[i], i))
            prev = chain[0]
            for nxt in chain[1:]:
                if ret[nxt] >= ret[prev]:
                    if not ret[prev] < inv[nxt]:  # rt gives it anyway
                        out.append((prev, nxt, "canon"))
                        if len(out) >= cap:
                            return out
                    prev = nxt
    return out


def _window_effective(seq: OpSeq, edges) -> tuple[int, int]:
    """(raw, effective) window bounds — the effective one recomputed
    with must-order edges removed from each position's freedom span;
    the basis of the pruned config bound."""
    ok = np.asarray(seq.ok, dtype=bool)
    det_rows = np.nonzero(ok)[0]
    nd = len(det_rows)
    if nd == 0:
        return 1, 1
    pos_of = {int(r): p for p, r in enumerate(det_rows)}
    det_inv = np.asarray(seq.inv, dtype=np.int64)[det_rows]
    det_ret = np.asarray(seq.ret, dtype=np.int64)[det_rows]
    upper = np.searchsorted(det_inv, det_ret, side="left")
    spans = (upper - np.arange(nd)).astype(np.int64)
    raw = max(1, int(spans.max()))
    for (src, dst, _k) in edges:
        ps, pd = pos_of.get(src), pos_of.get(dst)
        if ps is None or pd is None or ps >= pd:
            continue
        # dst can no longer linearize while src (at ps) is the first
        # unlinearized op: one slot of ps's span freedom is gone
        if pd < int(upper[ps]):
            spans[ps] -= 1
    return raw, max(1, int(spans.max()))


# ---------------------------------------------------------------------------
# The pre-pass
# ---------------------------------------------------------------------------


def _decided_result(valid, *, certificate: dict, stats: dict) -> dict:
    stats["pruned_upper_bound"] = 0
    stats["prune_ratio"] = 0.0
    out = {"valid": valid, "configs": 0, "max_depth": 0,
           "engine": "hb-decide"}
    out.update(certificate)
    out["hb"] = stats
    return out


def analyze_hb(seq: OpSeq, model: ModelSpec, *,
               canon: bool = True) -> HBAnalysis:
    """The full pre-pass.  Never raises on in-scope inputs; anything
    out of scope comes back ``applies=False`` and undecided."""
    n = len(seq)
    stats = {"applies": False, "decided": None, "reason": None,
             "edges": {"rf": 0, "ww": 0, "init": 0, "canon": 0},
             "must_edges": 0}
    hb = HBAnalysis(n=n, applies=False, decided=None, stats=stats)
    if n == 0:
        stats["reason"] = "empty history"
        return hb
    sc = _scan(seq, model)
    if sc is None:
        stats["reason"] = f"model {model.name!r} out of scope"
        return hb
    if sc.has_cas:
        stats["reason"] = ("cas ops present (no unique-writes "
                          "algebra; canonical read-order only)")
    hb.applies = True
    stats["applies"] = True
    stats["keys"] = len(sc.keys)
    stats["clusters"] = sum(len(ks.clusters) for ks in sc.keys.values())

    _TLS.inv = [int(x) for x in seq.inv]
    _TLS.ret = [int(x) for x in seq.ret]
    try:
        # ---- decide-fast: impossible reads --------------------------
        impossible = sorted(r for ks in sc.keys.values()
                            for r in ks.impossible)
        if impossible:
            stats["decided"] = False
            stats["reason"] = "impossible-read"
            hb.decided = _decided_result(
                False, certificate={"final_ops": impossible},
                stats=stats)
            return hb

        # ---- decide-fast: forced-edge cycle -------------------------
        cyc = _find_cycle(seq, sc)
        if cyc is not None:
            stats["decided"] = False
            stats["reason"] = "hb-cycle"
            hb.decided = _decided_result(
                False, certificate={"hb_cycle": cyc}, stats=stats)
            return hb

        # ---- decide-fast: full interval decision (all-:ok class) ----
        if sc.all_ok and all(not ks.tainted for ks in sc.keys.values()):
            orders = []
            for ks in sc.keys.values():
                o = _gk_key_order(ks)
                if o is None:
                    orders = None
                    break
                orders.append(o)
            if orders is not None:
                if len(orders) == 1:
                    order = orders[0]
                else:
                    from ..decompose.partition import \
                        merge_linearizations

                    order = merge_linearizations(seq, orders)
                if order is not None and \
                        _verify_witness(seq, model, order):
                    stats["decided"] = True
                    stats["reason"] = "gk-interval"
                    hb.decided = _decided_result(
                        True,
                        certificate={
                            "linearization": [int(r) for r in order],
                            "max_depth": len(order)},
                        stats=stats)
                    return hb

        # ---- undecided: emit the prune ------------------------------
        cap = max(EDGE_CAP_MIN, EDGE_CAP_FACTOR * n)
        edges = _forced_edges(sc, cap)
        if canon:
            edges += _canon_edges(sc, max(0, cap - len(edges)))
        for (_s, _d, k) in edges:
            stats["edges"][k] += 1
        stats["must_edges"] = len(edges)
        must: dict[int, list[int]] = {}
        for (src, dst, _k) in edges:
            must.setdefault(int(dst), []).append(int(src))
        hb.must_pred = {d: tuple(sorted(set(s)))
                        for d, s in must.items()}
        w_raw, w_eff = _window_effective(seq, edges)
        ok = np.asarray(seq.ok, dtype=bool)
        nd = int(ok.sum())
        raw = (nd + 1) << (max(0, w_raw - 1) + (n - nd))
        pruned = min((nd + 1) << (max(0, w_eff - 1) + (n - nd)), raw)
        stats["window_effective"] = w_eff
        stats["pruned_upper_bound"] = pruned
        stats["prune_ratio"] = (round(pruned / raw, 6) if raw
                                else None)
        return hb
    finally:
        _TLS.inv = _TLS.ret = None


def maybe_hb(seq: OpSeq, model: ModelSpec,
             flag: bool | None = None,
             dpor: bool | None = None) -> HBAnalysis | None:
    """The engines' shared pre-pass preamble: resolve the three-state
    flag (None follows JEPSEN_TPU_HB, default on), run the analysis
    under an ``obs`` span, and feed the ``jtpu_hb_*`` metrics.  ONE
    home for the policy, mirroring ``lint.maybe_lint``.

    This is the unified prepass SLOT: register-family models run the
    HB order-solver below; queue/lock families dispatch to the
    model-generic constraint compiler (analyze/constraints.py), which
    returns the same :class:`HBAnalysis` shape — every consumer of
    this function (host DFS mask, linear frame mask, batch disposal,
    decomposed and streamed sub-searches) gets both solvers' verdicts
    and must-order edges with no extra wiring."""
    if not resolve_hb(flag) or len(seq) == 0:
        return None
    from .constraints import family_of, maybe_constraints
    from .dpor import merge_dup_edges

    if family_of(model) is not None:
        # the dynamic layer's duplicate-op edges are model-agnostic
        # (label-swap symmetry), so the constraint-compiler families
        # get them through the same transport
        return merge_dup_edges(seq, model,
                               maybe_constraints(seq, model), dpor)
    from .. import obs

    with obs.span("hb.prepass", cat="analyze", rows=len(seq)):
        hb = analyze_hb(seq, model)
    merge_dup_edges(seq, model, hb, dpor)
    if not hb.applies:
        _M_PREPASS.inc(outcome="skipped")
        return hb
    if hb.decided is not None:
        _M_PREPASS.inc(outcome="decided_valid"
                       if hb.decided["valid"] else "decided_invalid")
        _M_RATIO.set(0.0)
    else:
        _M_PREPASS.inc(outcome="undecided")
        _M_RATIO.set(hb.stats.get("prune_ratio") or 1.0)
        for k, v in hb.stats["edges"].items():
            if v:
                _M_EDGES.inc(v, kind=k)
    return hb


def hb_dispose(seq: OpSeq, model: ModelSpec,
               flag: bool | None = True) -> dict | None:
    """Decide-fast only — the per-key disposal the batch schedulers
    run next to the greedy witness.  Returns a full engine-style result
    dict (certificate included) or None when the key must be searched.
    Dispatches through the unified prepass, so queue/lock-family keys
    dispose on constraint-compiler verdicts the same way register keys
    dispose on HB verdicts."""
    hbres = maybe_hb(seq, model, flag)
    if hbres is not None and hbres.decided is not None:
        return dict(hbres.decided)
    return None


def attach(result: dict, hb: HBAnalysis | None) -> dict:
    """Record the pre-pass summary on an engine result (undecided
    histories only; decided ones already carry it).  HB-solver stats
    land under ``result["hb"]``, constraint-compiler stats under
    ``result["constraints"]`` — the key names the solver."""
    if hb is not None and hb.applies:
        key = "constraints" if hb.stats.get("solver") == "constraints" \
            else "hb"
        if key not in result:
            result[key] = hb.stats
    return result


# ---------------------------------------------------------------------------
# Plan integration (analyze/plan.py's explain() consumes this)
# ---------------------------------------------------------------------------


def plan_block(seq: OpSeq, model: ModelSpec, raw_bound: int,
               n_crash: int, window: int, hb_analysis=None) -> dict:
    """The static ``hb`` block for explain(): decidability, inferred
    edge counts, and the pruned config bound next to the raw one.
    Pure description — the analysis already computed the bounds, and
    describing a plan must not touch the live ``jtpu_hb_prune_ratio``
    gauge (that tracks pre-passes that actually ran).  ``hb_analysis``
    lets the caller share one solve across plan blocks."""
    hb = hb_analysis if hb_analysis is not None else analyze_hb(seq, model)
    st = dict(hb.stats)
    st["enabled"] = hb_enabled()
    if "pruned_upper_bound" not in st:
        st["pruned_upper_bound"] = raw_bound
        st["prune_ratio"] = 1.0
    return st


# ---------------------------------------------------------------------------
# Streamed / decomposed segment folds
# ---------------------------------------------------------------------------


def hb_fold_states(sseq: OpSeq, model: ModelSpec, instates, *,
                   witness: bool = False):
    """Answer one crash-free segment fold with the interval pass:
    the set of reachable final states from ``instates`` — the value of
    a can-be-last block per instate — without the level-synchronous
    sweep.  Returns ``states`` (or ``(states, wit)`` with
    ``witness=True``, ``wit`` mapping each out-state to
    ``(in_state, row_chain)``), or None when the segment is outside
    the decidable class (the caller falls back to the generic fold).
    Exact by construction: witnesses (when requested) replay clean or
    the fold cedes."""
    from dataclasses import replace as _dc_replace

    if _family(model) != "register":
        return None
    n = len(sseq)
    instates = [tuple(int(x) for x in s) for s in instates]
    if not instates or len(instates) > FOLD_INSTATE_CAP:
        return None
    if n and not bool(np.asarray(sseq.ok, dtype=bool).all()):
        return None
    states: set = set()
    wit: dict | None = {} if witness else None
    for ins in instates:
        m = _dc_replace(model, init=ins)
        sc = _scan(sseq, m)
        if sc is None or sc.has_cas or \
                any(ks.tainted for ks in sc.keys.values()):
            return None
        _TLS.inv = [int(x) for x in sseq.inv]
        _TLS.ret = [int(x) for x in sseq.ret]
        try:
            if any(ks.impossible for ks in sc.keys.values()) or \
                    _find_cycle(sseq, sc) is not None:
                continue  # no linearization from this instate
            ks = sc.keys.get(0)
            if ks is None:  # empty segment
                states.add(ins)
                if wit is not None:
                    wit.setdefault(ins, (ins, []))
                continue
            spans = _spans(ks)
            if not spans:
                # no writes: the state cannot move
                order = _gk_key_order(ks)
                if order is None or \
                        not _verify_witness(sseq, m, order):
                    return None
                states.add(ins)
                if wit is not None:
                    wit.setdefault(ins, (ins, [int(r) for r in order]))
                continue
            # can-be-last blocks: no outgoing span edge
            e_sorted = sorted(e for s, e, _c in spans)
            lasts = []
            for s, e, cl in spans:
                e_max = e_sorted[-1] if e_sorted[-1] != e \
                    else (e_sorted[-2] if len(e_sorted) > 1 else -1)
                if s >= e_max:
                    lasts.append(cl)
            if not lasts:
                return None  # acyclic spans always have a sink
            if len(lasts) > FOLD_WITNESS_STATES:
                # many reachable out-states: cede the WHOLE fold —
                # a truncated state set would be a wrong frontier
                # (and would poison the shared segment cache)
                return None
            # every can-be-last block contributes exactly one
            # out-state; each gets a constructed, verified order or
            # the whole fold cedes — the state set is exact or absent,
            # never truncated
            for cl in lasts:
                st = (int(cl.val),)
                others = [(s, e, c) for s, e, c in spans if c is not cl]
                topo = _topo_clusters(sorted(others,
                                             key=lambda t: t[0]))
                if topo is None:
                    return None
                _inv = _TLS.inv
                order = sorted(ks.init_reads, key=lambda i: _inv[i])
                for c in [*topo, cl]:
                    order.append(c.write)
                    order.extend(sorted(c.ok_reads,
                                        key=lambda i: _inv[i]))
                order = _insert_by_rt(order, ks.nil_reads)
                if order is None or \
                        not _verify_witness(sseq, m, order):
                    return None
                states.add(st)
                if wit is not None:
                    wit.setdefault(st, (ins, [int(r) for r in order]))
        finally:
            _TLS.inv = _TLS.ret = None
    _M_FOLDS.inc()
    if witness:
        return states, wit
    return states
