"""Static analysis over histories, search plans, and suites.

Three passes, one O(n) substrate (the GPUexplore lesson, arXiv:1801.05857:
validate on the cheap host before paying for accelerated search):

  * :mod:`jepsen_tpu.analyze.lint` — well-formedness linter.  A single
    O(n) scan over an event history (or an encoded OpSeq) producing
    structured diagnostics with stable codes (H001 double-invoke, H002
    orphan completion, ... M001 op unknown to model).  Wired on by
    default into ``check_opseq``, ``check_opseq_linear``,
    ``Linearizable.check``, ``search_batch`` and the decompose engine:
    errors are fatal (:class:`HistoryLintError`), warnings ride the
    result dict.  ``JEPSEN_TPU_LINT=0`` (or ``lint=False`` per call)
    restores the old silent tolerance.

  * :mod:`jepsen_tpu.analyze.plan` — search-plan explainer.
    :func:`explain` predicts, without running anything, exactly what the
    live engines would do: concurrency width, window, crash words,
    ``SearchDims``, the shape bucket, which decompositions apply
    (key-partition / value-blocks / quiescence), the engine route, and a
    state-space upper bound.  The decomposition applicability gates LIVE
    here and are consumed by ``decompose/partition.py`` — predictor and
    engine cannot drift.

  * :mod:`jepsen_tpu.analyze.suites` — suite protocol lint.  AST checks
    over ``jepsen_tpu/suites/*`` (S-codes: invoke must return a typed
    completion, no broad except converting crashes to determinate
    verdicts, setup/teardown pairing, nemesis completions are :info).
    ``tools/lint_suites.py`` is the standalone CLI;
    ``tests/test_suite_lint.py`` gates the bundled suites in tier-1.

  * :mod:`jepsen_tpu.analyze.constraints` — model-generic constraint
    compiler.  The non-register half of the static prepass slot
    (``hb.maybe_hb`` dispatches by model family): queue families get
    enqueue->dequeue read-from edges, FIFO must-order, and decide-fast
    certificates (W007/W008 — lost-acked-enqueue, duplicate delivery,
    FIFO inversion); locks get acquire/release alternation sweeps;
    event-level multiset analysis backs the streaming total-queue fold
    route and the Q-code history lint.

Two further passes close the loop on the *output* side (ISSUE 4 —
proof-carrying verdicts):

  * :mod:`jepsen_tpu.analyze.audit` — independent certificate audit.
    Every engine verdict now carries a certificate (``linearization``
    or ``witness_dropped`` on valid; ``final_ops`` or
    ``frontier_dropped`` on invalid); :func:`audit` replays it against
    the model in pure Python (W001-W005), sharing no code with the
    engines.  Opt-in via ``audit=True`` per call, ``JEPSEN_TPU_AUDIT=1``
    fleet-wide, or the CLI ``--audit``; on by default in the
    differential-fuzz tests.

  * :mod:`jepsen_tpu.analyze.shrink` — counterexample minimization.
    :func:`shrink_invalid` delta-debugs an invalid history to a
    1-minimal failing subhistory, independently confirmed by a naive
    brute-force permutation checker; failure reports (linear_report /
    web UI) render the minimal core as the failure story.

``analyze(history, model)`` runs lint + plan in one call;
``python -m jepsen_tpu.analyze history.jsonl --model cas-register
--explain`` does the same from a stored history, and ``--audit
result.json`` replays a stored result's certificate against it.
"""

from __future__ import annotations

from .audit import (  # noqa: F401
    AUDIT_CODES,
    AuditError,
    audit,
    audit_enabled,
    audit_events,
)
from .constraints import (  # noqa: F401
    MultisetFold,
    analyze_constraints,
    analyze_prepass,
    analyze_queue_events,
    analyze_set_events,
    family_of,
)
from .dpor import (  # noqa: F401
    SleepSets,
    dpor_enabled,
    duplicate_op_edges,
    resolve_dpor,
)
from .hb import (  # noqa: F401
    HBAnalysis,
    analyze_hb,
    hb_dispose,
    hb_enabled,
    hb_fold_states,
    maybe_hb,
)
from .lint import (  # noqa: F401
    Diagnostic,
    HistoryLintError,
    HistoryScan,
    lint_enabled,
    lint_history,
    lint_opseq,
    scan_events,
)
from .plan import explain, explain_batch  # noqa: F401
from .shrink import brute_force_check, shrink_invalid  # noqa: F401


def analyze(history, model=None) -> dict:
    """Lint + plan in one call.

    ``history`` is an event-level list of :class:`~jepsen_tpu.history.Op`
    or an encoded :class:`~jepsen_tpu.history.OpSeq`.  Returns::

        {"diagnostics": [Diagnostic...], "errors": n, "warnings": n,
         "plan": {...} | None}

    The plan is computed only when the history is well-formed enough to
    encode (no error diagnostics) and a model is given.
    """
    from ..history import OpSeq

    if isinstance(history, OpSeq):
        diags = lint_opseq(history, model)
    else:
        diags = lint_history(history, model)
    errors = [d for d in diags if d.severity == "error"]
    plan = None
    if model is not None and not errors:
        plan = explain(history, model)
    return {"diagnostics": diags, "errors": len(errors),
            "warnings": len(diags) - len(errors), "plan": plan}
