"""Well-formedness linter — one O(n) scan, structured diagnostics.

Every verdict the search engines emit is only as trustworthy as the
history fed in, yet ``history.pair_index``/``complete`` silently tolerate
malformed input: a double-invoke overwrites the open op, an orphan
completion is dropped, an unknown completion type falls through the
``type == INVOKE`` test as if it were a completion.  Each of those can
flow into the exponential search and produce a wrong verdict or a
device-shape crash.  This module is the cheap host-side guard in front of
the accelerator (the GPUexplore pattern, arXiv:1801.05857).

Error codes (stable; documented in docs/analyze.md):

==== ======== ==========================================================
code severity meaning
==== ======== ==========================================================
H001 error    double-invoke: a process invoked with an op still open
H002 error    orphan completion: completion with no open invoke
H003 error    event type not in {invoke, ok, fail, info}
H004 warning* non-monotone ``op.index`` values (event level); at the
              OpSeq level (``inv``/``ret`` rank defects) it is an error
H005 error    value not encodable by ValueEncoder (unhashable)
H006 warning  ok completion's value conflicts with the invocation's
H007 error    OpSeq column shape mismatch
M001 error    op ``f`` unknown to the model's f_codes
Q001 error    ack of a job no :ok dequeue/claim ever delivered
Q002 error    double-ack: the same job acked :ok twice
Q003 warning* :ok dequeue (or drained element) of a value no enqueue
              ever attempted
==== ======== ==========================================================

(*) engines re-index events positionally, so a stale ``op.index`` cannot
change a verdict — it only misleads humans reading reports.

Severity of the Q (queue-history) codes follows checker semantics:
``Q003`` is exactly the violation the multiset checkers
(``checker.basic.queue``/``total_queue``) exist to JUDGE, so lint must
not preempt the verdict — it warns.  ``Q001``/``Q002`` describe
claim/ack protocol streams no checker consumes (the checkers ignore
``ack``/``claim`` ops entirely), so a malformed ack stream is a
recording defect that would otherwise vanish silently — they error.

The event-level scan (:func:`scan_events`) is a single O(n) pass that
also collects the facts the plan explainer (analyze/plan.py) reads:
event counts, processes, client concurrency, crash count.  The OpSeq
level (:func:`lint_opseq`) re-checks the columnar invariants the device
encoding relies on (``inv`` strictly increasing, ``ret`` after ``inv``,
ok rows completed, f codes known).

Verdict neutrality: on a well-formed history every check passes and the
engines behave bit-identically (differential fuzz in
tests/test_analyze.py); lint errors surface as
:class:`HistoryLintError` *instead of* an undefined search result.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Sequence

import numpy as np

from ..history import FAIL, INF_RET, INFO, INVOKE, OK, OpSeq, is_client_op

#: the four legal event types (core.clj:271-278)
EVENT_TYPES = (INVOKE, OK, FAIL, INFO)

ERROR_CODES = {
    "H001": "double-invoke on a process with an open op",
    "H002": "orphan completion (no open invoke on the process)",
    "H003": "event type not in {invoke, ok, fail, info}",
    "H004": "non-monotone indices",
    "H005": "value not encodable by ValueEncoder",
    "H006": "ok completion value conflicts with the invocation value",
    "H007": "OpSeq column shape mismatch",
    "M001": "op f unknown to the model",
    "Q001": "ack of a job no :ok dequeue/claim ever delivered",
    "Q002": "double-ack: the same job acked :ok twice",
    "Q003": ":ok dequeue of a value no enqueue ever attempted",
}

#: the queue-history lint family (docstring table) — runnable on its
#: own via ``scan_events(history, codes=QUEUE_CODES)``, which is how
#: the multiset checkers (checker/basic.py) wire it on by default
#: without dragging the pairing codes into their permissive contract
QUEUE_CODES = ("Q001", "Q002", "Q003")


@dataclass(frozen=True)
class Diagnostic:
    """One structured finding.  ``index`` is the event index (or OpSeq
    row), ``process``/``f`` the op coordinates when known."""

    code: str
    severity: str  # "error" | "warning"
    message: str
    index: int | None = None
    process: Any = None
    f: Any = None

    def to_dict(self) -> dict:
        d = {"code": self.code, "severity": self.severity,
             "message": self.message}
        if self.index is not None:
            d["index"] = self.index
        if self.process is not None:
            d["process"] = self.process
        if self.f is not None:
            d["f"] = self.f
        return d

    def __str__(self) -> str:
        where = f" @{self.index}" if self.index is not None else ""
        return f"{self.code}{where}: {self.message}"


class HistoryLintError(ValueError):
    """A history failed well-formedness lint.  ``diagnostics`` carries
    every finding (not just the first), so one round trip fixes all."""

    def __init__(self, diagnostics: Sequence[Diagnostic]):
        self.diagnostics = list(diagnostics)
        errs = [d for d in self.diagnostics if d.severity == "error"]
        head = "; ".join(str(d) for d in errs[:5])
        more = f" (+{len(errs) - 5} more)" if len(errs) > 5 else ""
        super().__init__(f"malformed history: {head}{more}")


def lint_enabled() -> bool:
    """The on-by-default knob: JEPSEN_TPU_LINT=0/off/false/no disables
    linting fleet-wide (engines also take a per-call ``lint=``)."""
    return os.environ.get("JEPSEN_TPU_LINT", "").strip().lower() not in (
        "0", "off", "false", "no")


@dataclass
class HistoryScan:
    """Everything one O(n) pass over an event history learns: the
    diagnostics plus the facts the plan explainer reads."""

    diagnostics: list[Diagnostic] = field(default_factory=list)
    n_events: int = 0
    n_invoke: int = 0
    n_ok: int = 0
    n_fail: int = 0
    n_info: int = 0
    #: client invokes whose fate is indeterminate (:info completion or
    #: no completion at all) — each costs a crash-mask bit on device
    n_crashed: int = 0
    #: peak simultaneously-open client ops (crashed ops stay open
    #: forever, matching history.max_concurrency's sweep)
    concurrency: int = 0
    processes: list = field(default_factory=list)
    has_nemesis: bool = False
    #: event index -> partner event index (same map pair_index builds)
    pairs: dict = field(default_factory=dict)

    @property
    def errors(self) -> list[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "error"]

    @property
    def warnings(self) -> list[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "warning"]


def _value_drift(inv_v, comp_v) -> bool:
    """Does an ok completion's value CONFLICT with the invocation's?

    A nil invocation lane is a wildcard the completion legitimately
    fills in (the complete() contract: an ok'd read invokes with value
    nil — or a compound value with nil lanes, e.g. multi-register's
    ``(key, nil)`` — and the completion supplies what was read)."""
    if inv_v is None or comp_v is None:
        return False
    a, b = _lanes_view(inv_v), _lanes_view(comp_v)
    if a is not None and b is not None and len(a) == len(b):
        return any(x is not None and y is not None and x != y
                   for x, y in zip(a, b))
    return inv_v != comp_v


def _lanes_view(v):
    """A value's nil-capable lanes, when it has that shape: a 2-seq, an
    independent.KV (``[key value]``), or a stored history's JSON
    round-trip of one (KV serializes as its ``"[k v]"`` repr, so a read
    pair like ``"[4 None]" -> "[4 1]"`` must still read as refinement,
    not drift)."""
    if isinstance(v, (tuple, list)):
        return list(v)
    if hasattr(v, "key") and hasattr(v, "value"):  # independent.KV
        return [v.key, v.value]
    if isinstance(v, str) and len(v) > 2 and v[0] == "[" and v[-1] == "]":
        parts = v[1:-1].split(" ")
        if len(parts) == 2:
            return [None if p in ("None", "nil") else p for p in parts]
    return None


def _encodable(value) -> bool:
    """Mirror encode_ops.default_lanes: a 2-tuple/list encodes per lane,
    anything else interns whole — both need hashable parts."""
    if isinstance(value, (tuple, list)) and len(value) == 2:
        parts = value
    else:
        parts = (value,)
    for p in parts:
        try:
            hash(p)
        except TypeError:
            return False
    return True


def _hashable(v) -> bool:
    try:
        hash(v)
    except TypeError:
        return False
    return True


def _q_scan(op, i: int, t: str, want: set, diags: list,
            attempts: set, claimed: set, acked: set,
            flagged: set) -> None:
    """The queue-history (Q-code) checks for one client event.

    ``enqueue`` invokes register attempts; :ok ``dequeue``/``claim``
    completions (and :ok ``drain`` elements) register deliveries and
    trip Q003 on values no enqueue ever attempted; ``ack`` ops trip
    Q001 (ack-without-claim) at their invoke and Q002 (double-ack) at
    their :ok completion.  Unhashable values are H005's beat, not
    ours."""
    f, v = op.f, op.value
    if f == "enqueue":
        if t == INVOKE and _hashable(v):
            attempts.add(v)
        return
    if f in ("dequeue", "claim"):
        if t == OK and _hashable(v):
            claimed.add(v)
            if "Q003" in want and f == "dequeue" \
                    and v is not None and v not in attempts \
                    and v not in flagged:
                flagged.add(v)
                diags.append(Diagnostic(
                    "Q003", "warning",
                    f":ok dequeue of {v!r} at event {i}, a value no "
                    f"enqueue ever attempted (the multiset checker "
                    f"will judge it unexpected)",
                    index=i, process=op.process, f=f))
        return
    if f == "drain" and t == OK and isinstance(v, (list, tuple)):
        for element in v:
            if _hashable(element):
                claimed.add(element)
                if "Q003" in want and element not in attempts \
                        and element not in flagged:
                    flagged.add(element)
                    diags.append(Diagnostic(
                        "Q003", "warning",
                        f":ok drain at event {i} delivered "
                        f"{element!r}, a value no enqueue ever "
                        f"attempted", index=i, process=op.process,
                        f=f))
        return
    if f == "ack" and _hashable(v):
        if t == INVOKE and "Q001" in want and v not in claimed:
            diags.append(Diagnostic(
                "Q001", "error",
                f"ack of {v!r} at event {i} but no :ok dequeue/claim "
                f"ever delivered it (ack-without-claim: the recorded "
                f"protocol stream is inconsistent)",
                index=i, process=op.process, f=f))
        elif t == OK and "Q002" in want:
            if v in acked:
                diags.append(Diagnostic(
                    "Q002", "error",
                    f"double-ack of {v!r} at event {i} (already acked "
                    f":ok earlier)", index=i, process=op.process, f=f))
            acked.add(v)


def scan_events(history: Sequence, model=None, *,
                codes: Sequence[str] | None = None) -> HistoryScan:
    """The single O(n) event-level pass.

    ``model`` enables the model-facing checks (M001, and H005 on the
    rows that will actually be encoded).  ``codes`` restricts which
    checks run (history.pair_index's strict mode wants only the pairing
    codes); None runs everything.
    """
    want = set(codes) if codes is not None else set(ERROR_CODES)
    sc = HistoryScan()
    open_by_process: dict[Any, int] = {}
    #: open client invoke events whose completion type decides whether
    #: their value reaches the model (H005/M001 mirror encode_ops: fail
    #: rows are dropped, so their defects are non-events)
    f_codes = getattr(model, "f_codes", None)
    check_f = bool(f_codes) and "M001" in want  # empty/noop table: skip
    last_index: int | None = None
    indices_flagged = False
    diags = sc.diagnostics
    # queue-history lint state (Q-codes; all O(1) per event)
    q_want = bool(want & {"Q001", "Q002", "Q003"})
    q_attempts: set = set()   # enqueue-invoke values
    q_claimed: set = set()    # values an :ok dequeue/claim delivered
    q_acked: set = set()      # values :ok acked
    q_flagged: set = set()    # one Q003 per value is plenty

    for i, op in enumerate(history):
        sc.n_events += 1
        t = op.type
        if t == INVOKE:
            sc.n_invoke += 1
        elif t == OK:
            sc.n_ok += 1
        elif t == FAIL:
            sc.n_fail += 1
        elif t == INFO:
            sc.n_info += 1
        elif "H003" in want:
            diags.append(Diagnostic(
                "H003", "error",
                f"event type {t!r} not in {{invoke, ok, fail, info}}",
                index=i, process=op.process, f=op.f))
            continue  # unknown type: neither invoke nor completion

        if op.process not in open_by_process and \
                op.process not in sc.processes:
            sc.processes.append(op.process)
        client = is_client_op(op)
        if not client:
            sc.has_nemesis = sc.has_nemesis or op.process == "nemesis"

        if op.index is not None and "H004" in want:
            if last_index is not None and op.index <= last_index \
                    and not indices_flagged:
                diags.append(Diagnostic(
                    "H004", "warning",
                    f"op.index {op.index} at event {i} not greater than "
                    f"previous index {last_index} (engines re-index "
                    f"positionally; reports may mislabel ops)",
                    index=i, process=op.process, f=op.f))
                indices_flagged = True  # once per history is plenty
            last_index = op.index

        if not client:
            # the nemesis journals :info events freely (core.clj:315-327
            # — both the invocation and the completion are :info), so
            # pairing/model rules apply to client processes only
            continue

        if q_want:
            _q_scan(op, i, t, want, diags, q_attempts, q_claimed,
                    q_acked, q_flagged)

        if t == INVOKE:
            prev = open_by_process.get(op.process)
            if prev is not None and "H001" in want:
                diags.append(Diagnostic(
                    "H001", "error",
                    f"process {op.process!r} invoked {op.f!r} at event "
                    f"{i} while its invoke at event {prev} is still "
                    f"open (single-threaded-process invariant, "
                    f"core.clj:387-404)",
                    index=i, process=op.process, f=op.f))
            open_by_process[op.process] = i
        elif t in (OK, FAIL, INFO):
            j = open_by_process.pop(op.process, None)
            if j is None:
                if "H002" in want:
                    diags.append(Diagnostic(
                        "H002", "error",
                        f"{t} completion for process {op.process!r} at "
                        f"event {i} has no open invoke "
                        f"(pair_index would silently drop it)",
                        index=i, process=op.process, f=op.f))
            else:
                sc.pairs[j] = i
                sc.pairs[i] = j
                inv_op = history[j]
                if inv_op.f != op.f and "H006" in want:
                    diags.append(Diagnostic(
                        "H006", "warning",
                        f"completion f={op.f!r} at event {i} differs "
                        f"from invocation f={inv_op.f!r} at event {j}",
                        index=i, process=op.process, f=op.f))
                elif (t == OK and "H006" in want
                        and _value_drift(inv_op.value, op.value)):
                    diags.append(Diagnostic(
                        "H006", "warning",
                        f"ok completion at event {i} carries value "
                        f"{op.value!r} but the invocation at event {j} "
                        f"had {inv_op.value!r} (complete() will "
                        f"overwrite the invocation's value)",
                        index=i, process=op.process, f=op.f))
                if t != FAIL:
                    # this row survives encode_ops: model-facing checks
                    val = op.value if (t == OK and op.value is not None) \
                        else inv_op.value
                    if "H005" in want and not _encodable(val):
                        diags.append(Diagnostic(
                            "H005", "error",
                            f"value {val!r} for {inv_op.f!r} at event "
                            f"{j} is not encodable by ValueEncoder "
                            f"(unhashable)",
                            index=j, process=op.process, f=inv_op.f))
                    if check_f and inv_op.f not in f_codes:
                        diags.append(Diagnostic(
                            "M001", "error",
                            f"op f={inv_op.f!r} at event {j} unknown to "
                            f"model {model.name!r} "
                            f"(f_codes: {sorted(map(str, f_codes))})",
                            index=j, process=op.process, f=inv_op.f))
            if t == INFO:
                sc.n_crashed += 1

    # crashed invokes with no completion at all
    for p, j in open_by_process.items():
        sc.n_crashed += 1
        inv_op = history[j]
        if "H005" in want and not _encodable(inv_op.value):
            diags.append(Diagnostic(
                "H005", "error",
                f"value {inv_op.value!r} for {inv_op.f!r} at event {j} "
                f"is not encodable by ValueEncoder (unhashable)",
                index=j, process=p, f=inv_op.f))
        if check_f and inv_op.f not in f_codes:
            diags.append(Diagnostic(
                "M001", "error",
                f"op f={inv_op.f!r} at event {j} unknown to model "
                f"{model.name!r} (f_codes: {sorted(map(str, f_codes))})",
                index=j, process=p, f=inv_op.f))

    # client concurrency sweep: +1 per invoke, -1 per ok/fail pairing;
    # info completions (and never-completed invokes) stay open forever
    cur = peak = 0
    for i, op in enumerate(history):
        if not is_client_op(op):
            continue
        if op.type == INVOKE:
            cur += 1
            peak = max(peak, cur)
        elif op.type in (OK, FAIL) and sc.pairs.get(i) is not None:
            cur -= 1
    sc.concurrency = peak
    return sc


def lint_history(history: Sequence, model=None) -> list[Diagnostic]:
    """Event-level lint.  Returns every diagnostic; raising on errors is
    the caller's policy (:func:`check_history` applies the default)."""
    return scan_events(history, model).diagnostics


def check_history(history: Sequence, model=None) -> list[Diagnostic]:
    """Lint and RAISE on errors; returns the warnings.

    The default policy the user-facing checkers apply: errors are fatal
    (a malformed history must not flow into the search), warnings ride
    the result dict.
    """
    diags = lint_history(history, model)
    errs = [d for d in diags if d.severity == "error"]
    if errs:
        raise HistoryLintError(diags)
    return diags


def lint_opseq(seq: OpSeq, model=None) -> list[Diagnostic]:
    """Columnar lint over an encoded OpSeq — the invariants the search
    engines (and the device encoding) rely on, O(n) numpy.

    Histories encoded by ``encode_ops`` satisfy all of these by
    construction; hand-built or corrupted OpSeqs are exactly what this
    catches before they reach an exponential search.
    """
    diags: list[Diagnostic] = []
    n = len(seq)
    cols = {"process": seq.process, "f": seq.f, "v1": seq.v1,
            "v2": seq.v2, "inv": seq.inv, "ret": seq.ret, "ok": seq.ok}
    bad_shape = [name for name, c in cols.items() if len(c) != n]
    if bad_shape:
        diags.append(Diagnostic(
            "H007", "error",
            f"OpSeq columns {bad_shape} disagree with len(process)={n}"))
        return diags  # nothing below is safe to vectorize
    if n == 0:
        return diags

    inv = np.asarray(seq.inv, dtype=np.int64)
    ret = np.asarray(seq.ret, dtype=np.int64)
    ok = np.asarray(seq.ok, dtype=bool)

    nonmono = np.nonzero(inv[1:] <= inv[:-1])[0]
    for i in nonmono[:8]:
        diags.append(Diagnostic(
            "H004", "error",
            f"inv not strictly increasing at row {int(i) + 1} "
            f"(inv[{int(i)}]={int(inv[i])}, "
            f"inv[{int(i) + 1}]={int(inv[i + 1])}); rows must be "
            f"sorted by invocation", index=int(i) + 1))
    completed = ret != INF_RET
    bad_ret = np.nonzero(completed & (ret <= inv))[0]
    for i in bad_ret[:8]:
        diags.append(Diagnostic(
            "H004", "error",
            f"row {int(i)} returns at rank {int(ret[i])} <= its "
            f"invocation rank {int(inv[i])}", index=int(i)))
    never_ret = np.nonzero(ok & ~completed)[0]
    for i in never_ret[:8]:
        diags.append(Diagnostic(
            "H002", "error",
            f"row {int(i)} is :ok but has ret=INF_RET (an ok op must "
            f"have completed)", index=int(i)))

    f_codes = getattr(model, "f_codes", None)
    if f_codes:
        known = np.array(sorted(set(int(c) for c in f_codes.values())),
                         dtype=np.int64)
        f = np.asarray(seq.f, dtype=np.int64)
        unknown = np.nonzero(~np.isin(f, known))[0]
        for i in unknown[:8]:
            diags.append(Diagnostic(
                "M001", "error",
                f"row {int(i)} f code {int(f[i])} unknown to model "
                f"{model.name!r} (codes: {known.tolist()})",
                index=int(i), f=int(f[i])))
    return diags


def check_opseq_lint(seq: OpSeq, model=None) -> list[Diagnostic]:
    """OpSeq-level lint with the default policy: raise on errors,
    return warnings."""
    diags = lint_opseq(seq, model)
    errs = [d for d in diags if d.severity == "error"]
    if errs:
        raise HistoryLintError(diags)
    return diags


def maybe_lint(seq: OpSeq, model=None,
               lint: bool | None = None) -> list[Diagnostic]:
    """The engines' shared lint preamble: resolve the three-state
    ``lint`` flag (None follows the JEPSEN_TPU_LINT knob) and apply the
    default policy — raise on errors, return warnings.  ONE home for
    the policy so every entry point changes together."""
    if lint if lint is not None else lint_enabled():
        return check_opseq_lint(seq, model)
    return []
