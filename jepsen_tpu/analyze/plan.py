"""Search-plan explainer — predict the search without paying for it.

Everything the live engines decide host-side before (or instead of)
dispatching the exponential search is derivable from one cheap scan:
concurrency width, the real-time window, crash-word count, the quantized
``SearchDims``, the shape bucket, the engine route, and which
P-compositional decompositions (arXiv:1504.00204) apply.  :func:`explain`
computes all of it statically — the dry-run cost model the ROADMAP's
"measure bucketing on a real TPU window" item needs — and
:func:`explain_batch` does the same for a batch, mirroring the bucketed
scheduler's plan.

Prediction = implementation: this module calls the engines' OWN
primitives (``encode_search``, ``choose_dims``, ``batch_dims``,
``bucket_key``, ``plan_buckets``, ``greedy_witness``) rather than
re-deriving them, and the decomposition applicability gates
(:func:`key_partition_applies`, :func:`value_block_gate`,
:func:`quiescence_cuts`) live HERE and are consumed by
``decompose/partition.py`` — so the plan a user reads is by construction
the plan the engines execute (verified against recorded run stats in
tests/test_analyze.py).
"""

from __future__ import annotations

import numpy as np

from ..history import NIL, OpSeq, encode_ops
from ..models import R_READ, R_WRITE, ModelSpec

# ---------------------------------------------------------------------------
# Decomposition applicability gates — the ONE home (partition.py consumes)
# ---------------------------------------------------------------------------


def key_partition_applies(model: ModelSpec) -> bool:
    """Herlihy–Wing locality applies to the multi-register model: each
    key's projection checks independently as a single register."""
    return model.name == "multi-register"


def value_block_gate(seq: OpSeq, model: ModelSpec):
    """Eligibility gate for the per-value block decomposition.

    Returns ``(applies, reason, writes)``: ``reason`` names the first
    disqualifier when ``applies`` is False; ``writes`` maps value ->
    writing row (the scan's byproduct, reused by
    ``partition.value_block_verdict`` so gate and verdict cannot
    diverge).

    Eligible class (partition.py's docstring, the P-compositionality
    instance for registers): single-register model, every row :ok, only
    read/write ops, every written value distinct and distinct from the
    initial value.
    """
    if model.name not in ("register", "cas-register"):
        return False, f"model {model.name!r} is not a single register", None
    if not bool(np.asarray(seq.ok).all()):
        return False, "crashed (:info) rows present", None
    n = len(seq)
    if n == 0:
        return True, None, {}
    f = np.asarray(seq.f)
    if not bool(np.isin(f, (R_READ, R_WRITE)).all()):
        return False, "non-read/write ops (cas or foreign codes)", None
    v1 = np.asarray(seq.v1)
    init = int(model.init[0])
    writes: dict[int, int] = {}  # value -> row
    for i in np.nonzero(f == R_WRITE)[0]:
        v = int(v1[i])
        if v == NIL:
            return False, "write of NIL", None
        if v == init:
            return False, "write of the initial value", None
        if v in writes:
            return False, f"duplicate write of value {v}", None
        writes[v] = int(i)
    return True, None, writes


def quiescence_cuts(seq: OpSeq) -> np.ndarray:
    """Row indices where a quiescence cut lands (segment STARTS, 0
    excluded): every op before row i returned before row i invokes
    (``max(ret[..i-1]) < inv[i]``).  A crashed row's +inf return
    suppresses every later cut.  Consumed by
    ``partition.quiescence_segments`` (row ranges) and the plan."""
    n = len(seq)
    if n <= 1:
        return np.zeros(0, dtype=np.int64)
    inv = np.asarray(seq.inv, dtype=np.int64)
    ret = np.asarray(seq.ret, dtype=np.int64)
    run_max = np.maximum.accumulate(ret)
    return np.nonzero(run_max[:-1] < inv[1:])[0] + 1


# ---------------------------------------------------------------------------
# Streaming applicability gate — the ONE home (stream/checker.py consumes)
# ---------------------------------------------------------------------------

#: model families whose segment folds can ride the device batch path
#: (stream/device.py's pseudo-write/pseudo-read state pinning needs a
#: single-value register)
STREAM_DEVICE_FAMILIES = ("register", "cas-register")

#: host-fold cost-proxy cap: a closed segment predicted past this folds
#: on the device batch path instead of the host sweep
STREAM_HOST_FOLD_MAX = 1 << 22


#: bounded `:info` lookahead — after this many post-crash :ok rows
#: accumulate at a pseudo-quiescent point, the stream runs a
#: speculative fork check (the `:info` op present at each frontier
#: position vs absent) so a kill-seeded violation flips the live
#: verdict mid-stream instead of at finalize.  0 disables (finalize-
#: only, the pre-lookahead behavior).
STREAM_INFO_LOOKAHEAD = 16

#: the legacy flat fork cap: past this many pending `:info` ops the
#: speculative check used to be skipped unconditionally — bounding what
#: the uncertain ops can do is what keeps the search online
#: (Parsimonious Optimal DPOR's point, arXiv:2405.11128); the verdict
#: still lands exactly at finalize.  Kept as the characteristic scale
#: the cost budget below is seeded from (6 pending infos over a
#: 64-row segment), and as the width-free predicate
#: :func:`info_fork_gate` still answers.
STREAM_INFO_FORK_MAX = 6

#: the cost budget the stream engine actually executes now: a fork
#: check is admitted while ``n_infos * (segment_rows + 1)`` stays under
#: this.  Seeded at STREAM_INFO_FORK_MAX x a 64-row characteristic
#: segment, so the old flat cap is recovered at that width while a
#: narrow crashed cell affords MORE pending infos and a wide one fewer
#: — the fork's host sub-search sweeps the whole open segment once per
#: carried state per placement, so infos x rows is its first-order
#: cost, not infos alone.
STREAM_INFO_FORK_BUDGET = STREAM_INFO_FORK_MAX * 64

#: absolute `:info` ceiling regardless of segment width: the
#: sub-search's crash dimension is padded in 32-lane words and capped
#: at 64 (checker.linearizable.MAX_CRASH); forking past what the
#: device path could even represent buys nothing
STREAM_INFO_FORK_HARD_MAX = 32


def info_fork_cost(n_infos: int, segment_rows: int) -> int:
    """The speculative fork check's cost proxy: pending `:info` count
    times the open segment's row count (+1 so an empty segment still
    prices each info).  The single number the budget gate compares."""
    return max(0, n_infos) * (max(0, segment_rows) + 1)


def info_fork_budget(n_infos: int, segment_rows: int, *,
                     budget: int | None = None) -> bool:
    """May the stream speculatively fork ``n_infos`` pending `:info`
    ops over a ``segment_rows``-row open segment?  The cost-model
    replacement for the old flat :func:`info_fork_gate` cap — THE rule
    the stream engine executes and :func:`stream_plan` predicts: small
    segments afford more pending infos, wide ones fewer, with
    :data:`STREAM_INFO_FORK_HARD_MAX` as the absolute ceiling."""
    cap = STREAM_INFO_FORK_BUDGET if budget is None else budget
    if not 0 < n_infos <= STREAM_INFO_FORK_HARD_MAX:
        return False
    return info_fork_cost(n_infos, segment_rows) <= cap


def info_fork_gate(n_infos: int, *, fork_max: int | None = None) -> bool:
    """The legacy width-free predicate: may the stream fork this many
    pending `:info` ops at the characteristic segment width?  Callers
    that know their segment width should use :func:`info_fork_budget`;
    this remains for width-free prediction surfaces."""
    cap = STREAM_INFO_FORK_MAX if fork_max is None else fork_max
    return 0 < n_infos <= cap


def segment_fold_cost(n_rows: int, window: int) -> int:
    """The host fold's cost proxy for one crash-free segment: rows times
    the window-bounded interleaving factor (``segment_states`` is the
    level-synchronous sweep, whose frontier is bounded by 2^(window-1)
    per prefix position)."""
    return (n_rows + 1) << min(max(window - 1, 0), 40)


def segment_fold_route(n_rows: int, window: int, model: ModelSpec, *,
                       host_fold_max: int | None = None) -> str:
    """``"host"`` or ``"device"`` for one closed streaming segment.

    The single routing rule the stream engine executes and
    :func:`stream_plan` predicts: device dispatch needs the register
    family (the state-pinning trick) AND a predicted host-fold cost
    past the cap; everything else folds on host."""
    if model.name not in STREAM_DEVICE_FAMILIES:
        return "host"
    cap = STREAM_HOST_FOLD_MAX if host_fold_max is None else host_fold_max
    return "device" if segment_fold_cost(n_rows, window) > cap else "host"


def stream_plan(seq: OpSeq, model: ModelSpec, *,
                host_fold_max: int | None = None,
                info_lookahead: int | None = None) -> dict:
    """The streaming-applicability gate: would the incremental checker
    (jepsen_tpu/stream/) pay off on this history, and how would it
    route?  Predicts quiescence-cut density, expected segment sizes,
    rows until the first closed segment (the time-to-first-verdict
    proxy), and the host-fold vs device-dispatch split — using the SAME
    cut primitive (:func:`quiescence_cuts`) and the SAME routing rule
    (:func:`segment_fold_route`) the stream engine executes, so the
    prediction cannot drift from the fold."""
    from ..decompose.partition import partition_by_key, subseq
    from ..history import max_concurrency

    cells_map, cell_model, early = (None, model, None)
    if key_partition_applies(model):
        cells_map, cell_model, early = partition_by_key(seq, model)
    cells = list(cells_map.values()) if cells_map else [seq]
    if cell_model is None:
        cell_model = model

    horizon = STREAM_INFO_LOOKAHEAD if info_lookahead is None \
        else max(0, int(info_lookahead))
    seg_rows: list[int] = []
    routes = {"host": 0, "device": 0}
    ttfv_rows = None
    crashed_cells = info_rows = spec_checks = 0
    forkable = True
    fork_cost_max = 0
    for cseq in cells:
        n = len(cseq)
        if n == 0:
            continue
        cuts = quiescence_cuts(cseq)
        bounds = [0, *cuts.tolist(), n]
        infos = int((~cseq.ok).sum())
        if infos:
            crashed_cells += 1
            info_rows += infos
            # the fork check sweeps the cell's OPEN segment (rows past
            # the last quiescence cut) — the budget's width term, and
            # the same basis the engine uses (its cell buffer holds
            # exactly the un-folded tail)
            open_rows = bounds[-1] - bounds[-2]
            fork_cost_max = max(fork_cost_max,
                                info_fork_cost(infos, open_rows))
            if not info_fork_budget(infos, open_rows):
                forkable = False
            elif horizon:
                # one speculative fork check per horizon's worth of
                # post-crash ok rows — the same counting basis the
                # stream engine uses (it counts post-crash ok
                # COMPLETIONS; statically, ok rows after the first
                # crash row approximate that)
                first = int(np.argmax(~cseq.ok))
                spec_checks += int(cseq.ok[first:].sum()) // horizon
        if len(cuts) and (ttfv_rows is None or int(cuts[0]) < ttfv_rows):
            ttfv_rows = int(cuts[0])
        for i in range(len(bounds) - 1):
            rows = bounds[i + 1] - bounds[i]
            seg_rows.append(rows)
            if i < len(bounds) - 2:  # closed segments fold mid-stream
                w = max_concurrency(
                    subseq(cseq, np.arange(bounds[i], bounds[i + 1])))
                routes[segment_fold_route(
                    rows, w, cell_model,
                    host_fold_max=host_fold_max)] += 1
    n_cells = max(1, len(cells))
    n_rows = max(1, len(seq))
    closed = sum(routes.values())
    return {
        "applies": closed > 0 and early is not False,
        "cells": n_cells,
        "segments": len(seg_rows),
        "closed_segments": closed,
        "cut_density": round(closed / n_rows, 4),
        "expected_segment_rows": {
            "mean": round(sum(seg_rows) / len(seg_rows), 2)
            if seg_rows else 0,
            "max": max(seg_rows) if seg_rows else 0,
        },
        "ttfv_rows": ttfv_rows,
        "routes": routes,
        "device_eligible": cell_model.name in STREAM_DEVICE_FAMILIES,
        "info_lookahead": {
            "horizon": horizon,
            "fork_max": STREAM_INFO_FORK_MAX,
            "fork_budget": STREAM_INFO_FORK_BUDGET,
            "fork_cost_max": fork_cost_max,
            "crashed_cells": crashed_cells,
            "info_rows": info_rows,
            "forkable": forkable,
            "speculative_checks": spec_checks,
        },
    }


def independent_keys(seq: OpSeq, model: ModelSpec):
    """Detect a jepsen.independent ``[k v]`` composite history encoded
    under a single-register model — the shape every keyed live family
    (pgwire, replicated, kv) records.

    ``encode_ops``'s default lanes split a pair value across (v1, v2),
    so a register WRITE row carrying a second lane can only be a keyed
    write (a plain register write never uses v2; cas rows legitimately
    do and are ignored here).  Returns the sorted key list when
    detected, else None.  Consumers (``explain``, the analyze CLI) use
    it to report the per-key demux route — the route
    ``independent.checker`` and the stream checker's independent mode
    actually execute — instead of mis-reading key lanes as values.
    """
    if model.name not in ("register", "cas-register"):
        return None
    f = np.asarray(seq.f)
    writes = f == R_WRITE
    if not bool(writes.any()):
        return None
    v2 = np.asarray(seq.v2)
    if not bool((v2[writes] != NIL).all()):
        return None
    v1 = np.asarray(seq.v1)
    keyed = np.isin(f, (R_READ, R_WRITE)) & (v1 != NIL)
    return sorted(int(k) for k in np.unique(v1[keyed]))


def schedule_weight(seq: OpSeq) -> int:
    """The cell schedulers' cost proxy (largest-first ordering in
    decompose/schedule.py's host pool and device batch).

    Row count — finer-grained than the bucket quantization's padded
    rows (``bucket_key`` rounds n_det to a power of two, so many cells
    tie) while strictly monotone with it; one home so the schedulers
    and the plan explainer rank cells identically."""
    return len(seq)


# ---------------------------------------------------------------------------
# The plan
# ---------------------------------------------------------------------------


def _dims_dict(dims) -> dict:
    return {"n_det_pad": dims.n_det_pad, "n_crash_pad": dims.n_crash_pad,
            "window": dims.window, "k": dims.k,
            "state_width": dims.state_width, "frontier": dims.frontier}


def _decompositions(seq: OpSeq, model: ModelSpec) -> dict:
    """Which decompositions the engine (decompose/engine.py's funnel)
    would apply, in funnel order: key partition -> per cell: value
    blocks -> quiescence cuts."""
    from ..decompose.partition import partition_by_key

    out: dict = {}
    cells_map = None
    cell_model = model
    if key_partition_applies(model):
        cells_map, cell_model, early = partition_by_key(seq, model)
        out["key_partition"] = {
            "applies": True,
            "cells": len(cells_map) if cells_map else 0,
            "early_verdict": early,
        }
        if early is False or not cells_map:
            out["value_blocks"] = {"applies": False,
                                   "reason": "decided by key partition"}
            out["quiescence"] = {"applies": False, "segments": 1}
            return out
    else:
        out["key_partition"] = {"applies": False,
                                "reason": f"model {model.name!r} is not "
                                          f"multi-register"}
    cells = list(cells_map.values()) if cells_map else [seq]

    vb_cells = 0
    vb_reason = None
    segs_total = 0
    cut_cells = 0
    for cseq in cells:
        applies, reason, _writes = value_block_gate(cseq, cell_model)
        if applies:
            vb_cells += 1
        elif vb_reason is None:
            vb_reason = reason
        nsegs = len(quiescence_cuts(cseq)) + 1
        segs_total += nsegs
        if nsegs > 1:
            cut_cells += 1
    out["value_blocks"] = {"applies": vb_cells > 0,
                           "eligible_cells": vb_cells}
    if vb_reason is not None:
        out["value_blocks"]["reason"] = vb_reason
    out["quiescence"] = {"applies": segs_total > len(cells),
                         "segments": segs_total,
                         "cells_with_cuts": cut_cells}
    return out


def _telemetry_block(engine: str) -> dict:
    """How this plan's PREDICTIONS become observations: whether the
    device telemetry layer (obs/telemetry.py) is on, and where its
    observed twin of the hb/dpor predicted prune ratios will land.
    Plans are predictions; a run of the predicted engine attaches the
    measured side, and the two are diffed everywhere downstream
    (result block, trace_report, obs_guard)."""
    from ..obs import telemetry as tele

    on = tele.enabled()
    out = {"enabled": on}
    if on:
        out["observed_at"] = (
            "search_telemetry.observed_prune_ratio on device results "
            "(prune_ratio_delta vs the predicted ratio above)"
            if engine == "device-bfs" else
            "search.telemetry trace span (observed=0 for a "
            "statically decided / host-routed history)")
    else:
        out["note"] = ("JEPSEN_TPU_TELEMETRY=0: predictions will not "
                       "be observable on results")
    return out


def explain(history, model: ModelSpec, *,
            frontier: int | None = None,
            host_threshold: int = 48) -> dict:
    """The static plan for ONE history: what the live engines would do.

    ``history`` is an event-level Op list or an encoded OpSeq.
    ``host_threshold`` mirrors ``Linearizable``'s small-history host
    routing; ``frontier`` pins the initial frontier as
    ``choose_dims`` would accept it.
    """
    from ..checker import linearizable as lin
    from ..checker.bucket import bucket_key

    seq = history if isinstance(history, OpSeq) else \
        encode_ops(history, model.f_codes)
    es = lin.encode_search(seq)
    dims = lin.choose_dims(es, model, frontier=frontier)

    greedy = lin.greedy_witness(seq, model)
    device_ok = (es.window <= lin.MAX_WINDOW
                 and es.n_crash <= lin.MAX_CRASH)
    if es.n_det == 0 and es.n_crash == 0:
        engine = "trivial"
    elif greedy:
        engine = "greedy-witness"
    elif not device_ok:
        engine = "host-linear(fallback)"
    else:
        engine = "device-bfs"

    # distinct reachable configs, model state EXCLUDED: det prefix
    # position x window mask (the first window bit is the prefix
    # boundary itself) x crash mask — the count the frontier + budget
    # must cover in the worst case
    ub_log2 = (max(0, es.window - 1) + es.n_crash)
    upper = (es.n_det + 1) << ub_log2

    from .constraints import plan_block as constraints_block
    from .dpor import plan_block as dpor_block
    from .hb import analyze_hb, plan_block

    # one HB solve shared by the hb and dpor blocks below
    hbres = analyze_hb(seq, model) if len(seq) else None

    # keyed-composite gate (the live pgwire/replicated/kv families):
    # a [k v] history under a register model routes per key — every
    # whole-history prediction below would mis-read key lanes as
    # values, so the plan says so instead of falling through
    ind = independent_keys(seq, model)
    independent = {"detected": ind is not None}
    if ind is not None:
        independent.update({
            "keys": len(ind),
            "route": "per-key demux (independent.checker post-hoc; "
                     "stream independent mode live)",
            "note": "whole-history dims/decomposition/hb predictions "
                    "below do not apply to a keyed composite — demux "
                    "first, then explain each key's subhistory",
        })

    return {
        "model": model.name,
        "independent": independent,
        "n_rows": len(seq),
        "n_det": es.n_det,
        "n_crash": es.n_crash,
        "window": es.window,
        "concurrency": es.concurrency,
        "crash_words": dims.crash_words,
        "config_words": dims.words,
        "search_dims": _dims_dict(dims),
        "bucket": list(bucket_key(es)),
        "greedy_witness": greedy,
        "device_eligible": device_ok,
        "host_threshold_route": len(seq) <= host_threshold,
        "engine": engine,
        "config_upper_bound": upper,
        "config_upper_bound_log2": round(
            ub_log2 + float(np.log2(max(1, es.n_det + 1))), 2),
        "hb": plan_block(seq, model, upper, es.n_crash, es.window,
                         hb_analysis=hbres),
        "constraints": constraints_block(seq, model),
        "dpor": dpor_block(seq, model, upper, hb_analysis=hbres),
        "decompositions": _decompositions(seq, model),
        "streaming": stream_plan(seq, model),
        "telemetry": _telemetry_block(engine),
    }


def explain_batch(seqs: list[OpSeq], model: ModelSpec, *,
                  hb: bool | None = None,
                  n_devices: int | None = None) -> dict:
    """The static plan for a BATCH: per-key routing plus the bucketed
    scheduler's exact bucket assignment (checker/bucket.py's
    ``plan_buckets`` over the same keys, merged to the same cap).

    Mirrors ``search_batch_bucketed``: greedy witnesses dispose keys
    host-side, window/crash outliers fall back to the host sweep, and
    the rest group into power-of-two dims buckets, each searched at its
    own tight dims.

    ``n_devices`` switches the mirror to the MESH scheduler
    (``search_batch_sharded_bucketed`` over that many devices): dims
    start at the wide frontier, every bucket's lane count rounds up to
    mesh divisibility (the inert pad lanes bill into ``padded_ops``
    exactly as the live ``shard_batch`` stats bill them), and the
    totals carry the fused single-shape counterfactual — so the
    prediction is field-for-field comparable with the stats the live
    run reports.
    """
    from ..checker import linearizable as lin
    from ..checker.bucket import _bucket_mode, bucket_key, plan_buckets

    ess = [lin.encode_search(s) for s in seqs]
    hard, fit = [], []
    for i, e in enumerate(ess):
        (hard if e.window > lin.MAX_WINDOW
         or e.n_crash > lin.MAX_CRASH else fit).append(i)
    _enabled, max_buckets = _bucket_mode()
    plans = plan_buckets([bucket_key(ess[i]) for i in fit], max_buckets)
    plans = [[fit[p] for p in grp] for grp in plans]

    greedy = [i for i in range(len(seqs))
              if lin.greedy_witness(seqs[i], model)]
    greedy_set = set(greedy)
    # the static prepass disposes decided keys next to the greedy
    # witness (checker/bucket.py's prep stage) — mirror the split
    # exactly, including the per-call flag resolution AND the solver
    # dispatch (HB for registers, the constraint compiler for
    # queue/lock families), so the predicted per-bucket dims match the
    # scheduler's under any hb setting
    from .constraints import analyze_prepass, family_of
    from .hb import resolve_hb

    hb_set: set[int] = set()
    constraint_set: set[int] = set()
    # HB-solver analyses kept for the dpor block below (one solve per
    # key, not one per block); constraint-family analyses don't fit
    # its HBAnalysis shape and are cheap for it to skip
    analyses: dict[int, object] = {}
    hb_solver = family_of(model) is None
    if resolve_hb(hb):
        for i in range(len(seqs)):
            if i in greedy_set:
                continue
            a = analyze_prepass(seqs[i], model)
            if hb_solver:
                analyses[i] = a
            if a.decided is not None:
                (constraint_set
                 if a.stats.get("solver") == "constraints"
                 else hb_set).add(i)
    disposed = greedy_set | hb_set | constraint_set
    # the dpor block, batch form — SAME primitive as explain()'s
    # (dpor.plan_block per undecided key), aggregated: what the device
    # planes will mask, what the dead-value dedup should collapse, and
    # the sleep-set bound the host legs would carry
    from .dpor import plan_block as dpor_block

    dpor_keys = [i for i in range(len(seqs)) if i not in disposed]
    per_key = [dpor_block(seqs[i], model,
                          (ess[i].n_det + 1)
                          << (max(0, ess[i].window - 1)
                              + ess[i].n_crash),
                          hb_analysis=analyses.get(i))
               for i in dpor_keys]
    dedup_rates = [b["dedup"].get("hit_rate_prediction", 0.0)
                   for b in per_key if b["dedup"].get("applies")]
    dpor_plan = {
        "enabled": per_key[0]["enabled"] if per_key else True,
        "keys": len(dpor_keys),
        "masked_keys": sum(1 for b in per_key if b["masked_rows"]),
        "dedup_keys": sum(1 for b in per_key
                          if b["dedup"].get("applies")),
        "dup_edges": sum(b["dup_edges"] for b in per_key),
        "mask_coverage": (round(sum(b["mask_coverage"]
                                    for b in per_key)
                                / len(per_key), 4) if per_key else 0.0),
        "dedup_hit_rate_prediction": (round(sum(dedup_rates)
                                            / len(dedup_rates), 4)
                                      if dedup_rates else 0.0),
        "sleep_set_bound": max((b["sleep_set_bound"]
                                for b in per_key), default=0),
    }
    frontier = 64 if n_devices else 32
    buckets = []
    useful_total = padded_total = 0
    run_all: list[int] = []
    for idxs in plans:
        run = [i for i in idxs if i not in disposed]
        dims = (lin.batch_dims([ess[i] for i in run], model,
                               frontier=frontier)
                if run else None)
        useful = sum(ess[i].n_det + ess[i].n_crash for i in run)
        lanes = (lin._round_up(len(run), n_devices)
                 if run and n_devices else len(run))
        padded = (lanes * (dims.n_det_pad + dims.n_crash_pad)
                  if run else 0)
        useful_total += useful
        padded_total += padded
        run_all += run
        bk = {
            "keys": idxs,
            "n_keys": len(idxs),
            "searched": len(run),
            "dims": ([dims.n_det_pad, dims.window, dims.n_crash_pad]
                     if run else None),
            "useful_ops": useful,
            "padded_ops": padded,
            "padding_efficiency": (round(useful / padded, 4)
                                   if padded else None),
        }
        if n_devices:
            bk["lanes"] = lanes if run else 0
            bk["pad_lanes"] = (lanes - len(run)) if run else 0
        buckets.append(bk)
    out = {
        "n_keys": len(seqs),
        "n_buckets": len(plans),
        "bucketing": _enabled,
        "greedy": len(greedy),
        "hb_decided": len(hb_set),
        "constraint_decided": len(constraint_set),
        "hard": len(hard),
        "hard_keys": hard,
        "dpor": dpor_plan,
        "buckets": buckets,
    }
    if n_devices:
        fused_padded = 0
        if run_all:
            fdims = lin.batch_dims([ess[i] for i in run_all], model,
                                   frontier=frontier)
            fused_padded = lin._round_up(len(run_all), n_devices) \
                * (fdims.n_det_pad + fdims.n_crash_pad)
        out.update({
            "n_devices": n_devices,
            "useful_ops": useful_total,
            "padded_ops": padded_total,
            "padding_efficiency": (round(useful_total / padded_total,
                                         4) if padded_total else None),
            "fused_padded_ops": fused_padded or None,
            "fused_padding_efficiency": (
                round(useful_total / fused_padded, 4)
                if fused_padded else None),
        })
    return out


def _log2(x) -> float:
    return round(float(np.log2(max(1, int(x or 0)))), 1)


def render_plan(plan: dict, *, batch: bool = False) -> str:
    """Human-readable plan (the CLI --explain output)."""
    lines = []
    if batch or "buckets" in plan:
        lines.append(f"batch plan: {plan['n_keys']} keys -> "
                     f"{plan['n_buckets']} bucket(s), "
                     f"{plan['greedy']} greedy-disposed, "
                     f"{plan.get('hb_decided', 0)} hb-decided, "
                     f"{plan.get('constraint_decided', 0)} "
                     f"constraint-decided, "
                     f"{plan['hard']} host-fallback")
        if plan.get("n_devices"):
            lines.append(
                f"  sharded over {plan['n_devices']} device(s): "
                f"padding_efficiency={plan.get('padding_efficiency')} "
                f"(fused counterfactual "
                f"{plan.get('fused_padding_efficiency')})")
        dp = plan.get("dpor")
        if dp:
            lines.append(
                f"  dpor: {'on' if dp.get('enabled') else 'OFF'}; "
                f"{dp.get('masked_keys', 0)}/{dp.get('keys', 0)} keys "
                f"device-masked ({dp.get('dup_edges', 0)} dup edges), "
                f"{dp.get('dedup_keys', 0)} dedup-eligible "
                f"(predicted hit-rate "
                f"{dp.get('dedup_hit_rate_prediction')}), sleep-set "
                f"bound {dp.get('sleep_set_bound')}")
        for b, bk in enumerate(plan["buckets"]):
            dims = bk["dims"]
            eff = bk["padding_efficiency"]
            lines.append(
                f"  bucket {b}: {bk['n_keys']} keys, {bk['searched']} "
                f"searched, dims={dims}, padding_efficiency={eff}")
        return "\n".join(lines)
    d = plan["search_dims"]
    lines += [
        f"plan: {plan['n_rows']} rows ({plan['n_det']} det, "
        f"{plan['n_crash']} crashed) under model {plan['model']!r}",
        f"  window={plan['window']} concurrency={plan['concurrency']} "
        f"crash_words={plan['crash_words']} "
        f"config_words={plan['config_words']}",
        f"  SearchDims: n_det_pad={d['n_det_pad']} "
        f"n_crash_pad={d['n_crash_pad']} window={d['window']} "
        f"k={d['k']} frontier={d['frontier']}",
        f"  bucket={tuple(plan['bucket'])} engine={plan['engine']}"
        + (" (greedy witness exists)" if plan["greedy_witness"] else ""),
        f"  config upper bound ~2^"
        f"{plan['config_upper_bound_log2']}",
    ]
    dec = plan["decompositions"]
    kp = dec["key_partition"]
    vb = dec["value_blocks"]
    qc = dec["quiescence"]
    lines.append(
        "  decompositions: key-partition "
        + (f"applies ({kp.get('cells')} cells)" if kp["applies"]
           else "n/a")
        + "; value-blocks "
        + ("applies" if vb["applies"]
           else f"n/a ({vb.get('reason', '')})")
        + "; quiescence "
        + (f"applies ({qc['segments']} segments)" if qc["applies"]
           else "n/a"))
    ind = plan.get("independent")
    if ind and ind.get("detected"):
        lines.append(
            f"  KEYED COMPOSITE: {ind['keys']} independent key(s) — "
            f"engines route {ind['route']}; whole-history predictions "
            f"below are the un-demuxed counterfactual")
    hb = plan.get("hb")
    if hb:
        if not hb.get("applies"):
            line = f"n/a ({hb.get('reason')})"
        elif hb.get("decided") is not None:
            line = (f"DECIDES this history "
                    f"({'valid' if hb['decided'] else 'invalid'} via "
                    f"{hb.get('reason')}; no search needed)")
        else:
            line = (f"undecided; {hb.get('must_edges', 0)} must-order "
                    f"edge(s) {hb.get('edges')}, pruned bound "
                    f"~2^{_log2(hb.get('pruned_upper_bound', 0))} of "
                    f"raw ~2^{_log2(plan.get('config_upper_bound', 0))}"
                    f" (ratio {hb.get('prune_ratio')})")
        lines.append("  happens-before: " + line)
    cs = plan.get("constraints")
    if cs and cs.get("applies"):
        if cs.get("decided") is not None:
            line = (f"DECIDES this history "
                    f"({'valid' if cs['decided'] else 'invalid'} via "
                    f"{cs.get('reason')}; no search needed)")
        else:
            line = (f"undecided; {cs.get('must_edges', 0)} must-order "
                    f"edge(s) {cs.get('edges')}")
        sf = cs.get("stream_fold") or {}
        if sf.get("eligible"):
            line += f"; streamed fold route: {sf.get('route')}"
        lines.append(f"  constraints[{cs.get('family')}]: " + line)
    dp = plan.get("dpor")
    if dp:
        dd = dp.get("dedup", {})
        lines.append(
            f"  dpor: {'on' if dp.get('enabled') else 'OFF'}; "
            f"{dp.get('dup_edges', 0)} duplicate-op edge(s), "
            f"device-mask coverage {dp.get('mask_coverage')} "
            f"({dp.get('masked_rows', 0)} rows), dedup "
            + (f"applies ({dd.get('dead_values')}/{dd.get('values')} "
               f"values die; predicted hit-rate "
               f"{dd.get('hit_rate_prediction')})"
               if dd.get("applies") else "n/a")
            + f", sleep-set bound {dp.get('sleep_set_bound')}, "
              f"pruned bound ~2^"
              f"{_log2(dp.get('pruned_upper_bound', 0))}")
    tl = plan.get("telemetry")
    if tl:
        lines.append(
            "  telemetry: "
            + (f"on — observed at {tl.get('observed_at')}"
               if tl.get("enabled") else f"off ({tl.get('note')})"))
    st = plan.get("streaming")
    if st:
        lines.append(
            "  streaming: "
            + ("applies" if st["applies"] else "n/a")
            + f" ({st['closed_segments']} closed segment(s), cut "
              f"density {st['cut_density']}, ttfv ~{st['ttfv_rows']} "
              f"rows, routes {st['routes']})")
    return "\n".join(lines)
