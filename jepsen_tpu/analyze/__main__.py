"""``python -m jepsen_tpu.analyze`` — lint/explain/audit a stored history.

Reads a ``history.jsonl`` (store.write_history's format: one op per
line), lints it; ``--explain`` prints the static search plan;
``--audit RESULT.json`` replays a stored result's certificate
(``linearization``/``final_ops``) against the history and model — the
standalone certificate checker::

    python -m jepsen_tpu.analyze store/t/latest/history.jsonl \\
        --model cas-register --explain
    python -m jepsen_tpu.analyze history.jsonl --json
    python -m jepsen_tpu.analyze history.jsonl --model cas-register \\
        --audit result.json

``--devlint`` takes no history: it stages every registered kernel
route (single-XLA, bucketed-batch, mesh-sharded, pallas-fused) over
representative dims and walks the jaxprs for the K-code device
contract (host callbacks in level loops, dtype widening, weak-type
cache-key splits, donation policy, dynamic shapes, in-loop transfers,
compile-span cache-key drift — see docs/analyze.md)::

    python -m jepsen_tpu.analyze --devlint
    python -m jepsen_tpu.analyze --devlint --json

``--mc`` takes no history either: it model-checks the live backend
state machines at bounded scope (analyze/modelcheck.py, MC1xx codes —
see docs/analyze.md §11).  The default sweeps every family x mode and
exits 0 exactly when the matrix matches expectations (clean modes
violation-free, seeded modes caught with replaying certificates); a
specific ``--mc-family``/``--mc-mode`` pair exits 1 iff violations
were found.  ``--replay`` re-executes an emitted schedule
certificate::

    python -m jepsen_tpu.analyze --mc --json
    python -m jepsen_tpu.analyze --mc --mc-scope shell   # MC2xx layer
    python -m jepsen_tpu.analyze --mc --mc-family replicated \\
        --mc-mode volatile --mc-bank store
    python -m jepsen_tpu.analyze --mc --replay cert.json
    python -m jepsen_tpu.analyze --mc --explain   # scope plan only

``--mc-scope`` picks the checked layer: ``core`` (the lifted state
machines, MC1xx), ``shell`` (the daemons' request-dispatch shells
under a simulated transport — analyze/simnet.py, MC2xx), or ``all``.

Exit codes follow cli.py's contract: 0 clean, 1 lint errors or audit
W-codes found, 254 bad arguments.
"""

from __future__ import annotations

import argparse
import json
import sys

#: model factories reachable by name; parameterized ones take their
#: knob from --model-arg
MODELS = ("register", "cas-register", "mutex", "noop", "multi-register",
          "unordered-queue", "fifo-queue")


def _model(name: str, arg: int | None):
    from .. import models

    if name == "register":
        return models.register(arg if arg is not None else 0)
    if name == "cas-register":
        return models.cas_register()
    if name == "mutex":
        return models.mutex()
    if name == "noop":
        return models.noop()
    if name == "multi-register":
        return models.multi_register(arg if arg is not None else 8)
    if name == "unordered-queue":
        return models.unordered_queue(arg if arg is not None else 16)
    if name == "fifo-queue":
        return models.fifo_queue(arg if arg is not None else 16)
    raise ValueError(f"unknown model {name!r}; one of {MODELS}")


def _mc_pairs(opts) -> list[tuple]:
    from .modelcheck import ALL_FAMILIES, ALL_MODES, FAMILIES, \
        SHELL_FAMILIES

    scoped = {"core": FAMILIES, "shell": SHELL_FAMILIES,
              "all": ALL_FAMILIES}[opts.mc_scope]
    # a named family always runs, whatever the scope filter says
    fams = scoped if opts.mc_family == "all" else (opts.mc_family,)
    pairs = []
    for fam in fams:
        for mode in ALL_MODES[fam]:
            if opts.mc_mode in ("all", mode):
                pairs.append((fam, mode))
    return pairs


def _run_mc_cli(opts) -> int:
    from . import modelcheck as mc

    dpor = False if opts.no_dpor else None
    if opts.replay:
        try:
            cert = mc.load_certificate(opts.replay)
        except (OSError, ValueError) as e:
            print(f"cannot read certificate {opts.replay}: {e}",
                  file=sys.stderr)
            return 254
        try:
            rep = mc.replay_certificate(cert)
        except (KeyError, ValueError) as e:
            print(f"malformed certificate: {e}", file=sys.stderr)
            return 254
        if opts.as_json:
            print(json.dumps(rep, indent=2, default=str))
        else:
            print(f"replay: {'reproduced' if rep['reproduced'] else 'DID NOT reproduce'} "
                  f"{cert.get('code')} (got {rep['code']})")
        return 0 if rep["reproduced"] else 1
    pairs = _mc_pairs(opts)
    if not pairs:
        print(f"--mc-mode {opts.mc_mode!r} matches no mode of "
              f"--mc-family {opts.mc_family!r}", file=sys.stderr)
        return 254

    def scope_for(fam, mode):
        return mc.scope_from_args(
            fam, mode, crashes=opts.mc_crashes,
            partitions=opts.mc_partitions,
            max_events=opts.mc_max_events,
            max_states=opts.mc_max_states)

    if opts.explain:
        blocks = [mc.mc_plan_block(f, m, scope_for(f, m))
                  for f, m in pairs]
        if opts.as_json:
            print(json.dumps({"mc_plan": blocks}, indent=2,
                             default=str))
        else:
            for b in blocks:
                s = b["scope"]
                print(f"{b['family']}/{b['mode']}: nodes={s['nodes']} "
                      f"ops={s['ops']} crashes={s['crashes']} "
                      f"partitions={s['partitions']} "
                      f"max_events={s['max_events']}")
            print(f"codes: {', '.join(blocks[0]['codes'])}")
        return 0
    runs = []
    for fam, mode in pairs:
        runs.append(mc.run_mc(
            fam, mode, scope=scope_for(fam, mode), dpor=dpor,
            bank_base=opts.mc_bank if mode != "clean" else None))
    sweep = opts.mc_family == "all" and opts.mc_mode == "all"
    if sweep:
        # expected-outcome matrix: clean modes pass, seeded modes
        # caught with replaying certificates
        ok = all(
            r["ok"] if r["mode"] == "clean"
            else (not r["ok"]
                  and all(c.get("replayed") for c in r["violations"]))
            for r in runs)
    else:
        ok = all(r["ok"] for r in runs)
    if opts.as_json:
        print(json.dumps({"ok": ok, "runs": runs}, indent=2,
                         default=str))
    else:
        for r in runs:
            ex = r["explored"]
            codes = sorted({c["code"] for c in r["violations"]})
            verdict = "clean" if r["ok"] else \
                f"VIOLATIONS {', '.join(codes)}"
            print(f"{r['family']}/{r['mode']}: {verdict} — "
                  f"{ex['states']} states, {ex['schedules']} "
                  f"schedules, prune ratio {ex['prune_ratio']}, "
                  f"complete={ex['complete']}")
        print(f"mc: {'ok' if ok else 'FAILED'} "
              f"({len(runs)} run(s){' , sweep expectations' if sweep else ''})")
    return 0 if ok else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m jepsen_tpu.analyze",
        description="Lint a stored history; --explain adds the static "
                    "search plan (dims, bucket, engine route, "
                    "decompositions).")
    p.add_argument("history", nargs="?", default=None,
                   help="history.jsonl path (one op/line); not needed "
                        "with --devlint")
    p.add_argument("--model", choices=MODELS, default=None,
                   help="Model for the model-facing checks + plan")
    p.add_argument("--model-arg", type=int, default=None,
                   help="Model parameter (initial value / width / "
                        "capacity)")
    p.add_argument("--explain", action="store_true",
                   help="Print the static search plan (needs --model)")
    p.add_argument("--audit", metavar="RESULT_JSON", default=None,
                   help="Audit a stored result's certificate against "
                        "this history (needs --model); exits 1 on any "
                        "W-code")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="Machine-readable output")
    p.add_argument("--devlint", action="store_true",
                   help="Stage every kernel route and lint the jaxprs "
                        "for the K-code device contract (no history "
                        "needed)")
    p.add_argument("--mc", action="store_true",
                   help="Model-check the live backend state machines "
                        "at bounded scope (no history needed)")
    p.add_argument("--mc-scope", default="core",
                   choices=("core", "shell", "all"),
                   help="Which layer to check: the lifted cores "
                        "(default), the daemon shells under the "
                        "simulated transport (analyze/simnet.py), or "
                        "both")
    p.add_argument("--mc-family", default="all",
                   choices=("all", "replicated", "rqueue", "lock",
                            "shell-kv", "shell-queue",
                            "shell-replicated", "shell-rqueue"),
                   help="Backend family for --mc (default: sweep the "
                        "--mc-scope families)")
    p.add_argument("--mc-mode", default="all",
                   choices=("all", "clean", "volatile", "split-brain",
                            "session-leak", "proxy-loop",
                            "stale-proxy"),
                   help="Backend mode for --mc (default: every mode "
                        "of the family)")
    p.add_argument("--mc-max-events", type=int, default=None,
                   help="Scope override: schedule depth bound")
    p.add_argument("--mc-crashes", type=int, default=None,
                   help="Scope override: crash budget")
    p.add_argument("--mc-partitions", type=int, default=None,
                   help="Scope override: partition budget")
    p.add_argument("--mc-max-states", type=int, default=None,
                   help="Scope override: state-expansion budget")
    p.add_argument("--mc-bank", metavar="DIR", default=None,
                   help="Bank violation histories into this corpus "
                        "base directory")
    p.add_argument("--no-dpor", action="store_true",
                   help="Disable sleep-set reduction for --mc "
                        "(soundness A/B; same violation set, slower)")
    p.add_argument("--replay", metavar="CERT_JSON", default=None,
                   help="Replay a --mc schedule certificate; exits 0 "
                        "iff it reproduces its recorded MC code")
    try:
        opts = p.parse_args(argv)
    except SystemExit as e:
        return 0 if e.code in (0, None) else 254

    if opts.mc:
        return _run_mc_cli(opts)
    if opts.devlint:
        from .devlint import run_devlint

        rep = run_devlint(live=True)
        if opts.as_json:
            print(json.dumps(rep, indent=2, default=str))
        else:
            for d in rep["diagnostics"]:
                print(f"{d['severity'].upper()} {d['code']} "
                      f"{d['message']}")
            print(f"devlint: {rep['errors']} error(s), "
                  f"{rep['warnings']} warning(s) over "
                  f"{len(rep['routes'])} route(s): "
                  f"{', '.join(rep['routes'])}")
        return 1 if rep["errors"] else 0
    if opts.history is None:
        print("history path required (or --devlint)", file=sys.stderr)
        return 254

    from .. import store
    from . import analyze
    from .plan import render_plan

    try:
        history = store.read_history(opts.history)
    except OSError as e:
        print(f"cannot read {opts.history}: {e}", file=sys.stderr)
        return 254
    model = _model(opts.model, opts.model_arg) if opts.model else None
    if opts.explain and model is None:
        print("--explain needs --model", file=sys.stderr)
        return 254
    if opts.audit and model is None:
        print("--audit needs --model", file=sys.stderr)
        return 254

    audit_rep = None
    if opts.audit:
        from .audit import audit as run_audit

        try:
            with open(opts.audit) as f:
                result = json.load(f)
        except (OSError, ValueError) as e:
            print(f"cannot read result {opts.audit}: {e}",
                  file=sys.stderr)
            return 254
        audit_rep = run_audit(history, model, result)

    rep = analyze(history, model)
    diags = rep["diagnostics"]
    if opts.as_json:
        out = {"errors": rep["errors"], "warnings": rep["warnings"],
               "diagnostics": [d.to_dict() for d in diags]}
        if opts.explain:
            out["plan"] = rep["plan"]
        if audit_rep is not None:
            out["audit"] = {
                "ok": audit_rep["ok"], "checked": audit_rep["checked"],
                "codes": audit_rep["codes"],
                "diagnostics": [d.to_dict()
                                for d in audit_rep["diagnostics"]]}
        print(json.dumps(out, indent=2, default=str))
    else:
        for d in diags:
            print(f"{d.severity.upper()} {d}")
        print(f"{rep['errors']} error(s), {rep['warnings']} warning(s) "
              f"over {len(history)} events")
        if opts.explain and rep["plan"] is not None:
            print(render_plan(rep["plan"]))
        elif opts.explain:
            print("plan skipped: history has lint errors")
        if audit_rep is not None:
            for d in audit_rep["diagnostics"]:
                print(f"AUDIT {d}")
            print(f"audit: {'ok' if audit_rep['ok'] else 'FAILED'} "
                  f"(checked {audit_rep['checked']}, "
                  f"{len(audit_rep['diagnostics'])} finding(s))")
    if audit_rep is not None and not audit_rep["ok"]:
        return 1
    return 1 if rep["errors"] else 0


if __name__ == "__main__":
    sys.exit(main())
