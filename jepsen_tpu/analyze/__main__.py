"""``python -m jepsen_tpu.analyze`` — lint/explain/audit a stored history.

Reads a ``history.jsonl`` (store.write_history's format: one op per
line), lints it; ``--explain`` prints the static search plan;
``--audit RESULT.json`` replays a stored result's certificate
(``linearization``/``final_ops``) against the history and model — the
standalone certificate checker::

    python -m jepsen_tpu.analyze store/t/latest/history.jsonl \\
        --model cas-register --explain
    python -m jepsen_tpu.analyze history.jsonl --json
    python -m jepsen_tpu.analyze history.jsonl --model cas-register \\
        --audit result.json

``--devlint`` takes no history: it stages every registered kernel
route (single-XLA, bucketed-batch, mesh-sharded, pallas-fused) over
representative dims and walks the jaxprs for the K-code device
contract (host callbacks in level loops, dtype widening, weak-type
cache-key splits, donation policy, dynamic shapes, in-loop transfers,
compile-span cache-key drift — see docs/analyze.md)::

    python -m jepsen_tpu.analyze --devlint
    python -m jepsen_tpu.analyze --devlint --json

Exit codes follow cli.py's contract: 0 clean, 1 lint errors or audit
W-codes found, 254 bad arguments.
"""

from __future__ import annotations

import argparse
import json
import sys

#: model factories reachable by name; parameterized ones take their
#: knob from --model-arg
MODELS = ("register", "cas-register", "mutex", "noop", "multi-register",
          "unordered-queue", "fifo-queue")


def _model(name: str, arg: int | None):
    from .. import models

    if name == "register":
        return models.register(arg if arg is not None else 0)
    if name == "cas-register":
        return models.cas_register()
    if name == "mutex":
        return models.mutex()
    if name == "noop":
        return models.noop()
    if name == "multi-register":
        return models.multi_register(arg if arg is not None else 8)
    if name == "unordered-queue":
        return models.unordered_queue(arg if arg is not None else 16)
    if name == "fifo-queue":
        return models.fifo_queue(arg if arg is not None else 16)
    raise ValueError(f"unknown model {name!r}; one of {MODELS}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m jepsen_tpu.analyze",
        description="Lint a stored history; --explain adds the static "
                    "search plan (dims, bucket, engine route, "
                    "decompositions).")
    p.add_argument("history", nargs="?", default=None,
                   help="history.jsonl path (one op/line); not needed "
                        "with --devlint")
    p.add_argument("--model", choices=MODELS, default=None,
                   help="Model for the model-facing checks + plan")
    p.add_argument("--model-arg", type=int, default=None,
                   help="Model parameter (initial value / width / "
                        "capacity)")
    p.add_argument("--explain", action="store_true",
                   help="Print the static search plan (needs --model)")
    p.add_argument("--audit", metavar="RESULT_JSON", default=None,
                   help="Audit a stored result's certificate against "
                        "this history (needs --model); exits 1 on any "
                        "W-code")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="Machine-readable output")
    p.add_argument("--devlint", action="store_true",
                   help="Stage every kernel route and lint the jaxprs "
                        "for the K-code device contract (no history "
                        "needed)")
    try:
        opts = p.parse_args(argv)
    except SystemExit as e:
        return 0 if e.code in (0, None) else 254

    if opts.devlint:
        from .devlint import run_devlint

        rep = run_devlint(live=True)
        if opts.as_json:
            print(json.dumps(rep, indent=2, default=str))
        else:
            for d in rep["diagnostics"]:
                print(f"{d['severity'].upper()} {d['code']} "
                      f"{d['message']}")
            print(f"devlint: {rep['errors']} error(s), "
                  f"{rep['warnings']} warning(s) over "
                  f"{len(rep['routes'])} route(s): "
                  f"{', '.join(rep['routes'])}")
        return 1 if rep["errors"] else 0
    if opts.history is None:
        print("history path required (or --devlint)", file=sys.stderr)
        return 254

    from .. import store
    from . import analyze
    from .plan import render_plan

    try:
        history = store.read_history(opts.history)
    except OSError as e:
        print(f"cannot read {opts.history}: {e}", file=sys.stderr)
        return 254
    model = _model(opts.model, opts.model_arg) if opts.model else None
    if opts.explain and model is None:
        print("--explain needs --model", file=sys.stderr)
        return 254
    if opts.audit and model is None:
        print("--audit needs --model", file=sys.stderr)
        return 254

    audit_rep = None
    if opts.audit:
        from .audit import audit as run_audit

        try:
            with open(opts.audit) as f:
                result = json.load(f)
        except (OSError, ValueError) as e:
            print(f"cannot read result {opts.audit}: {e}",
                  file=sys.stderr)
            return 254
        audit_rep = run_audit(history, model, result)

    rep = analyze(history, model)
    diags = rep["diagnostics"]
    if opts.as_json:
        out = {"errors": rep["errors"], "warnings": rep["warnings"],
               "diagnostics": [d.to_dict() for d in diags]}
        if opts.explain:
            out["plan"] = rep["plan"]
        if audit_rep is not None:
            out["audit"] = {
                "ok": audit_rep["ok"], "checked": audit_rep["checked"],
                "codes": audit_rep["codes"],
                "diagnostics": [d.to_dict()
                                for d in audit_rep["diagnostics"]]}
        print(json.dumps(out, indent=2, default=str))
    else:
        for d in diags:
            print(f"{d.severity.upper()} {d}")
        print(f"{rep['errors']} error(s), {rep['warnings']} warning(s) "
              f"over {len(history)} events")
        if opts.explain and rep["plan"] is not None:
            print(render_plan(rep["plan"]))
        elif opts.explain:
            print("plan skipped: history has lint errors")
        if audit_rep is not None:
            for d in audit_rep["diagnostics"]:
                print(f"AUDIT {d}")
            print(f"audit: {'ok' if audit_rep['ok'] else 'FAILED'} "
                  f"(checked {audit_rep['checked']}, "
                  f"{len(audit_rep['diagnostics'])} finding(s))")
    if audit_rep is not None and not audit_rep["ok"]:
        return 1
    return 1 if rep["errors"] else 0


if __name__ == "__main__":
    sys.exit(main())
