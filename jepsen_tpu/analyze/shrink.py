"""Counterexample minimization — delta-debug invalid verdicts.

An invalid verdict on a 10k-op history is true but useless to a human:
the defect usually lives in a handful of ops.  :func:`shrink_invalid`
is a ddmin-style delta debugger over the completed-op rows of an OpSeq:
it removes row chunks while a bounded engine still answers ``invalid``,
halving the chunk size down to single rows, and terminates in a
1-minimal failing subhistory (removing any one remaining op makes the
engine stop answering invalid).  The result is *independently*
confirmed by :func:`brute_force_check`, a deliberately naive exact
permutation search that shares no code with the engines — small enough
concurrency makes exhaustive enumeration cheap, and an engine bug that
survived the differential fuzz would have to be shared by this ~40-line
recursion too.

The minimal core is *explanatory*, not a substitute for the verdict's
own certificate (the blocking frontier): removing ops can change a
history's verdict in either direction, so each removal is re-validated
by re-checking — the chain starts at the full history the engine
decided invalid, and every link (including the final core) is a
machine-confirmed invalid history.  ``linear_report``/the web UI render
the core as the failure story — a 6-op story, not a 10k-op dump.
"""

from __future__ import annotations

import os

from ..history import INF_RET, OpSeq
from ..models import ModelSpec


def shrink_enabled() -> bool:
    """JEPSEN_TPU_SHRINK=0/off/false/no disables counterexample
    minimization in failure reports (default on; it only ever touches
    reporting, never verdicts)."""
    return os.environ.get("JEPSEN_TPU_SHRINK", "").strip().lower() not in (
        "0", "off", "false", "no")


def brute_force_check(seq: OpSeq, model: ModelSpec, *,
                      max_ops: int = 16,
                      max_nodes: int = 2_000_000):
    """Exhaustive linearizability check by permutation enumeration.

    True/False exactly; None when the history is too big (``max_ops``)
    or the node budget runs out.  Deliberately engine-independent: a
    plain DFS that at each step tries EVERY unlinearized op allowed by
    the O(n) pairwise real-time test (op j may go next iff no other
    unlinearized op returned before j invoked) and the model — no
    window encodings, no dominance pruning, no candidate memoization.
    A visited set on (linearized-set, state) keeps it finite; that is
    bookkeeping, not search strategy.
    """
    n = len(seq)
    if n > max_ops:
        return None
    inv = [int(x) for x in seq.inv]
    ret = [int(x) for x in seq.ret]
    f = [int(x) for x in seq.f]
    v1 = [int(x) for x in seq.v1]
    v2 = [int(x) for x in seq.v2]
    ok_mask = 0
    for i in range(n):
        if bool(seq.ok[i]):
            ok_mask |= 1 << i
    pystep = model.pystep
    visited: set = set()
    stack = [(0, model.init)]
    nodes = 0
    while stack:
        mask, state = stack.pop()
        if (mask, state) in visited:
            continue
        visited.add((mask, state))
        nodes += 1
        if nodes > max_nodes:
            return None
        if mask & ok_mask == ok_mask:
            return True
        for j in range(n):
            if (mask >> j) & 1:
                continue
            # real-time: some other unlinearized op returned before j
            # invoked -> j cannot go next
            if any(not (mask >> k) & 1 and k != j and ret[k] < inv[j]
                   for k in range(n)):
                continue
            ns = pystep(state, f[j], v1[j], v2[j])
            if ns is None:
                continue
            stack.append((mask | (1 << j), ns))
    return False


def _default_check(max_configs: int):
    def check(sub: OpSeq, model: ModelSpec) -> dict:
        from ..checker.seq import check_opseq

        return check_opseq(sub, model, max_configs=max_configs,
                           lint=False)

    return check


def shrink_invalid(seq: OpSeq, model: ModelSpec, *,
                   check=None,
                   max_checks: int = 400,
                   max_configs: int = 200_000,
                   brute_max_ops: int = 16) -> dict:
    """ddmin an invalid history down to a minimal failing subhistory.

    ``check(sub_seq, model) -> result dict`` re-verdicts candidates
    (default: the bounded WGL host oracle); a removal is kept only while
    the answer stays ``False``.  Returns::

        {"rows": kept original-row indices, "n_from": n, "n_to": k,
         "checks": engine calls spent, "minimal": 1-minimality proven,
         "brute_force": True|False|None}

    ``brute_force`` is the independent confirmation of the final core
    (None when it exceeded ``brute_max_ops``).  ``minimal`` is False
    when ``max_checks`` ran out first — the core is still a confirmed
    invalid subhistory, just possibly not 1-minimal.  Idempotent:
    shrinking a minimal core returns every row unchanged.
    """
    from ..decompose.partition import subseq

    if check is None:
        check = _default_check(max_configs)
    checks = 0

    def still_invalid(rows: list[int]) -> bool:
        nonlocal checks
        checks += 1
        return check(subseq(seq, rows), model).get("valid") is False

    rows = list(range(len(seq)))
    out = {"rows": rows, "n_from": len(seq), "n_to": len(rows),
           "checks": 0, "minimal": False, "brute_force": None}
    if not rows or not still_invalid(rows):
        # the bounded re-check cannot reproduce the invalid verdict
        # (budget, or the result was not invalid): nothing to shrink
        out["checks"] = checks
        return out

    chunk = max(1, len(rows) // 2)
    minimal = False
    while checks < max_checks:
        i = 0
        removed = False
        while i < len(rows) and checks < max_checks:
            cand = rows[:i] + rows[i + chunk:]
            if cand and still_invalid(cand):
                rows = cand
                removed = True
            else:
                i += chunk
        if chunk == 1:
            if not removed:
                minimal = True  # a clean single-row pass: 1-minimal
                break
        else:
            chunk = max(1, chunk // 2)

    sub = subseq(seq, rows)
    out.update({
        "rows": [int(r) for r in rows],
        "n_to": len(rows),
        "checks": checks,
        "minimal": minimal,
        "brute_force": brute_force_check(sub, model,
                                         max_ops=brute_max_ops),
    })
    return out


def ddmin_list(items: list, still_failing, *,
               max_checks: int = 200) -> dict:
    """The bare ddmin chunk loop over an arbitrary item list — the
    generic core :func:`shrink_invalid`/:func:`shrink_invalid_events`
    specialize and the model checker's schedule shrinker
    (``analyze/modelcheck.py``) reuses directly.

    ``still_failing(sub_items) -> bool`` re-validates a candidate; a
    removal is kept only while it answers True, so the chain starts
    and ends at a confirmed-failing list.  Returns::

        {"items": minimal list, "n_from": n, "n_to": k,
         "checks": n_calls, "minimal": 1-minimality proven}
    """
    checks = 0

    def check(sub: list) -> bool:
        nonlocal checks
        checks += 1
        try:
            return bool(still_failing(sub))
        except Exception:  # noqa: BLE001 — a crashing candidate is
            return False   # not a confirmed-failing one

    kept = list(items)
    out = {"items": list(items), "n_from": len(items),
           "n_to": len(items), "checks": 0, "minimal": False}
    if not kept or not check(kept):
        out["checks"] = checks
        return out

    chunk = max(1, len(kept) // 2)
    minimal = False
    while checks < max_checks:
        i = 0
        removed = False
        while i < len(kept) and checks < max_checks:
            cand = kept[:i] + kept[i + chunk:]
            if cand and check(cand):
                kept = cand
                removed = True
            else:
                i += chunk
        if chunk == 1:
            if not removed:
                minimal = True  # a clean single-item pass: 1-minimal
                break
        else:
            chunk = max(1, chunk // 2)
    out.update({"items": kept, "n_to": len(kept), "checks": checks,
                "minimal": minimal})
    return out


def shrink_invalid_events(ops: list, check, *,
                          max_checks: int = 200) -> dict:
    """ddmin an EVENT-LEVEL invalid history down to a minimal failing
    subhistory — the bank-time corpus shrinker (live/corpus.py).

    Events group into removal *units* (an invoke plus its same-process
    completion; orphan events are their own unit), so every candidate
    stays a well-formed history.  ``check(ops) -> bool`` answers
    "still invalid" — the multiset checker for queue entries, a
    bounded engine for model entries — and a removal is kept only
    while it says True, so the chain starts and ends at a
    machine-confirmed invalid history (the same contract as
    :func:`shrink_invalid`).  Returns::

        {"ops": minimal event list, "n_from": units, "n_to": units,
         "checks": n, "minimal": 1-minimality proven}
    """
    # unit grouping: invoke -> [invoke, next same-process event]
    units: list[list[int]] = []
    open_of: dict = {}
    for i, op in enumerate(ops):
        if op.type == "invoke":
            open_of[op.process] = len(units)
            units.append([i])
        else:
            u = open_of.pop(op.process, None)
            if u is None:
                units.append([i])
            else:
                units[u].append(i)

    def build(kept: list[int]) -> list:
        rows = sorted(i for u in kept for i in units[u])
        return [ops[i] for i in rows]

    checks = 0

    def still_invalid(kept: list[int]) -> bool:
        nonlocal checks
        checks += 1
        try:
            return bool(check(build(kept)))
        except Exception:  # noqa: BLE001 — a crashing candidate is
            return False   # not a confirmed-invalid one

    kept = list(range(len(units)))
    out = {"ops": list(ops), "n_from": len(units), "n_to": len(units),
           "checks": 0, "minimal": False}
    if not kept or not still_invalid(kept):
        out["checks"] = checks
        return out

    chunk = max(1, len(kept) // 2)
    minimal = False
    while checks < max_checks:
        i = 0
        removed = False
        while i < len(kept) and checks < max_checks:
            cand = kept[:i] + kept[i + chunk:]
            if cand and still_invalid(cand):
                kept = cand
                removed = True
            else:
                i += chunk
        if chunk == 1:
            if not removed:
                minimal = True
                break
        else:
            chunk = max(1, chunk // 2)
    out.update({"ops": build(kept), "n_to": len(kept),
                "checks": checks, "minimal": minimal})
    return out


def shrink_summary(seq: OpSeq, shrunk: dict) -> dict:
    """The JSON/report-ready form of a shrink outcome: the stats plus
    the core rendered as op dicts (the "6-op story") when the OpSeq
    still carries its source ops."""
    out = {k: shrunk[k] for k in ("rows", "n_from", "n_to", "checks",
                                  "minimal", "brute_force")}
    if seq.ops:
        ops = []
        for r in shrunk["rows"]:
            op = seq.ops[r]
            d = op.to_dict()
            d["crashed"] = int(seq.ret[r]) == INF_RET
            ops.append(d)
        out["ops"] = ops
    return out
