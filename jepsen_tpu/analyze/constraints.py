"""Model-generic constraint compiler — static order-solving beyond
registers.

PR 12's happens-before order-solver (analyze/hb.py) is register-only:
its read-from / value-block algebra needs a unique writer per value.
This module generalizes the same static-constraint idea to the OTHER
model families the engines search — the P-compositional per-key
decomposition (arXiv:1504.00204) and the static half of partial-order
reduction (arXiv:2405.11128) apply to enqueue/dequeue read-from edges
exactly as they did to register read-from edges:

  * **queue** (``unordered-queue-N`` / ``fifo-queue-N``) — an :ok
    dequeue of v *reads from* the (unique-payload) enqueue of v, so
    enqueue->dequeue is a forced edge; under FIFO, real time between
    two enqueues forces the same order on their dequeues (same-node
    enqueue pairs are real-time chains for free).  Decide-fast rules:
    dequeue-of-never-enqueued, duplicate delivery (more :ok dequeues
    than enqueue rows of a value), read-from cycles (a dequeue wholly
    before its only enqueue), FIFO inversion — each with a certificate
    the independent audit (analyze/audit.py, W007/W008) re-justifies
    without re-running this compiler.  All-:ok unique-payload
    unordered-queue histories decide *valid* constructively: a
    completion-order schedule with each enqueue pulled in front of its
    dequeue is real-time consistent whenever no read-from cycle
    exists, and the constructed witness is model-replayed before it is
    ever emitted (decide-valid is self-verified, exactly as hb.py's
    GK witness is).
  * **lock** (``mutex``) — acquire/release alternation is a counting
    invariant over forced linearization points: at any rank t, the
    acquires forced linearized (:ok, returned by t) minus the releases
    that could possibly have linearized (invoked before t) bound the
    held count from below; >= 2 is a forced double-hold, and the dual
    sweep catches a release forced with no possible acquire.  Both are
    O(n log n) and crash-sound (crashed rows count as *possible*,
    never *forced*).
  * **set** — event-level only (sets have no searchable ModelSpec):
    add->member-read edges and the SetChecker verdicts (lost /
    unexpected) with row-level evidence, the same multiset algebra the
    streamed fold executes incrementally.

The OpSeq half rides the SAME prepass slot as ``hb.py``
(``hb.maybe_hb`` dispatches by model family), so every consumer the HB
solver already reaches — ``checker/seq.py``'s DFS mask,
``checker/linear.py``'s frame mask, ``search_batch``/``bucket.py``
disposal, the decomposed and streamed sub-searches — consumes these
verdicts and must-order edges with zero new wiring.  The event-level
half (:class:`MultisetFold`) is the incremental edge form the
streaming checker's total-queue fold route executes so queue campaign
cells grade ``detection.at="streamed"``.

Soundness invariants (what keeps this verdict-identical by
construction):

  * decide-``valid`` only ever fires after the constructed witness
    replays clean against the model AND real time;
  * decide-``invalid`` only ever fires on independently re-checkable
    evidence (a forced-edge cycle, an impossible dequeue, a counting
    contradiction);
  * must-order edges are forced (hold in every valid linearization),
    so masking them can never flip a verdict;
  * anything outside the gates returns "undecided" and the engines
    run exactly as before.

Knobs: the SAME three-state flag as hb.py (``hb=False`` per call,
``JEPSEN_TPU_HB=0`` fleet-wide) — one prepass slot, one switch.
"""

from __future__ import annotations

import bisect
from collections import Counter

import numpy as np

from ..history import NIL, OpSeq
from ..obs.metrics import REGISTRY
from .hb import (
    EDGE_CAP_FACTOR,
    EDGE_CAP_MIN,
    HBAnalysis,
    _verify_witness,
    _window_effective,
    hb_enabled,
)

_M_PREPASS = REGISTRY.counter(
    "jtpu_constraint_prepass_total",
    "Constraint-compiler pre-pass outcomes by model family",
    ("family", "outcome"))
_M_EDGES = REGISTRY.counter(
    "jtpu_constraint_edges_total",
    "Forced constraint edges inferred beyond real time, by kind",
    ("kind",))
_M_FOLD_FLIPS = REGISTRY.counter(
    "jtpu_constraint_fold_flips_total",
    "Streamed multiset-fold verdict flips, by evidence kind",
    ("kind",))
_M_FOLD_EVENTS = REGISTRY.counter(
    "jtpu_constraint_fold_events_total",
    "Events ingested by streamed multiset folds")


# ---------------------------------------------------------------------------
# family dispatch
# ---------------------------------------------------------------------------


def family_of(model) -> str | None:
    """The constraint family a ModelSpec belongs to, or None when the
    register-family HB solver (or nothing) owns it."""
    name = getattr(model, "name", "") or ""
    if name.startswith("unordered-queue-"):
        return "queue"
    if name.startswith("fifo-queue-"):
        return "fifo-queue"
    if name == "mutex":
        return "lock"
    return None


def analyze_prepass(seq: OpSeq, model) -> HBAnalysis:
    """The unified static prepass: registers go to the HB order-solver,
    queue/lock families to the constraint compiler.  One entry so the
    batch schedulers (bucket.py) and their mirror (explain_batch)
    cannot diverge on which solver disposed a key."""
    from .hb import analyze_hb

    if family_of(model) is None:
        return analyze_hb(seq, model)
    return analyze_constraints(seq, model)


# ---------------------------------------------------------------------------
# the OpSeq pre-pass
# ---------------------------------------------------------------------------


def _decided(valid, *, certificate: dict, stats: dict) -> dict:
    stats["pruned_upper_bound"] = 0
    stats["prune_ratio"] = 0.0
    out = {"valid": valid, "configs": 0, "max_depth": 0,
           "engine": "constraint-decide"}
    out.update(certificate)
    out["constraints"] = stats
    return out


def _edge(src: int, dst: int, kind: str, via=None) -> dict:
    e = {"src": int(src), "dst": int(dst), "kind": kind}
    if via is not None:
        e["via"] = [int(via[0]), int(via[1])]
    return e


def analyze_constraints(seq: OpSeq, model) -> HBAnalysis:
    """The full pre-pass for the non-register families.  Never raises
    on in-scope inputs; anything out of scope comes back
    ``applies=False`` and undecided."""
    fam = family_of(model)
    n = len(seq)
    stats = {"solver": "constraints", "family": fam, "applies": False,
             "decided": None, "reason": None,
             "edges": {"rf": 0, "fifo": 0}, "must_edges": 0}
    out = HBAnalysis(n=n, applies=False, decided=None, stats=stats)
    if fam is None:
        stats["reason"] = f"model {getattr(model, 'name', None)!r} " \
                          f"out of scope"
        return out
    if n == 0:
        stats["reason"] = "empty history"
        return out
    if fam == "lock":
        return _analyze_lock(seq, model, out)
    return _analyze_queue(seq, model, out, fifo=fam == "fifo-queue")


# ---------------------------------------------------------------------------
# queue family
# ---------------------------------------------------------------------------


class _QVal:
    """One payload value's rows."""

    __slots__ = ("enq", "enq_ok", "deq_ok", "deq_info")

    def __init__(self):
        self.enq: list[int] = []       # enqueue rows, ok + crashed
        self.enq_ok: list[int] = []
        self.deq_ok: list[int] = []
        self.deq_info: list[int] = []


def _analyze_queue(seq: OpSeq, model, out: HBAnalysis,
                   *, fifo: bool) -> HBAnalysis:
    from ..models import Q_DEQ, Q_EMPTY, Q_ENQ

    stats = out.stats
    n = len(seq)
    if tuple(model.init) != (Q_EMPTY,) * model.state_width:
        # a segment fold's carried state seeds the queue: the
        # empty-start algebra (impossible dequeue, counting) is wrong
        stats["reason"] = "non-empty initial queue state"
        return out
    f = np.asarray(seq.f)
    if not bool(np.isin(f, (Q_ENQ, Q_DEQ)).all()):
        stats["reason"] = "foreign op code"
        return out
    out.applies = True
    stats["applies"] = True

    v1 = [int(x) for x in seq.v1]
    ok = [bool(x) for x in seq.ok]
    inv = [int(x) for x in seq.inv]
    ret = [int(x) for x in seq.ret]
    fl = [int(x) for x in f]
    vals: dict[int, _QVal] = {}
    n_enq = 0
    for i in range(n):
        v = v1[i]
        if v == NIL:
            continue  # a NIL-valued row never constrains the multiset
        q = vals.get(v)
        if q is None:
            q = vals[v] = _QVal()
        if fl[i] == Q_ENQ:
            n_enq += 1
            q.enq.append(i)
            if ok[i]:
                q.enq_ok.append(i)
        elif ok[i]:
            q.deq_ok.append(i)
        else:
            q.deq_info.append(i)
    stats["values"] = len(vals)

    def rt(a: int, b: int) -> bool:
        return ret[a] < inv[b]

    # ---- decide-fast: impossible dequeue -----------------------------
    impossible = sorted(r for q in vals.values() if not q.enq
                        for r in q.deq_ok)
    if impossible:
        stats["decided"] = False
        stats["reason"] = "impossible-dequeue"
        out.decided = _decided(False, certificate={
            "final_ops": impossible,
            "queue_evidence": {"family": "queue",
                               "kind": "unexpected-dequeue",
                               "rows": impossible}}, stats=stats)
        return out

    # ---- decide-fast: duplicate delivery -----------------------------
    for q in vals.values():
        if len(q.deq_ok) > len(q.enq):
            stats["decided"] = False
            stats["reason"] = "duplicate-delivery"
            out.decided = _decided(False, certificate={
                "final_ops": sorted(q.deq_ok),
                "queue_dup": {"dequeues": sorted(q.deq_ok),
                              "enqueues": sorted(q.enq)}}, stats=stats)
            return out

    # ---- decide-fast: read-from cycle --------------------------------
    # a dequeue wholly before the ONLY enqueue that could feed it
    for q in vals.values():
        if len(q.enq) != 1:
            continue
        e = q.enq[0]
        for d in q.deq_ok:
            if rt(d, e):
                stats["decided"] = False
                stats["reason"] = "rf-cycle"
                out.decided = _decided(False, certificate={
                    "queue_cycle": [_edge(e, d, "rf"),
                                    _edge(d, e, "rt")]}, stats=stats)
                return out

    # unique (enqueue, dequeue) pairs — the edge/FIFO substrate
    pairs = [(q.enq[0], q.deq_ok[0]) for q in vals.values()
             if len(q.enq) == 1 and len(q.deq_ok) == 1
             and not q.deq_info]

    # ---- decide-fast: FIFO inversion ---------------------------------
    if fifo and len(pairs) >= 2:
        # find (i, j): enq_i wholly before enq_j AND deq_j wholly
        # before deq_i.  Sweep j by increasing inv(enq); the admitted
        # prefix (ret(enq_i) < inv(enq_j)) grows monotonically, and
        # only its max-inv(deq) member can witness the inversion.
        by_einv = sorted(pairs, key=lambda p: inv[p[0]])
        by_eret = sorted(pairs, key=lambda p: ret[p[0]])
        k = 0
        best = None  # (inv(deq_i), pair_i) over the admitted prefix
        for (ej, dj) in by_einv:
            while k < len(by_eret) and ret[by_eret[k][0]] < inv[ej]:
                p = by_eret[k]
                if best is None or inv[p[1]] > best[0]:
                    best = (inv[p[1]], p)
                k += 1
            if best is not None and ret[dj] < best[0]:
                ei, di = best[1]
                if ei != ej:
                    stats["decided"] = False
                    stats["reason"] = "fifo-inversion"
                    out.decided = _decided(False, certificate={
                        "queue_cycle": [
                            _edge(di, dj, "fifo", via=(ei, ej)),
                            _edge(dj, di, "rt")]}, stats=stats)
                    return out

    # ---- decide-fast: constructive valid (unordered only) ------------
    all_ok = all(ok)
    unique = all(len(q.enq) <= 1 and len(q.deq_ok) <= 1
                 for q in vals.values())
    if not fifo and all_ok and unique and not any(v == NIL for v in v1) \
            and model.state_width >= n_enq:
        # completion order, with each enqueue pulled in front of its
        # dequeue: rt-consistent because no rf 2-cycle survived above
        # (ret(deq) >= inv(enq) for every pair), then self-verified by
        # model replay before the decision ever leaves this module
        key = {}
        for q in vals.values():
            if q.enq and q.deq_ok:
                e, d = q.enq[0], q.deq_ok[0]
                key[e] = min(ret[e], ret[d])
        order = sorted(range(n),
                       key=lambda i: (key.get(i, ret[i]),
                                      0 if fl[i] == Q_ENQ else 1, i))
        if _verify_witness(seq, model, order):
            stats["decided"] = True
            stats["reason"] = "completion-schedule"
            out.decided = _decided(True, certificate={
                "linearization": [int(r) for r in order],
                "max_depth": n}, stats=stats)
            return out

    # ---- undecided: emit the prune -----------------------------------
    cap = max(EDGE_CAP_MIN, EDGE_CAP_FACTOR * n)
    edges: list[tuple[int, int, str]] = []
    for q in vals.values():
        if len(q.enq) != 1:
            continue  # no unique writer: no forced read-from
        e = q.enq[0]
        for d in (*q.deq_ok, *q.deq_info):
            if not rt(e, d):
                edges.append((e, d, "rf"))
                if len(edges) >= cap:
                    break
        if len(edges) >= cap:
            break
    if fifo and len(edges) < cap and len(pairs) >= 2:
        # one FIFO predecessor per dequeue: the min-ret enqueue wholly
        # before it forces its dequeue first (edges are individually
        # forced, so a star is as sound as a chain)
        by_einv = sorted(pairs, key=lambda p: inv[p[0]])
        best = None  # (ret(enq), deq) with min ret(enq) so far
        for (e, d) in by_einv:
            if best is not None and best[0] < inv[e] \
                    and not rt(best[1], d):
                edges.append((best[1], d, "fifo"))
                if len(edges) >= cap:
                    break
            if best is None or ret[e] < best[0]:
                best = (ret[e], d)
    for (_s, _d, k) in edges:
        stats["edges"][k] += 1
    stats["must_edges"] = len(edges)
    must: dict[int, list[int]] = {}
    for (src, dst, _k) in edges:
        must.setdefault(int(dst), []).append(int(src))
    out.must_pred = {d: tuple(sorted(set(s))) for d, s in must.items()}
    _prune_stats(seq, edges, stats)
    return out


def _prune_stats(seq: OpSeq, edges, stats: dict) -> None:
    w_raw, w_eff = _window_effective(seq, edges)
    ok = np.asarray(seq.ok, dtype=bool)
    nd = int(ok.sum())
    n = len(seq)
    raw = (nd + 1) << (max(0, w_raw - 1) + (n - nd))
    pruned = min((nd + 1) << (max(0, w_eff - 1) + (n - nd)), raw)
    stats["window_effective"] = w_eff
    stats["pruned_upper_bound"] = pruned
    stats["prune_ratio"] = round(pruned / raw, 6) if raw else None


# ---------------------------------------------------------------------------
# lock family
# ---------------------------------------------------------------------------


def _analyze_lock(seq: OpSeq, model, out: HBAnalysis) -> HBAnalysis:
    from ..models import M_ACQUIRE, M_RELEASE

    stats = out.stats
    if tuple(model.init) != (0,):
        stats["reason"] = "non-free initial lock state"
        return out
    f = np.asarray(seq.f)
    if not bool(np.isin(f, (M_ACQUIRE, M_RELEASE)).all()):
        stats["reason"] = "foreign op code"
        return out
    out.applies = True
    stats["applies"] = True
    ok = [bool(x) for x in seq.ok]
    inv = [int(x) for x in seq.inv]
    ret = [int(x) for x in seq.ret]
    fl = [int(x) for x in f]
    n = len(seq)
    acq_rows = [i for i in range(n) if fl[i] == M_ACQUIRE]
    rel_rows = [i for i in range(n) if fl[i] == M_RELEASE]
    stats["acquires"] = len(acq_rows)
    stats["releases"] = len(rel_rows)

    # forced double-hold: at the k-th :ok acquire completion, fewer
    # than k-1 releases could possibly have linearized
    acq_ok = sorted((i for i in acq_rows if ok[i]),
                    key=lambda i: ret[i])
    rel_inv = sorted(inv[i] for i in rel_rows)
    for k, i in enumerate(acq_ok, start=1):
        possible_rel = bisect.bisect_left(rel_inv, ret[i])
        if k - possible_rel >= 2:
            stats["decided"] = False
            stats["reason"] = "lock-overhold"
            out.decided = _decided(False, certificate={
                "final_ops": sorted(acq_ok[max(0, k - 2):k])},
                stats=stats)
            return out

    # forced release-of-free: at the k-th :ok release completion,
    # fewer than k acquires could possibly have linearized
    rel_ok = sorted((i for i in rel_rows if ok[i]),
                    key=lambda i: ret[i])
    acq_inv = sorted(inv[i] for i in acq_rows)
    for k, i in enumerate(rel_ok, start=1):
        possible_acq = bisect.bisect_left(acq_inv, ret[i])
        if k - possible_acq >= 1:
            stats["decided"] = False
            stats["reason"] = "release-unheld"
            out.decided = _decided(False, certificate={
                "final_ops": [i]}, stats=stats)
            return out

    # alternation has no unique-writer structure: no forced edges to
    # emit, and decide-valid stays with the engines
    _prune_stats(seq, [], stats)
    return out


# ---------------------------------------------------------------------------
# the prepass slot (hb.maybe_hb dispatches here)
# ---------------------------------------------------------------------------


def maybe_constraints(seq: OpSeq, model) -> HBAnalysis:
    """Run the constraint pre-pass under a span + the
    ``jtpu_constraint_*`` metrics — the non-register twin of
    ``hb.maybe_hb``'s body (the flag was already resolved there)."""
    from .. import obs

    fam = family_of(model) or "none"
    with obs.span("constraints.prepass", cat="analyze", rows=len(seq),
                  family=fam):
        a = analyze_constraints(seq, model)
    if not a.applies:
        _M_PREPASS.inc(family=fam, outcome="skipped")
        return a
    if a.decided is not None:
        _M_PREPASS.inc(family=fam, outcome="decided_valid"
                       if a.decided["valid"] else "decided_invalid")
    else:
        _M_PREPASS.inc(family=fam, outcome="undecided")
        for k, v in a.stats["edges"].items():
            if v:
                _M_EDGES.inc(v, kind=k)
    return a


def plan_block(seq: OpSeq, model) -> dict:
    """The static ``constraints`` block for explain(): family,
    decidability, inferred edge counts, and the streamed-fold
    eligibility (which incremental fold route the family has).  Pure
    description — no live metrics are touched."""
    fam = family_of(model)
    if fam is None:
        return {"applies": False, "family": None, "enabled": hb_enabled(),
                "reason": "register-family model (see the hb block)",
                "stream_fold": {"eligible": False, "route": None}}
    a = analyze_constraints(seq, model)
    st = dict(a.stats)
    st["enabled"] = hb_enabled()
    st["stream_fold"] = {
        "eligible": fam in ("queue", "fifo-queue"),
        "route": "total-queue" if fam in ("queue", "fifo-queue")
        else None}
    if "pruned_upper_bound" not in st:
        st.setdefault("pruned_upper_bound", None)
        st.setdefault("prune_ratio", 1.0)
    return st


# ---------------------------------------------------------------------------
# event-level multiset analysis (the checkers' and the fold's substrate)
# ---------------------------------------------------------------------------


def analyze_queue_events(history) -> dict:
    """Static multiset analysis of an event-level queue history — the
    same verdict ``checker.basic.total_queue`` computes, carried as
    row-level evidence (event indices) the W007 audit re-justifies.
    Returns::

        {"valid": bool, "evidence": {...} | None,
         "edges": n_rf, "lost": {...}, "unexpected": {...}}

    Drains expand exactly as the checker expands them; an
    unexpandable (crashed) drain yields ``{"valid": "unknown"}``, the
    checker's own behavior under ``check_safe``.
    """
    from ..history import is_invoke, is_ok

    attempts: Counter = Counter()
    enq_ok: Counter = Counter()
    enq_ok_row: dict = {}
    deq: Counter = Counter()
    first_deq_row: dict = {}
    edges = 0
    for i, op in enumerate(history):
        if not isinstance(op.process, int):
            continue
        if op.f == "enqueue":
            if is_invoke(op):
                attempts[op.value] += 1
            elif is_ok(op):
                enq_ok[op.value] += 1
                enq_ok_row.setdefault(op.value, i)
        elif op.f == "dequeue" and is_ok(op):
            deq[op.value] += 1
            first_deq_row.setdefault(op.value, i)
            if op.value in enq_ok_row:
                edges += 1  # enqueue -> dequeue read-from
        elif op.f == "drain":
            if is_ok(op) and isinstance(op.value, (list, tuple)):
                for element in op.value:
                    deq[element] += 1
                    first_deq_row.setdefault(element, i)
                    if element in enq_ok_row:
                        edges += 1
            elif not is_invoke(op) and op.type != "fail":
                return {"valid": "unknown", "evidence": None,
                        "edges": edges,
                        "info": "crashed drain: removed elements "
                                "unidentifiable"}
    lost = enq_ok - deq
    unexpected = Counter({v: c for v, c in deq.items()
                          if v not in attempts})
    evidence = None
    if unexpected:
        rows = sorted(first_deq_row[v] for v in unexpected)
        evidence = {"family": "queue", "kind": "unexpected-dequeue",
                    "rows": rows, "values": sorted(map(str, unexpected))}
    elif lost:
        rows = sorted(enq_ok_row[v] for v in lost if v in enq_ok_row)
        evidence = {"family": "queue", "kind": "lost-acked-enqueue",
                    "rows": rows, "values": sorted(map(str, lost))}
    return {"valid": not lost and not unexpected, "evidence": evidence,
            "edges": edges, "lost": dict(lost),
            "unexpected": dict(unexpected)}


def analyze_set_events(history) -> dict:
    """Static set analysis: add->member-read edges plus the SetChecker
    verdict (lost / unexpected against the final read) with row-level
    evidence."""
    from ..history import is_invoke, is_ok

    attempts: set = set()
    add_ok_row: dict = {}
    final_read = None
    final_row = None
    edges = 0
    for i, op in enumerate(history):
        if not isinstance(op.process, int):
            continue
        if op.f == "add":
            if is_invoke(op):
                attempts.add(op.value)
            elif is_ok(op):
                add_ok_row.setdefault(op.value, i)
        elif op.f == "read" and is_ok(op):
            final_read, final_row = set(op.value or ()), i
    if final_read is None:
        return {"valid": "unknown", "evidence": None, "edges": 0}
    edges = sum(1 for v in final_read if v in add_ok_row)
    lost = set(add_ok_row) - final_read
    unexpected = final_read - attempts
    evidence = None
    if unexpected:
        evidence = {"family": "set", "kind": "unexpected-member",
                    "rows": [final_row],
                    "values": sorted(map(str, unexpected))}
    elif lost:
        evidence = {"family": "set", "kind": "lost-acked-add",
                    "rows": sorted(add_ok_row[v] for v in lost),
                    "values": sorted(map(str, lost))}
    return {"valid": not lost and not unexpected, "evidence": evidence,
            "edges": edges, "lost": sorted(map(str, lost)),
            "unexpected": sorted(map(str, unexpected))}


class MultisetFold:
    """The incremental edge form of the multiset analysis — what the
    streaming checker's total-queue fold route executes per event.

    ``step(op, event_idx)`` folds one history event and returns flip
    evidence (a dict shaped like :func:`analyze_queue_events`'s
    ``evidence``) the FIRST time the running state proves the history
    invalid, else None.  Two flip rules, both confirmed at finalize by
    the post-hoc checker (the final verdict is always the checker's):

      * **unexpected** — an :ok dequeue (or drained element) of a
        value no enqueue ever attempted: flagged at the dequeue's
        event.
      * **lost** — AT an :ok drain's own completion with no client op
        pending, acked enqueues missing from every dequeue/drain so
        far are lost: flagged at the drain event (the moment the final
        drain returns short, not minutes later at teardown).  Never
        evaluated at other completions — an enqueue acked after the
        drain must not be flagged the instant its own :ok lands.

    ``family="set"``: adds/reads with the read as the drain analog.
    """

    def __init__(self, family: str = "total-queue"):
        self.family = "set" if family == "set" else "total-queue"
        self.attempts: Counter = Counter()
        self.enq_ok: Counter = Counter()
        self.enq_ok_row: dict = {}
        self.deq: Counter = Counter()
        self.pending: dict = {}     # process -> f
        self.drained = False        # an :ok drain/read has landed
        self.lossy = False          # crashed drain: lost undecidable
        self.last_read: set | None = None
        self.last_read_row: int | None = None

    # -- event fold ----------------------------------------------------

    def step(self, op, i: int) -> dict | None:
        from ..history import INVOKE

        _M_FOLD_EVENTS.inc()
        if not isinstance(op.process, int):
            return None
        if op.type == INVOKE:
            self.pending[op.process] = op.f
            if op.f in ("enqueue", "add"):
                self.attempts[op.value] += 1
            return None
        self.pending.pop(op.process, None)
        if self.family == "set":
            flip = self._step_set(op, i)
        else:
            flip = self._step_queue(op, i)
        if flip is not None:
            _M_FOLD_FLIPS.inc(kind=flip["kind"])
        return flip

    def _step_queue(self, op, i: int) -> dict | None:
        from ..history import is_ok

        if op.f == "enqueue" and is_ok(op):
            self.enq_ok[op.value] += 1
            self.enq_ok_row.setdefault(op.value, i)
        elif op.f == "dequeue" and is_ok(op):
            self.deq[op.value] += 1
            if op.value not in self.attempts:
                return {"family": "queue", "kind": "unexpected-dequeue",
                        "rows": [i], "values": [str(op.value)]}
        elif op.f == "drain":
            if is_ok(op) and isinstance(op.value, (list, tuple)):
                self.drained = True
                for element in op.value:
                    self.deq[element] += 1
                    if element not in self.attempts:
                        return {"family": "queue",
                                "kind": "unexpected-dequeue",
                                "rows": [i],
                                "values": [str(element)]}
                # the lost rule runs ONLY here, at a drain's own
                # completion with nothing pending — never at later
                # quiescent completions, where an enqueue acked AFTER
                # the drain would be flagged the instant its :ok lands
                if not self.lossy and not self.pending:
                    lost = self.enq_ok - self.deq
                    if lost:
                        rows = sorted(self.enq_ok_row[v] for v in lost
                                      if v in self.enq_ok_row)
                        return {"family": "queue",
                                "kind": "lost-acked-enqueue",
                                "rows": rows,
                                "values": sorted(map(str, lost))}
            elif op.type == "info":
                self.lossy = True  # removed elements unidentifiable
        return None

    def _step_set(self, op, i: int) -> dict | None:
        from ..history import is_ok

        if op.f == "add" and is_ok(op):
            self.enq_ok[op.value] += 1
            self.enq_ok_row.setdefault(op.value, i)
        elif op.f == "read" and is_ok(op):
            self.drained = True
            self.last_read = set(op.value or ())
            self.last_read_row = i
            unexpected = self.last_read - set(self.attempts)
            if unexpected:
                return {"family": "set", "kind": "unexpected-member",
                        "rows": [i],
                        "values": sorted(map(str, unexpected))}
            # as with drains: lost evaluates only AT the read itself
            # (an add acked after the final read is not lost)
            if not self.pending:
                lost = set(self.enq_ok_row) - self.last_read
                if lost:
                    return {"family": "set", "kind": "lost-acked-add",
                            "rows": sorted(self.enq_ok_row[v]
                                           for v in lost),
                            "values": sorted(map(str, lost))}
        return None
